"""recurrent_group — the dynamic recurrent engine.

Reference: RecurrentGradientMachine (gserver/gradientmachines/
RecurrentGradientMachine.h:32) unrolls a sub-network per timestep with
"memory" links across frames (in-links/out-links/memories in
SubModelConfig, ModelConfig.proto:608), driven from the DSL's
recurrent_group (trainer_config_helpers/layers.py:3818) with memory(),
StaticInput, and beam_search (:4101).

TPU design: the step sub-network is captured as its own Topology at build
time (the user's step function runs ONCE, on placeholder nodes); apply runs
it under `lax.scan` over the padded time axis with the memory pytree as the
scan carry — XLA compiles the step once and pipelines it, replacing the
reference's per-frame re-execution. Padded steps freeze the carry, matching
ragged semantics. Generation-time beam search lives in
paddle_tpu/layers/beam.py.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.data_type import InputType, SeqType
from paddle_tpu.core.registry import (ApplyContext, LayerMeta, LayerOutput,
                                      ParamSpec, make_layer, register_layer)
from paddle_tpu.core.sequence import SequenceBatch


class StaticInput:
    """Per-sample constant visible at every step (reference StaticInput)."""

    def __init__(self, input: LayerOutput, is_seq: bool = False, size=None):
        self.input = input
        self.is_seq = is_seq


class SubsequenceInput:
    """Nested in-link: the group iterates over SUBSEQUENCES — at outer step
    t the step function receives the t-th subsequence of each sample as a
    level-1 sequence (reference SubsequenceInput,
    trainer_config_helpers/layers.py + RecurrentGradientMachine.h:32's
    hasSubseq in-frame path). max_segments / max_sub_len bound the dense
    per-subsequence view (default: the input's max_len, always safe)."""

    def __init__(self, input: LayerOutput, max_segments: Optional[int] = None,
                 max_sub_len: Optional[int] = None):
        self.input = input
        self.max_segments = max_segments
        self.max_sub_len = max_sub_len


class GeneratedInput:
    """Generation-mode input: the step consumes its own previous prediction
    (reference GeneratedInput for beam_search). Used by layers/beam.py."""

    def __init__(self, size: int, embedding_name: str, embedding_size: int,
                 bos_id: int = 0, eos_id: int = 1):
        self.size = size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size
        self.bos_id = bos_id
        self.eos_id = eos_id


class _GroupBuildCtx(threading.local):
    def __init__(self):
        self.stack: List[Dict[str, Any]] = []


_build_ctx = _GroupBuildCtx()


def memory(name: str, size: int, boot_layer: Optional[LayerOutput] = None,
           boot_with_const_id: Optional[int] = None, is_seq: bool = False,
           **kw) -> LayerOutput:
    """Inside a recurrent_group step: the value the layer called `name`
    produced at the previous timestep (zero / boot_layer value at t=0)."""
    assert _build_ctx.stack, "memory() must be called inside recurrent_group"
    group = _build_ctx.stack[-1]
    feed_name = f"@mem@{group['name']}@{name}@{len(group['memories'])}"
    node = make_layer(
        "data", feed_name, [],
        input_type=InputType(size, "integer" if boot_with_const_id is not None
                             else "dense"))
    group["memories"].append({
        "feed_name": feed_name,
        "link_name": name,
        "size": size,
        "boot_const_id": boot_with_const_id,
        "has_boot_layer": boot_layer is not None,
    })
    if boot_layer is not None:
        group["boot_layers"].append(boot_layer)
    return node


def recurrent_group(step, input, reverse: bool = False,
                    name: Optional[str] = None, remat: bool = False,
                    **kw) -> LayerOutput:
    """Run `step` over every timestep of the input sequence(s).

    input: LayerOutput sequence(s) and/or StaticInput(s). Returns the
    sequence of step outputs (a level-1 SequenceBatch node).
    remat=True jax.checkpoints the step body: the backward pass keeps
    only the per-step memory carries and recomputes step interiors
    (identical numerics, less activation memory on long sequences).
    """
    from paddle_tpu.core.registry import _auto_name
    from paddle_tpu.core.topology import Topology

    gname = name or _auto_name("recurrent_group")
    inputs = input if isinstance(input, (list, tuple)) else [input]
    sub_inputs = [i for i in inputs if isinstance(i, SubsequenceInput)]
    seq_inputs = [i for i in inputs if isinstance(i, LayerOutput)]
    static_inputs = [i for i in inputs if isinstance(i, StaticInput)]
    nested = bool(sub_inputs)
    if nested:
        assert not seq_inputs, \
            "recurrent_group: mix of SubsequenceInput and plain sequence " \
            "in-links is not supported — wrap all of them"
        bounds = {(s.max_segments, s.max_sub_len) for s in sub_inputs}
        assert len(bounds) == 1, \
            "recurrent_group: every SubsequenceInput must carry the same " \
            f"max_segments/max_sub_len bounds, got {sorted(bounds)}"
        seq_inputs = [s.input for s in sub_inputs]
    assert seq_inputs, "recurrent_group needs at least one sequence input"

    # Build step placeholders: plain groups peel one seq level off; nested
    # groups hand the step a level-1 subsequence per outer step.
    group = {"name": gname, "memories": [], "boot_layers": []}
    placeholders = []
    for i, si in enumerate(seq_inputs):
        ph = make_layer(
            "data", f"@in@{gname}@{i}", [],
            input_type=InputType(si.meta.size,
                                 "integer" if si.meta.is_integer else "dense",
                                 SeqType(1) if nested else SeqType(0)))
        placeholders.append(ph)
    static_phs = []
    for i, si in enumerate(static_inputs):
        kind = "integer" if si.input.meta.is_integer else "dense"
        # a full sequence visible at each step (e.g. attention source):
        # the seq level must live in the InputType so it survives the
        # sub-topology JSON round-trip.
        seq_t = SeqType(si.input.meta.seq_level if si.is_seq else 0)
        ph = make_layer("data", f"@static@{gname}@{i}", [],
                        input_type=InputType(si.input.meta.size, kind, seq_t))
        static_phs.append(ph)

    _build_ctx.stack.append(group)
    try:
        step_args = placeholders + static_phs
        out = step(*step_args)
    finally:
        _build_ctx.stack.pop()
    step_outputs = out if isinstance(out, (list, tuple)) else [out]

    # Sub-topology: step outputs + every memory's linked layer.
    sub_nodes = list(step_outputs)
    probe = Topology(sub_nodes)
    extra = []
    for mem in group["memories"]:
        if mem["link_name"] not in probe.by_name:
            raise ValueError(
                f"recurrent_group {gname}: memory links to layer "
                f"{mem['link_name']!r} but the step graph doesn't define it")
        extra.append(probe.by_name[mem["link_name"]])
    sub_topo = Topology(step_outputs, extra_outputs=extra)

    # Hoist sub-params into the group node.
    outer_inputs = seq_inputs + [s.input for s in static_inputs] + \
        group["boot_layers"]
    group_kw = {"remat": True} if remat else {}
    node = make_layer(
        "recurrent_group", gname, outer_inputs,
        **group_kw,
        n_seq=len(seq_inputs), n_static=len(static_inputs),
        reverse=reverse,
        nested=nested,
        max_segments=(sub_inputs[0].max_segments if nested else None),
        max_sub_len=(sub_inputs[0].max_sub_len if nested else None),
        memories=group["memories"],
        step_in_names=[p.name for p in placeholders],
        static_names=[p.name for p in static_phs],
        static_is_seq=[s.is_seq for s in static_inputs],
        out_name=step_outputs[0].name,
        out_names=[o.name for o in step_outputs],
        sub_topology=sub_topo.serialize(),
    )
    # attach hoisted params and rebuild meta
    node.params = list(sub_topo.param_specs.values())
    out0 = step_outputs[0].meta
    out_level = (out0.seq_level + 1) if nested else 1
    node.meta = LayerMeta(size=out0.size, seq_level=out_level,
                          is_integer=out0.is_integer)
    node.config["_obj_sub_topo"] = sub_topo
    return node


@register_layer("recurrent_group")
class RecurrentGroupLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        # When rebuilt from JSON, reconstruct the sub-topology object and
        # re-hoist its params.
        from paddle_tpu.core.topology import Topology
        sub = cfg.get("_obj_sub_topo")
        if sub is None:
            sub = Topology.deserialize(cfg["sub_topology"])
            cfg["_obj_sub_topo"] = sub
        out_meta = sub.by_name[cfg["out_name"]].meta
        params = list(sub.param_specs.values())
        out_level = (out_meta.seq_level + 1) if cfg.get("nested") else 1
        meta = LayerMeta(size=out_meta.size, seq_level=out_level,
                         is_integer=out_meta.is_integer)
        return meta, params, []

    @staticmethod
    def apply(ctx: ApplyContext, name, cfg, params, inputs):
        if cfg.get("nested"):
            return _apply_nested_group(ctx, name, cfg, params, inputs)
        sub = cfg["_obj_sub_topo"]
        n_seq = cfg["n_seq"]
        n_static = cfg["n_static"]
        seqs: List[SequenceBatch] = list(inputs[:n_seq])
        statics = list(inputs[n_seq:n_seq + n_static])
        boots = list(inputs[n_seq + n_static:])
        lengths = seqs[0].lengths
        T = seqs[0].max_len
        b = seqs[0].batch_size
        reverse = cfg.get("reverse", False)

        # memory init
        mems = []
        boot_i = 0
        for m in cfg["memories"]:
            if m["has_boot_layer"]:
                bv = boots[boot_i]
                boot_i += 1
                mems.append(bv.data if isinstance(bv, SequenceBatch) else bv)
            elif m["boot_const_id"] is not None:
                mems.append(jnp.full((b,), m["boot_const_id"], jnp.int32))
            else:
                mems.append(jnp.zeros((b, m["size"]), jnp.float32))

        # time-major step inputs (reversed per-row if requested)
        def time_major(s: SequenceBatch):
            x = s.data
            if reverse:
                idx = jnp.clip(s.lengths[:, None] - 1 -
                               jnp.arange(T, dtype=jnp.int32)[None, :], 0,
                               T - 1)
                x = jnp.take_along_axis(
                    x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1) \
                    if x.ndim > 2 else jnp.take_along_axis(x, idx, axis=1)
            return jnp.moveaxis(x, 1, 0)

        xs = tuple(time_major(s) for s in seqs)
        static_feed = {}
        for sname, sval, is_seq in zip(cfg["static_names"], statics,
                                       cfg["static_is_seq"]):
            static_feed[sname] = sval

        mem_feed_names = [m["feed_name"] for m in cfg["memories"]]
        link_names = [m["link_name"] for m in cfg["memories"]]
        out_names = cfg.get("out_names") or [cfg["out_name"]]

        def body(carry, inp):
            t, x_t = inp
            feed = dict(static_feed)
            for ph_name, xv in zip(cfg["step_in_names"], x_t):
                feed[ph_name] = xv
            for fname, mv in zip(mem_feed_names, carry):
                feed[fname] = mv
            outs, _ = sub.forward(params, {}, feed, mode=ctx.mode,
                                  rng=ctx.rng_for(f"{name}@{0}"),
                                  output_names=list(out_names) + link_names,
                                  n_real=getattr(ctx, "n_real", None))
            new_mems = tuple(
                outs[ln].data if isinstance(outs[ln], SequenceBatch)
                else outs[ln] for ln in link_names)
            valid = t < lengths

            def freeze(n, o):
                v = valid.reshape((-1,) + (1,) * (n.ndim - 1))
                return jnp.where(v, n, o)

            merged = tuple(jax.tree_util.tree_map(freeze, n, o)
                           for n, o in zip(new_mems, carry))
            outs_t = []
            for on in out_names:
                ot = outs[on]
                ot = ot.data if isinstance(ot, SequenceBatch) else ot
                vo = valid.reshape((-1,) + (1,) * (ot.ndim - 1))
                outs_t.append(jnp.where(vo, ot, jnp.zeros_like(ot)))
            return merged, tuple(outs_t)

        tidx = jnp.arange(T, dtype=jnp.int32)
        if cfg.get("remat"):
            # jax.checkpoint the step body: backward keeps only the memory
            # carries per timestep and recomputes the step interior — the
            # FLOPs-for-memory trade for long sequences
            body = jax.checkpoint(body)
        _, outs_all = lax.scan(body, tuple(mems), (tidx, xs))

        def finalize(outs):
            outs = jnp.moveaxis(outs, 0, 1)
            if reverse:
                idx = jnp.clip(lengths[:, None] - 1 -
                               jnp.arange(T, dtype=jnp.int32)[None, :], 0,
                               T - 1)
                outs = jnp.take_along_axis(
                    outs, idx.reshape(idx.shape + (1,) * (outs.ndim - 2)),
                    axis=1) if outs.ndim > 2 else \
                    jnp.take_along_axis(outs, idx, axis=1)
                m = (jnp.arange(T, dtype=jnp.int32)[None, :] <
                     lengths[:, None])
                outs = jnp.where(
                    m.reshape(m.shape + (1,) * (outs.ndim - 2)), outs,
                    jnp.zeros_like(outs))
            return SequenceBatch(outs, lengths)

        results = [finalize(o) for o in outs_all]
        # non-primary step outputs are retrievable via layer.get_output
        # (GetOutputLayer reads them off the apply context)
        aux = getattr(ctx, "aux_outputs", None)
        if aux is None:
            aux = ctx.aux_outputs = {}
        for on, val in zip(out_names, results):
            aux[(name, on)] = val
        return results[0]


def _apply_nested_group(ctx: ApplyContext, name, cfg, params, inputs):
    """Level-2 unroll: outer scan over subsequences, each outer step runs
    the sub-topology on a level-1 SequenceBatch view of the t-th
    subsequence (RecurrentGradientMachine.h:32 hasSubseq path — the
    reference rebuilds in-frames per outer step via createInFrameInfo; here
    it is one nested_to_padded scatter + a lax.scan over the segment axis).
    """
    from paddle_tpu.ops import sequence_ops as seq_ops

    sub = cfg["_obj_sub_topo"]
    n_seq = cfg["n_seq"]
    n_static = cfg["n_static"]
    seqs: List[SequenceBatch] = list(inputs[:n_seq])
    statics = list(inputs[n_seq:n_seq + n_static])
    boots = list(inputs[n_seq + n_static:])
    ref = seqs[0]
    assert ref.is_nested, \
        f"recurrent_group {name}: SubsequenceInput needs a nested sequence"
    b = ref.batch_size
    T = ref.max_len
    S = int(cfg.get("max_segments") or T)
    Lm = int(cfg.get("max_sub_len") or T)
    n_seg = ref.num_segments
    reverse = cfg.get("reverse", False)

    def rev_segments(data, ilen):
        """Per-row flip of the segment axis: step i sees segment
        n_seg-1-i, giving the backward walk over subsequences."""
        idx = jnp.clip(n_seg[:, None] - 1 -
                       jnp.arange(S, dtype=jnp.int32)[None, :], 0, S - 1)
        d = jnp.take_along_axis(
            data, idx.reshape(idx.shape + (1,) * (data.ndim - 2)), axis=1)
        l = jnp.take_along_axis(ilen, idx, axis=1)
        keep = jnp.arange(S, dtype=jnp.int32)[None, :] < n_seg[:, None]
        return (jnp.where(keep.reshape(keep.shape + (1,) * (d.ndim - 2)),
                          d, jnp.zeros_like(d)),
                jnp.where(keep, l, 0))

    views = [seq_ops.nested_to_padded(s, S, Lm) for s in seqs]
    if reverse:
        views = [rev_segments(d, l) for d, l in views]

    # memory init (same as the flat path)
    mems = []
    boot_i = 0
    for m in cfg["memories"]:
        if m["has_boot_layer"]:
            bv = boots[boot_i]
            boot_i += 1
            mems.append(bv.data if isinstance(bv, SequenceBatch) else bv)
        elif m["boot_const_id"] is not None:
            mems.append(jnp.full((b,), m["boot_const_id"], jnp.int32))
        else:
            mems.append(jnp.zeros((b, m["size"]), jnp.float32))

    static_feed = dict(zip(cfg["static_names"], statics))
    mem_feed_names = [m["feed_name"] for m in cfg["memories"]]
    link_names = [m["link_name"] for m in cfg["memories"]]
    out_names = cfg.get("out_names") or [cfg["out_name"]]
    out_is_seq = {
        on: sub.by_name[on].meta.seq_level >= 1 for on in out_names}

    def to_mem(v):
        if isinstance(v, SequenceBatch):
            return seq_ops.last_instance(v)
        return v

    def body(carry, inp):
        s_idx, per_in = inp
        feed = dict(static_feed)
        for ph_name, (dat, ilen) in zip(cfg["step_in_names"], per_in):
            feed[ph_name] = SequenceBatch(dat, ilen)
        for fname, mv in zip(mem_feed_names, carry):
            feed[fname] = mv
        outs, _ = sub.forward(params, {}, feed, mode=ctx.mode,
                              rng=ctx.rng_for(f"{name}@nested"),
                              output_names=list(out_names) + link_names,
                              n_real=getattr(ctx, "n_real", None))
        valid = s_idx < n_seg

        def freeze(nv, ov):
            v = valid.reshape((-1,) + (1,) * (nv.ndim - 1))
            return jnp.where(v, nv, ov)

        new_mems = tuple(
            jax.tree_util.tree_map(freeze, to_mem(outs[ln]), ov)
            for ln, ov in zip(link_names, carry))
        outs_t = []
        for on in out_names:
            ov = outs[on]
            if isinstance(ov, SequenceBatch):
                od = jnp.where(
                    valid.reshape((-1,) + (1,) * (ov.data.ndim - 1)),
                    ov.data, jnp.zeros_like(ov.data))
                ol = jnp.where(valid, ov.lengths, 0)
                outs_t.append((od, ol))
            else:
                vo = valid.reshape((-1,) + (1,) * (ov.ndim - 1))
                outs_t.append((jnp.where(vo, ov, jnp.zeros_like(ov)), None))
        return new_mems, tuple(outs_t)

    s_idx = jnp.arange(S, dtype=jnp.int32)
    xs = tuple((jnp.moveaxis(dat, 0, 1), jnp.moveaxis(ilen, 0, 1))
               for dat, ilen in views)          # [S, b, L, d], [S, b]
    if cfg.get("remat"):
        body = jax.checkpoint(body)     # same trade as the flat path
    _, outs_all = lax.scan(body, tuple(mems), (s_idx, xs))

    results = []
    for on, (od, ol) in zip(out_names, outs_all):
        if out_is_seq[on]:
            # [S, b, L, d] -> nested SequenceBatch over the original T axis
            data = jnp.moveaxis(od, 0, 1)       # [b, S, L, d]
            ilen = jnp.moveaxis(ol, 0, 1)       # [b, S]
            if reverse:
                data, ilen = rev_segments(data, ilen)
            results.append(seq_ops.padded_to_nested(data, ilen, n_seg, T))
        else:
            out = jnp.moveaxis(od, 0, 1)        # [b, S, d]
            if reverse:
                out, _ = rev_segments(out,
                                      jnp.zeros(out.shape[:2], jnp.int32))
            results.append(SequenceBatch(out, n_seg))

    aux = getattr(ctx, "aux_outputs", None)
    if aux is None:
        aux = ctx.aux_outputs = {}
    for on, val in zip(out_names, results):
        aux[(name, on)] = val
    return results[0]


def beam_search(step, input, bos_id: int, eos_id: int, beam_size: int,
                max_length: int = 100, num_results_per_sample: int = 1,
                name: Optional[str] = None, **kw):
    """Generation-time beam search (reference beam_search:4101 +
    RecurrentGradientMachine::generateSequence). Returns a BeamResult:
    best path as a SequenceBatch plus num_results_per_sample paths with
    scores. Implemented in layers/beam.py; wired here for API parity."""
    from paddle_tpu.layers.beam import build_beam_search
    return build_beam_search(step, input, bos_id=bos_id, eos_id=eos_id,
                             beam_size=beam_size, max_length=max_length,
                             num_results_per_sample=num_results_per_sample,
                             name=name)


@register_layer("get_output")
class GetOutputLayer:
    """get_output_layer parity (GetOutputLayer.cpp): select a non-default
    output of a recurrent_group whose step returned several layers."""

    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=cfg["size"], seq_level=1,
                         is_integer=cfg.get("is_integer", False)), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        aux = getattr(ctx, "aux_outputs", {})
        key = (cfg["group_name"], cfg["arg_name"])
        if key not in aux:
            raise KeyError(
                f"get_output: group {cfg['group_name']!r} produced no "
                f"output {cfg['arg_name']!r} this pass")
        return aux[key]


def get_output(input: LayerOutput, arg_name: str, name=None,
               **kw) -> LayerOutput:
    """Select step-output `arg_name` from a multi-output recurrent_group
    (reference get_output_layer, trainer_config_helpers/layers.py)."""
    if arg_name == input.config.get("out_name"):
        return input                          # the primary output
    sub = input.config.get("_obj_sub_topo")
    assert sub is not None and arg_name in sub.by_name, \
        f"get_output: {arg_name!r} is not an output of {input.name!r}"
    assert arg_name in (input.config.get("out_names") or ()), \
        f"get_output: step did not RETURN {arg_name!r}; return it from " \
        "the step function to expose it"
    m = sub.by_name[arg_name].meta
    return make_layer("get_output", name, [input], arg_name=arg_name,
                      group_name=input.name, size=m.size,
                      is_integer=m.is_integer)
