"""Core layers: data, fc, embedding, mixed/projections, elementwise glue.

Reference: python/paddle/trainer_config_helpers/layers.py (fc_layer:991,
data_layer, embedding_layer, mixed_layer:847, addto_layer, concat_layer,
dropout, slope_intercept, interpolation, cos_sim, bilinear...), compute in
gserver/layers/{FullyConnectedLayer,MixedLayer,*Projection,AddtoLayer,
ConcatenateLayer,...}.

Conventions:
  - non-sequence values are [batch, size]; sequences are SequenceBatch with
    data [batch, T, size] (ids: [batch, T]).
  - `apply(ctx, name, cfg, params, inputs)` is pure; params is a dict of this
    layer's parameters keyed by full parameter name.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers
from paddle_tpu.core.data_type import InputType, SeqType
from paddle_tpu.core.registry import (LayerMeta, ParamAttr, ParamSpec,
                                      StateSpec, default_weight_init,
                                      make_layer, register_layer)
from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import activations as act_ops
from paddle_tpu.ops import linear as linear_ops
from paddle_tpu.ops import norm as norm_ops
from paddle_tpu.ops import embedding as emb_ops
from paddle_tpu import activation as act_mod
from paddle_tpu import attr as attr_mod


def _apply_act(x, act_name: str, mask=None):
    if act_name == "sequence_softmax":
        return act_ops.sequence_softmax(x, mask)
    return act_ops.get(act_name)(x)


def _map_seq(fn, value):
    """Apply fn to the dense payload whether value is a SequenceBatch or array."""
    if isinstance(value, SequenceBatch):
        return value.with_data(fn(value.data))
    return fn(value)


def _payload(value):
    return value.data if isinstance(value, SequenceBatch) else value


def _norm_attrs(param_attr, n: int) -> List[ParamAttr]:
    if param_attr is None:
        return [ParamAttr() for _ in range(n)]
    if isinstance(param_attr, (list, tuple)):
        out = [ParamAttr.of(a) for a in param_attr]
        assert len(out) == n, "param_attr list length mismatch"
        return out
    return [ParamAttr.of(param_attr) for _ in range(n)]


# ---------------------------------------------------------------------------


@register_layer("data")
class DataLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        it: InputType = cfg["input_type"]
        seq_level = it.seq_type.value
        height = cfg.get("height", 0)
        width = cfg.get("width", 0)
        channels = 0
        if height and width:
            channels = it.dim // (height * width)
        return (LayerMeta(size=it.dim, seq_level=seq_level, height=height,
                          width=width, channels=channels,
                          is_integer=(it.kind == "integer")), [], [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        v = inputs[0]
        # Mixed-precision entry cast: dense float feeds drop to the
        # compute dtype ONCE here, so the whole activation graph runs
        # bf16 (ops preserve their input dtype; without this, an f32
        # feed keeps every elementwise chain f32 and doubles HBM
        # traffic — see the resnet trace analysis in docs/perf.md).
        it: InputType = cfg["input_type"]
        if it.kind != "integer":
            from paddle_tpu.ops.linear import compute_dtype
            cd = compute_dtype()
            if cd != jnp.float32:
                if isinstance(v, SequenceBatch):
                    if jnp.issubdtype(v.data.dtype, jnp.floating):
                        v = v.with_data(v.data.astype(cd))
                elif jnp.issubdtype(v.dtype, jnp.floating):
                    v = v.astype(cd)
        return v


@register_layer("fc")
class FCLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        size = cfg["size"]
        attrs = _norm_attrs(cfg.get("param_attr"), len(input_metas))
        cfg["param_attr"] = attrs
        specs = []
        for i, (m, a) in enumerate(zip(input_metas, attrs)):
            pname = a.name or (f"_{name}.w{i}" if i else f"_{name}.w0")
            # tied_transpose stores the weight [out, in] — the shape of
            # an embedding table — so an LM head can SHARE the token
            # embedding parameter (weight tying: same name, same shape,
            # the fc applies it transposed)
            shape = (size, m.size) if cfg.get("tied_transpose") \
                else (m.size, size)
            # fan-in axis follows the storage layout: [out, in] when
            # transposed, so init scale still derives from the INPUT dim
            fan_in = (1,) if cfg.get("tied_transpose") else (0,)
            specs.append(ParamSpec(pname, shape,
                                   default_weight_init(a, fan_in), a))
        battr = ParamAttr.of(cfg.get("bias_attr")) if not isinstance(
            cfg.get("bias_attr"), bool) else ParamAttr()
        if cfg.get("bias_attr") is not False:
            bname = battr.name or f"_{name}.wbias"
            specs.append(ParamSpec(bname, (size,),
                                   battr.initializer or initializers.zeros,
                                   battr))
            cfg["_bias_name"] = bname
        seq_level = max(m.seq_level for m in input_metas)
        return LayerMeta(size=size, seq_level=seq_level), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        attrs = cfg["param_attr"]
        ws = []
        for i, a in enumerate(attrs):
            pname = a.name or f"_{name}.w{i}"
            ws.append(params[pname])
        b = params.get(cfg.get("_bias_name")) if cfg.get("_bias_name") else None
        out = None
        ref = None
        for val, w in zip(inputs, ws):
            x = _payload(val)
            if not isinstance(val, SequenceBatch) and x.ndim > 2:
                x = x.reshape(x.shape[0], -1)   # flatten image NHWC -> [b, hwc]
            y = linear_ops.matmul(x, w.T if cfg.get("tied_transpose")
                                  else w)
            out = y if out is None else out + y
            if isinstance(val, SequenceBatch):
                ref = val
        if b is not None:
            out = out + b.astype(out.dtype)   # f32 master bias: no promote
        mask = ref.mask() if ref is not None else None
        out = _apply_act(out, cfg.get("act", "linear"), mask)
        return ref.with_data(out) if ref is not None else out


@register_layer("embedding")
class EmbeddingLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        assert m.is_integer, "embedding input must be integer ids"
        size = cfg["size"]
        a = ParamAttr.of(cfg.get("param_attr"))
        pname = a.name or f"_{name}.w0"
        cfg["_w_name"] = pname
        if cfg.get("remote") or a.remote:
            # table lives in the sharded embedding store
            # (paddle_tpu/embed): NO local ParamSpec — the [vocab, size]
            # array never materializes on device; rows arrive per batch
            # through ctx.sparse_sub (embed.lookup.RemoteLookup)
            cfg["_remote"] = True
            cfg["_vocab"] = m.size
            return LayerMeta(size=size, seq_level=m.seq_level), [], []
        init = a.initializer or (initializers.normal(a.initial_std or 0.01))
        specs = [ParamSpec(pname, (m.size, size), init, a)]
        return LayerMeta(size=size, seq_level=m.seq_level), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        pname = cfg["_w_name"]
        val = inputs[0]
        ids = _payload(val)
        sub = getattr(ctx, "sparse_sub", None)
        if cfg.get("_remote"):
            if not sub or pname not in sub:
                raise KeyError(
                    f"embedding layer {name!r} uses a REMOTE table "
                    f"({pname}); pass sparse_sub={{...}} built by "
                    "paddle_tpu.embed.lookup.RemoteLookup for this batch")
            uids, rows = sub[pname]
            out = emb_ops.row_sub_lookup(uids, rows, ids, cfg["_vocab"],
                                         pad_id=cfg.get("pad_id", -1))
        elif sub and pname in sub:
            # row-sparse path: look up inside the prefetched row block so
            # gradients flow to the [k, emb] rows, not the whole table
            uids, rows = sub[pname]
            table = params[pname]
            out = emb_ops.row_sub_lookup(uids, rows, ids, table.shape[0],
                                         pad_id=cfg.get("pad_id", -1))
        else:
            table = params[pname]
            out = emb_ops.embedding_lookup(table, ids,
                                           pad_id=cfg.get("pad_id", -1))
        if isinstance(val, SequenceBatch):
            return val.with_data(out)
        return out


@register_layer("dropout")
class DropoutLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=m.seq_level, height=m.height,
                         width=m.width, channels=m.channels), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        rate = cfg.get("dropout_rate", 0.5)
        val = inputs[0]
        if not ctx.is_train or rate <= 0.0:
            return val

        def drop(x):
            keep = 1.0 - rate
            mask = jax.random.bernoulli(ctx.rng_for(name), keep, x.shape)
            return jnp.where(mask, x / keep, 0.0).astype(x.dtype)

        return _map_seq(drop, val)


@register_layer("addto")
class AddtoLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        size = input_metas[0].size
        for m in input_metas:
            assert m.size == size, "addto inputs must agree in size"
        specs = []
        if cfg.get("bias_attr") not in (False, None):
            a = ParamAttr.of(None if cfg.get("bias_attr") is True
                             else cfg.get("bias_attr"))
            bname = a.name or f"_{name}.wbias"
            specs.append(ParamSpec(bname, (size,), initializers.zeros, a))
            cfg["_bias_name"] = bname
        m0 = input_metas[0]
        return LayerMeta(size=size, seq_level=max(m.seq_level for m in input_metas),
                         height=m0.height, width=m0.width,
                         channels=m0.channels), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        ref = next((v for v in inputs if isinstance(v, SequenceBatch)), None)
        out = sum(_payload(v) for v in inputs)
        if cfg.get("_bias_name"):
            out = out + params[cfg["_bias_name"]].astype(out.dtype)
        out = _apply_act(out, cfg.get("act", "linear"))
        return ref.with_data(out) if ref is not None else out


@register_layer("concat")
class ConcatLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        size = sum(m.size for m in input_metas)
        m0 = input_metas[0]
        # Image channel-concat (Inception): same spatial dims -> channels add.
        if all(m.height and m.height == m0.height and m.width == m0.width
               and m.channels for m in input_metas):
            return LayerMeta(size=size,
                             seq_level=max(m.seq_level for m in input_metas),
                             height=m0.height, width=m0.width,
                             channels=sum(m.channels for m in input_metas)), [], []
        return LayerMeta(size=size,
                         seq_level=max(m.seq_level for m in input_metas)), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        ref = next((v for v in inputs if isinstance(v, SequenceBatch)), None)
        out = jnp.concatenate([_payload(v) for v in inputs], axis=-1)
        out = _apply_act(out, cfg.get("act", "linear"))
        return ref.with_data(out) if ref is not None else out


@register_layer("batch_norm")
class BatchNormLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        c = m.channels if m.channels else m.size
        a = ParamAttr.of(cfg.get("param_attr"))
        gname = a.name or f"_{name}.w0"
        specs = [ParamSpec(gname, (c,), initializers.ones, a)]
        battr = ParamAttr.of(None if cfg.get("bias_attr") in (True, None)
                             else cfg.get("bias_attr"))
        bname = battr.name or f"_{name}.wbias"
        specs.append(ParamSpec(bname, (c,), initializers.zeros, battr))
        states = [StateSpec(f"_{name}.moving_mean", (c,), 0.0),
                  StateSpec(f"_{name}.moving_var", (c,), 1.0)]
        cfg["_g_name"], cfg["_b_name"] = gname, bname
        cfg["_channels"] = c
        return (LayerMeta(size=m.size, seq_level=m.seq_level, height=m.height,
                          width=m.width, channels=m.channels), specs, states)

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        val = inputs[0]
        x = _payload(val)
        c = cfg["_channels"]
        gamma = params[cfg["_g_name"]]
        beta = params[cfg["_b_name"]]
        mm = ctx.get_state(f"_{name}.moving_mean")
        mv = ctx.get_state(f"_{name}.moving_var")
        shape = x.shape
        xr = x.reshape((-1, c)) if x.shape[-1] != c or x.ndim == 2 else x
        if x.ndim == 2 and shape[-1] != c:
            # image stored flat [b, c*h*w] channel-major (paddle layout)
            xr = x.reshape(shape[0], c, -1).transpose(0, 2, 1).reshape(-1, c)
        use_global = cfg.get("use_global_stats") or not ctx.is_train
        if use_global:
            y = norm_ops.batch_norm_infer(xr, gamma, beta, mm, mv)
        else:
            y, nm, nv = norm_ops.batch_norm_train(
                xr, gamma, beta, mm, mv,
                momentum=cfg.get("moving_average_fraction", 0.9))
            ctx.set_state(f"_{name}.moving_mean", nm)
            ctx.set_state(f"_{name}.moving_var", nv)
        if x.ndim == 2 and shape[-1] != c:
            y = y.reshape(shape[0], -1, c).transpose(0, 2, 1).reshape(shape)
        else:
            y = y.reshape(shape)
        y = _apply_act(y, cfg.get("act", "linear"))
        return val.with_data(y) if isinstance(val, SequenceBatch) else y


@register_layer("scaling")
class ScalingLayer:
    """ScalingLayer: per-row scalar (input0 [b,1]) times input1 [b,d]."""
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=input_metas[1].size,
                         seq_level=input_metas[1].seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        w, v = inputs
        ref = v if isinstance(v, SequenceBatch) else None
        out = _payload(w) * _payload(v)
        return ref.with_data(out) if ref is not None else out


@register_layer("dotmul")
class DotMulLayer:
    """dotmul_operator as a layer: elementwise a*b (optionally scaled)."""
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=input_metas[0].size,
                         seq_level=max(m.seq_level for m in input_metas)), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        a, b = inputs
        ref = next((v for v in inputs if isinstance(v, SequenceBatch)), None)
        out = cfg.get("scale", 1.0) * _payload(a) * _payload(b)
        return ref.with_data(out) if ref is not None else out


@register_layer("interpolation")
class InterpolationLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=input_metas[1].size,
                         seq_level=input_metas[1].seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        w, a, b = inputs
        out = linear_ops.interpolation(_payload(w), _payload(a), _payload(b))
        ref = next((v for v in (a, b) if isinstance(v, SequenceBatch)), None)
        return ref.with_data(out) if ref is not None else out


@register_layer("slope_intercept")
class SlopeInterceptLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=m.seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return _map_seq(
            lambda x: linear_ops.slope_intercept(
                x, cfg.get("slope", 1.0), cfg.get("intercept", 0.0)),
            inputs[0])


@register_layer("cos_sim")
class CosSimLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=1,
                         seq_level=max(m.seq_level for m in input_metas)), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        a, b = inputs
        out = linear_ops.cos_sim(_payload(a), _payload(b),
                                 cfg.get("scale", 1.0))[..., None]
        ref = next((v for v in inputs if isinstance(v, SequenceBatch)), None)
        return ref.with_data(out) if ref is not None else out


@register_layer("outer_prod")
class OuterProdLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=input_metas[0].size * input_metas[1].size), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return linear_ops.outer(_payload(inputs[0]), _payload(inputs[1]))


@register_layer("sum_to_one_norm")
class SumToOneNormLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=m.seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return _map_seq(linear_ops.sum_to_one_norm, inputs[0])


@register_layer("trans")
class TransLayer:
    """TransLayer: transpose a [b, n] weight-matrix-like activation. The
    reference transposes a full matrix within a sample batch (b=n use only)."""
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=input_metas[0].size), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return jnp.swapaxes(_payload(inputs[0]), -1, -2) \
            if _payload(inputs[0]).ndim > 2 else _payload(inputs[0]).T


@register_layer("slice")
class SliceLayer:
    """Feature slice [start, end) — identity_projection with offset.
    With channel_slice=True on an image input, [start, end) indexes
    CHANNELS instead (the payload is 4D NHWC, so x[..., a:b] slices c)
    and the image meta is preserved for downstream conv/pool layers —
    opt-in so pre-existing flat-feature slices keep their semantics."""
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        n = cfg["end"] - cfg["start"]
        if cfg.get("channel_slice"):
            assert m.channels and m.height and cfg["end"] <= m.channels, \
                f"channel_slice needs an image input with >= {cfg['end']} " \
                "channels"
            cfg["_chan"] = (m.channels, m.height, m.width)
            return LayerMeta(size=n * m.height * m.width, height=m.height,
                             width=m.width, channels=n,
                             seq_level=m.seq_level), [], []
        return LayerMeta(size=n, seq_level=m.seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        def cut(x):
            if cfg.get("_chan") and x.ndim == 2:
                from paddle_tpu.layers.conv_layers import ensure_nhwc
                x = ensure_nhwc(x, *cfg["_chan"])
            return x[..., cfg["start"]:cfg["end"]]

        return _map_seq(cut, inputs[0])


@register_layer("scaling_projection")
class ScalingProjection:
    """w * x with one scalar learned weight (ScalingProjection)."""
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        a = ParamAttr.of(cfg.get("param_attr"))
        pname = a.name or f"_{name}.w0"
        cfg["_w_name"] = pname
        return (LayerMeta(size=m.size, seq_level=m.seq_level),
                [ParamSpec(pname, (1,), initializers.ones, a)], [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return _map_seq(lambda x: params[cfg["_w_name"]] * x, inputs[0])


@register_layer("dotmul_projection")
class DotMulProjection:
    """x * w elementwise with a learned [size] weight (DotMulProjection)."""
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        a = ParamAttr.of(cfg.get("param_attr"))
        pname = a.name or f"_{name}.w0"
        cfg["_w_name"] = pname
        return (LayerMeta(size=m.size, seq_level=m.seq_level),
                [ParamSpec(pname, (m.size,), initializers.ones, a)], [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return _map_seq(lambda x: x * params[cfg["_w_name"]], inputs[0])


@register_layer("trans_fc")
class TransFCLayer:
    """trans_full_matrix_projection: y = x @ W^T with W [size, in] — lets a
    weight be shared between a projection and its transpose (tied embeddings).
    """
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        size = cfg["size"]
        a = ParamAttr.of(cfg.get("param_attr"))
        pname = a.name or f"_{name}.w0"
        cfg["_w_name"] = pname
        return (LayerMeta(size=size, seq_level=m.seq_level),
                [ParamSpec(pname, (size, m.size),
                           default_weight_init(a, (1,)), a)], [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        w = params[cfg["_w_name"]]
        return _map_seq(lambda x: linear_ops.matmul(x, w.T), inputs[0])


@register_layer("resize")
class ResizeLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=cfg["size"]), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x = _payload(inputs[0])
        return x.reshape(-1, cfg["size"])
