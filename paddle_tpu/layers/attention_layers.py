"""Multi-head dot-product attention layer with transparent sequence
parallelism.

The 2017 reference's attention story was additive attention built from
mixed/projection primitives (simple_attention, networks.py:1298) — kept in
paddle_tpu.networks. This layer is the modern head-split dot-product form,
and the user-facing handle for the context-parallel machinery: when the
trainer's mesh has an `sp` axis (>1), attention runs as a RING over ICI
(parallel/sequence_parallel.py ring_attention — K/V blocks rotate via
ppermute under an online softmax), otherwise as plain fused attention.
The switch is invisible to the model definition: same layer, same params,
sp is purely a mesh decision — SURVEY §2.4's sequence-parallel row.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import LayerMeta, make_layer, register_layer
from paddle_tpu.core.sequence import SequenceBatch


def _split_heads(x: jnp.ndarray, h: int) -> jnp.ndarray:
    b, t, d = x.shape
    return x.reshape(b, t, h, d // h)


def _merge_heads(x: jnp.ndarray) -> jnp.ndarray:
    b, t, h, dh = x.shape
    return x.reshape(b, t, h * dh)


@register_layer("dot_product_attention")
class DotProductAttentionLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        q, k, v = input_metas
        assert q.seq_level >= 1 and k.seq_level >= 1 and v.seq_level >= 1, \
            "attention inputs must be sequences"
        h = cfg.get("num_heads", 1)
        kv_h = cfg.get("num_kv_heads") or h
        assert h % kv_h == 0, \
            f"num_heads={h} must be a multiple of num_kv_heads={kv_h}"
        assert q.size % h == 0 and k.size % kv_h == 0 \
            and v.size % kv_h == 0, \
            f"head counts ({h}, kv {kv_h}) must divide q/k/v sizes " \
            f"({q.size}, {k.size}, {v.size})"
        assert q.size // h == k.size // kv_h, \
            "q and k head dims must match (grouped-query attention " \
            "shares each k/v head across num_heads/num_kv_heads queries)"
        return LayerMeta(size=(v.size // kv_h) * h, seq_level=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        from paddle_tpu.parallel import sequence_parallel as sp_ops
        from paddle_tpu.parallel.mesh import SP_AXIS
        qs, ks, vs = inputs
        h = cfg.get("num_heads", 1)
        kv_h = cfg.get("num_kv_heads") or h
        causal = cfg.get("causal", False)
        q = _split_heads(qs.data, h)
        k = _split_heads(ks.data, kv_h)
        v = _split_heads(vs.data, kv_h)
        if kv_h != h:
            # grouped-query attention: each k/v head serves h/kv_h query
            # heads — repeat to full width for the fused kernels (the
            # decode-time win is the kv_h-sized CACHE, models/decode.py)
            k = jnp.repeat(k, h // kv_h, axis=2)
            v = jnp.repeat(v, h // kv_h, axis=2)
        mesh = getattr(ctx, "mesh", None)
        if mesh is not None and SP_AXIS in mesh.shape and \
                mesh.shape[SP_AXIS] > 1:
            out = sp_ops.ring_attention(q, k, v, mesh, lengths=ks.lengths,
                                        causal=causal)
        else:
            # fused flash kernel on TPU when tile-friendly; XLA otherwise
            from paddle_tpu.config import global_config
            from paddle_tpu.ops import pallas_attention as flash
            if (global_config().use_flash_attention and
                    jax.default_backend() == "tpu" and
                    flash.flash_supported(q, k)):
                out = flash.flash_attention(q, k, v, kv_lens=ks.lengths,
                                            causal=causal)
            else:
                b, tq = q.shape[0], q.shape[1]
                tk = k.shape[1]
                kv_valid = (jnp.arange(tk)[None, :] <
                            ks.lengths[:, None])        # [b, Tk]
                mask = jnp.broadcast_to(kv_valid[:, None, :], (b, tq, tk))
                if causal:
                    tri = jnp.tril(jnp.ones((tq, tk), bool))
                    mask = mask & tri[None]
                out = sp_ops.attention(q, k, v, mask=mask)
        return qs.with_data(_merge_heads(out))


def dot_product_attention(query, key=None, value=None, num_heads: int = 1,
                          num_kv_heads=None, causal: bool = False,
                          name=None, **kw):
    """Multi-head scaled-dot-product attention over sequences.

    query/key/value: sequence layers [b, T, d] (key/value default to
    query — self-attention). Runs ring attention over the mesh `sp` axis
    when one exists; plain attention otherwise. num_kv_heads < num_heads
    is grouped-query attention (each k/v head shared by
    num_heads/num_kv_heads query heads — MQA at num_kv_heads=1)."""
    key = key if key is not None else query
    value = value if value is not None else key
    opts = {"num_kv_heads": num_kv_heads} if num_kv_heads else {}
    return make_layer("dot_product_attention", name, [query, key, value],
                      num_heads=num_heads, causal=causal, **opts)


multi_head_attention = dot_product_attention
