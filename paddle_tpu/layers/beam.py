"""Beam-search sequence generation.

Reference: RecurrentGradientMachine::generateSequence / beamSearch / Path
(RecurrentGradientMachine.h:186-419, .cpp) — decoder states are re-indexed
as beams are pruned; trainer_config_helpers beam_search(:4101) +
SequenceGenerator in the SWIG api.

TPU design: fixed-width beam kept as dense [batch, beam] tensors inside one
`lax.scan`; beam pruning is a top-k over (beam*vocab) scores followed by a
gather that re-indexes every memory — the same state shuffling the reference
did with Path copying, but batched and jit-compiled. Finished beams are
frozen with an additive -inf mask (only EOS continues a finished beam with
zero added score, the standard length-neutral trick).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.data_type import InputType, SeqType
from paddle_tpu.core.registry import (LayerMeta, LayerOutput, make_layer,
                                      register_layer)
from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.layers import group as group_mod

_NEG = -1e9


@jax.tree_util.register_pytree_node_class
class BeamResult(SequenceBatch):
    """Beam-search output: the best path as a SequenceBatch (data/lengths
    — downstream layers see a normal sequence) PLUS all
    num_results_per_sample paths with scores (SequenceGenerator /
    Path-with-logProb parity, RecurrentGradientMachine.h:186-309):

      all_data:    [b, N, L] token ids per returned path
      all_lengths: [b, N]    valid lengths (incl. the EOS position)
      scores:      [b, N]    accumulated log-probabilities, best first
    """

    def __init__(self, data, lengths, all_data, all_lengths, scores):
        super().__init__(data, lengths)
        self.all_data = all_data
        self.all_lengths = all_lengths
        self.scores = scores

    def tree_flatten(self):
        return ((self.data, self.lengths, self.all_data, self.all_lengths,
                 self.scores), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def to_list(self):
        """[[(score, [ids...]), ...] per sample] — the SWIG
        SequenceGenerator's generateSequence return shape."""
        import numpy as np
        out = []
        ad = np.asarray(self.all_data)
        al = np.asarray(self.all_lengths)
        sc = np.asarray(self.scores)
        for b in range(ad.shape[0]):
            out.append([(float(sc[b, n]),
                         [int(v) for v in ad[b, n, : al[b, n]]])
                        for n in range(ad.shape[1])])
        return out


def build_beam_search(step, input, *, bos_id: int, eos_id: int,
                      beam_size: int, max_length: int,
                      num_results_per_sample: int = 1,
                      name: Optional[str] = None) -> LayerOutput:
    from paddle_tpu.core.registry import _auto_name
    from paddle_tpu.core.topology import Topology

    gname = name or _auto_name("beam_search")
    inputs = input if isinstance(input, (list, tuple)) else [input]
    gen_inputs = [i for i in inputs if isinstance(i, group_mod.GeneratedInput)]
    static_inputs = [i for i in inputs
                     if isinstance(i, group_mod.StaticInput)]
    assert len(gen_inputs) == 1, "beam_search needs exactly one GeneratedInput"
    gen = gen_inputs[0]

    group = {"name": gname, "memories": [], "boot_layers": []}
    # placeholder for the previous generated token (integer ids)
    tok_ph = make_layer("data", f"@gen@{gname}", [],
                        input_type=InputType(gen.size, "integer"))
    static_phs = []
    for i, si in enumerate(static_inputs):
        kind = "integer" if si.input.meta.is_integer else "dense"
        seq_t = SeqType(si.input.meta.seq_level if si.is_seq else 0)
        ph = make_layer("data", f"@static@{gname}@{i}", [],
                        input_type=InputType(si.input.meta.size, kind, seq_t))
        static_phs.append(ph)

    group_mod._build_ctx.stack.append(group)
    try:
        out = step(tok_ph, *static_phs)
    finally:
        group_mod._build_ctx.stack.pop()
    assert isinstance(out, LayerOutput), "beam_search step must return probs"

    probe = Topology([out])
    extra = []
    for mem in group["memories"]:
        extra.append(probe.by_name[mem["link_name"]])
    sub_topo = Topology([out], extra_outputs=extra)

    outer_inputs = [s.input for s in static_inputs] + group["boot_layers"]
    node = make_layer(
        "beam_search", gname, outer_inputs,
        n_static=len(static_inputs),
        memories=group["memories"],
        tok_name=tok_ph.name,
        static_names=[p.name for p in static_phs],
        static_is_seq=[s.is_seq for s in static_inputs],
        out_name=out.name,
        vocab=out.meta.size,
        bos_id=bos_id, eos_id=eos_id, beam_size=beam_size,
        max_length=max_length,
        num_results_per_sample=min(num_results_per_sample, beam_size),
        sub_topology=sub_topo.serialize(),
    )
    node.params = list(sub_topo.param_specs.values())
    node.meta = LayerMeta(size=1, seq_level=1, is_integer=True)
    node.config["_obj_sub_topo"] = sub_topo
    return node


@register_layer("beam_search")
class BeamSearchLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        from paddle_tpu.core.topology import Topology
        sub = cfg.get("_obj_sub_topo")
        if sub is None:
            sub = Topology.deserialize(cfg["sub_topology"])
            cfg["_obj_sub_topo"] = sub
        params = list(sub.param_specs.values())
        return LayerMeta(size=1, seq_level=1, is_integer=True), params, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        sub = cfg["_obj_sub_topo"]
        K = cfg["beam_size"]
        V = cfg["vocab"]
        L = cfg["max_length"]
        eos = cfg["eos_id"]
        n_static = cfg["n_static"]
        statics = list(inputs[:n_static])
        boots = list(inputs[n_static:])

        # batch size from first static/boot input, else 1
        if statics:
            s0 = statics[0]
            b = (s0.batch_size if isinstance(s0, SequenceBatch)
                 else s0.shape[0])
        elif boots:
            b = boots[0].shape[0]
        else:
            b = 1

        def tile_beam(x):
            """[b, ...] -> [b*K, ...]"""
            if isinstance(x, SequenceBatch):
                return SequenceBatch(
                    tile_beam(x.data), tile_beam(x.lengths),
                    None if x.segment_ids is None else tile_beam(x.segment_ids),
                    None if x.num_segments is None else tile_beam(x.num_segments))
            return jnp.repeat(x, K, axis=0)

        static_feed = {sname: tile_beam(sv) for sname, sv in
                       zip(cfg["static_names"], statics)}

        # memory init (tiled over beams)
        mems = []
        boot_i = 0
        for m in cfg["memories"]:
            if m["has_boot_layer"]:
                bv = boots[boot_i]
                boot_i += 1
                mems.append(jnp.repeat(
                    bv.data if isinstance(bv, SequenceBatch) else bv, K,
                    axis=0))
            elif m["boot_const_id"] is not None:
                mems.append(jnp.full((b * K,), m["boot_const_id"], jnp.int32))
            else:
                mems.append(jnp.zeros((b * K, m["size"]), jnp.float32))
        mems = tuple(mems)

        tokens0 = jnp.full((b, K), cfg["bos_id"], jnp.int32)
        # only beam 0 live at t=0 so duplicates don't fill the beam
        scores0 = jnp.where(jnp.arange(K)[None, :] == 0, 0.0, _NEG) * \
            jnp.ones((b, 1))
        finished0 = jnp.zeros((b, K), bool)

        link_names = [m["link_name"] for m in cfg["memories"]]
        out_name = cfg["out_name"]

        def body(carry, _):
            tokens, scores, finished, mem_state, hist = carry
            feed = dict(static_feed)
            feed[cfg["tok_name"]] = tokens.reshape(b * K)
            for fname, mv in zip([m["feed_name"] for m in cfg["memories"]],
                                 mem_state):
                feed[fname] = mv
            outs, _ = sub.forward(params, {}, feed, mode="test",
                                  output_names=[out_name] + link_names)
            probs = outs[out_name]
            probs = probs.data if isinstance(probs, SequenceBatch) else probs
            logp = jnp.log(jnp.maximum(probs, 1e-12)).reshape(b, K, V)
            # finished beams: only EOS allowed, with zero added score
            eos_only = jnp.full((V,), _NEG).at[eos].set(0.0)
            logp = jnp.where(finished[..., None], eos_only[None, None, :],
                             logp)
            total = scores[..., None] + logp                  # [b, K, V]
            flat = total.reshape(b, K * V)
            new_scores, idx = lax.top_k(flat, K)              # [b, K]
            beam_idx = idx // V
            tok_idx = (idx % V).astype(jnp.int32)
            new_finished = jnp.take_along_axis(finished, beam_idx, axis=1) | \
                (tok_idx == eos)

            def reindex(mv):
                mvk = mv.reshape((b, K) + mv.shape[1:])
                bi = beam_idx.reshape((b, K) + (1,) * (mv.ndim - 1))
                out = jnp.take_along_axis(mvk, bi, axis=1)
                return out.reshape((b * K,) + mv.shape[1:])

            new_mems = tuple(
                reindex(outs[ln].data if isinstance(outs[ln], SequenceBatch)
                        else outs[ln]) for ln in link_names)
            # history re-indexing: hist [b, K, L] gathered by beam_idx
            hist = jnp.take_along_axis(
                hist, beam_idx[..., None].astype(jnp.int32), axis=1)
            return ((tok_idx, new_scores, new_finished, new_mems, hist),
                    tok_idx)

        # History is pre-allocated [b, K, L]; each step writes column t and
        # the gather inside `body` keeps it consistent with beam re-indexing.
        hist0 = jnp.zeros((b, K, L), jnp.int32)

        def step_t(carry, t):
            new_carry, tok_idx = body(carry, None)
            tokens_n, scores_n, fin_n, mems_n, hist_n = new_carry
            hist_n = lax.dynamic_update_slice(hist_n, tok_idx[:, :, None],
                                              (0, 0, t))
            return (tokens_n, scores_n, fin_n, mems_n, hist_n), None

        carry0 = (tokens0, scores0, finished0, mems, hist0)
        (tokens, scores, finished, _, hist), _ = lax.scan(
            step_t, carry0, jnp.arange(L))

        # rank beams per sample; keep num_results_per_sample paths with
        # their scores (SequenceGenerator semantics — Path::logProb,
        # RecurrentGradientMachine.h:186)
        N = cfg.get("num_results_per_sample", 1)
        top_scores, order = lax.top_k(scores, N)               # [b, N]
        top_seqs = jnp.take_along_axis(
            hist, order[:, :, None].astype(jnp.int32), axis=1)  # [b, N, L]
        is_eos = top_seqs == eos
        has_eos = jnp.any(is_eos, axis=2)
        first_eos = jnp.argmax(is_eos, axis=2)
        top_lens = jnp.where(has_eos, first_eos + 1, L).astype(jnp.int32)
        return BeamResult(top_seqs[:, 0, :], top_lens[:, 0],
                          top_seqs, top_lens, top_scores)


# ---------------------------------------------------------------------------
# cross_entropy_over_beam — learning-to-search cost
# (CrossEntropyOverBeam.cpp:193, .h BeamExpansion/CostForOneSequence;
#  DSL cross_entropy_over_beam + BeamInput, layers.py:5961-5985)


def _segment_starts(seg_ids: jnp.ndarray, n_rows: int) -> jnp.ndarray:
    """First position of each segment id in a [S] segment-id vector."""
    eq = seg_ids[None, :] == jnp.arange(n_rows, dtype=jnp.int32)[:, None]
    return jnp.argmax(eq, axis=1).astype(jnp.int32)


def _beam_cost_one_sequence(scores, starts, ids, gold):
    """The reference's CostForOneSequence as static-shape JAX.

    scores[e]: [S_e] flat candidate scores of expansion e
    starts[e]: [R_e]  start offset of each beam row inside scores[e]
    ids[e]:    [R_e, K_e] selected candidate ids per row, -1 padded
    gold[e]:   scalar int — gold candidate id within the gold row

    Follows CrossEntropyOverBeam.cpp: track the gold row through the
    expansions (calValidExpandStep), reconstruct every surviving path at
    the last valid expansion and walk parents backward
    (constructTotalExpansion), then softmax over all path scores with the
    gold appended as an extra path when it fell off the beam
    (globallyNormalizedScore).
    """
    E = len(ids)

    # --- calValidExpandStep: gold row/col per expansion -------------------
    gold_rows, gold_cols = [], []
    grow = jnp.int32(0)
    for e in range(E):
        ide = ids[e]
        K = ide.shape[1]
        row_ids = jnp.take(ide, jnp.clip(grow, 0, ide.shape[0] - 1), axis=0)
        hit = row_ids == gold[e]
        col = jnp.where(jnp.any(hit), jnp.argmax(hit), -1).astype(jnp.int32)
        gold_rows.append(grow)
        gold_cols.append(col)
        if e + 1 < E:
            # next expansion's gold row = # of selected (non -1) candidates
            # before the gold's flat slot in this expansion
            off = grow * K + jnp.maximum(col, 0)
            flat = ide.reshape(-1)
            before = jnp.arange(flat.shape[0]) < off
            grow = jnp.sum((flat != -1) & before).astype(jnp.int32)

    found = jnp.stack([c != -1 for c in gold_cols])            # [E]
    fell = jnp.argmax(~found).astype(jnp.int32)                # first miss
    last = jnp.where(jnp.any(~found), fell, E - 1)             # valid-1

    def branch(l):
        """Total-expansion softmax assuming expansion `l` is the last."""
        ide = ids[l]
        R, K = ide.shape
        flat = ide.reshape(-1)                                 # [R*K]
        valid = flat != -1
        cnt = jnp.cumsum(valid) - valid.astype(jnp.int32)      # exclusive
        n_paths = jnp.sum(valid).astype(jnp.int32)
        P = R * K + 1                                          # + gold slot

        # path slot p <- flat candidate position (scatter by compact rank)
        slot_of = jnp.where(valid, cnt, P)                     # drop invalid
        path_flat = jnp.full((P,), 0, jnp.int32).at[slot_of].set(
            jnp.arange(R * K, dtype=jnp.int32), mode="drop")
        parent = path_flat // K                                # row in exp l
        row_id = jnp.take(flat, path_flat) + jnp.take(starts[l], parent)

        extra = gold_cols[l] == -1
        gold_slot = jnp.where(extra, n_paths,
                              jnp.take(cnt, gold_rows[l] * K +
                                       jnp.maximum(gold_cols[l], 0)))
        slots = jnp.arange(P, dtype=jnp.int32)
        is_gold_extra = extra & (slots == gold_slot)
        row_id = jnp.where(
            is_gold_extra,
            gold[l] + jnp.take(starts[l], gold_rows[l]), row_id)
        parent = jnp.where(is_gold_extra, gold_rows[l], parent)

        Sl = scores[l].shape[0]
        total = jnp.take(scores[l], jnp.clip(row_id, 0, Sl - 1))

        # walk parents back through earlier expansions
        for b in range(l - 1, -1, -1):
            idb = ids[b].reshape(-1)
            Kb = ids[b].shape[1]
            # row r of expansion b+1 <-> flat candidate slot r of
            # expansion b (the reference's parentIdsInBeam_ indexing)
            pidx = jnp.clip(parent, 0, idb.shape[0] - 1)
            cand = jnp.take(idb, pidx)
            prow = pidx // Kb
            rid = cand + jnp.take(starts[b], prow)
            rid = jnp.where(is_gold_extra,
                            gold[b] + jnp.take(starts[b], gold_rows[b]), rid)
            parent = jnp.where(is_gold_extra, gold_rows[b], prow)
            Sb = scores[b].shape[0]
            total = total + jnp.take(scores[b], jnp.clip(rid, 0, Sb - 1))

        live = slots < (n_paths + extra.astype(jnp.int32))
        logits = jnp.where(live, total, _NEG)
        return jax.nn.logsumexp(logits) - jnp.take(logits, gold_slot)

    return lax.switch(last, [lambda l=l: branch(l) for l in range(E)])


@register_layer("cross_entropy_over_beam")
class CrossEntropyOverBeamLayer:
    """Cross entropy over all candidate paths of a multi-step beam search
    (CrossEntropyOverBeam.cpp:193). Inputs come in triples per expansion:
    candidate scores (sequence or nested sequence of scalars), selected
    candidate ids (kmax_seq_score output), and the gold id."""

    @staticmethod
    def build(name, cfg, input_metas):
        assert len(input_metas) % 3 == 0, \
            "cross_entropy_over_beam takes triples of inputs"
        cfg["n_beams"] = len(input_metas) // 3
        return LayerMeta(size=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        E = cfg["n_beams"]
        scores, starts, ids, gold = [], [], [], []
        b = None
        for e in range(E):
            sc, sel, gd = inputs[3 * e], inputs[3 * e + 1], inputs[3 * e + 2]
            assert isinstance(sc, SequenceBatch), \
                "candidate_scores must be a sequence"
            b = sc.batch_size
            s = sc.data.reshape(b, sc.max_len)
            sel_d = sel.data if isinstance(sel, SequenceBatch) else sel
            if sel_d.ndim == 2:
                sel_d = sel_d[:, None, :]                       # [b, 1, K]
            R = sel_d.shape[1]
            if sc.is_nested:
                st = jax.vmap(lambda g: _segment_starts(g, R))(sc.segment_ids)
            else:
                st = jnp.zeros((b, R), jnp.int32)
            gd_d = gd.data if isinstance(gd, SequenceBatch) else gd
            scores.append(s)
            starts.append(st)
            ids.append(sel_d.astype(jnp.int32))
            gold.append(gd_d.reshape(b).astype(jnp.int32))

        def one(args):
            sc_r, st_r, id_r, gd_r = args
            return _beam_cost_one_sequence(sc_r, st_r, id_r, gd_r)

        return jax.vmap(one)((scores, starts, ids, gold))


class BeamInput:
    """One beam expansion triple for cross_entropy_over_beam
    (layers.py:5961)."""

    def __init__(self, candidate_scores, selected_candidates, gold):
        self.candidate_scores = candidate_scores
        self.selected_candidates = selected_candidates
        self.gold = gold


def cross_entropy_over_beam(input, name=None, **kw) -> LayerOutput:
    beams = input if isinstance(input, (list, tuple)) else [input]
    nodes = []
    for bi in beams:
        nodes += [bi.candidate_scores, bi.selected_candidates, bi.gold]
    return make_layer("cross_entropy_over_beam", name, nodes)
