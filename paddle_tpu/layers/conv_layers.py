"""Image layers: conv, pool, norm, pad/crop, maxout, spp, bilinear.

Reference: gserver/layers/{ExpandConvLayer,CudnnConvLayer,ConvBaseLayer,
PoolLayer,CudnnPoolLayer,NormLayer(CMRProjectionNorm),SpatialPyramidPoolLayer,
MaxOutLayer,PadLayer,CropLayer,BilinearInterpLayer,BlockExpandLayer,
Conv3DLayer,DeConv3DLayer}; shape arithmetic from config_parser.py
(cnn_output_size). Internal image tensors are NHWC [b,h,w,c] (TPU layout);
flat channel-major feeds (paddle convention [b, c*h*w]) are reshaped on
entry.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from paddle_tpu.core import initializers
from paddle_tpu.core.registry import (LayerMeta, ParamAttr, ParamSpec,
                                      StateSpec, default_weight_init,
                                      register_layer)
from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.ops import pool as pool_ops
from paddle_tpu.ops import norm as norm_ops
from paddle_tpu.ops import activations as act_ops


def ensure_nhwc(x: jnp.ndarray, meta_c: int, meta_h: int, meta_w: int) -> jnp.ndarray:
    """Accept [b, c*h*w] flat channel-major or already-NHWC [b,h,w,c]."""
    if x.ndim == 4:
        return x
    b = x.shape[0]
    return x.reshape(b, meta_c, meta_h, meta_w).transpose(0, 2, 3, 1)


@register_layer("conv")
class ConvLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        ic = cfg.get("channels") or m.channels
        assert ic, f"conv layer {name}: input channel count unknown"
        ih = m.height or cfg.get("input_height", 0)
        iw = m.width or cfg.get("input_width", 0)
        oc = cfg["num_filters"]
        k = cfg["filter_size"]
        s = cfg.get("stride", 1)
        p = cfg.get("padding", 0)
        d = cfg.get("dilation", 1)
        g = cfg.get("groups", 1)
        oh = conv_ops.conv_out_size(ih, k, s, p, d, cfg.get("caffe_mode", True))
        ow = conv_ops.conv_out_size(iw, k, s, p, d, cfg.get("caffe_mode", True))
        a = ParamAttr.of(cfg.get("param_attr"))
        wname = a.name or f"_{name}.w0"
        init = a.initializer or initializers.msra((0, 1, 2))
        specs = [ParamSpec(wname, (k, k, ic // g, oc), init, a)]
        cfg["_w_name"] = wname
        if cfg.get("bias_attr") is not False:
            battr = ParamAttr.of(None if cfg.get("bias_attr") in (True, None)
                                 else cfg.get("bias_attr"))
            bname = battr.name or f"_{name}.wbias"
            specs.append(ParamSpec(bname, (oc,), initializers.zeros, battr))
            cfg["_bias_name"] = bname
        cfg["_ic"], cfg["_ih"], cfg["_iw"] = ic, ih, iw
        return (LayerMeta(size=oc * oh * ow, height=oh, width=ow, channels=oc),
                specs, [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x = ensure_nhwc(inputs[0], cfg["_ic"], cfg["_ih"], cfg["_iw"])
        w = params[cfg["_w_name"]]
        if cfg.get("trans"):
            y = conv_ops.conv2d_transpose(x, w, stride=cfg.get("stride", 1),
                                          padding=cfg.get("padding", 0))
        else:
            y = conv_ops.conv2d(x, w, stride=cfg.get("stride", 1),
                                padding=cfg.get("padding", 0),
                                dilation=cfg.get("dilation", 1),
                                groups=cfg.get("groups", 1))
        if cfg.get("_bias_name"):
            # f32 master bias must not promote the bf16 activation map
            y = y + params[cfg["_bias_name"]].astype(y.dtype)
        return act_ops.get(cfg.get("act", "linear"))(y)


@register_layer("conv_bn")
class ConvBNLayer:
    """Fused conv + batch-norm (beyond-parity, TPU-first): one layer so
    the op boundary never forces the conv output to materialize between
    the conv and the normalize. With fuse_stats=True, 1x1/s1/p0 convs
    train through ops/fused.conv_bn_train (recompute-fused stats
    epilogue — see that module's docstring for the measured verdict);
    every other shape runs conv2d + batch_norm_train inside the layer.
    Reference analogue: CudnnBatchNormLayer riding
    cudnnBatchNormalizationForwardTraining's fused reductions."""

    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        ic = cfg.get("channels") or m.channels
        assert ic, f"conv_bn layer {name}: input channel count unknown"
        ih = m.height or cfg.get("input_height", 0)
        iw = m.width or cfg.get("input_width", 0)
        oc = cfg["num_filters"]
        k = cfg["filter_size"]
        s = cfg.get("stride", 1)
        p = cfg.get("padding", 0)
        d = cfg.get("dilation", 1)
        oh = conv_ops.conv_out_size(ih, k, s, p, d,
                                    cfg.get("caffe_mode", True))
        ow = conv_ops.conv_out_size(iw, k, s, p, d,
                                    cfg.get("caffe_mode", True))
        a = ParamAttr.of(cfg.get("param_attr"))
        wname = a.name or f"_{name}.w0"
        init = a.initializer or initializers.msra((0, 1, 2))
        specs = [ParamSpec(wname, (k, k, ic, oc), init, a),
                 ParamSpec(f"_{name}.wgamma", (oc,), initializers.ones,
                           ParamAttr.of(None)),
                 ParamSpec(f"_{name}.wbeta", (oc,), initializers.zeros,
                           ParamAttr.of(None))]
        cfg["_w_name"] = wname
        cfg["_ic"], cfg["_ih"], cfg["_iw"] = ic, ih, iw
        states = [StateSpec(f"_{name}.moving_mean", (oc,), 0.0),
                  StateSpec(f"_{name}.moving_var", (oc,), 1.0)]
        return (LayerMeta(size=oc * oh * ow, height=oh, width=ow,
                          channels=oc), specs, states)

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        from paddle_tpu.ops import fused as fused_ops
        x = ensure_nhwc(inputs[0], cfg["_ic"], cfg["_ih"], cfg["_iw"])
        w = params[cfg["_w_name"]]
        gamma = params[f"_{name}.wgamma"]
        beta = params[f"_{name}.wbeta"]
        mm = ctx.get_state(f"_{name}.moving_mean")
        mv = ctx.get_state(f"_{name}.moving_var")
        k = cfg["filter_size"]
        s = cfg.get("stride", 1)
        p = cfg.get("padding", 0)
        d = cfg.get("dilation", 1)
        oc = cfg["num_filters"]
        eps = cfg.get("epsilon", 1e-5)
        train = ctx.is_train and not cfg.get("use_global_stats")
        mom = cfg.get("moving_average_fraction", 0.9)
        # fuse_stats opts into the recompute-fused stats epilogue
        # (ops/fused.conv_bn_train). Default OFF: it measured ~9% SLOWER
        # end-to-end on ResNet-50 than XLA's own conv+BN fusion (see
        # docs/perf.md); kept behind the flag for future compiler /
        # hardware revisits.
        fusable = (cfg.get("fuse_stats") and k == 1 and s == 1
                   and p == 0 and d == 1)
        if train and fusable:
            y, mean, var = fused_ops.conv_bn_train(x, w, gamma, beta, eps)
            ctx.set_state(f"_{name}.moving_mean",
                          mm * mom + mean * (1.0 - mom))
            ctx.set_state(f"_{name}.moving_var",
                          mv * mom + var * (1.0 - mom))
        else:
            c = conv_ops.conv2d(x, w, stride=s, padding=p, dilation=d)
            if train:
                y, nm, nv = norm_ops.batch_norm_train(
                    c, gamma, beta, mm, mv, momentum=mom, eps=eps)
                ctx.set_state(f"_{name}.moving_mean", nm)
                ctx.set_state(f"_{name}.moving_var", nv)
            else:
                y = norm_ops.batch_norm_infer(c, gamma, beta, mm, mv,
                                              eps=eps)
        return act_ops.get(cfg.get("act", "linear"))(y)


@register_layer("pool")
class PoolLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        c = cfg.get("channels") or m.channels
        ih, iw = m.height, m.width
        ky = cfg["pool_size"]
        kx = cfg.get("pool_size_x") or ky
        s = cfg.get("stride", 1)
        p = cfg.get("padding", 0)
        cm = cfg.get("ceil_mode", True)
        oh = pool_ops.pool_out_size(ih, ky, s, p, cm)
        ow = pool_ops.pool_out_size(iw, kx, s, p, cm)
        cfg["_ic"], cfg["_ih"], cfg["_iw"] = c, ih, iw
        return (LayerMeta(size=c * oh * ow, height=oh, width=ow, channels=c),
                [], [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x = ensure_nhwc(inputs[0], cfg["_ic"], cfg["_ih"], cfg["_iw"])
        ky = cfg["pool_size"]
        kx = cfg.get("pool_size_x") or ky
        s = cfg.get("stride", 1)
        p = cfg.get("padding", 0)
        cm = cfg.get("ceil_mode", True)
        ptype = cfg.get("pool_type", "max")
        if ptype in ("max", "cudnn-max"):
            return pool_ops.max_pool2d(x, (ky, kx), s, p, ceil_mode=cm)
        return pool_ops.avg_pool2d(x, (ky, kx), s, p, ceil_mode=cm)


@register_layer("img_cmrnorm")
class CMRNormLayer:
    """Cross-map response norm (LRN)."""
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        cfg["_ic"], cfg["_ih"], cfg["_iw"] = m.channels, m.height, m.width
        return (LayerMeta(size=m.size, height=m.height, width=m.width,
                          channels=m.channels), [], [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x = ensure_nhwc(inputs[0], cfg["_ic"], cfg["_ih"], cfg["_iw"])
        return norm_ops.lrn_cross_map(x, cfg.get("size", 5),
                                      cfg.get("scale", 0.0128),
                                      cfg.get("power", 0.75))


@register_layer("maxout")
class MaxOutLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        g = cfg["groups"]
        oc = m.channels // g
        cfg["_ic"], cfg["_ih"], cfg["_iw"] = m.channels, m.height, m.width
        return (LayerMeta(size=oc * m.height * m.width, height=m.height,
                          width=m.width, channels=oc), [], [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x = ensure_nhwc(inputs[0], cfg["_ic"], cfg["_ih"], cfg["_iw"])
        return pool_ops.maxout(x, cfg["groups"])


@register_layer("spp")
class SPPLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        h = cfg.get("pyramid_height", 3)
        total_bins = sum(4 ** l for l in range(h))
        cfg["_ic"], cfg["_ih"], cfg["_iw"] = m.channels, m.height, m.width
        return LayerMeta(size=m.channels * total_bins), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x = ensure_nhwc(inputs[0], cfg["_ic"], cfg["_ih"], cfg["_iw"])
        return pool_ops.spatial_pyramid_pool(
            x, cfg.get("pyramid_height", 3), cfg.get("pool_type", "max"))


@register_layer("pad")
class PadLayer:
    """PadLayer: zero-pad channel/height/width dims (paddle/function/PadOp)."""
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        pc = cfg.get("pad_c", [0, 0])
        ph = cfg.get("pad_h", [0, 0])
        pw = cfg.get("pad_w", [0, 0])
        oc = m.channels + sum(pc)
        oh = m.height + sum(ph)
        ow = m.width + sum(pw)
        cfg["_ic"], cfg["_ih"], cfg["_iw"] = m.channels, m.height, m.width
        return (LayerMeta(size=oc * oh * ow, height=oh, width=ow, channels=oc),
                [], [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x = ensure_nhwc(inputs[0], cfg["_ic"], cfg["_ih"], cfg["_iw"])
        pc = cfg.get("pad_c", [0, 0])
        ph = cfg.get("pad_h", [0, 0])
        pw = cfg.get("pad_w", [0, 0])
        return jnp.pad(x, ((0, 0), tuple(ph), tuple(pw), tuple(pc)))


@register_layer("crop")
class CropLayer:
    """CropLayer (paddle/function/CropOp): crop h/w/c with offsets."""
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        shape = cfg["shape"]          # [c, h, w] target
        cfg["_ic"], cfg["_ih"], cfg["_iw"] = m.channels, m.height, m.width
        oc, oh, ow = shape
        return (LayerMeta(size=oc * oh * ow, height=oh, width=ow, channels=oc),
                [], [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x = ensure_nhwc(inputs[0], cfg["_ic"], cfg["_ih"], cfg["_iw"])
        oc, oh, ow = cfg["shape"]
        off = cfg.get("offset", [0, 0, 0])
        return x[:, off[1]:off[1] + oh, off[2]:off[2] + ow,
                 off[0]:off[0] + oc]


@register_layer("bilinear_interp")
class BilinearInterpLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        oh, ow = cfg["out_size_y"], cfg["out_size_x"]
        cfg["_ic"], cfg["_ih"], cfg["_iw"] = m.channels, m.height, m.width
        return (LayerMeta(size=m.channels * oh * ow, height=oh, width=ow,
                          channels=m.channels), [], [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        import jax
        x = ensure_nhwc(inputs[0], cfg["_ic"], cfg["_ih"], cfg["_iw"])
        oh, ow = cfg["out_size_y"], cfg["out_size_x"]
        return jax.image.resize(x, (x.shape[0], oh, ow, x.shape[3]),
                                method="bilinear")


@register_layer("block_expand")
class BlockExpandLayer:
    """BlockExpandLayer: image -> sequence of flattened patches (for OCR
    pipelines feeding RNN/CTC)."""
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        bx, by = cfg["block_x"], cfg["block_y"]
        sx, sy = cfg.get("stride_x", 1), cfg.get("stride_y", 1)
        px, py = cfg.get("padding_x", 0), cfg.get("padding_y", 0)
        c = cfg.get("channels") or m.channels
        oh = conv_ops.conv_out_size(m.height, by, sy, py, caffe_mode=False)
        ow = conv_ops.conv_out_size(m.width, bx, sx, px, caffe_mode=False)
        cfg["_ic"], cfg["_ih"], cfg["_iw"] = c, m.height, m.width
        cfg["_steps"] = oh * ow
        return LayerMeta(size=bx * by * c, seq_level=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        from paddle_tpu.core.sequence import SequenceBatch
        x = ensure_nhwc(inputs[0], cfg["_ic"], cfg["_ih"], cfg["_iw"])
        patches = conv_ops.im2col(
            x, (cfg["block_y"], cfg["block_x"]),
            (cfg.get("stride_y", 1), cfg.get("stride_x", 1)),
            (cfg.get("padding_y", 0), cfg.get("padding_x", 0)))
        b, oh, ow, d = patches.shape
        data = patches.reshape(b, oh * ow, d)
        lengths = jnp.full((b,), oh * ow, jnp.int32)
        return SequenceBatch(data, lengths)


def ensure_ndhwc(x: jnp.ndarray, c: int, d: int, h: int, w: int) -> jnp.ndarray:
    """Accept [b, c*d*h*w] flat channel-major or already-NDHWC."""
    if x.ndim == 5:
        return x
    b = x.shape[0]
    return x.reshape(b, c, d, h, w).transpose(0, 2, 3, 4, 1)


from paddle_tpu.ops.pool import _triple  # noqa: E402 — shared int->3-tuple


@register_layer("conv3d")
class Conv3DLayer:
    """Volumetric convolution (gserver/layers/Conv3DLayer.cpp); shape math
    from config_parser.py's depth-extended cnn_output_size. Input is
    [b, c*d*h*w] flat channel-major (paddle layout) or NDHWC."""
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        ic = cfg.get("channels") or m.channels
        idp = cfg["input_depth"]
        ih = cfg.get("input_height") or m.height or \
            int(round((m.size // (ic * idp)) ** 0.5))
        iw = cfg.get("input_width") or m.width or (m.size // (ic * idp * ih))
        oc = cfg["num_filters"]
        kd, kh, kw = _triple(cfg["filter_size"])
        sd, sh, sw = _triple(cfg.get("stride", 1))
        pd, ph, pw = _triple(cfg.get("padding", 0))
        od = conv_ops.conv_out_size(idp, kd, sd, pd)
        oh = conv_ops.conv_out_size(ih, kh, sh, ph)
        ow = conv_ops.conv_out_size(iw, kw, sw, pw)
        a = ParamAttr.of(cfg.get("param_attr"))
        wname = a.name or f"_{name}.w0"
        specs = [ParamSpec(wname, (kd, kh, kw, ic, oc),
                           a.initializer or initializers.msra((0, 1, 2, 3)), a)]
        cfg["_w_name"] = wname
        if cfg.get("bias_attr") is not False:
            battr = ParamAttr.of(None if cfg.get("bias_attr") in (True, None)
                                 else cfg.get("bias_attr"))
            bname = battr.name or f"_{name}.wbias"
            specs.append(ParamSpec(bname, (oc,), initializers.zeros, battr))
            cfg["_bias_name"] = bname
        cfg["_in"] = (ic, idp, ih, iw)
        cfg["_out"] = (oc, od, oh, ow)
        return (LayerMeta(size=oc * od * oh * ow, height=oh, width=ow,
                          channels=oc), specs, [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x = ensure_ndhwc(inputs[0], *cfg["_in"])
        y = conv_ops.conv3d(x, params[cfg["_w_name"]],
                            stride=cfg.get("stride", 1),
                            padding=cfg.get("padding", 0))
        if cfg.get("_bias_name"):
            y = y + params[cfg["_bias_name"]].astype(y.dtype)
        return act_ops.get(cfg.get("act", "linear"))(y)


@register_layer("deconv3d")
class DeConv3DLayer:
    """Volumetric transposed convolution (DeConv3DLayer.cpp)."""
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        ic = cfg.get("channels") or m.channels
        idp = cfg["input_depth"]
        ih = cfg.get("input_height") or m.height
        iw = cfg.get("input_width") or m.width
        oc = cfg["num_filters"]
        kd, kh, kw = _triple(cfg["filter_size"])
        sd, sh, sw = _triple(cfg.get("stride", 1))
        pd, ph, pw = _triple(cfg.get("padding", 0))
        od = (idp - 1) * sd - 2 * pd + kd
        oh = (ih - 1) * sh - 2 * ph + kh
        ow = (iw - 1) * sw - 2 * pw + kw
        a = ParamAttr.of(cfg.get("param_attr"))
        wname = a.name or f"_{name}.w0"
        specs = [ParamSpec(wname, (kd, kh, kw, ic, oc),
                           a.initializer or initializers.msra((0, 1, 2, 3)), a)]
        cfg["_w_name"] = wname
        if cfg.get("bias_attr") is not False:
            battr = ParamAttr.of(None if cfg.get("bias_attr") in (True, None)
                                 else cfg.get("bias_attr"))
            bname = battr.name or f"_{name}.wbias"
            specs.append(ParamSpec(bname, (oc,), initializers.zeros, battr))
            cfg["_bias_name"] = bname
        cfg["_in"] = (ic, idp, ih, iw)
        return (LayerMeta(size=oc * od * oh * ow, height=oh, width=ow,
                          channels=oc), specs, [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x = ensure_ndhwc(inputs[0], *cfg["_in"])
        y = conv_ops.conv3d_transpose(x, params[cfg["_w_name"]],
                                      stride=cfg.get("stride", 1),
                                      padding=cfg.get("padding", 0))
        if cfg.get("_bias_name"):
            y = y + params[cfg["_bias_name"]].astype(y.dtype)
        return act_ops.get(cfg.get("act", "linear"))(y)


@register_layer("pool3d")
class Pool3DLayer:
    """Volumetric pooling (Pool3DLayer.cpp)."""
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        c = cfg.get("channels") or m.channels
        idp = cfg["input_depth"]
        ih = cfg.get("input_height") or m.height
        iw = cfg.get("input_width") or m.width
        kd, kh, kw = _triple(cfg["pool_size"])
        sd, sh, sw = _triple(cfg.get("stride", 1))
        pd, ph, pw = _triple(cfg.get("padding", 0))
        od = pool_ops.pool_out_size(idp, kd, sd, pd)
        oh = pool_ops.pool_out_size(ih, kh, sh, ph)
        ow = pool_ops.pool_out_size(iw, kw, sw, pw)
        cfg["_in"] = (c, idp, ih, iw)
        return (LayerMeta(size=c * od * oh * ow, height=oh, width=ow,
                          channels=c), [], [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x = ensure_ndhwc(inputs[0], *cfg["_in"])
        k = _triple(cfg["pool_size"])
        s = _triple(cfg.get("stride", 1))
        p = _triple(cfg.get("padding", 0))
        if cfg.get("pool_type", "max") in ("max", "cudnn-max"):
            return pool_ops.max_pool3d(x, k, s, p)
        return pool_ops.avg_pool3d(x, k, s, p)
