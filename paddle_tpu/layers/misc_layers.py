"""Assorted reference-parity layers: id/sampling helpers, selective FC,
row convolution, data normalization, multiplex, elementwise utilities.

Reference: paddle/gserver/layers/{MaxIdLayer.cpp, SamplingIdLayer.cpp,
EosIdCheckLayer.cpp, MultiplexLayer.cpp, SelectiveFullyConnectedLayer.cpp,
RowConvLayer.cpp, DataNormLayer.cpp (.h:41 NormalizationStrategy),
ClipLayer.cpp, ScaleShiftLayer.cpp, PowerLayer.cpp,
FeatureMapExpandLayer.cpp, RotateLayer.cpp, PrintLayer.cpp}; DSL wrappers
trainer_config_helpers/layers.py (maxid_layer:3989, sampling_id_layer:4859,
eos_layer:4062, selective_fc_layer:4776, row_conv_layer:6197,
multiplex_layer:6123, clip_layer:6566, scale_shift_layer:6849,
power_layer:2046, rotate_layer:2167).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers
from paddle_tpu.core.registry import (LayerMeta, ParamAttr, ParamSpec,
                                      default_weight_init, register_layer)
from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.layers.base import _map_seq, _payload
from paddle_tpu.layers.conv_layers import ensure_nhwc
from paddle_tpu.ops import activations as act_ops
from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.ops import linear as linear_ops


@register_layer("maxid")
class MaxIdLayer:
    """Argmax id per row (MaxIdLayer.cpp; beam_size top ids when asked)."""

    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=cfg.get("beam_size", 1), seq_level=m.seq_level,
                         is_integer=True), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        k = cfg.get("beam_size", 1)

        def top(x):
            if k == 1:
                return jnp.argmax(x, axis=-1).astype(jnp.int32)[..., None]
            _, idx = jax.lax.top_k(x, k)
            return idx.astype(jnp.int32)

        return _map_seq(top, inputs[0])


@register_layer("sampling_id")
class SamplingIdLayer:
    """Sample one id from each row's distribution (SamplingIdLayer.cpp,
    MultinomialSampler.cpp). In eval mode falls back to argmax so test
    passes stay deterministic."""

    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=1, seq_level=m.seq_level, is_integer=True), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        def sample(x):
            logits = jnp.log(jnp.clip(x, 1e-20))
            if not ctx.is_train:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)[..., None]
            flat = logits.reshape(-1, logits.shape[-1])
            ids = jax.random.categorical(ctx.rng_for(name), flat)
            return ids.reshape(logits.shape[:-1] + (1,)).astype(jnp.int32)

        return _map_seq(sample, inputs[0])


@register_layer("eos_id")
class EosIdCheckLayer:
    """1.0 where the input id equals eos_id (EosIdCheckLayer.cpp)."""

    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=1, seq_level=m.seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        eos = cfg["eos_id"]
        val = inputs[0]
        ids = _payload(val)
        # id payloads are [b] / [b, T] (or already [.., 1] from maxid) —
        # always emit a trailing size-1 feature axis
        base_rank = 2 if isinstance(val, SequenceBatch) else 1
        if ids.ndim == base_rank:
            ids = ids[..., None]
        out = (ids == eos).astype(jnp.float32)
        return val.with_data(out) if isinstance(val, SequenceBatch) else out


@register_layer("multiplex")
class MultiplexLayer:
    """Row-wise select among k value inputs by an id input
    (MultiplexLayer.cpp: input 0 is ids, inputs 1..k are candidates)."""

    @staticmethod
    def build(name, cfg, input_metas):
        size = input_metas[1].size
        for m in input_metas[2:]:
            assert m.size == size, "multiplex candidates must agree in size"
        return LayerMeta(size=size), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        ids = _payload(inputs[0]).reshape(-1).astype(jnp.int32)
        stacked = jnp.stack([_payload(v) for v in inputs[1:]], axis=0)
        return stacked[ids, jnp.arange(stacked.shape[1])]


@register_layer("clip")
class ClipLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=m.seq_level, height=m.height,
                         width=m.width, channels=m.channels), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        lo, hi = cfg["min"], cfg["max"]
        return _map_seq(lambda x: jnp.clip(x, lo, hi), inputs[0])


@register_layer("scale_shift")
class ScaleShiftLayer:
    """y = w * x + b with scalar learned w (and optional scalar b)
    (ScaleShiftLayer.cpp)."""

    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        a = ParamAttr.of(cfg.get("param_attr"))
        wname = a.name or f"_{name}.w0"
        specs = [ParamSpec(wname, (1,), a.initializer or initializers.ones, a)]
        cfg["_w_name"] = wname
        if cfg.get("bias_attr") is not False:
            battr = ParamAttr.of(None if cfg.get("bias_attr") in (True, None)
                                 else cfg.get("bias_attr"))
            bname = battr.name or f"_{name}.wbias"
            specs.append(ParamSpec(bname, (1,), initializers.zeros, battr))
            cfg["_bias_name"] = bname
        return LayerMeta(size=m.size, seq_level=m.seq_level), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        w = params[cfg["_w_name"]]
        b = params.get(cfg.get("_bias_name"), jnp.zeros((1,))) \
            if cfg.get("_bias_name") else 0.0
        return _map_seq(lambda x: w * x + b, inputs[0])


@register_layer("power")
class PowerLayer:
    """y = v ** w with per-row scalar exponent input 0 (PowerLayer.cpp)."""

    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=input_metas[1].size,
                         seq_level=input_metas[1].seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        w = _payload(inputs[0])
        v = inputs[1]
        ref = v if isinstance(v, SequenceBatch) else None
        out = jnp.power(_payload(v), w)   # direct pow, as the reference
        return ref.with_data(out) if ref is not None else out


@register_layer("featmap_expand")
class FeatureMapExpandLayer:
    """Tile a [b, d] input across num_filters channels -> [b, num_filters*d]
    (FeatureMapExpandLayer.cpp; as_row_vector matches the reference flag)."""

    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        nf = cfg["num_filters"]
        return LayerMeta(size=m.size * nf, seq_level=m.seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        nf = cfg["num_filters"]
        as_row = cfg.get("as_row_vector", True)

        def expand(x):
            if as_row:
                return jnp.tile(x, (1,) * (x.ndim - 1) + (nf,))
            return jnp.repeat(x, nf, axis=-1)

        return _map_seq(expand, inputs[0])


@register_layer("rotate")
class RotateLayer:
    """Rotate a CHW feature map 90 degrees counter-clockwise
    (RotateLayer.cpp; used by trans_layer's spatial sibling)."""

    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        h = cfg.get("height") or m.height
        w = cfg.get("width") or m.width
        c = m.channels or (m.size // max(h * w, 1))
        cfg["_ic"], cfg["_ih"], cfg["_iw"] = c, h, w
        return LayerMeta(size=m.size, height=w, width=h, channels=c), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x = ensure_nhwc(inputs[0], cfg["_ic"], cfg["_ih"], cfg["_iw"])
        return jnp.rot90(x, k=1, axes=(1, 2))


@register_layer("data_norm")
class DataNormLayer:
    """Feature normalization from precomputed stats (DataNormLayer.h:41
    strategies: z-score, min-max, decimal-scaling). The stats live in one
    non-trainable [5, size] parameter with rows (min, max, mean, std,
    decimal_scale), loaded rather than learned — matching the reference's
    externally-computed stats parameter."""

    STRATS = {"z-score": 0, "min-max": 1, "decimal-scaling": 2}

    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        a = ParamAttr.of(cfg.get("param_attr"))
        a.is_static = True
        pname = a.name or f"_{name}.w0"
        cfg["_w_name"] = pname

        def stats_init(key, shape, dtype=jnp.float32):
            base = jnp.zeros(shape, dtype)
            return base.at[1].set(1.0).at[3].set(1.0).at[4].set(1.0)

        specs = [ParamSpec(pname, (5, m.size), stats_init, a)]
        return LayerMeta(size=m.size, seq_level=m.seq_level), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        stats = params[cfg["_w_name"]]
        mn, mx, mean, std, dscale = (stats[i] for i in range(5))
        strat = cfg.get("data_norm_strategy", "z-score")

        def norm(x):
            if strat == "min-max":
                return (x - mn) / jnp.maximum(mx - mn, 1e-8)
            if strat == "decimal-scaling":
                return x / jnp.maximum(dscale, 1e-8)
            return (x - mean) / jnp.maximum(std, 1e-8)

        return _map_seq(norm, inputs[0])


@register_layer("selective_fc")
class SelectiveFCLayer:
    """FC computed only on selected output columns
    (SelectiveFullyConnectedLayer.cpp). The selection arrives as a dense
    0/1 mask [b, size] (the reference's sparse selection matrix densified —
    on the MXU a masked full matmul beats a gather for the typical
    size/selection ratios). With no selection input it degrades to plain fc,
    matching the reference's full-output mode."""

    @staticmethod
    def build(name, cfg, input_metas):
        size = cfg["size"]
        m = input_metas[0]
        a = ParamAttr.of(cfg.get("param_attr"))
        wname = a.name or f"_{name}.w0"
        # weight is stored transposed [size, in] as the reference does
        # (selective rows = output columns)
        specs = [ParamSpec(wname, (size, m.size),
                           default_weight_init(a, (1,)), a)]
        cfg["_w_name"] = wname
        if cfg.get("bias_attr") is not False:
            battr = ParamAttr.of(None if cfg.get("bias_attr") in (True, None)
                                 else cfg.get("bias_attr"))
            bname = battr.name or f"_{name}.wbias"
            specs.append(ParamSpec(bname, (size,), initializers.zeros, battr))
            cfg["_bias_name"] = bname
        cfg["_has_select"] = len(input_metas) > 1
        return LayerMeta(size=size, seq_level=m.seq_level), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        w = params[cfg["_w_name"]]
        b = params.get(cfg.get("_bias_name")) if cfg.get("_bias_name") else None
        x = inputs[0]
        sel = _payload(inputs[1]) if cfg.get("_has_select") else None

        def run(v):
            y = linear_ops.matmul(v, w.T)
            if b is not None:
                y = y + b
            y = act_ops.get(cfg.get("act", "linear"))(y)
            if sel is not None:
                y = y * sel.astype(y.dtype)
            return y

        return _map_seq(run, x)


@register_layer("row_conv")
class RowConvLayer:
    """Lookahead row convolution over a sequence (RowConvLayer.cpp:27-91,
    DeepSpeech2): out[t] = sum_{i<ctx} in[t+i] * w[i], per-channel weights
    [context, d]. Future context = context - 1 steps (RowConvLayer.h:40)."""

    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        ctxlen = cfg["context_len"]
        a = ParamAttr.of(cfg.get("param_attr"))
        pname = a.name or f"_{name}.w0"
        cfg["_w_name"] = pname
        specs = [ParamSpec(pname, (ctxlen, m.size),
                           default_weight_init(a, (0,)), a)]
        return LayerMeta(size=m.size, seq_level=1), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq: SequenceBatch = inputs[0]
        w = params[cfg["_w_name"]]
        out = conv_ops.row_conv(seq.masked_data(), w)
        act = cfg.get("act", "linear")
        return seq.with_data(act_ops.get(act)(out))


@register_layer("print")
class PrintLayer:
    """Identity layer that prints its input during execution
    (PrintLayer.cpp / ValuePrinter) via jax.debug.print — works under jit."""

    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=m.seq_level, height=m.height,
                         width=m.width, channels=m.channels,
                         is_integer=m.is_integer), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        val = inputs[0]
        fmt = cfg.get("format", name + ": {x}")
        jax.debug.print(fmt, x=_payload(val))
        return val
