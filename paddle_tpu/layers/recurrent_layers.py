"""Recurrent layers: simple RNN, LSTM, GRU memories (full-sequence scans).

Reference: gserver/layers/{RecurrentLayer, LstmLayer, GatedRecurrentLayer}
with their fused CUDA kernels (hl_cuda_lstm.cu, hl_gpu_gru.cuh). Paddle's
API convention: the input to lstmemory/grumemory is ALREADY projected by a
preceding fc/mixed layer to 4*size (LSTM) or 3*size (GRU)
(trainer_config_helpers/layers.py lstmemory:1414 docstring); the layer owns
only the recurrent weight and bias. The step-level counterparts for
recurrent_group live in group_layers.py.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core import initializers
from paddle_tpu.core.registry import (LayerMeta, ParamAttr, ParamSpec,
                                      register_layer)
from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import recurrent as rnn_ops


@register_layer("lstmemory")
class LstmemoryLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        assert m.size % 4 == 0, "lstmemory input must be projected to 4*size"
        h = m.size // 4
        a = ParamAttr.of(cfg.get("param_attr"))
        wname = a.name or f"_{name}.w0"
        specs = [ParamSpec(wname, (h, 4 * h),
                           a.initializer or initializers.smart_normal(0), a)]
        cfg["_w_name"] = wname
        if cfg.get("bias_attr") is not False:
            battr = ParamAttr.of(None if cfg.get("bias_attr") in (True, None)
                                 else cfg.get("bias_attr"))
            bname = battr.name or f"_{name}.wbias"
            # 7h bias = 4h gate bias + 3h peephole (reference LstmLayer bias
            # layout with check_input/forget/output weights)
            specs.append(ParamSpec(bname, (7 * h,), initializers.zeros, battr))
            cfg["_b_name"] = bname
        return LayerMeta(size=h, seq_level=1), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq: SequenceBatch = inputs[0]
        h = seq.data.shape[-1] // 4
        w = params[cfg["_w_name"]]
        bias = peep = None
        if cfg.get("_b_name"):
            full = params[cfg["_b_name"]]
            bias, peep = full[:4 * h], full[4 * h:]
        return rnn_ops.lstm_scan(
            seq, w, bias, peep, reverse=cfg.get("reverse", False),
            act=cfg.get("act", "tanh"),
            gate_act=cfg.get("gate_act", "sigmoid"),
            state_act=cfg.get("state_act", "tanh"))


@register_layer("gru")
class GrumemoryLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        assert m.size % 3 == 0, "grumemory input must be projected to 3*size"
        h = m.size // 3
        a = ParamAttr.of(cfg.get("param_attr"))
        wname = a.name or f"_{name}.w0"
        specs = [ParamSpec(wname, (h, 3 * h),
                           a.initializer or initializers.smart_normal(0), a)]
        cfg["_w_name"] = wname
        if cfg.get("bias_attr") is not False:
            battr = ParamAttr.of(None if cfg.get("bias_attr") in (True, None)
                                 else cfg.get("bias_attr"))
            bname = battr.name or f"_{name}.wbias"
            specs.append(ParamSpec(bname, (3 * h,), initializers.zeros, battr))
            cfg["_b_name"] = bname
        return LayerMeta(size=h, seq_level=1), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq: SequenceBatch = inputs[0]
        w = params[cfg["_w_name"]]
        bias = params.get(cfg.get("_b_name")) if cfg.get("_b_name") else None
        return rnn_ops.gru_scan(
            seq, w, bias, reverse=cfg.get("reverse", False),
            act=cfg.get("act", "tanh"), gate_act=cfg.get("gate_act", "sigmoid"))


@register_layer("recurrent")
class SimpleRecurrentLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        h = m.size
        a = ParamAttr.of(cfg.get("param_attr"))
        wname = a.name or f"_{name}.w0"
        specs = [ParamSpec(wname, (h, h),
                           a.initializer or initializers.smart_normal(0), a)]
        cfg["_w_name"] = wname
        if cfg.get("bias_attr") is not False:
            battr = ParamAttr.of(None if cfg.get("bias_attr") in (True, None)
                                 else cfg.get("bias_attr"))
            bname = battr.name or f"_{name}.wbias"
            specs.append(ParamSpec(bname, (h,), initializers.zeros, battr))
            cfg["_b_name"] = bname
        return LayerMeta(size=h, seq_level=1), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq: SequenceBatch = inputs[0]
        w = params[cfg["_w_name"]]
        bias = params.get(cfg.get("_b_name")) if cfg.get("_b_name") else None
        return rnn_ops.rnn_scan(seq, w, bias,
                                reverse=cfg.get("reverse", False),
                                act=cfg.get("act", "tanh"))


@register_layer("gru_step")
class GruStepLayer:
    """Step-level GRU for recurrent_group decoders (gru_step_layer,
    gserver/layers/GruStepLayer.cpp). Inputs: [x3 (3h projection),
    prev_state (h memory)]; owns the recurrent weight + gate bias."""

    @staticmethod
    def build(name, cfg, input_metas):
        h = cfg.get("size") or input_metas[1].size
        assert input_metas[0].size == 3 * h, \
            f"gru_step {name}: input must be 3*size projection"
        a = ParamAttr.of(cfg.get("param_attr"))
        wname = a.name or f"_{name}.w0"
        specs = [ParamSpec(wname, (h, 3 * h),
                           a.initializer or initializers.smart_normal(0), a)]
        cfg["_w_name"] = wname
        if cfg.get("bias_attr") is not False:
            battr = ParamAttr.of(None if cfg.get("bias_attr") in (True, None)
                                 else cfg.get("bias_attr"))
            bname = battr.name or f"_{name}.wbias"
            specs.append(ParamSpec(bname, (3 * h,), initializers.zeros, battr))
            cfg["_b_name"] = bname
        return LayerMeta(size=h), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x3, h = inputs
        w = params[cfg["_w_name"]]
        bias = params.get(cfg.get("_b_name")) if cfg.get("_b_name") else None
        return rnn_ops.gru_cell(x3, h, w, bias,
                                act=cfg.get("act", "tanh"),
                                gate_act=cfg.get("gate_act", "sigmoid"))


@register_layer("lstm_step")
class LstmStepLayer:
    """Step-level LSTM (lstm_step_layer, gserver/layers/LstmStepLayer.cpp).

    Reference semantics: inputs are [gate_input (4h), prev_cell (h)]; the
    previous HIDDEN state is projected into gate_input by the caller (a
    mixed/fc layer over the output memory), so this layer owns only the 3h
    peephole "check" weights (LstmStepLayer.cpp:84-92 maps the bias
    parameter onto checkIg/checkFg/checkOg). Output is h'; with
    cfg["expose_state"] the output packs [h' | c'] so a cell memory can
    link to it (get_output 'state' parity)."""

    @staticmethod
    def build(name, cfg, input_metas):
        # h always follows from the 4h gate projection; the state input may
        # be h (cell only) or 2h (packed [h|c] from expose_state).
        h = cfg.get("size") or input_metas[0].size // 4
        assert input_metas[0].size == 4 * h, \
            f"lstm_step {name}: input must be 4*size projection"
        assert input_metas[1].size in (h, 2 * h), \
            f"lstm_step {name}: state must be size h or 2h (packed [h|c])"
        specs = []
        if cfg.get("bias_attr") is not False:
            battr = ParamAttr.of(None if cfg.get("bias_attr") in (True, None)
                                 else cfg.get("bias_attr"))
            bname = battr.name or f"_{name}.wbias"
            specs.append(ParamSpec(bname, (3 * h,), initializers.zeros, battr))
            cfg["_b_name"] = bname
        cfg["_h"] = h
        size = 2 * h if cfg.get("expose_state") else h
        return LayerMeta(size=size), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x4, c_prev = inputs
        hdim = cfg["_h"]
        if c_prev.shape[-1] == 2 * hdim:
            c_prev = c_prev[..., hdim:]
        peep = params.get(cfg.get("_b_name")) if cfg.get("_b_name") else None
        zero_w = jnp.zeros((hdim, 4 * hdim), x4.dtype)
        h_new, c_new = rnn_ops.lstm_cell(
            x4, jnp.zeros((x4.shape[0], hdim), x4.dtype), c_prev,
            zero_w, None, peep,
            act=cfg.get("act", "tanh"),
            gate_act=cfg.get("gate_act", "sigmoid"),
            state_act=cfg.get("state_act", "tanh"))
        if cfg.get("expose_state"):
            return jnp.concatenate([h_new, c_new], axis=-1)
        return h_new


@register_layer("mdlstm")
class MDLstmLayer:
    """2-D multi-directional LSTM over an image (MDLstmLayer.cpp).

    Input: an image whose channel count is 5*size (the pre-projected gate
    input, as lstmemory expects 4*size — reference layout
    numBlocks*(3+numDims), numDims=2). Owns the shared recurrent weight
    [size, 5*size] and the (5+2*2)*size bias (gates + peepholes).
    directions: [bool, bool] — False reverses the walk along (height,
    width), matching config.directions."""

    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        assert m.channels and m.channels % 5 == 0, \
            f"mdlstm {name}: input channels must be 5*size"
        h = m.channels // 5
        a = ParamAttr.of(cfg.get("param_attr"))
        wname = a.name or f"_{name}.w0"
        specs = [ParamSpec(wname, (h, 5 * h),
                           a.initializer or initializers.smart_normal(0), a)]
        cfg["_w_name"] = wname
        if cfg.get("bias_attr") is not False:
            battr = ParamAttr.of(None if cfg.get("bias_attr") in (True, None)
                                 else cfg.get("bias_attr"))
            bname = battr.name or f"_{name}.wbias"
            specs.append(ParamSpec(bname, (9 * h,), initializers.zeros, battr))
            cfg["_b_name"] = bname
        cfg["_in"] = (m.channels, m.height, m.width)
        return (LayerMeta(size=h * m.height * m.width, height=m.height,
                          width=m.width, channels=h), specs, [])

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        from paddle_tpu.layers.conv_layers import ensure_nhwc
        x = ensure_nhwc(inputs[0], *cfg["_in"])
        w = params[cfg["_w_name"]]
        bias = params.get(cfg.get("_b_name")) if cfg.get("_b_name") else None
        dirs = cfg.get("directions", [True, True])
        return rnn_ops.mdlstm_2d(
            x, w, bias, act=cfg.get("act", "tanh"),
            gate_act=cfg.get("gate_act", "sigmoid"),
            reverse_h=not dirs[0], reverse_w=not dirs[1])
