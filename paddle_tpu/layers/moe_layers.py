"""Mixture-of-experts layers — the `ep` (expert-parallel) leg of the mesh.

No 2017 reference counterpart (like dot_product_attention and layer_norm,
a TPU-era extra beyond parity): a capacity-routed top-k MoE FFN
(ops/moe.py) as a graph layer, plus a companion cost layer exposing the
router's load-balance auxiliary loss through the normal multi-cost
trainer path (SGD accepts a list of cost nodes).

The two layers share the gate parameter by name, so `moe_aux_cost`
re-derives the routing statistics from the same router the forward pass
used — one extra [n,d]x[d,E] matmul, which keeps the aux loss an
ordinary cost node instead of a side channel through the forward ctx.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers
from paddle_tpu.core.registry import (LayerMeta, ParamAttr, ParamSpec,
                                      default_weight_init, make_layer,
                                      register_layer)
from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import moe as moe_ops


def _gate_name(name, cfg):
    a = ParamAttr.of(cfg.get("param_attr"))
    return a.name or f"_{name}.gate", a


def _flatten(v, ctx=None):
    """-> (x2d [n,d], valid [n] or None, restore(y2d) -> like v).

    Routing is row-COUPLED (padded rows eat expert capacity and change
    real rows' outputs), so validity must come from the data: sequence
    inputs carry it in their lengths (the feeder pads rows at length 0);
    dense inputs take it from ctx.n_real (the trainer's un-padded row
    count), falling back to all-valid outside a trainer step."""
    if isinstance(v, SequenceBatch):
        b, t, d = v.data.shape
        valid = v.mask().reshape(b * t)
        return (v.data.reshape(b * t, d), valid,
                lambda y: v.with_data(y.reshape(b, t, d)))
    n_real = getattr(ctx, "n_real", None) if ctx is not None else None
    valid = None
    if n_real is not None:
        valid = (jnp.arange(v.shape[0]) < n_real).astype(jnp.float32)
    return v, valid, lambda y: y


@register_layer("moe")
class MoELayer:
    """Top-k capacity-routed expert FFN: x -> combine(experts(dispatch(x))).

    cfg: expert_num E, expert_hidden f, k (default 2), capacity_factor
    (default 1.25). Parameters: gate [d,E], up [E,d,f], down [E,f,d]
    (no biases — router + expert matmuls carry the capacity, matching
    the usual MoE formulation). Output size = input size."""

    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        d = m.size
        E = cfg["expert_num"]
        k = cfg.get("k", 2)
        assert 1 <= k <= E, (
            f"moe {name}: k={k} must be in [1, expert_num={E}] "
            "(a third round over 2 experts would double-dispatch)")
        f = cfg.get("expert_hidden") or 4 * d
        gname, a = _gate_name(name, cfg)
        cfg["_gate"], cfg["_up"], cfg["_down"] = \
            gname, f"_{name}.moe_up", f"_{name}.moe_down"
        specs = [
            ParamSpec(gname, (d, E), default_weight_init(a, fan_in_axes=(0,)),
                      a),
            ParamSpec(cfg["_up"], (E, d, f),
                      initializers.msra((1,)), ParamAttr()),
            ParamSpec(cfg["_down"], (E, f, d),
                      initializers.msra((1,)), ParamAttr()),
        ]
        return LayerMeta(size=d, seq_level=m.seq_level), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x2d, valid, restore = _flatten(inputs[0], ctx)
        y, _aux = moe_ops.moe_ffn(
            x2d, valid, params[cfg["_gate"]], params[cfg["_up"]],
            params[cfg["_down"]], k=cfg.get("k", 2),
            capacity_factor=cfg.get("capacity_factor", 1.25),
            mesh=getattr(ctx, "mesh", None),
            dispatch_mode=cfg.get("dispatch_mode", "auto"))
        return restore(y)


@register_layer("moe_aux_cost")
class MoEAuxCostLayer:
    """Router load-balance loss of a `moe` layer as a per-sample cost node
    (constant across the batch row dim so the trainer's batch-mean
    recovers the scalar). Shares the moe layer's gate parameter by name;
    `coeff` scales the loss (0.01 is the usual setting)."""

    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        d = m.size
        E = cfg["expert_num"]
        gname = cfg["gate_param"]
        cfg["_gate"] = gname
        # shared parameter: declare a spec IDENTICAL to the moe layer's
        # (same attr + initializer, built from the forwarded param_attr),
        # so Topology's first-seen dedup picks the same thing either way
        a = ParamAttr.of(cfg.get("param_attr"))
        specs = [ParamSpec(gname, (d, E),
                           default_weight_init(a, fan_in_axes=(0,)), a)]
        return LayerMeta(size=1, seq_level=0), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        v = inputs[0]
        x2d, valid, _ = _flatten(v, ctx)
        logits = jnp.dot(x2d.astype(jnp.float32),
                         params[cfg["_gate"]].astype(jnp.float32))
        capacity = moe_ops.moe_capacity(
            x2d.shape[0], cfg["expert_num"], cfg.get("k", 2),
            cfg.get("capacity_factor", 1.25))
        _, _, aux = moe_ops.moe_dispatch(logits, valid, k=cfg.get("k", 2),
                                         capacity=capacity)
        b = v.data.shape[0] if isinstance(v, SequenceBatch) else v.shape[0]
        return jnp.full((b,), cfg.get("coeff", 0.01), jnp.float32) * aux


def moe(input, expert_num: int, expert_hidden=None, k: int = 2,
        capacity_factor: float = 1.25, name=None, param_attr=None,
        dispatch_mode: str = "auto", **kw):
    """Mixture-of-experts FFN layer (see MoELayer). dispatch_mode:
    'auto' (default: sort single-host, einsum under an ep mesh),
    'einsum' (ep-shardable dispatch tensors), or 'sort'
    (argsort+scatter — faster at every measured single-host size and
    the only option past ~100k tokens; see ops/moe.py + docs/perf.md)."""
    return make_layer("moe", name, [input], expert_num=expert_num,
                      expert_hidden=expert_hidden, k=k,
                      capacity_factor=capacity_factor,
                      param_attr=param_attr, dispatch_mode=dispatch_mode)


def moe_aux_cost(input, moe_layer, coeff: float = 0.01, name=None, **kw):
    """Load-balance cost for `moe_layer`, fed the same input node."""
    return make_layer("moe_aux_cost", name, [input],
                      expert_num=moe_layer.config["expert_num"],
                      k=moe_layer.config.get("k", 2),
                      capacity_factor=moe_layer.config.get(
                          "capacity_factor", 1.25),
                      gate_param=moe_layer.config["_gate"],
                      param_attr=moe_layer.config.get("param_attr"),
                      coeff=coeff)
