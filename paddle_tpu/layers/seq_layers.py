"""Sequence manipulation layers.

Reference: gserver/layers/{SequencePoolLayer, SequenceLastInstanceLayer,
ExpandLayer, SequenceConcatLayer, SequenceReshapeLayer, SequenceSliceLayer,
SubSequenceLayer}; trainer_config_helpers wrappers pooling_layer, last_seq,
first_seq, expand_layer, seq_concat_layer, ...
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.registry import LayerMeta, register_layer
from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import sequence_ops as seq_ops


@register_layer("seqpool")
class SeqPoolLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        assert m.seq_level >= 1, "sequence pooling needs a sequence input"
        # agg_level 0 ('to sample'): pool whole sequence -> level 0.
        # agg_level 1 ('to sequence', nested input): pool each subsequence ->
        # a level-1 sequence of pooled vectors (AggregateLevel.TO_SEQUENCE).
        agg_level = cfg.get("agg_level", 0)
        out_level = 1 if (m.seq_level == 2 and agg_level != 0) else 0
        return LayerMeta(size=m.size, seq_level=out_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq: SequenceBatch = inputs[0]
        ptype = cfg.get("pool_type", "average")
        if seq.is_nested and cfg.get("agg_level", 0) != 0:
            return seq_ops.sub_seq_pool(seq, ptype,
                                        cfg.get("max_segments"))
        return seq_ops.seq_pool(seq, ptype)


@register_layer("seqlastins")
class SeqLastInsLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=max(m.seq_level - 1, 0)), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq: SequenceBatch = inputs[0]
        if cfg.get("first"):
            return seq_ops.first_instance(seq)
        return seq_ops.last_instance(seq)


@register_layer("expand")
class ExpandLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        like = input_metas[1]
        return LayerMeta(size=m.size, seq_level=like.seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x, like = inputs
        payload = x.data if isinstance(x, SequenceBatch) else x
        return seq_ops.expand_to_sequence(payload, like)


@register_layer("seqconcat")
class SeqConcatLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return seq_ops.seq_concat(inputs[0], inputs[1])


@register_layer("seqreshape")
class SeqReshapeLayer:
    """SequenceReshapeLayer: reinterpret [b, T, d] as [b, T*d/size, size]."""
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=cfg["reshape_size"], seq_level=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq: SequenceBatch = inputs[0]
        ns = cfg["reshape_size"]
        b, T = seq.data.shape[0], seq.data.shape[1]
        d = seq.data.shape[-1]
        total = T * d
        assert total % ns == 0, "seq reshape size must divide T*d"
        new_t = total // ns
        data = seq.data.reshape(b, new_t, ns)
        new_len = (seq.lengths * d) // ns
        return SequenceBatch(data, new_len.astype(jnp.int32))


@register_layer("seqslice")
class SeqSliceLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=m.seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq = inputs[0]
        starts = inputs[1] if len(inputs) > 1 else None
        ends = inputs[2] if len(inputs) > 2 else None
        s = starts[..., 0].astype(jnp.int32) if starts is not None else \
            jnp.zeros((seq.batch_size,), jnp.int32)
        e = ends[..., 0].astype(jnp.int32) if ends is not None else seq.lengths
        return seq_ops.seq_slice(seq, s, e)


@register_layer("seqreverse")
class SeqReverseLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=m.seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return seq_ops.seq_reverse(inputs[0])


@register_layer("context_projection")
class ContextProjectionLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        from paddle_tpu.core.registry import ParamAttr, ParamSpec
        from paddle_tpu.core import initializers
        m = input_metas[0]
        clen = cfg["context_len"]
        specs = []
        if cfg.get("trainable_padding"):
            cstart = cfg.get("context_start", -(clen // 2))
            n_pad = max(0, -cstart) + max(0, cstart + clen - 1)
            a = ParamAttr.of(cfg.get("param_attr"))
            pname = a.name or f"_{name}.w0"
            specs = [ParamSpec(pname, (max(n_pad, 1), m.size),
                               initializers.zeros, a)]
            cfg["_pad_name"] = pname
        return LayerMeta(size=m.size * clen, seq_level=1), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        clen = cfg["context_len"]
        cstart = cfg.get("context_start", -(clen // 2))
        pad = params.get(cfg.get("_pad_name")) if cfg.get("_pad_name") else None
        return seq_ops.context_projection(inputs[0], clen, cstart, pad)
