"""Sequence manipulation layers.

Reference: gserver/layers/{SequencePoolLayer, SequenceLastInstanceLayer,
ExpandLayer, SequenceConcatLayer, SequenceReshapeLayer, SequenceSliceLayer,
SubSequenceLayer}; trainer_config_helpers wrappers pooling_layer, last_seq,
first_seq, expand_layer, seq_concat_layer, ...
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import LayerMeta, register_layer
from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import sequence_ops as seq_ops


@register_layer("seqpool")
class SeqPoolLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        assert m.seq_level >= 1, "sequence pooling needs a sequence input"
        # agg_level 0 ('to sample'): pool whole sequence -> level 0.
        # agg_level 1 ('to sequence', nested input): pool each subsequence ->
        # a level-1 sequence of pooled vectors (AggregateLevel.TO_SEQUENCE).
        agg_level = cfg.get("agg_level", 0)
        out_level = 1 if (m.seq_level == 2 and agg_level != 0) else 0
        return LayerMeta(size=m.size, seq_level=out_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq: SequenceBatch = inputs[0]
        ptype = cfg.get("pool_type", "average")
        if seq.is_nested and cfg.get("agg_level", 0) != 0:
            return seq_ops.sub_seq_pool(seq, ptype,
                                        cfg.get("max_segments"))
        return seq_ops.seq_pool(seq, ptype)


@register_layer("seqlastins")
class SeqLastInsLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=max(m.seq_level - 1, 0)), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq: SequenceBatch = inputs[0]
        if cfg.get("first"):
            return seq_ops.first_instance(seq)
        return seq_ops.last_instance(seq)


@register_layer("expand")
class ExpandLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        like = input_metas[1]
        return LayerMeta(size=m.size, seq_level=like.seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        x, like = inputs
        payload = x.data if isinstance(x, SequenceBatch) else x
        return seq_ops.expand_to_sequence(payload, like)


@register_layer("seqconcat")
class SeqConcatLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return seq_ops.seq_concat(inputs[0], inputs[1])


@register_layer("seqreshape")
class SeqReshapeLayer:
    """SequenceReshapeLayer: reinterpret [b, T, d] as [b, T*d/size, size]."""
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=cfg["reshape_size"], seq_level=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq: SequenceBatch = inputs[0]
        ns = cfg["reshape_size"]
        b, T = seq.data.shape[0], seq.data.shape[1]
        d = seq.data.shape[-1]
        total = T * d
        assert total % ns == 0, "seq reshape size must divide T*d"
        new_t = total // ns
        data = seq.data.reshape(b, new_t, ns)
        new_len = (seq.lengths * d) // ns
        return SequenceBatch(data, new_len.astype(jnp.int32))


@register_layer("seqslice")
class SeqSliceLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=m.seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq = inputs[0]
        starts = inputs[1] if len(inputs) > 1 else None
        ends = inputs[2] if len(inputs) > 2 else None
        s = starts[..., 0].astype(jnp.int32) if starts is not None else \
            jnp.zeros((seq.batch_size,), jnp.int32)
        e = ends[..., 0].astype(jnp.int32) if ends is not None else seq.lengths
        return seq_ops.seq_slice(seq, s, e)


@register_layer("seqreverse")
class SeqReverseLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=m.seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return seq_ops.seq_reverse(inputs[0])


@register_layer("context_projection")
class ContextProjectionLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        from paddle_tpu.core.registry import ParamAttr, ParamSpec
        from paddle_tpu.core import initializers
        m = input_metas[0]
        clen = cfg["context_len"]
        specs = []
        if cfg.get("trainable_padding"):
            cstart = cfg.get("context_start", -(clen // 2))
            n_pad = max(0, -cstart) + max(0, cstart + clen - 1)
            a = ParamAttr.of(cfg.get("param_attr"))
            pname = a.name or f"_{name}.w0"
            specs = [ParamSpec(pname, (max(n_pad, 1), m.size),
                               initializers.zeros, a)]
            cfg["_pad_name"] = pname
        return LayerMeta(size=m.size * clen, seq_level=1), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        clen = cfg["context_len"]
        cstart = cfg.get("context_start", -(clen // 2))
        pad = params.get(cfg.get("_pad_name")) if cfg.get("_pad_name") else None
        return seq_ops.context_projection(inputs[0], clen, cstart, pad)


@register_layer("subseq")
class SubSeqLayer:
    """SubSequenceLayer: per-row slice given offset and size id inputs
    (gserver/layers/SubSequenceLayer.cpp; DSL sub_seq_layer)."""
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=m.seq_level), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq, offsets, sizes = inputs
        off = _first_col(offsets)
        sz = _first_col(sizes)
        return seq_ops.seq_slice(seq, off, off + sz)


def _first_col(v):
    x = v.data if isinstance(v, SequenceBatch) else v
    x = x.reshape(x.shape[0], -1)
    return x[:, 0].astype(jnp.int32)


@register_layer("kmax_seq_score")
class KmaxSeqScoreLayer:
    """Top-k positions of per-step scores within each sequence
    (KmaxSeqScoreLayer.cpp; DSL kmax_seq_score_layer:6667). Output [b, k]
    int32 position ids, -1 padded past the sequence length — feeds
    sub_nested_seq selection in beam decoding stacks. On a nested input
    the reference emits one row of top-k ids PER SUBSEQUENCE, relative to
    the subsequence start (CrossEntropyOverBeam adds the start back as
    basePos) — here that is a [b, R, k] SequenceBatch over subsequences."""
    @staticmethod
    def build(name, cfg, input_metas):
        lvl = 1 if input_metas[0].seq_level == 2 else 0
        return LayerMeta(size=cfg.get("beam_size", 1), seq_level=lvl,
                         is_integer=True), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq: SequenceBatch = inputs[0]
        k = cfg.get("beam_size", 1)
        scores = seq.data.reshape(seq.batch_size, seq.max_len)
        if seq.is_nested:
            T = seq.max_len
            rows = jnp.arange(T, dtype=jnp.int32)
            eq = seq.segment_ids[:, None, :] == rows[None, :, None]  # [b,R,T]
            per_row = jnp.where(eq, scores[:, None, :], -jnp.inf)
            vals, idx = jax.lax.top_k(per_row, min(k, T))      # [b, R, k]
            start = jnp.argmax(eq, axis=2).astype(jnp.int32)   # [b, R]
            rel = idx.astype(jnp.int32) - start[..., None]
            rel = jnp.where(jnp.isfinite(vals), rel, -1)
            if rel.shape[2] < k:
                rel = jnp.pad(rel, ((0, 0), (0, 0), (0, k - rel.shape[2])),
                              constant_values=-1)
            return SequenceBatch(rel, seq.num_segments)
        scores = jnp.where(seq.bool_mask(), scores, -jnp.inf)
        vals, idx = jax.lax.top_k(scores, min(k, scores.shape[1]))
        idx = jnp.where(jnp.isfinite(vals), idx, -1).astype(jnp.int32)
        if idx.shape[1] < k:
            idx = jnp.pad(idx, ((0, 0), (0, k - idx.shape[1])),
                          constant_values=-1)
        return idx


@register_layer("sub_nested_seq")
class SubNestedSeqLayer:
    """Select subsequences of a nested sequence by index
    (SubNestedSequenceLayer.cpp:36-60; DSL sub_nested_seq_layer:6520).

    Input 0: nested SequenceBatch; input 1: selected segment indices
    [b, k] int32 (-1 = unused slot). Output: nested SequenceBatch holding
    only the selected subsequences, renumbered 0..k'-1 and packed to the
    front of the time axis.
    """
    @staticmethod
    def build(name, cfg, input_metas):
        m = input_metas[0]
        return LayerMeta(size=m.size, seq_level=2), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq: SequenceBatch = inputs[0]
        assert seq.is_nested, "sub_nested_seq needs a nested sequence input"
        sel = inputs[1]
        sel = (sel.data if isinstance(sel, SequenceBatch) else sel)
        sel = sel.reshape(sel.shape[0], -1).astype(jnp.int32)   # [b, k]
        T = seq.max_len

        def per_row(data, segs, sel_row):
            k = sel_row.shape[0]
            # new segment index of each input position (-1 = dropped)
            eq = (segs[None, :] == sel_row[:, None]) & \
                (sel_row[:, None] >= 0) & (segs[None, :] >= 0)   # [k, T]
            nj = jnp.where(jnp.any(eq, axis=0),
                           jnp.argmax(eq, axis=0), -1)           # [T]
            seg_len = jnp.sum(eq, axis=1)                        # [k]
            offset = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32),
                 jnp.cumsum(seg_len)[:-1].astype(jnp.int32)])
            # rank within the source segment: segments are contiguous, so
            # rank = t - first position of that segment
            first = jnp.argmax(eq, axis=1).astype(jnp.int32)     # [k]
            t_idx = jnp.arange(T, dtype=jnp.int32)
            rank = t_idx - first[jnp.clip(nj, 0)]
            newpos = jnp.where(nj >= 0, offset[jnp.clip(nj, 0)] + rank, T)
            out = jnp.zeros_like(data).at[newpos].set(data, mode="drop")
            out_segs = jnp.full((T,), -1, jnp.int32).at[newpos].set(
                nj, mode="drop")
            return out, out_segs, jnp.sum(seg_len).astype(jnp.int32), \
                jnp.sum(sel_row >= 0).astype(jnp.int32)

        data, segs, lengths, nsegs = jax.vmap(per_row)(
            seq.data, seq.segment_ids, sel)
        return SequenceBatch(data, lengths, segs, nsegs)
