"""Cost layers — graph nodes wrapping ops/cost.py.

Reference: gserver/layers/CostLayer.cpp registrations ('multi-class-cross-
entropy', 'square_error', 'rank-cost', 'lambda_cost', 'huber_regression',
'huber_classification', 'multi_binary_label_cross_entropy', 'smooth_l1',
'sum_cost', 'soft_binary_class_cross_entropy') + NCELayer, CRFLayer,
CTCLayer, HierarchicalSigmoidLayer.

Every cost layer outputs per-sample loss [batch]; the trainer's total loss
is the mean over the batch (matching the reference's batch-averaged cost).
A `weight` input scales per-sample losses (the v2 `weight_layer` support).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers
from paddle_tpu.core.registry import (LayerMeta, ParamAttr, ParamSpec,
                                      register_layer)
from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import cost as cost_ops


def _payload(v):
    return v.data if isinstance(v, SequenceBatch) else v


def _flatten_seq_cost(per_pos, seq: SequenceBatch, average: bool = False):
    """Reduce per-position costs [b, T] over valid positions -> [b]."""
    m = seq.mask(per_pos.dtype)
    tot = jnp.sum(per_pos * m, axis=1)
    if average:
        tot = tot / jnp.maximum(jnp.sum(m, axis=1), 1.0)
    return tot


def _seq_or_sample_cost(fn, pred, label):
    """Apply a per-row cost either per sample or per (valid) timestep."""
    if isinstance(pred, SequenceBatch):
        lab = _payload(label)
        per_pos = fn(pred.data, lab)
        return _flatten_seq_cost(per_pos, pred)
    return fn(_payload(pred), _payload(label))


@register_layer("multi-class-cross-entropy")
class CrossEntropyCost:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        pred, label = inputs[0], inputs[1]
        fn = lambda p, l: cost_ops.cross_entropy(  # noqa: E731
            p, l, from_logits=cfg.get("from_logits", False),
            label_smoothing=cfg.get("label_smoothing", 0.0))
        w = inputs[2] if len(inputs) > 2 else None
        if isinstance(pred, SequenceBatch) and isinstance(w, SequenceBatch):
            # PER-TOKEN weights (the masked-LM objective: weight 1.0 on
            # masked slots selects which positions contribute) — applied
            # before the valid-position reduction
            per_pos = fn(pred.data, _payload(label))
            per_pos = per_pos * w.data.reshape(per_pos.shape)
            return _flatten_seq_cost(per_pos, pred)
        out = _seq_or_sample_cost(fn, pred, label)
        if w is not None:  # per-sample weight (v2 weight_layer support)
            out = out * _payload(w).reshape(out.shape)
        return out


@register_layer("square_error")
class SquareErrorCost:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        out = _seq_or_sample_cost(cost_ops.square_error, inputs[0], inputs[1])
        if len(inputs) > 2:
            out = out * _payload(inputs[2]).reshape(out.shape)
        return out


@register_layer("soft_binary_class_cross_entropy")
class SoftBinaryCECost:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return _seq_or_sample_cost(cost_ops.soft_binary_class_cross_entropy,
                                   inputs[0], inputs[1])


@register_layer("multi_binary_label_cross_entropy")
class MultiBinaryLabelCECost:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return _seq_or_sample_cost(cost_ops.multi_binary_label_cross_entropy,
                                   inputs[0], inputs[1])


@register_layer("rank-cost")
class RankCost:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        left, right, label = inputs[0], inputs[1], inputs[2]
        w = _payload(inputs[3]) if len(inputs) > 3 else None
        return cost_ops.rank_cost(_payload(left), _payload(right),
                                  _payload(label), w)


@register_layer("lambda_cost")
class LambdaCost:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        scores, rel = inputs[0], inputs[1]
        assert isinstance(scores, SequenceBatch), \
            "lambda_cost expects a sequence of document scores per query"
        s = scores.data[..., 0]
        r = _payload(rel)
        r = r[..., 0] if r.ndim == 3 else r
        return cost_ops.lambda_cost(s, r, scores.mask(s.dtype),
                                    cfg.get("NDCG_num", 5))


@register_layer("huber_regression")
class HuberRegressionCost:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return _seq_or_sample_cost(
            lambda p, l: cost_ops.huber_regression(p, l, cfg.get("delta", 1.0)),
            inputs[0], inputs[1])


@register_layer("huber_classification")
class HuberClassificationCost:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return cost_ops.huber_classification(_payload(inputs[0]),
                                             _payload(inputs[1]))


@register_layer("smooth_l1")
class SmoothL1Cost:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return _seq_or_sample_cost(
            lambda p, l: cost_ops.smooth_l1(p, l, cfg.get("sigma", 1.0)),
            inputs[0], inputs[1])


@register_layer("sum_cost")
class SumCost:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        v = inputs[0]
        if isinstance(v, SequenceBatch):
            return _flatten_seq_cost(jnp.sum(v.data, axis=-1), v)
        return cost_ops.sum_cost(v)


@register_layer("cross_entropy_with_selfnorm")
class CrossEntropySelfNormCost:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return _seq_or_sample_cost(
            lambda p, l: cost_ops.cross_entropy_with_selfnorm(
                p, l, cfg.get("softmax_selfnorm_alpha", 0.1)),
            inputs[0], inputs[1])


@register_layer("nce")
class NCELayer:
    @staticmethod
    def build(name, cfg, input_metas):
        num_classes = cfg["num_classes"]
        feat_dim = input_metas[0].size
        a = ParamAttr.of(cfg.get("param_attr"))
        wname = a.name or f"_{name}.w0"
        specs = [ParamSpec(wname, (num_classes, feat_dim),
                           a.initializer or initializers.smart_normal(1), a)]
        cfg["_w_name"] = wname
        battr = ParamAttr.of(None if cfg.get("bias_attr") in (True, None)
                             else cfg.get("bias_attr"))
        bname = battr.name or f"_{name}.wbias"
        specs.append(ParamSpec(bname, (num_classes,), initializers.zeros,
                               battr))
        cfg["_b_name"] = bname
        return LayerMeta(size=1), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        feats, labels = _payload(inputs[0]), _payload(inputs[1])
        k = cfg.get("num_neg_samples", 10)
        nc = cfg["num_classes"]
        sample_ids = jax.random.randint(ctx.rng_for(name),
                                        (feats.shape[0], k), 0, nc)
        return cost_ops.nce_loss(feats, params[cfg["_w_name"]],
                                 params[cfg["_b_name"]], labels, sample_ids, nc)


@register_layer("hsigmoid")
class HSigmoidLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        num_classes = cfg["num_classes"]
        feat_dim = sum(m.size for m in input_metas[:-1])  # last input = label
        a = ParamAttr.of(cfg.get("param_attr"))
        wname = a.name or f"_{name}.w0"
        specs = [ParamSpec(wname, (max(num_classes - 1, 1), feat_dim),
                           a.initializer or initializers.smart_normal(1), a)]
        cfg["_w_name"] = wname
        battr = ParamAttr.of(None if cfg.get("bias_attr") in (True, None)
                             else cfg.get("bias_attr"))
        bname = battr.name or f"_{name}.wbias"
        specs.append(ParamSpec(bname, (max(num_classes - 1, 1),),
                               initializers.zeros, battr))
        cfg["_b_name"] = bname
        return LayerMeta(size=1), specs, []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        feats = jnp.concatenate([_payload(v) for v in inputs[:-1]], axis=-1)
        labels = _payload(inputs[-1])
        return cost_ops.hsigmoid_loss(feats, params[cfg["_w_name"]],
                                      params[cfg["_b_name"]], labels,
                                      cfg["num_classes"])


@register_layer("classification_error")
class ClassificationErrorLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        return _seq_or_sample_cost(cost_ops.classification_error,
                                   inputs[0], inputs[1])
