"""The layer DSL — paddle.v2.layer-compatible construction functions.

Reference: python/paddle/trainer_config_helpers/layers.py (~120 wrappers)
re-exported by python/paddle/v2/layer.py under short names (fc, data,
embedding, img_conv, ...). Each function normalizes arguments (activation
objects -> names, attrs -> ParamAttr) and creates a graph node via the
build half of the registered layer implementation.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

from paddle_tpu import activation as act_mod
from paddle_tpu import pooling as pool_mod
from paddle_tpu.core.data_type import InputType
from paddle_tpu.core.registry import LayerOutput, make_layer

# import implementations to populate the registry
from paddle_tpu.layers import base as _base            # noqa: F401
from paddle_tpu.layers import conv_layers as _conv     # noqa: F401
from paddle_tpu.layers import seq_layers as _seq       # noqa: F401
from paddle_tpu.layers import cost_layers as _cost     # noqa: F401
from paddle_tpu.layers import recurrent_layers as _rec  # noqa: F401
from paddle_tpu.layers import group as _group          # noqa: F401
from paddle_tpu.layers.group import (recurrent_group, memory, beam_search,
                                     get_output, StaticInput,
                                     GeneratedInput, SubsequenceInput)
from paddle_tpu.layers import crf_layers as _crf       # noqa: F401
from paddle_tpu.layers import attention_layers as _attn  # noqa: F401
from paddle_tpu.layers import misc_layers as _misc     # noqa: F401
from paddle_tpu.layers import detection_layers as _det  # noqa: F401
from paddle_tpu.layers import extra_layers as _extra   # noqa: F401
from paddle_tpu.layers.beam import (BeamInput,
                                    cross_entropy_over_beam)  # noqa: F401
from paddle_tpu.layers.attention_layers import (dot_product_attention,
                                                multi_head_attention)
from paddle_tpu.layers.moe_layers import moe, moe_aux_cost  # noqa: F401


def _listify(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


# ---------------------------------------------------------------------------
# data & core


def data(name: str, type: InputType, height: int = 0, width: int = 0,
         **kw) -> LayerOutput:
    return make_layer("data", name, [], input_type=type, height=height,
                      width=width)


data_layer = data


def fc(input, size: int, act=None, name: Optional[str] = None,
       param_attr=None, bias_attr=None, layer_attr=None,
       tied_transpose: bool = False, **kw) -> LayerOutput:
    inputs = _listify(input)
    opts = {"tied_transpose": True} if tied_transpose else {}
    node = make_layer("fc", name, inputs, size=size,
                      act=act_mod.to_name(act), param_attr=param_attr,
                      bias_attr=bias_attr, **opts)
    return _maybe_dropout(node, layer_attr)


fc_layer = fc


def embedding(input, size: int, name: Optional[str] = None, param_attr=None,
              remote: bool = False, **kw) -> LayerOutput:
    """``remote=True`` (or ``ParamAttr(remote=True)``) places the table
    in the sharded embedding store (:mod:`paddle_tpu.embed`) instead of
    a local parameter — same config surface, tables bigger than one
    device."""
    # only record ``remote`` when set — keeps the serialized topology
    # (and the golden files gating it) byte-identical for local tables
    kw = dict(size=size, param_attr=param_attr)
    if remote:
        kw["remote"] = True
    return make_layer("embedding", name, [input], **kw)


embedding_layer = embedding


def dropout(input, dropout_rate: float = 0.5,
            name: Optional[str] = None) -> LayerOutput:
    return make_layer("dropout", name, [input], dropout_rate=dropout_rate)


dropout_layer = dropout


def _maybe_dropout(node: LayerOutput, layer_attr) -> LayerOutput:
    if layer_attr is not None and getattr(layer_attr, "drop_rate", None):
        return dropout(node, layer_attr.drop_rate)
    return node


def addto(input, act=None, name: Optional[str] = None,
          bias_attr=None, **kw) -> LayerOutput:
    return make_layer("addto", name, _listify(input),
                      act=act_mod.to_name(act), bias_attr=bias_attr)


addto_layer = addto


def concat(input, act=None, name: Optional[str] = None, **kw) -> LayerOutput:
    return make_layer("concat", name, _listify(input),
                      act=act_mod.to_name(act))


concat_layer = concat


def batch_norm(input, act=None, name: Optional[str] = None, num_channels=None,
               param_attr=None, bias_attr=None, use_global_stats=None,
               moving_average_fraction: float = 0.9, **kw) -> LayerOutput:
    return make_layer("batch_norm", name, [input], act=act_mod.to_name(act),
                      param_attr=param_attr, bias_attr=bias_attr,
                      channels=num_channels,
                      use_global_stats=use_global_stats,
                      moving_average_fraction=moving_average_fraction)


batch_norm_layer = batch_norm


def scaling(weight, input, name: Optional[str] = None, **kw) -> LayerOutput:
    return make_layer("scaling", name, [weight, input])


scaling_layer = scaling


def dotmul(a, b, scale: float = 1.0, name: Optional[str] = None) -> LayerOutput:
    return make_layer("dotmul", name, [a, b], scale=scale)


def interpolation(input, weight, name: Optional[str] = None, **kw) -> LayerOutput:
    a, b = input
    return make_layer("interpolation", name, [weight, a, b])


interpolation_layer = interpolation


def slope_intercept(input, slope: float = 1.0, intercept: float = 0.0,
                    name: Optional[str] = None, **kw) -> LayerOutput:
    return make_layer("slope_intercept", name, [input], slope=slope,
                      intercept=intercept)


slope_intercept_layer = slope_intercept


def cos_sim(a, b, scale: float = 1.0, size: int = 1,
            name: Optional[str] = None, **kw) -> LayerOutput:
    return make_layer("cos_sim", name, [a, b], scale=scale)


def outer_prod(a, b, name: Optional[str] = None) -> LayerOutput:
    return make_layer("outer_prod", name, [a, b])


def sum_to_one_norm(input, name: Optional[str] = None) -> LayerOutput:
    return make_layer("sum_to_one_norm", name, [input])


sum_to_one_norm_layer = sum_to_one_norm


def trans(input, name: Optional[str] = None) -> LayerOutput:
    return make_layer("trans", name, [input])


trans_layer = trans


def resize(input, size: int, name: Optional[str] = None) -> LayerOutput:
    return make_layer("resize", name, [input], size=size)


resize_layer = resize


def mixed(size: int = 0, input=None, act=None, name: Optional[str] = None,
          bias_attr=None, **kw) -> LayerOutput:
    """mixed_layer: sum of projections. Projections are expressed as layer
    nodes already (full_matrix_projection etc. return nodes); mixed sums
    them (addto semantics) with optional bias+activation."""
    return make_layer("addto", name, _listify(input),
                      act=act_mod.to_name(act), bias_attr=bias_attr)


mixed_layer = mixed


# Projections (reference: 12 Projection subclasses under MixedLayer). In this
# graph they are plain nodes summed by mixed()/addto.

def full_matrix_projection(input, size: int, param_attr=None, **kw) -> LayerOutput:
    return make_layer("fc", None, [input], size=size, act="linear",
                      param_attr=param_attr, bias_attr=False)


def identity_projection(input, offset: int = 0, size: Optional[int] = None, **kw):
    if offset == 0 and size is None:
        return input
    sz = size if size is not None else input.size - offset
    return slice_projection(input, offset, offset + sz)


def slice_projection(input, start: int, end: int,
                     channel_slice: bool = False, **kw) -> LayerOutput:
    return make_layer("slice", None, [input], start=start, end=end,
                      channel_slice=channel_slice)


def table_projection(input, size: int, param_attr=None, **kw) -> LayerOutput:
    return make_layer("embedding", None, [input], size=size,
                      param_attr=param_attr)


def scaling_projection(input, param_attr=None, **kw) -> LayerOutput:
    return make_layer("scaling_projection", None, [input],
                      param_attr=param_attr)


def dotmul_projection(input, param_attr=None, **kw) -> LayerOutput:
    return make_layer("dotmul_projection", None, [input],
                      param_attr=param_attr)


def trans_full_matrix_projection(input, size: int, param_attr=None, **kw) -> LayerOutput:
    return make_layer("trans_fc", None, [input], size=size,
                      param_attr=param_attr)


def context_projection(input, context_len: int, context_start=None,
                       padding_attr=False, **kw) -> LayerOutput:
    trainable = padding_attr not in (False, None)
    return make_layer(
        "context_projection", None, [input], context_len=context_len,
        context_start=(context_start if context_start is not None
                       else -(context_len // 2)),
        trainable_padding=trainable,
        param_attr=None if padding_attr in (False, True, None) else padding_attr)


# ---------------------------------------------------------------------------
# image layers


def img_conv(input, filter_size: int, num_filters: int, name=None,
             num_channels=None, act=None, groups: int = 1, stride: int = 1,
             padding: int = 0, dilation: int = 1, bias_attr=None,
             param_attr=None, trans: bool = False, layer_attr=None,
             **kw) -> LayerOutput:
    node = make_layer("conv", name, [input], filter_size=filter_size,
                      num_filters=num_filters, channels=num_channels,
                      act=act_mod.to_name(act), groups=groups, stride=stride,
                      padding=padding, dilation=dilation, bias_attr=bias_attr,
                      param_attr=param_attr, trans=trans)
    return _maybe_dropout(node, layer_attr)


img_conv_layer = img_conv


def conv_bn(input, filter_size: int, num_filters: int, name=None,
            num_channels=None, act=None, stride: int = 1, padding: int = 0,
            dilation: int = 1, param_attr=None, use_global_stats=None,
            moving_average_fraction: float = 0.9, epsilon: float = 1e-5,
            fuse_stats: bool = False, groups: int = 1,
            **kw) -> LayerOutput:
    """Conv + batch-norm in one node; semantically identical to
    img_conv(bias_attr=False) -> batch_norm. fuse_stats=True opts
    1x1/s1/p0 convs into the recompute-fused stats epilogue
    (ops/fused.conv_bn_train) — measured SLOWER than XLA's own fusion
    on current TPUs (docs/perf.md), kept for future revisits."""
    assert groups == 1, \
        "conv_bn does not support grouped convs — use img_conv + batch_norm"
    return make_layer("conv_bn", name, [input], filter_size=filter_size,
                      num_filters=num_filters, channels=num_channels,
                      act=act_mod.to_name(act), stride=stride,
                      padding=padding, dilation=dilation,
                      param_attr=param_attr,
                      use_global_stats=use_global_stats,
                      moving_average_fraction=moving_average_fraction,
                      epsilon=epsilon, fuse_stats=fuse_stats)


conv_bn_layer = conv_bn


def img_pool(input, pool_size: int, name=None, num_channels=None,
             pool_type=None, stride: int = 1, padding: int = 0,
             pool_size_x=None, ceil_mode: bool = True, **kw) -> LayerOutput:
    return make_layer("pool", name, [input], pool_size=pool_size,
                      pool_size_x=pool_size_x,
                      channels=num_channels, pool_type=pool_mod.to_name(
                          pool_type or "max"),
                      stride=stride, padding=padding, ceil_mode=ceil_mode)


def global_img_pool(input, name=None, pool_type=None, **kw) -> LayerOutput:
    """Global spatial pool (the GAP of ResNet/GoogleNet heads)."""
    return make_layer("pool", name, [input], pool_size=input.meta.height,
                      pool_size_x=input.meta.width,
                      pool_type=pool_mod.to_name(pool_type or "average"),
                      stride=1, padding=0)


img_pool_layer = img_pool


def space_to_depth(input, factor: int = 2, name=None, num_channels=None,
                   **kw) -> LayerOutput:
    """Fold factor x factor spatial blocks into channels (TPU stem trick;
    see layers/extra_layers.py SpaceToDepthLayer)."""
    return make_layer("space_to_depth", name, [input], factor=factor,
                      channels=num_channels)


def img_cmrnorm(input, size: int = 5, scale: float = 0.0128,
                power: float = 0.75, name=None, **kw) -> LayerOutput:
    return make_layer("img_cmrnorm", name, [input], size=size, scale=scale,
                      power=power)


img_cmrnorm_layer = img_cmrnorm


def maxout(input, groups: int, name=None, **kw) -> LayerOutput:
    return make_layer("maxout", name, [input], groups=groups)


maxout_layer = maxout


def spp(input, pyramid_height: int = 3, pool_type=None, name=None,
        **kw) -> LayerOutput:
    return make_layer("spp", name, [input], pyramid_height=pyramid_height,
                      pool_type=pool_mod.to_name(pool_type or "max"))


spp_layer = spp


def pad(input, pad_c=None, pad_h=None, pad_w=None, name=None, **kw) -> LayerOutput:
    return make_layer("pad", name, [input], pad_c=pad_c or [0, 0],
                      pad_h=pad_h or [0, 0], pad_w=pad_w or [0, 0])


pad_layer = pad


def crop(input, shape, offset=None, name=None, **kw) -> LayerOutput:
    return make_layer("crop", name, [input], shape=shape,
                      offset=offset or [0, 0, 0])


def bilinear_interp(input, out_size_x: int, out_size_y: int, name=None,
                    **kw) -> LayerOutput:
    return make_layer("bilinear_interp", name, [input], out_size_x=out_size_x,
                      out_size_y=out_size_y)


bilinear_interp_layer = bilinear_interp


def block_expand(input, block_x: int, block_y: int, stride_x: int = 1,
                 stride_y: int = 1, padding_x: int = 0, padding_y: int = 0,
                 num_channels=None, name=None, **kw) -> LayerOutput:
    return make_layer("block_expand", name, [input], block_x=block_x,
                      block_y=block_y, stride_x=stride_x, stride_y=stride_y,
                      padding_x=padding_x, padding_y=padding_y,
                      channels=num_channels)


block_expand_layer = block_expand


# ---------------------------------------------------------------------------
# sequence layers


def pooling(input, pooling_type=None, agg_level: int = 0, name=None,
            max_segments=None, **kw) -> LayerOutput:
    return make_layer("seqpool", name, [input],
                      pool_type=pool_mod.to_name(pooling_type),
                      agg_level=agg_level, max_segments=max_segments)


pooling_layer = pooling


def last_seq(input, name=None, agg_level: int = 0, **kw) -> LayerOutput:
    return make_layer("seqlastins", name, [input], first=False)


def first_seq(input, name=None, agg_level: int = 0, **kw) -> LayerOutput:
    return make_layer("seqlastins", name, [input], first=True)


def expand(input, expand_as, name=None, expand_level: int = 0, **kw) -> LayerOutput:
    return make_layer("expand", name, [input, expand_as])


expand_layer = expand


def seq_concat(a, b, name=None, **kw) -> LayerOutput:
    return make_layer("seqconcat", name, [a, b])


seq_concat_layer = seq_concat


def seq_reshape(input, reshape_size: int, name=None, **kw) -> LayerOutput:
    return make_layer("seqreshape", name, [input], reshape_size=reshape_size)


seq_reshape_layer = seq_reshape


def seq_slice(input, starts=None, ends=None, name=None, **kw) -> LayerOutput:
    nodes = [input] + [n for n in (starts, ends) if n is not None]
    return make_layer("seqslice", name, nodes)


seq_slice_layer = seq_slice


def seq_reverse(input, name=None, **kw) -> LayerOutput:
    return make_layer("seqreverse", name, [input])


# ---------------------------------------------------------------------------
# recurrent layers


def lstmemory(input, name=None, reverse: bool = False, act=None,
              gate_act=None, state_act=None, bias_attr=None, param_attr=None,
              **kw) -> LayerOutput:
    return make_layer("lstmemory", name, [input], reverse=reverse,
                      act=act_mod.to_name(act or "tanh"),
                      gate_act=act_mod.to_name(gate_act or "sigmoid"),
                      state_act=act_mod.to_name(state_act or "tanh"),
                      bias_attr=bias_attr, param_attr=param_attr)


def grumemory(input, name=None, reverse: bool = False, act=None,
              gate_act=None, bias_attr=None, param_attr=None, **kw) -> LayerOutput:
    return make_layer("gru", name, [input], reverse=reverse,
                      act=act_mod.to_name(act or "tanh"),
                      gate_act=act_mod.to_name(gate_act or "sigmoid"),
                      bias_attr=bias_attr, param_attr=param_attr)


def recurrent(input, name=None, reverse: bool = False, act=None,
              bias_attr=None, param_attr=None, **kw) -> LayerOutput:
    return make_layer("recurrent", name, [input], reverse=reverse,
                      act=act_mod.to_name(act or "tanh"),
                      bias_attr=bias_attr, param_attr=param_attr)


recurrent_layer = recurrent


def gru_step(input, output_mem, size=None, name=None, act=None,
             gate_act=None, bias_attr=None, param_attr=None, **kw) -> LayerOutput:
    """Step-level GRU for recurrent_group decoders (gru_step_layer)."""
    return make_layer("gru_step", name, [input, output_mem], size=size,
                      act=act_mod.to_name(act or "tanh"),
                      gate_act=act_mod.to_name(gate_act or "sigmoid"),
                      bias_attr=bias_attr, param_attr=param_attr)


gru_step_layer = gru_step


def lstm_step(input, state, size=None, name=None, act=None, gate_act=None,
              state_act=None, bias_attr=None, expose_state: bool = False,
              **kw) -> LayerOutput:
    """Step-level LSTM (lstm_step_layer): state is the prev-cell memory."""
    return make_layer("lstm_step", name, [input, state], size=size,
                      act=act_mod.to_name(act or "tanh"),
                      gate_act=act_mod.to_name(gate_act or "sigmoid"),
                      state_act=act_mod.to_name(state_act or "tanh"),
                      bias_attr=bias_attr, expose_state=expose_state)


lstm_step_layer = lstm_step


# ---------------------------------------------------------------------------
# cost layers


def classification_cost(input, label, weight=None, name=None,
                        **kw) -> LayerOutput:
    """CE over softmax probabilities (v2 classification_cost). The input is
    expected to carry a softmax activation already."""
    nodes = [input, label] + ([weight] if weight is not None else [])
    return make_layer("multi-class-cross-entropy", name, nodes)


def cross_entropy_cost(input, label, name=None, weight=None,
                       from_logits: bool = False,
                       label_smoothing: float = 0.0, **kw) -> LayerOutput:
    # (name stays the 3rd positional — the v2 signature; weight is the
    # per-sample or per-token scale, keyword-preferred)
    # non-default options only, so existing serialized topologies (and
    # the golden corpus) are byte-stable
    if not 0.0 <= label_smoothing < 1.0:
        raise ValueError(
            f"label_smoothing={label_smoothing} must be in [0, 1)")
    if label_smoothing > 0.0 and not from_logits:
        raise ValueError(
            "label_smoothing needs from_logits=True (the probs CE path "
            "gathers only the label column)")
    opts = {}
    if from_logits:
        opts["from_logits"] = True
    if label_smoothing > 0.0:
        opts["label_smoothing"] = label_smoothing
    nodes = [input, label] + ([weight] if weight is not None else [])
    return make_layer("multi-class-cross-entropy", name, nodes, **opts)


def cross_entropy_with_selfnorm_cost(input, label, name=None,
                                     softmax_selfnorm_alpha: float = 0.1,
                                     **kw) -> LayerOutput:
    return make_layer("cross_entropy_with_selfnorm", name, [input, label],
                      softmax_selfnorm_alpha=softmax_selfnorm_alpha)


def square_error_cost(input, label, weight=None, name=None, **kw) -> LayerOutput:
    nodes = [input, label] + ([weight] if weight is not None else [])
    return make_layer("square_error", name, nodes)


mse_cost = square_error_cost
regression_cost = square_error_cost


def soft_binary_class_cross_entropy_cost(input, label, name=None, **kw):
    return make_layer("soft_binary_class_cross_entropy", name, [input, label])


def multi_binary_label_cross_entropy_cost(input, label, name=None, **kw):
    return make_layer("multi_binary_label_cross_entropy", name, [input, label])


def rank_cost(left, right, label, weight=None, name=None, **kw) -> LayerOutput:
    nodes = [left, right, label] + ([weight] if weight is not None else [])
    return make_layer("rank-cost", name, nodes)


def lambda_cost(input, score, NDCG_num: int = 5, name=None, **kw) -> LayerOutput:
    return make_layer("lambda_cost", name, [input, score], NDCG_num=NDCG_num)


def huber_regression_cost(input, label, delta: float = 1.0, name=None, **kw):
    return make_layer("huber_regression", name, [input, label], delta=delta)


def huber_classification_cost(input, label, name=None, **kw) -> LayerOutput:
    return make_layer("huber_classification", name, [input, label])


def smooth_l1_cost(input, label, sigma: float = 1.0, name=None, **kw):
    return make_layer("smooth_l1", name, [input, label], sigma=sigma)


def sum_cost(input, name=None, **kw) -> LayerOutput:
    return make_layer("sum_cost", name, [input])


def nce(input, label, num_classes: int, num_neg_samples: int = 10,
        param_attr=None, bias_attr=None, name=None, **kw) -> LayerOutput:
    return make_layer("nce", name, [input, label], num_classes=num_classes,
                      num_neg_samples=num_neg_samples, param_attr=param_attr,
                      bias_attr=bias_attr)


nce_layer = nce


def hsigmoid(input, label, num_classes: int, param_attr=None, bias_attr=None,
             name=None, **kw) -> LayerOutput:
    nodes = _listify(input) + [label]
    return make_layer("hsigmoid", name, nodes, num_classes=num_classes,
                      param_attr=param_attr, bias_attr=bias_attr)


def classification_error(input, label, name=None, **kw) -> LayerOutput:
    return make_layer("classification_error", name, [input, label])


# crf / ctc re-exported from crf_layers
from paddle_tpu.layers.crf_layers import (crf, crf_decoding, crf_error, ctc,
                                          warp_ctc)  # noqa: E402,F401


# ---------------------------------------------------------------------------
# id / sampling / generation helpers
# (reference layers.py maxid_layer:3989, sampling_id_layer:4859,
#  eos_layer:4062, multiplex_layer:6123)


def max_id(input, name=None, beam_size: int = 1, **kw) -> LayerOutput:
    return make_layer("maxid", name, [input], beam_size=beam_size)


maxid = max_id


def sampling_id(input, name=None, **kw) -> LayerOutput:
    return make_layer("sampling_id", name, [input])


def eos(input, eos_id: int, name=None, **kw) -> LayerOutput:
    return make_layer("eos_id", name, [input], eos_id=eos_id)


def multiplex(input, name=None, **kw) -> LayerOutput:
    return make_layer("multiplex", name, _listify(input))


# ---------------------------------------------------------------------------
# elementwise / feature utilities
# (clip_layer:6566, scale_shift_layer:6849, power_layer:2046,
#  rotate_layer:2167, featmap_expand FeatureMapExpandLayer.cpp,
#  data_norm DataNormLayer.cpp, selective_fc_layer:4776,
#  row_conv_layer:6197)


def clip(input, min: float, max: float, name=None, **kw) -> LayerOutput:
    return make_layer("clip", name, [input], min=min, max=max)


def scale_shift(input, name=None, param_attr=None, bias_attr=None,
                **kw) -> LayerOutput:
    return make_layer("scale_shift", name, [input], param_attr=param_attr,
                      bias_attr=bias_attr)


def power(input, weight, name=None, **kw) -> LayerOutput:
    return make_layer("power", name, [weight, input])


def rotate(input, height=None, width=None, name=None, **kw) -> LayerOutput:
    return make_layer("rotate", name, [input], height=height, width=width)


def featmap_expand(input, num_filters: int, as_row_vector: bool = True,
                   name=None, **kw) -> LayerOutput:
    return make_layer("featmap_expand", name, [input],
                      num_filters=num_filters, as_row_vector=as_row_vector)


def data_norm(input, data_norm_strategy: str = "z-score", name=None,
              param_attr=None, **kw) -> LayerOutput:
    return make_layer("data_norm", name, [input],
                      data_norm_strategy=data_norm_strategy,
                      param_attr=param_attr)


def selective_fc(input, size: int, select=None, act=None, name=None,
                 param_attr=None, bias_attr=None, **kw) -> LayerOutput:
    inputs = _listify(input) + ([select] if select is not None else [])
    return make_layer("selective_fc", name, inputs, size=size,
                      act=act_mod.to_name(act), param_attr=param_attr,
                      bias_attr=bias_attr)


def row_conv(input, context_len: int, act=None, name=None, param_attr=None,
             **kw) -> LayerOutput:
    return make_layer("row_conv", name, [input], context_len=context_len,
                      act=act_mod.to_name(act), param_attr=param_attr)


def print_layer(input, format=None, name=None, **kw) -> LayerOutput:
    return make_layer("print", name, [input],
                      **({"format": format} if format else {}))


# ---------------------------------------------------------------------------
# sequence selection (sub_seq SubSequenceLayer.cpp,
#  kmax_seq_score_layer:6667, sub_nested_seq_layer:6520)


def sub_seq(input, offsets, sizes, name=None, **kw) -> LayerOutput:
    return make_layer("subseq", name, [input, offsets, sizes])


def kmax_seq_score(input, beam_size: int = 1, name=None, **kw) -> LayerOutput:
    return make_layer("kmax_seq_score", name, [input], beam_size=beam_size)


def sub_nested_seq(input, selected_indices, name=None, **kw) -> LayerOutput:
    return make_layer("sub_nested_seq", name, [input, selected_indices])


# ---------------------------------------------------------------------------
# 3D conv/pool (Conv3DLayer.cpp, DeConv3DLayer.cpp, Pool3DLayer.cpp)


def img_conv3d(input, filter_size, num_filters: int, input_depth: int,
               name=None, num_channels=None, act=None, stride=1, padding=0,
               trans: bool = False, param_attr=None, bias_attr=None,
               input_height=None, input_width=None, **kw) -> LayerOutput:
    layer_type = "deconv3d" if trans else "conv3d"
    return make_layer(layer_type, name, [input], filter_size=filter_size,
                      num_filters=num_filters, input_depth=input_depth,
                      channels=num_channels, act=act_mod.to_name(act),
                      stride=stride, padding=padding, param_attr=param_attr,
                      bias_attr=bias_attr, input_height=input_height,
                      input_width=input_width)


def img_pool3d(input, pool_size, input_depth: int, name=None,
               num_channels=None, pool_type=None, stride=1, padding=0,
               input_height=None, input_width=None, **kw) -> LayerOutput:
    return make_layer("pool3d", name, [input], pool_size=pool_size,
                      input_depth=input_depth, channels=num_channels,
                      pool_type=pool_mod.to_name(pool_type) if pool_type
                      else "max",
                      stride=stride, padding=padding,
                      input_height=input_height, input_width=input_width)


def mdlstm(input, name=None, directions=None, act=None, gate_act=None,
           param_attr=None, bias_attr=None, **kw) -> LayerOutput:
    return make_layer("mdlstm", name, [input],
                      directions=directions or [True, True],
                      act=act_mod.to_name(act) if act else "tanh",
                      gate_act=act_mod.to_name(gate_act) if gate_act
                      else "sigmoid",
                      param_attr=param_attr, bias_attr=bias_attr)


# ---------------------------------------------------------------------------
# SSD detection (priorbox_layer:1095, multibox_loss_layer:1141,
#  detection_output_layer:1214, cross_channel_norm_layer:1294)


def priorbox(input, image, aspect_ratio, variance, min_size, max_size=None,
             name=None, **kw) -> LayerOutput:
    return make_layer("priorbox", name, [input, image],
                      aspect_ratio=list(aspect_ratio),
                      variance=list(variance), min_size=list(min_size),
                      max_size=list(max_size or []))


def cross_channel_norm(input, name=None, param_attr=None, **kw) -> LayerOutput:
    return make_layer("cross_channel_norm", name, [input],
                      param_attr=param_attr)


def multibox_loss(input_loc, input_conf, priorbox, label, num_classes: int,
                  overlap_threshold: float = 0.5, neg_pos_ratio: float = 3.0,
                  neg_overlap: float = 0.5, background_id: int = 0,
                  name=None, **kw) -> LayerOutput:
    locs = _listify(input_loc)
    confs = _listify(input_conf)
    assert len(locs) == len(confs)
    return make_layer("multibox_loss", name,
                      [priorbox, label] + locs + confs,
                      input_num=len(locs), num_classes=num_classes,
                      overlap_threshold=overlap_threshold,
                      neg_pos_ratio=neg_pos_ratio, neg_overlap=neg_overlap,
                      background_id=background_id)


def detection_output(input_loc, input_conf, priorbox, num_classes: int,
                     nms_threshold: float = 0.45, nms_top_k: int = 400,
                     keep_top_k: int = 200,
                     confidence_threshold: float = 0.01,
                     background_id: int = 0, name=None, **kw) -> LayerOutput:
    locs = _listify(input_loc)
    confs = _listify(input_conf)
    assert len(locs) == len(confs)
    return make_layer("detection_output", name, [priorbox] + locs + confs,
                      input_num=len(locs), num_classes=num_classes,
                      nms_threshold=nms_threshold, nms_top_k=nms_top_k,
                      keep_top_k=keep_top_k,
                      confidence_threshold=confidence_threshold,
                      background_id=background_id)


# ---------------------------------------------------------------------------
# bilinear / addressing / normalization extras
# (reference layers.py tensor_layer:4714, conv_shift_layer:4659,
#  linear_comb_layer:4604, prelu_layer:6262, row_l2_norm_layer:2889,
#  switch_order_layer:6445)


def tensor(a, b, size: int, act=None, name=None, param_attr=None,
           bias_attr=None, **kw) -> LayerOutput:
    return make_layer("tensor", name, [a, b], size=size,
                      act=act_mod.to_name(act), param_attr=param_attr,
                      bias_attr=bias_attr)


tensor_layer = tensor


def conv_shift(a, b, name=None, **kw) -> LayerOutput:
    return make_layer("conv_shift", name, [a, b])


conv_shift_layer = conv_shift


def linear_comb(weights, vectors, size: int = None, name=None,
                **kw) -> LayerOutput:
    return make_layer("convex_comb", name, [weights, vectors], size=size)


linear_comb_layer = linear_comb
convex_comb_layer = linear_comb


def prelu(input, partial_sum: int = 1, name=None, param_attr=None,
          **kw) -> LayerOutput:
    return make_layer("prelu", name, [input], partial_sum=partial_sum,
                      param_attr=param_attr)


prelu_layer = prelu


def row_l2_norm(input, name=None, **kw) -> LayerOutput:
    return make_layer("row_l2_norm", name, [input])


row_l2_norm_layer = row_l2_norm


def switch_order(input, reshape_axis=None, height=None, width=None,
                 name=None, **kw) -> LayerOutput:
    return make_layer("switch_order", name, [input], height=height,
                      width=width)


switch_order_layer = switch_order


def layer_norm(input, name=None, param_attr=None, **kw) -> LayerOutput:
    """Per-position layer normalization (modern extra for the
    transformer zoo)."""
    return make_layer("layer_norm", name, [input], param_attr=param_attr)
