"""Linear-chain CRF and CTC layers.

Reference: gserver/layers/{CRFLayer, CRFDecodingLayer, LinearChainCRF.cpp}
(forward-algorithm NLL + viterbi decode; parameter layout (n+2, n): row 0 =
start scores a, row 1 = end scores b, rows 2.. = transition matrix w — see
LinearChainCRF.h comments) and {CTCLayer, LinearChainCTC.cpp,
WarpCTCLayer.cpp}. CTC uses the in-tree lattice forward algorithm
(paddle_tpu/ops/ctc.py, LinearChainCTC.cpp parity).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core import initializers
from paddle_tpu.core.registry import (LayerMeta, ParamAttr, ParamSpec,
                                      make_layer, register_layer)
from paddle_tpu.core.sequence import SequenceBatch

_NEG = -1e30


def crf_nll(emissions: jnp.ndarray, labels: jnp.ndarray, lengths: jnp.ndarray,
            start: jnp.ndarray, end: jnp.ndarray,
            trans: jnp.ndarray) -> jnp.ndarray:
    """Negative log-likelihood of label paths under a linear-chain CRF.

    emissions: [b, T, n]; labels: [b, T] int; lengths: [b];
    start,end: [n]; trans: [n, n] (trans[i, j] = score i -> j).
    """
    b, T, n = emissions.shape
    labels = labels.astype(jnp.int32)

    # --- score of the gold path ---------------------------------------
    t_idx = jnp.arange(T, dtype=jnp.int32)[None, :]
    valid = (t_idx < lengths[:, None])
    emit_scores = jnp.take_along_axis(emissions, labels[..., None],
                                      axis=-1)[..., 0]
    gold_emit = jnp.sum(jnp.where(valid, emit_scores, 0.0), axis=1)
    prev_lab = labels[:, :-1]
    next_lab = labels[:, 1:]
    trans_scores = trans[prev_lab, next_lab]
    pair_valid = valid[:, 1:]
    gold_trans = jnp.sum(jnp.where(pair_valid, trans_scores, 0.0), axis=1)
    first_lab = labels[:, 0]
    last_idx = jnp.maximum(lengths - 1, 0)
    last_lab = jnp.take_along_axis(labels, last_idx[:, None], axis=1)[:, 0]
    gold = gold_emit + gold_trans + start[first_lab] + end[last_lab]

    # --- log partition via forward algorithm ---------------------------
    def step(alpha, inp):
        t, e_t = inp                                  # e_t: [b, n]
        prev = alpha[:, :, None] + trans[None, :, :]  # [b, n, n]
        new = jax.nn.logsumexp(prev, axis=1) + e_t
        keep = (t < lengths)[:, None]
        return jnp.where(keep, new, alpha), None

    alpha0 = start[None, :] + emissions[:, 0, :]
    es = jnp.moveaxis(emissions[:, 1:, :], 1, 0)
    ts = jnp.arange(1, T, dtype=jnp.int32)
    alphaT, _ = lax.scan(step, alpha0, (ts, es))
    log_z = jax.nn.logsumexp(alphaT + end[None, :], axis=-1)
    return log_z - gold


def crf_viterbi(emissions: jnp.ndarray, lengths: jnp.ndarray,
                start: jnp.ndarray, end: jnp.ndarray,
                trans: jnp.ndarray) -> jnp.ndarray:
    """Viterbi decode -> best path [b, T] (padding positions hold 0)."""
    b, T, n = emissions.shape

    def fwd(carry, inp):
        t, e_t = inp
        score = carry
        cand = score[:, :, None] + trans[None, :, :]      # [b, n_prev, n]
        best_prev = jnp.argmax(cand, axis=1)              # [b, n]
        new = jnp.max(cand, axis=1) + e_t
        keep = (t < lengths)[:, None]
        new = jnp.where(keep, new, score)
        return new, best_prev

    score0 = start[None, :] + emissions[:, 0, :]
    es = jnp.moveaxis(emissions[:, 1:, :], 1, 0)
    ts = jnp.arange(1, T, dtype=jnp.int32)
    scoreT, backptrs = lax.scan(fwd, score0, (ts, es))    # backptrs [T-1,b,n]
    last = jnp.argmax(scoreT + end[None, :], axis=-1)     # [b]

    def bwd(carry, inp):
        t, bp_t = inp                                     # bp_t: [b, n]
        lab = carry
        prev = jnp.take_along_axis(bp_t, lab[:, None], axis=1)[:, 0]
        # only move back while t < length (position t is inside the sequence)
        lab_new = jnp.where(t < lengths, prev, lab)
        return lab_new, lab_new

    ts_rev = jnp.arange(1, T, dtype=jnp.int32)[::-1]
    bp_rev = backptrs[::-1]
    _, labs_rev = lax.scan(bwd, last, (ts_rev, bp_rev))   # labels for t-1
    path = jnp.concatenate([labs_rev[::-1].T, last[:, None]], axis=1)  # [b, T]
    t_idx = jnp.arange(T, dtype=jnp.int32)[None, :]
    return jnp.where(t_idx < lengths[:, None], path, 0)


def _crf_param_specs(name, cfg, n):
    a = ParamAttr.of(cfg.get("param_attr"))
    pname = a.name or f"_{name}.w0"
    cfg["_w_name"] = pname
    # (n+2, n) layout matching LinearChainCRF.h: [start; end; trans]
    return [ParamSpec(pname, (n + 2, n),
                      a.initializer or initializers.normal(0.01), a)]


@register_layer("crf")
class CRFLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        n = cfg.get("size") or input_metas[0].size
        return LayerMeta(size=1), _crf_param_specs(name, cfg, n), []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq: SequenceBatch = inputs[0]
        labels = inputs[1]
        lab = labels.data if isinstance(labels, SequenceBatch) else labels
        w = params[cfg["_w_name"]]
        start, endw, trans = w[0], w[1], w[2:]
        return crf_nll(seq.data, lab, seq.lengths, start, endw, trans)


@register_layer("crf_decoding")
class CRFDecodingLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        n = cfg.get("size") or input_metas[0].size
        return LayerMeta(size=1, seq_level=1,
                         is_integer=True), _crf_param_specs(name, cfg, n), []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        seq: SequenceBatch = inputs[0]
        w = params[cfg["_w_name"]]
        path = crf_viterbi(seq.data, seq.lengths, w[0], w[1], w[2:])
        if len(inputs) > 1:
            # with a label input, output per-position error indicator
            labels = inputs[1]
            lab = labels.data if isinstance(labels, SequenceBatch) else labels
            err = (path != lab).astype(jnp.float32)
            return seq.with_data(err)
        return SequenceBatch(path, seq.lengths)


@register_layer("crf_error")
class CRFDecodingErrorLayer(CRFDecodingLayer):
    """Alias registration for decoding-error output (Layer.h:30
    REGISTER_LAYER(crf_error, CRFDecodingErrorLayer)): viterbi-decode and
    emit the per-position 0/1 disagreement with the label, which is what
    CRFDecodingLayer already does when given a label input."""

    @staticmethod
    def build(name, cfg, input_metas):
        assert len(input_metas) == 2, "crf_error needs emissions + label"
        return CRFDecodingLayer.build(name, cfg, input_metas)


@register_layer("ctc")
class CTCLayer:
    @staticmethod
    def build(name, cfg, input_metas):
        return LayerMeta(size=1), [], []

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        from paddle_tpu.ops.ctc import ctc_loss
        seq: SequenceBatch = inputs[0]       # [b, T, n] probs or logits
        labels: SequenceBatch = inputs[1]    # [b, U] int
        logits = seq.data
        # reference ctc_layer consumes SOFTMAX output (CTCLayer::forward
        # works on normalized probs), so the default converts probs ->
        # log-space; warp_ctc passes from_logits=True (raw activations,
        # warp-ctc softmaxes internally — here ctc_loss's internal
        # log_softmax does, a no-op on already-normalized log-probs).
        if not cfg.get("from_logits", False):
            logits = jnp.log(jnp.maximum(logits, 1e-10))
        logit_pad = 1.0 - seq.mask()
        lab = labels.data if isinstance(labels, SequenceBatch) else labels
        lab_pad = 1.0 - labels.mask() if isinstance(labels, SequenceBatch) \
            else jnp.zeros_like(lab, jnp.float32)
        # Blank convention (resolved against the reference):
        # LinearChainCTC.cpp:86 pins blank = numClasses-1 (the LAST id) —
        # `ctc` therefore defaults to last; WarpCTCLayer.cpp:33 reads a
        # configurable blank from config (proto default 0) — `warp_ctc`
        # passes blank=0 unless overridden.
        blank = cfg.get("blank")
        if blank is None:
            blank = logits.shape[-1] - 1
        return ctc_loss(logits, logit_pad, lab.astype(jnp.int32),
                        lab_pad, blank_id=blank)


@register_layer("warp_ctc")
class WarpCTCLayer(CTCLayer):
    """WarpCTCLayer.cpp:22 registers a distinct type; configs naming it
    must resolve AND get warp-ctc semantics even when the config blob
    carries only the type name: raw-logits input, blank=0
    (WarpCTCLayer.cpp:33) — not the ctc layer's probs/blank=last."""

    @staticmethod
    def apply(ctx, name, cfg, params, inputs):
        cfg = {"from_logits": True, "blank": 0, **cfg}
        return CTCLayer.apply(ctx, name, cfg, params, inputs)


def crf(input, label, size=None, param_attr=None, name=None, **kw):
    return make_layer("crf", name, [input, label], size=size,
                      param_attr=param_attr)


crf_layer = crf


def crf_decoding(input, size=None, label=None, param_attr=None, name=None, **kw):
    nodes = [input] + ([label] if label is not None else [])
    return make_layer("crf_decoding", name, nodes, size=size,
                      param_attr=param_attr)


crf_decoding_layer = crf_decoding


def crf_error(input, label, size=None, param_attr=None, name=None, **kw):
    return make_layer("crf_error", name, [input, label], size=size,
                      param_attr=param_attr)


def ctc(input, label, size=None, blank=None, name=None, **kw):
    """CTC cost; blank defaults to the LAST class id (LinearChainCTC.cpp:86
    convention)."""
    return make_layer("ctc", name, [input, label], size=size, blank=blank)


ctc_layer = ctc


def warp_ctc(input, label, size=None, blank=0, name=None, **kw):
    """warp_ctc parity — same XLA CTC under the hood; blank configurable,
    default 0 (WarpCTCLayer.cpp:33 / ModelConfig blank default)."""
    return make_layer("warp_ctc", name, [input, label], size=size,
                      blank=blank, from_logits=True)
