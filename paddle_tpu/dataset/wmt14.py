"""WMT14 French->English — v2/dataset/wmt14.py parity.

Samples: (src_ids, trg_ids, trg_next_ids) id sequences; trg starts with
<s> (START), trg_next ends with <e> (END). Real data:
DATA_HOME/wmt14/{train,test}.{src,trg} — parallel files, one tokenized
sentence per line, ids or words; otherwise synthetic "copy-ish" pairs."""

from __future__ import annotations

import os

import numpy as np

from paddle_tpu.dataset import common

START = 0      # <s>
END = 1        # <e>
UNK = 2        # <unk>
DEFAULT_DICT_SIZE = 30000


def _encode(line, vocab, dict_size):
    toks = line.strip().split()
    out = []
    for t in toks:
        if t.isdigit():
            out.append(min(int(t), dict_size - 1))
        else:
            out.append(vocab.setdefault(t, 3 + len(vocab) % (dict_size - 3)))
    return out


def _parse_real(src_path, trg_path, dict_size):
    sv, tv = {}, {}
    with open(src_path, encoding="utf8") as fs, \
            open(trg_path, encoding="utf8") as ft:
        for s_line, t_line in zip(fs, ft):
            src = _encode(s_line, sv, dict_size)
            trg = _encode(t_line, tv, dict_size)
            if not src or not trg:
                continue
            yield src, [START] + trg, trg + [END]


def _synthetic(n, dict_size, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        ln = int(rng.randint(3, 12))
        src = [int(w) for w in rng.randint(3, dict_size, ln)]
        trg = [(w + 1) % dict_size for w in src]     # learnable mapping
        yield src, [START] + trg, trg + [END]


def _reader(split, n_syn, seed, dict_size):
    src_p = os.path.join(common.DATA_HOME, "wmt14", f"{split}.src")
    trg_p = os.path.join(common.DATA_HOME, "wmt14", f"{split}.trg")

    def reader():
        if os.path.exists(src_p) and os.path.exists(trg_p):
            yield from _parse_real(src_p, trg_p, dict_size)
        else:
            yield from _synthetic(n_syn, dict_size, seed)
    return reader


def train(dict_size: int = DEFAULT_DICT_SIZE):
    return _reader("train", 2000, 14, dict_size)


def test(dict_size: int = DEFAULT_DICT_SIZE):
    return _reader("test", 400, 15, dict_size)


def convert(path, dict_size: int = DEFAULT_DICT_SIZE):
    """RecordIO shards for cloud dispatch (v2/dataset/wmt14.py parity)."""
    from paddle_tpu.dataset import common
    common.convert(path, train(dict_size), 1000, "wmt14-train")
    common.convert(path, test(dict_size), 1000, "wmt14-test")
