"""IMDB sentiment — python/paddle/v2/dataset/imdb.py parity.
Samples: (token ids int64[seq_len], label 0/1). Synthetic fallback matches
the benchmark config (dict 30k, seq ~100) from benchmark/paddle/rnn."""

from __future__ import annotations

from paddle_tpu.dataset import synthetic

_VOCAB = 30000


def word_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _reader(n, seed):
    def reader():
        for toks, lab in synthetic.token_sequences(
                n, _VOCAB, 2, seed, min_len=50, max_len=100,
                profile_seed=1000):
            yield toks, lab
    return reader


def train(word_idx=None):
    return _reader(4096, 11)


def test(word_idx=None):
    return _reader(512, 12)


def convert(path):
    """RecordIO shards for cloud dispatch (v2/dataset/imdb.py parity)."""
    from paddle_tpu.dataset import common
    w = word_dict()
    common.convert(path, train(w), 1000, "imdb-train")
    common.convert(path, test(w), 1000, "imdb-test")
