"""Deterministic synthetic data generators shared by the dataset loaders."""

from __future__ import annotations

import numpy as np


def class_clustered(n: int, dim: int, n_classes: int, seed: int,
                    noise: float = 0.7, center_seed: int = None):
    """Per-class Gaussian clusters — linearly separable-ish, so models
    actually converge (lets convergence tests assert decreasing loss).

    center_seed fixes the class centers independently of the sample seed so
    a train/test pair drawn with different `seed`s shares the same underlying
    classes (otherwise test accuracy on the synthetic fallback is noise)."""
    rng_c = np.random.RandomState(center_seed if center_seed is not None
                                  else seed)
    centers = rng_c.randn(n_classes, dim).astype(np.float32) * 1.5
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, size=n).astype(np.int64)
    feats = centers[labels] + noise * rng.randn(n, dim).astype(np.float32)
    return feats.astype(np.float32), labels


def token_sequences(n: int, vocab: int, n_classes: int, seed: int,
                    min_len: int = 10, max_len: int = 100,
                    profile_seed: int = None):
    """Class-conditioned token sequences: each class draws from a distinct
    token-frequency profile, so bag-of-words/LSTM classifiers converge.

    profile_seed fixes the class profiles independently of the sample seed
    (same reason as class_clustered's center_seed: train/test must share
    classes)."""
    rng_p = np.random.RandomState(profile_seed if profile_seed is not None
                                  else seed)
    profiles = rng_p.dirichlet(np.ones(vocab) * 0.05, size=n_classes)
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        lab = int(rng.randint(n_classes))
        L = int(rng.randint(min_len, max_len + 1))
        toks = rng.choice(vocab, size=L, p=profiles[lab])
        out.append((toks.astype(np.int64), lab))
    return out


def regression(n: int, dim: int, seed: int, noise: float = 0.1):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim).astype(np.float32)
    x = rng.randn(n, dim).astype(np.float32)
    y = x @ w + noise * rng.randn(n).astype(np.float32)
    return x, y.astype(np.float32)
