"""MovieLens-1M — python/paddle/v2/dataset/movielens.py parity.

Samples: (user_id, gender_id, age_id, job_id, movie_id, category_ids,
title_ids, score). Real data: drop ml-1m's users.dat / movies.dat /
ratings.dat under DATA_HOME/movielens/; otherwise a deterministic
synthetic catalog with the same field ranges."""

from __future__ import annotations

import os
import re

import numpy as np

from paddle_tpu.dataset import common

AGES = [1, 18, 25, 35, 45, 50, 56]
MAX_JOB = 20
N_CATEGORIES = 18
TITLE_VOCAB = 5000


def _real_dir():
    d = os.path.join(common.DATA_HOME, "movielens")
    if all(os.path.exists(os.path.join(d, f))
           for f in ("users.dat", "movies.dat", "ratings.dat")):
        return d
    return None


def _load_real(d):
    users = {}
    with open(os.path.join(d, "users.dat"), encoding="latin1") as f:
        for line in f:
            uid, gender, age, job, _zip = line.strip().split("::")
            users[int(uid)] = (0 if gender == "F" else 1,
                               AGES.index(int(age)), int(job))
    movies, categories, title_vocab = {}, {}, {}
    with open(os.path.join(d, "movies.dat"), encoding="latin1") as f:
        for line in f:
            mid, title, cats = line.strip().split("::")
            cat_ids = [categories.setdefault(c, len(categories))
                       for c in cats.split("|")]
            words = re.sub(r"\(\d{4}\)$", "", title).strip().lower().split()
            tids = [title_vocab.setdefault(w, len(title_vocab))
                    for w in words]
            movies[int(mid)] = (cat_ids, tids)
    ratings = []
    with open(os.path.join(d, "ratings.dat"), encoding="latin1") as f:
        for line in f:
            uid, mid, score, _ts = line.strip().split("::")
            uid, mid = int(uid), int(mid)
            if uid in users and mid in movies:
                g, a, j = users[uid]
                cats, tids = movies[mid]
                ratings.append((uid, g, a, j, mid, cats, tids,
                                float(score)))
    return ratings


def _load_synthetic(n=8000, seed=1337):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        uid = int(rng.randint(1, max_user_id() + 1))
        mid = int(rng.randint(1, max_movie_id() + 1))
        cats = [int(c) for c in
                rng.randint(0, N_CATEGORIES, rng.randint(1, 4))]
        tids = [int(t) for t in
                rng.randint(0, TITLE_VOCAB, rng.randint(1, 6))]
        score = float(1 + (uid * 7 + mid * 13) % 5)   # learnable signal
        out.append((uid, int(rng.randint(2)), int(rng.randint(len(AGES))),
                    int(rng.randint(MAX_JOB + 1)), mid, cats, tids, score))
    return out


_cache = {}


def _load():
    # memoize per DATA_HOME (reference __initialize_meta_info__ parity —
    # don't re-parse ~1M ratings every pass)
    key = common.DATA_HOME
    if key not in _cache:
        d = _real_dir()
        _cache[key] = _load_real(d) if d else _load_synthetic()
    return _cache[key]


def max_user_id() -> int:
    return 6040


def max_movie_id() -> int:
    return 3952


def max_job_id() -> int:
    return MAX_JOB


def age_table():
    return list(AGES)


def movie_categories():
    return N_CATEGORIES


def train(seed: int = 0):
    def reader():
        data = _load()
        for i, s in enumerate(data):
            if i % 10 != 1:                 # ~90/10 split, deterministic
                yield s
    return reader


def test(seed: int = 0):
    def reader():
        data = _load()
        for i, s in enumerate(data):
            if i % 10 == 1:
                yield s
    return reader


def convert(path):
    """RecordIO shards for cloud dispatch (v2/dataset/movielens.py parity)."""
    from paddle_tpu.dataset import common
    common.convert(path, train(), 1000, "movielens-train")
    common.convert(path, test(), 1000, "movielens-test")
