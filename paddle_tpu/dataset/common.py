"""Dataset cache helpers.

Reference: python/paddle/v2/dataset/common.py (download + md5 cache under
~/.cache/paddle/dataset). This environment has no network egress, so every
loader first checks the cache dir for real data and otherwise falls back to
a DETERMINISTIC synthetic generator with the same shapes/vocab — keeping
demos, tests, and benchmarks hermetic. Drop real files into DATA_HOME to
train on true data with zero code changes.
"""

from __future__ import annotations

import os

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


#: optional per-module integrity manifest: DATA_HOME/<module>/MD5SUMS
#: with `md5sum`-format lines ("<hex digest>  <filename>"). When a real
#: file is listed there, has_cached()/verified loaders check it before
#: training on it — a corrupt/truncated drop-in WARNS and falls back to
#: the synthetic generator instead of silently training on garbage.
MANIFEST_NAME = "MD5SUMS"


def cache_path(module: str, filename: str) -> str:
    d = os.path.join(DATA_HOME, module)
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, filename)


def _manifest_md5(module: str, filename: str):
    """Expected digest for `filename` from the module's MD5SUMS manifest
    (None when no manifest or no entry)."""
    mpath = os.path.join(DATA_HOME, module, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 2 and parts[-1] in (
                        filename, "*" + filename):
                    return parts[0].lower()
    except OSError:
        return None
    return None


def file_md5(path: str) -> str:
    import hashlib
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def has_cached(module: str, filename: str, md5: str = None) -> bool:
    """True when a REAL data file is present (and intact). Integrity is
    checked against an explicit ``md5`` argument or the module's
    optional MD5SUMS manifest; on mismatch this WARNS and returns False
    so every loader falls back to its deterministic synthetic generator
    instead of training on corrupt data."""
    path = os.path.join(DATA_HOME, module, filename)
    if not os.path.exists(path):
        return False
    expected = (md5 or _manifest_md5(module, filename) or "").lower()
    if not expected:
        return True
    actual = file_md5(path)
    if actual == expected:
        return True
    import warnings
    warnings.warn(
        f"{path}: md5 mismatch (expected {expected}, got {actual}) — "
        "the file is corrupt or truncated; IGNORING it and falling back "
        "to the synthetic generator. Re-download it or fix the "
        f"{MANIFEST_NAME} entry.", stacklevel=2)
    return False


def convert(output_path: str, reader, line_count: int,
            name_prefix: str) -> list:
    """Emit a reader's samples as RecordIO shards for cloud dispatch —
    python/paddle/v2/dataset/common.py convert():143 parity. Each shard
    holds up to `line_count` pickled samples; the coordinator then
    partitions the shards' CHUNKS as tasks (go/master/service.go:106,
    chunk-as-task contract: reader/recordio.chunk_descriptors) and
    workers deserialize with `record_deserializer`.

    Returns the list of shard paths ({name_prefix}-{i:05d})."""
    import pickle

    from paddle_tpu.reader import recordio

    assert line_count >= 1
    os.makedirs(output_path, exist_ok=True)
    paths = []

    def write_shard(idx, lines):
        p = os.path.join(output_path, f"{name_prefix}-{idx:05d}")
        recordio.write_records(
            p, (pickle.dumps(l, protocol=pickle.HIGHEST_PROTOCOL)
                for l in lines))
        paths.append(p)

    lines = []
    for d in reader():
        lines.append(d)
        if len(lines) >= line_count:
            write_shard(len(paths), lines)
            lines = []
    if lines:
        write_shard(len(paths), lines)
    return paths


def record_deserializer(rec: bytes):
    """Inverse of convert()'s per-record pickling (for
    recordio.chunk_reader / coordinator task_reader).

    TRUST BOUNDARY: pickle executes arbitrary code on load, so shards and
    the coordinator handing them out must be as trusted as the training
    code itself — the same assumption the reference's cloud data path
    makes (its RecordIO chunks carry cPickle records too,
    python/paddle/v2/dataset/common.py:143). Do NOT point task_reader at
    shards from an untrusted writer; for data crossing a trust boundary,
    serialize samples yourself (npz/arrow/flat bytes) and hand convert()
    a reader that yields those."""
    import pickle
    return pickle.loads(rec)
