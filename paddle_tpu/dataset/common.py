"""Dataset cache helpers.

Reference: python/paddle/v2/dataset/common.py (download + md5 cache under
~/.cache/paddle/dataset). This environment has no network egress, so every
loader first checks the cache dir for real data and otherwise falls back to
a DETERMINISTIC synthetic generator with the same shapes/vocab — keeping
demos, tests, and benchmarks hermetic. Drop real files into DATA_HOME to
train on true data with zero code changes.
"""

from __future__ import annotations

import os

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def cache_path(module: str, filename: str) -> str:
    d = os.path.join(DATA_HOME, module)
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, filename)


def has_cached(module: str, filename: str) -> bool:
    return os.path.exists(os.path.join(DATA_HOME, module, filename))
