"""PTB (imikolov) language-model ngrams — dataset/imikolov.py parity.
Samples: n-gram tuples of word ids (for the word-embedding demo)."""

from __future__ import annotations

import numpy as np

from paddle_tpu.dataset import synthetic

_VOCAB = 2048


def build_dict(min_word_freq: int = 50):
    return {f"w{i}": i for i in range(_VOCAB)}


def _ngram_reader(n_samples, n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        seqs = synthetic.token_sequences(n_samples // 16 + 1, _VOCAB, 4, seed,
                                         min_len=n * 8, max_len=n * 16)
        count = 0
        for toks, _ in seqs:
            for i in range(len(toks) - n + 1):
                yield tuple(int(t) for t in toks[i:i + n])
                count += 1
                if count >= n_samples:
                    return
    return reader


def train(word_idx=None, n: int = 5):
    return _ngram_reader(8192, n, 21)


def test(word_idx=None, n: int = 5):
    return _ngram_reader(1024, n, 22)


def convert(path):
    """RecordIO shards for cloud dispatch (v2/dataset/imikolov.py parity)."""
    from paddle_tpu.dataset import common
    w = build_dict()
    common.convert(path, train(w), 1000, "imikolov-train")
    common.convert(path, test(w), 1000, "imikolov-test")
