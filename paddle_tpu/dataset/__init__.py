from paddle_tpu.dataset import mnist, cifar, uci_housing, imdb, imikolov
from paddle_tpu.dataset import synthetic, common

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "imikolov", "synthetic",
           "common"]
