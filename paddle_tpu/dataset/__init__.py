from paddle_tpu.dataset import mnist, cifar, uci_housing, imdb, imikolov
from paddle_tpu.dataset import (conll05, flowers, movielens, mq2007,
                                sentiment, voc2012, wmt14)
from paddle_tpu.dataset import synthetic, common

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "imikolov", "conll05",
           "flowers", "movielens", "mq2007", "sentiment", "voc2012",
           "wmt14", "synthetic", "common"]
