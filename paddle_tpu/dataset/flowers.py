"""Oxford-102 flowers — v2/dataset/flowers.py parity.

Samples: (image float32[3*H*W] flattened channel-major, label int
0..101). Real data: DATA_HOME/flowers/{train,valid,test}.npz with arrays
`images` [n, 3, H, W] uint8/float and `labels` [n] (decode the jpgs once
into that cache — image codecs stay out of the loader); otherwise
deterministic synthetic images whose class tints the channels."""

from __future__ import annotations

import os

import numpy as np

from paddle_tpu.dataset import common

N_CLASSES = 102
DEFAULT_SIZE = 32     # synthetic fallback resolution (3*32*32 features)


_real_cache = {}


def _real(split):
    if split in _real_cache:
        return _real_cache[split]
    p = os.path.join(common.DATA_HOME, "flowers", f"{split}.npz")
    if not os.path.exists(p):
        return None
    blob = np.load(p)
    imgs = blob["images"].astype(np.float32)
    if imgs.max() > 1.5:
        imgs = imgs / 255.0
    out = (imgs.reshape(len(imgs), -1), blob["labels"].astype(np.int64))
    _real_cache[split] = out
    return out


def _synthetic(split, n, seed, size=DEFAULT_SIZE):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, N_CLASSES, n)
    imgs = rng.rand(n, 3, size, size).astype(np.float32) * 0.3
    # class-dependent channel tint => linearly separable signal
    for c in range(3):
        imgs[:, c] += ((labels % (3 + c + 1)) / (3.0 + c)).reshape(-1, 1, 1)
    return imgs.reshape(n, -1), labels


def _reader(split, n_syn, seed):
    def reader():
        real = _real(split)
        x, y = real if real is not None else _synthetic(split, n_syn, seed)
        for i in range(len(x)):
            yield x[i], int(y[i])
    return reader


def train():
    return _reader("train", 1020, 41)


def valid():
    return _reader("valid", 306, 42)


def test():
    return _reader("test", 306, 43)


def convert(path):
    """RecordIO shards for cloud dispatch (v2/dataset/flowers.py parity)."""
    from paddle_tpu.dataset import common
    common.convert(path, train(), 200, "flowers-train")
    common.convert(path, valid(), 200, "flowers-valid")
    common.convert(path, test(), 200, "flowers-test")
