"""PASCAL VOC2012 segmentation — v2/dataset/voc2012.py parity.

Samples: (image float32[3*H*W], label int32[H*W] class map 0..20 with 255
= void). Real data: DATA_HOME/voc2012/{train,val}.npz with `images`
[n, 3, H, W] and `masks` [n, H, W] (decode the VOC jpg/png pairs into
that cache once); otherwise synthetic scenes of class-colored rectangles
with a consistent mask."""

from __future__ import annotations

import os

import numpy as np

from paddle_tpu.dataset import common

N_CLASSES = 21
VOID = 255
DEFAULT_SIZE = 32


_real_cache = {}


def _real(split):
    if split in _real_cache:
        return _real_cache[split]
    p = os.path.join(common.DATA_HOME, "voc2012", f"{split}.npz")
    if not os.path.exists(p):
        return None
    blob = np.load(p)
    imgs = blob["images"].astype(np.float32)
    if imgs.max() > 1.5:
        imgs = imgs / 255.0
    out = (imgs.reshape(len(imgs), -1),
           blob["masks"].astype(np.int32).reshape(len(imgs), -1))
    _real_cache[split] = out
    return out


def _synthetic(n, seed, size=DEFAULT_SIZE):
    rng = np.random.RandomState(seed)
    imgs = np.zeros((n, 3, size, size), np.float32)
    masks = np.zeros((n, size, size), np.int32)
    for i in range(n):
        for _ in range(int(rng.randint(1, 4))):
            c = int(rng.randint(1, N_CLASSES))
            x0, y0 = rng.randint(0, size // 2, 2)
            w, h = rng.randint(4, size // 2, 2)
            masks[i, y0:y0 + h, x0:x0 + w] = c
            imgs[i, :, y0:y0 + h, x0:x0 + w] = \
                (np.array([c % 3, c % 5, c % 7], np.float32) / 7.0
                 ).reshape(3, 1, 1)
        imgs[i] += 0.05 * rng.rand(3, size, size)
    return imgs.reshape(n, -1), masks.reshape(n, -1)


def _reader(split, n_syn, seed):
    def reader():
        real = _real(split)
        x, y = real if real is not None else _synthetic(n_syn, seed)
        for i in range(len(x)):
            yield x[i], y[i]
    return reader


def train():
    return _reader("train", 400, 51)


def val():
    return _reader("val", 100, 52)


test = val


def convert(path):
    """RecordIO shards for cloud dispatch (v2/dataset/voc2012.py parity)."""
    from paddle_tpu.dataset import common
    common.convert(path, train(), 200, "voc2012-train")
    common.convert(path, val(), 200, "voc2012-val")
