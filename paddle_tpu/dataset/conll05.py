"""CoNLL-2005 semantic role labeling — v2/dataset/conll05.py parity.

Samples (the 9-slot SRL layout the sequence_tagging demo feeds):
  (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, verb_ids, mark_ids,
   label_ids) — all equal-length id sequences per sentence.
Real data: DATA_HOME/conll05/{train,test}.txt with lines
  "word<TAB>verb<TAB>label", blank line between sentences; otherwise a
deterministic synthetic corpus over the same dict sizes."""

from __future__ import annotations

import os

import numpy as np

from paddle_tpu.dataset import common

WORD_DICT_LEN = 44068
LABEL_DICT_LEN = 106
PRED_DICT_LEN = 3162


def word_dict_len() -> int:
    return WORD_DICT_LEN


def label_dict_len() -> int:
    return LABEL_DICT_LEN


def pred_dict_len() -> int:
    return PRED_DICT_LEN


def get_dict():
    """(word_dict, verb_dict, label_dict) as id maps (synthetic: ranges)."""
    return ({i: i for i in range(WORD_DICT_LEN)},
            {i: i for i in range(PRED_DICT_LEN)},
            {i: i for i in range(LABEL_DICT_LEN)})


def _ctx(words, i, off):
    j = min(max(i + off, 0), len(words) - 1)
    return words[j]


def _to_sample(words, verb, marks, labels):
    n = len(words)
    return (words,
            [_ctx(words, i, -2) for i in range(n)],
            [_ctx(words, i, -1) for i in range(n)],
            list(words),
            [_ctx(words, i, 1) for i in range(n)],
            [_ctx(words, i, 2) for i in range(n)],
            [verb] * n, marks, labels)


def _parse_real(path):
    """One SRL sample PER PREDICATE (the reference yields a separate
    sample for each predicate, marks set only at that predicate)."""
    wd, vd, ld = {}, {}, {}

    def emit(rows):
        words = [wd.setdefault(w, len(wd)) % WORD_DICT_LEN
                 for w, _, _ in rows]
        labels = [ld.setdefault(l, len(ld)) % LABEL_DICT_LEN
                  for _, _, l in rows]
        for pos, (_, v, _) in enumerate(rows):
            if v in ("-", "_"):
                continue
            verb = vd.setdefault(v, len(vd)) % PRED_DICT_LEN
            marks = [1 if i == pos else 0 for i in range(len(rows))]
            yield _to_sample(words, verb, marks, labels)

    rows = []
    with open(path, encoding="utf8") as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                yield from emit(rows)
                rows = []
                continue
            w, v, l = (line.split("\t") + ["-", "O"])[:3]
            rows.append((w, v, l))
    yield from emit(rows)


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        ln = int(rng.randint(4, 20))
        words = [int(w) for w in rng.randint(0, WORD_DICT_LEN, ln)]
        pred_pos = int(rng.randint(ln))
        marks = [1 if i == pred_pos else 0 for i in range(ln)]
        verb = int(rng.randint(PRED_DICT_LEN))
        labels = [int(l) for l in rng.randint(0, LABEL_DICT_LEN, ln)]
        yield _to_sample(words, verb, marks, labels)


def _reader(split, n_syn, seed):
    path = os.path.join(common.DATA_HOME, "conll05", f"{split}.txt")

    def reader():
        if os.path.exists(path):
            yield from _parse_real(path)
        else:
            yield from _synthetic(n_syn, seed)
    return reader


def train():
    return _reader("train", 2000, 5)


def test():
    return _reader("test", 400, 6)


def convert(path):
    """RecordIO shards for cloud dispatch (v2/dataset/conll05.py parity)."""
    from paddle_tpu.dataset import common
    common.convert(path, test(), 1000, "conll05-test")
