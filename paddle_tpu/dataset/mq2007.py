"""MQ2007 learning-to-rank (LETOR 4.0) — v2/dataset/mq2007.py parity.

Modes (the reference's pointwise/pairwise/listwise readers):
  train/test(format="pointwise") -> (features[46], relevance)
  ...("pairwise")                -> (better_features, worse_features)
  ...("listwise")                -> (query_id, [features...], [labels...])
Real data: DATA_HOME/mq2007/{train,test}.txt in LETOR format
("rel qid:ID 1:v 2:v ... # docid"); otherwise synthetic queries whose
relevance is a noisy linear function of the features."""

from __future__ import annotations

import os
from collections import OrderedDict

import numpy as np

from paddle_tpu.dataset import common

FEATURE_DIM = 46


def _parse_real(path):
    queries = OrderedDict()
    with open(path, encoding="utf8") as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            rel = int(parts[0])
            qid = parts[1].split(":")[1]
            feats = np.zeros(FEATURE_DIM, np.float32)
            for p in parts[2:]:
                k, v = p.split(":")
                k = int(k) - 1
                if 0 <= k < FEATURE_DIM:
                    feats[k] = float(v)
            queries.setdefault(qid, []).append((feats, rel))
    return queries


def _synthetic(n_queries, seed):
    rng = np.random.RandomState(seed)
    w = rng.randn(FEATURE_DIM)
    queries = OrderedDict()
    for q in range(n_queries):
        docs = []
        for _ in range(int(rng.randint(5, 15))):
            f = rng.randn(FEATURE_DIM).astype(np.float32)
            score = float(f @ w) + 0.1 * rng.randn()
            rel = int(np.clip(np.digitize(score, [-3, 3]), 0, 2))
            docs.append((f, rel))
        queries[f"q{q}"] = docs
    return queries


_cache = {}


def _load(split, n_syn, seed):
    key = (common.DATA_HOME, split)
    if key not in _cache:
        path = os.path.join(common.DATA_HOME, "mq2007", f"{split}.txt")
        _cache[key] = _parse_real(path) if os.path.exists(path) \
            else _synthetic(n_syn, seed)
    return _cache[key]


def _reader(split, fmt, n_syn, seed):
    def pointwise():
        for docs in _load(split, n_syn, seed).values():
            for f, rel in docs:
                yield f, float(rel)

    def pairwise():
        for docs in _load(split, n_syn, seed).values():
            for i, (fi, ri) in enumerate(docs):
                for fj, rj in docs[i + 1:]:
                    if ri > rj:
                        yield fi, fj
                    elif rj > ri:
                        yield fj, fi

    def listwise():
        for qi, (qid, docs) in enumerate(
                _load(split, n_syn, seed).items()):
            yield (qi, [f for f, _ in docs], [float(r) for _, r in docs])

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[fmt]


def train(format: str = "pointwise"):
    return _reader("train", format, 120, 31)


def test(format: str = "pointwise"):
    return _reader("test", format, 30, 32)


def convert(path):
    """RecordIO shards for cloud dispatch."""
    from paddle_tpu.dataset import common
    common.convert(path, train(), 1000, "mq2007-train")
    common.convert(path, test(), 1000, "mq2007-test")
