"""MNIST loader — python/paddle/v2/dataset/mnist.py parity.

Samples are (image: float32[784] scaled to [-1, 1], label: int). Reads the
standard IDX files from the cache dir when present; otherwise falls back to
a deterministic synthetic set with the same shapes (see common.py).
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from paddle_tpu.dataset import common, synthetic

_TRAIN_IMAGES = "train-images-idx3-ubyte.gz"
_TRAIN_LABELS = "train-labels-idx1-ubyte.gz"
_TEST_IMAGES = "t10k-images-idx3-ubyte.gz"
_TEST_LABELS = "t10k-labels-idx1-ubyte.gz"


def _read_idx(images_path: str, labels_path: str):
    with gzip.open(labels_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)
    with gzip.open(images_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    images = images.astype(np.float32) / 255.0 * 2.0 - 1.0
    return images, labels


def _reader(images_file, labels_file, synth_n, synth_seed):
    def reader():
        ip = os.path.join(common.DATA_HOME, "mnist", images_file)
        lp = os.path.join(common.DATA_HOME, "mnist", labels_file)
        # has_cached verifies the optional MD5SUMS manifest: a corrupt
        # drop-in warns and falls back to synthetic (common.py)
        if common.has_cached("mnist", images_file) and \
                common.has_cached("mnist", labels_file):
            images, labels = _read_idx(ip, lp)
        else:
            images, labels = synthetic.class_clustered(
                synth_n, 784, 10, synth_seed, center_seed=99)
            images = np.clip(images, -1.0, 1.0)
        for i in range(len(labels)):
            yield images[i], int(labels[i])
    return reader


def train():
    return _reader(_TRAIN_IMAGES, _TRAIN_LABELS, 8192, 1234)


def test():
    return _reader(_TEST_IMAGES, _TEST_LABELS, 1024, 4321)


def convert(path):
    """Emit train/test as RecordIO shards for the cloud data path
    (python/paddle/v2/dataset/mnist.py:107 parity)."""
    from paddle_tpu.dataset import common
    common.convert(path, train(), 1000, "mnist-train")
    common.convert(path, test(), 1000, "mnist-test")
