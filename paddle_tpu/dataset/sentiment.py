"""Movie-review sentiment (NLTK corpus) — v2/dataset/sentiment.py parity.

Samples: (word_ids, label) with label 0=negative, 1=positive. Real data:
DATA_HOME/sentiment/{train,test}.txt lines "label<TAB>word word ...";
otherwise deterministic synthetic reviews with a sentiment-bearing
vocabulary split."""

from __future__ import annotations

import os

import numpy as np

from paddle_tpu.dataset import common

WORD_DICT_LEN = 5147


def get_word_dict():
    return {i: i for i in range(WORD_DICT_LEN)}


def _parse_real(path):
    vocab = {}
    with open(path, encoding="utf8") as f:
        for line in f:
            parts = line.rstrip("\n").split("\t", 1)
            if len(parts) != 2:
                continue
            label, text = parts
            ids = [vocab.setdefault(w, len(vocab) % WORD_DICT_LEN)
                   for w in text.split()]
            if ids:
                yield ids, int(label)


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    half = WORD_DICT_LEN // 2
    for _ in range(n):
        label = int(rng.randint(2))
        ln = int(rng.randint(5, 40))
        base = rng.randint(0, half, ln)
        ids = [int(w + (half if label else 0)) for w in base]
        yield ids, label


def _reader(split, n_syn, seed):
    path = os.path.join(common.DATA_HOME, "sentiment", f"{split}.txt")

    def reader():
        if os.path.exists(path):
            yield from _parse_real(path)
        else:
            yield from _synthetic(n_syn, seed)
    return reader


def train():
    return _reader("train", 1600, 21)


def test():
    return _reader("test", 400, 22)


def convert(path):
    """RecordIO shards for cloud dispatch (v2/dataset/sentiment.py parity)."""
    from paddle_tpu.dataset import common
    common.convert(path, train(), 1000, "sentiment-train")
    common.convert(path, test(), 1000, "sentiment-test")
