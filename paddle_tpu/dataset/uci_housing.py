"""UCI housing regression — python/paddle/v2/dataset/uci_housing.py parity.
Samples: (features float32[13], price float32[1])."""

from __future__ import annotations

import os

import numpy as np

from paddle_tpu.dataset import common, synthetic

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS",
                 "RAD", "TAX", "PTRATIO", "B", "LSTAT"]


def _load():
    p = os.path.join(common.DATA_HOME, "uci_housing", "housing.data")
    if os.path.exists(p):
        data = np.loadtxt(p).astype(np.float32)
        x, y = data[:, :13], data[:, 13:14]
        x = (x - x.mean(0)) / (x.std(0) + 1e-6)
        return x, y
    x, y = synthetic.regression(506, 13, seed=13)
    return x.astype(np.float32), y[:, None].astype(np.float32)


def train():
    def reader():
        x, y = _load()
        n = int(len(x) * 0.8)
        for i in range(n):
            yield x[i], y[i]
    return reader


def test():
    def reader():
        x, y = _load()
        n = int(len(x) * 0.8)
        for i in range(n, len(x)):
            yield x[i], y[i]
    return reader


def convert(path):
    """Emit train/test as RecordIO shards
    (python/paddle/v2/dataset/uci_housing.py convert parity)."""
    from paddle_tpu.dataset import common
    common.convert(path, train(), 100, "uci_housing-train")
    common.convert(path, test(), 100, "uci_housing-test")
