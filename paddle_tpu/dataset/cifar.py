"""CIFAR-10/100 loader — python/paddle/v2/dataset/cifar.py parity.

Samples are (image: float32[3072] channel-major scaled to [0,1], label).
Falls back to synthetic class-clustered images.
"""

from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from paddle_tpu.dataset import common, synthetic


def _synthetic_reader(n, n_classes, seed):
    def reader():
        feats, labels = synthetic.class_clustered(n, 3072, n_classes, seed,
                                                  noise=0.5, center_seed=n_classes)
        feats = (feats - feats.min()) / (feats.max() - feats.min() + 1e-6)
        for i in range(n):
            yield feats[i].astype(np.float32), int(labels[i])
    return reader


def _tar_reader(path, members_prefix, n_classes):
    def reader():
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                if members_prefix in m.name and ("data_batch" in m.name or
                                                 "test_batch" in m.name or
                                                 "train" in m.name):
                    d = pickle.loads(tf.extractfile(m).read(),
                                     encoding="bytes")
                    data = d[b"data"].astype(np.float32) / 255.0
                    labels = d.get(b"labels", d.get(b"fine_labels"))
                    for x, y in zip(data, labels):
                        yield x, int(y)
    return reader


def train10():
    p = os.path.join(common.DATA_HOME, "cifar", "cifar-10-python.tar.gz")
    if os.path.exists(p):
        return _tar_reader(p, "data_batch", 10)
    return _synthetic_reader(8192, 10, 77)


def test10():
    p = os.path.join(common.DATA_HOME, "cifar", "cifar-10-python.tar.gz")
    if os.path.exists(p):
        return _tar_reader(p, "test_batch", 10)
    return _synthetic_reader(1024, 10, 78)


def train100():
    return _synthetic_reader(8192, 100, 79)


def test100():
    return _synthetic_reader(1024, 100, 80)


def convert(path):
    """Emit cifar-10/100 train/test as RecordIO shards
    (python/paddle/v2/dataset/cifar.py convert parity)."""
    from paddle_tpu.dataset import common
    common.convert(path, train100(), 1000, "cifar-100-train")
    common.convert(path, test100(), 1000, "cifar-100-test")
    common.convert(path, train10(), 1000, "cifar-10-train")
    common.convert(path, test10(), 1000, "cifar-10-test")
