"""Online/continuous training — the loop the 2017 pserver ran in prod.

Serving journals every ranked request (``embed/sample`` records: the
feature ids it looked up, and the click/label once feedback lands);
this module re-ingests that journal as a TRAINING stream through the
self-healing reader pipeline (:func:`reader.pipeline.supervised` —
crashed-worker restart, error-budget quarantine, stall watchdog) and
pushes the resulting sparse gradients back into the LIVE store through
the async :class:`EmbeddingClient` — while the same shards keep serving
lookups. Freshness loop closed: a click at time t reshapes the rows the
very next request gathers.

The model is the classic linear-over-embeddings CTR ranker:
``p = sigmoid(sum_i row(id_i) . w)`` — each sample's gradient touches
exactly its own rows (d row_i = (p - y) * w), which is what makes the
updates sparse and the pserver pattern work.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from paddle_tpu.embed.shard import _emit_embed

__all__ = ["log_sample", "journal_sample_reader", "OnlineTrainer",
           "run_online"]


def log_sample(ids: Sequence[int], label: float, **fields):
    """Journal one serving sample (domain ``embed``, kind ``sample``) —
    the feedback record the online loop trains from. Wire it as
    ``InferenceServer(sample_log=...)`` via :func:`serving_sample_log`,
    or call it directly where the label (click) becomes known."""
    _emit_embed("sample", ids=[int(i) for i in np.asarray(ids).reshape(-1)
                               if int(i) >= 0],
                label=float(label), **fields)


def serving_sample_log(label_fn: Optional[Callable] = None):
    """Adapter for ``InferenceServer(sample_log=...)``: journals every
    served batch's integer feature ids as ``embed/sample`` records.
    ``label_fn(sample) -> float`` supplies the label (default 0.0 — a
    served-not-yet-clicked impression; the click pipeline rewrites it
    by journaling the sample again with label 1.0)."""
    def hook(samples):
        for s in samples:
            ids = np.asarray(s[0] if isinstance(s, (tuple, list)) else s)
            label = float(label_fn(s)) if label_fn is not None else 0.0
            log_sample(ids.reshape(-1), label)
    return hook


def journal_sample_reader(path: str, *, domain: str = "embed",
                          kind: str = "sample"):
    """A v2 Reader factory (zero-arg callable -> iterable) over the
    journal's sample records — feed it to ``supervised()`` like any
    other source; rotated segments are spanned by ``read_journal``."""
    from paddle_tpu.obs.events import read_journal

    def reader():
        for rec in read_journal(path, domain=domain, kind=kind):
            yield (np.asarray(rec["ids"], np.int64),
                   float(rec.get("label", 0.0)))
    return reader


class OnlineTrainer:
    """Linear-over-embeddings CTR model against a live sharded table.

    Forward gathers each batch's rows through the client (so it sees
    every peer's pushes within the staleness bound); backward pushes
    row gradients asynchronously. The small dense ``w`` is local to
    this trainer — the 2017 split exactly: sparse parameters on the
    pserver, dense ones with the trainer."""

    def __init__(self, client, *, lr: float = 0.1, dense_lr: float = 0.05,
                 seed: int = 0):
        self.client = client
        self.lr = float(lr)
        self.dense_lr = float(dense_lr)
        rng = np.random.default_rng(seed)
        self.w = rng.normal(0.0, 0.1, client.dim).astype(np.float32)
        self.steps = 0
        self.samples = 0

    def step(self, batch: Sequence) -> float:
        """One update from ``batch`` = [(ids, label), ...]. Returns the
        mean logloss BEFORE the update."""
        all_ids = np.unique(np.concatenate(
            [np.asarray(ids, np.int64).reshape(-1) for ids, _ in batch]))
        rows = self.client.gather(all_ids)
        index = {int(k): i for i, k in enumerate(all_ids.tolist())}
        loss = 0.0
        g_rows = np.zeros_like(rows)
        g_w = np.zeros_like(self.w)
        for ids, label in batch:
            idx = [index[int(i)] for i in np.asarray(ids).reshape(-1)
                   if int(i) >= 0]
            x = rows[idx]                        # [k, dim]
            score = float(x.sum(axis=0) @ self.w)
            p = 1.0 / (1.0 + np.exp(-score))
            eps = 1e-7
            loss += -(label * np.log(p + eps)
                      + (1.0 - label) * np.log(1.0 - p + eps))
            err = np.float32(p - label)
            g_rows[idx] += err * self.w          # d loss / d row_i
            g_w += err * x.sum(axis=0)           # d loss / d w
        self.client.push(all_ids, g_rows / len(batch), lr=self.lr)
        self.w -= self.dense_lr * (g_w / len(batch))
        self.steps += 1
        self.samples += len(batch)
        return float(loss / len(batch))


def run_online(client, reader: Callable, *, batch_size: int = 8,
               lr: float = 0.1, max_batches: Optional[int] = None,
               num_workers: int = 2, seed: int = 0,
               trainer: Optional[OnlineTrainer] = None) -> Dict[str, Any]:
    """Drive the continuous loop: journal reader -> self-healing
    pipeline -> sparse updates against the live store. Returns stats
    (batches, samples, last/mean loss, client counters). The pipeline
    is the SAME supervised prefetcher training uses — a crashed decode
    worker or a corrupt journal record quarantines instead of stopping
    the freshness loop."""
    from paddle_tpu.reader.pipeline import supervised

    trainer = trainer or OnlineTrainer(client, lr=lr, seed=seed)
    pipe = supervised(reader, num_workers=num_workers,
                      name="embed-online")
    losses: List[float] = []
    batch: List = []
    batches = 0
    t0 = time.perf_counter()
    for sample in pipe():
        batch.append(sample)
        if len(batch) < batch_size:
            continue
        losses.append(trainer.step(batch))
        batch = []
        batches += 1
        if max_batches is not None and batches >= max_batches:
            break
    if batch and (max_batches is None or batches < max_batches):
        losses.append(trainer.step(batch))
        batches += 1
    client.flush()
    elapsed = time.perf_counter() - t0
    stats = {"batches": batches, "samples": trainer.samples,
             "elapsed_s": round(elapsed, 4),
             "loss_last": losses[-1] if losses else None,
             "loss_mean": float(np.mean(losses)) if losses else None,
             "client": client.stats()}
    _emit_embed("online_pass", batches=batches,
                samples=trainer.samples,
                loss_last=stats["loss_last"])
    return stats
