"""Shard registration on the membership plane + an in-process harness.

A shard is a WORKER of the elastic coordinator (PR 9): it joins as
``embed/<shard_id>`` publishing its RPC endpoint in the join info, renews
its lease from a heartbeat thread (``pt-embed-hb-*``), and leaves
gracefully on stop. A SIGKILL'd shard simply stops heartbeating — its
lease lapses, `worker_info` starts returning None, and the REPLACEMENT
that restores the key range from snapshot+WAL re-joins under the same
worker id with a new endpoint. Clients that re-resolve through the
membership plane fail over with no configuration change: the directory
IS the failover mechanism, and every membership transition rides the
coordinator's existing generation stamps and journal.

:class:`EmbedService` is the multi-shard harness the tests, bench rows,
chaos suite and the CLI demo use: N shards + servers (+ registrations
when a coordinator is given) over one shared snapshot store, with
`kill()` / `replace()` to drive the failover story in-process.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, List, Optional

from paddle_tpu.analysis.lockdep import named_lock
from paddle_tpu.trainer.coordinator import InMemStore, KVStore

from paddle_tpu.embed.client import EmbeddingClient
from paddle_tpu.embed.shard import (EmbeddingShard, EmbeddingShardServer,
                                    _emit_embed)

__all__ = ["ShardRegistration", "EmbedService"]


class ShardRegistration:
    """Keep one shard's membership lease alive.

    coordinator: a Coordinator (in-process) or a CoordinatorServer
    proxy — both expose join/worker_heartbeat/leave. The heartbeat
    thread re-JOINS when the coordinator answers -1 (our lease lapsed,
    e.g. a long GC pause or a coordinator restart): the endpoint gets
    re-published, so directory-based clients recover on their own."""

    def __init__(self, coordinator: Any, shard: EmbeddingShard,
                 endpoint: str, heartbeat_s: float = 1.0):
        self.coordinator = coordinator
        self.shard = shard
        self.endpoint = endpoint
        self.worker_id = f"embed/{shard.shard_id}"
        self.heartbeat_s = float(heartbeat_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.generation: Optional[int] = None
        self.rejoins = 0

    def _info(self) -> Dict[str, Any]:
        return {"role": "embed_shard", "endpoint": self.endpoint,
                "shard_id": self.shard.shard_id,
                "num_shards": self.shard.num_shards,
                "dim": self.shard.dim}

    def join(self) -> "ShardRegistration":
        grant = self.coordinator.join(self.worker_id, self._info())
        self.generation = grant["generation"]
        self._thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name=f"pt-embed-hb-{self.shard.shard_id}")
        self._thread.start()
        return self

    def _heartbeat_loop(self):
        while not self._stop.wait(self.heartbeat_s):
            try:
                gen = self.coordinator.worker_heartbeat(self.worker_id)
                if gen == -1:          # lease lapsed: re-join, re-publish
                    grant = self.coordinator.join(self.worker_id,
                                                  self._info())
                    gen = grant["generation"]
                    self.rejoins += 1
                self.generation = gen
            except Exception:  # noqa: BLE001 — a coordinator blip must
                pass           # not kill the lease keeper; next tick retries

    def stop(self, leave: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if leave:
            try:
                self.coordinator.leave(self.worker_id)
            except Exception:  # noqa: BLE001 — best-effort goodbye
                pass


class _Member:
    """One live shard: table + server + (optional) registration."""

    def __init__(self, shard, server, registration):
        self.shard = shard
        self.server = server
        self.registration = registration


class EmbedService:
    """In-process N-shard embedding service (tests/bench/demo harness).

    store: shared snapshot/WAL KVStore (default InMemStore — it must be
    SHARED so a replacement can restore a dead shard's key range).
    coordinator: optional; when given, every shard registers on the
    membership plane and :meth:`client` resolves endpoints through it
    (the failover path); without one, clients get a static endpoint map.
    """

    def __init__(self, num_shards: int, dim: int, *,
                 store: Optional[KVStore] = None, coordinator: Any = None,
                 seed: int = 0, init_std: float = 0.01,
                 heartbeat_s: float = 0.2, restore: bool = False):
        self.num_shards = int(num_shards)
        self.dim = int(dim)
        self.seed = int(seed)
        self.init_std = float(init_std)
        self.store = store if store is not None else InMemStore()
        self.coordinator = coordinator
        self.heartbeat_s = float(heartbeat_s)
        self._lock = named_lock("embed.service")
        self._members: Dict[int, _Member] = {}  # ptlint: guarded-by(embed.service)
        for sid in range(self.num_shards):
            self._spawn(sid, restore=restore)

    def _spawn(self, shard_id: int, restore: bool) -> _Member:
        shard = EmbeddingShard(shard_id, self.num_shards, self.dim,
                               seed=self.seed, init_std=self.init_std,
                               store=self.store)
        if restore:
            shard.restore_from_store()
        server = EmbeddingShardServer(shard).start()
        registration = None
        if self.coordinator is not None:
            registration = ShardRegistration(
                self.coordinator, shard, server.endpoint,
                heartbeat_s=self.heartbeat_s).join()
        member = _Member(shard, server, registration)
        with self._lock:
            self._members[shard_id] = member
        return member

    # ------------------------------------------------------------- accessors
    def shard(self, shard_id: int) -> EmbeddingShard:
        with self._lock:
            return self._members[shard_id].shard

    def server(self, shard_id: int) -> EmbeddingShardServer:
        with self._lock:
            return self._members[shard_id].server

    def endpoints(self) -> Dict[int, str]:
        with self._lock:
            return {sid: m.server.endpoint
                    for sid, m in self._members.items()}

    def client(self, **kw) -> EmbeddingClient:
        """A client wired to this service — through the coordinator
        directory when there is one (failover-capable), else the static
        endpoint map."""
        if self.coordinator is not None:
            kw.setdefault("coordinator", self.coordinator)
        else:
            kw.setdefault("endpoints", self.endpoints())
        return EmbeddingClient(self.num_shards, self.dim, **kw)

    # -------------------------------------------------------------- failover
    def kill(self, shard_id: int):
        """SIGKILL twin: tear the shard's server out with no snapshot
        and no goodbye — its lease lapses on its own. The dead table
        object is dropped; only the store (snapshot + WAL) survives,
        which is the point."""
        with self._lock:
            member = self._members.pop(shard_id)
        if member.registration is not None:
            # the heartbeat thread dies WITHOUT leave() — the lease must
            # lapse exactly as a killed process's would
            member.registration.stop(leave=False)
        member.server.kill()

    def replace(self, shard_id: int) -> EmbeddingShard:
        """Spawn the replacement: restore the key range from
        snapshot+WAL, serve on a NEW endpoint, re-join the membership
        plane under the same worker id. Any remnant of the dead member
        (a server the chaos seam killed in place, its lease keeper) is
        reaped first — a real SIGKILL takes the whole process; the
        in-process twin has to collect the corpse itself."""
        with self._lock:
            old = self._members.pop(shard_id, None)
        if old is not None:
            if old.registration is not None:
                old.registration.stop(leave=False)
            if not old.server._dead:
                old.server.kill()
        member = self._spawn(shard_id, restore=True)
        _emit_embed("shard_replaced", shard_id=shard_id,
                    replayed=member.shard.stats()["replayed_wal"],
                    endpoint=member.server.endpoint)
        return member.shard

    # ------------------------------------------------------------- integrity
    def table_digest(self) -> str:
        """Combined digest over every live shard (sorted by shard id) —
        THE acceptance value: equal across an uninterrupted run and a
        kill/replace run iff no update was lost or doubled."""
        with self._lock:
            members = sorted(self._members.items())
        h = hashlib.md5()
        for sid, m in members:
            h.update(f"{sid}:{m.shard.digest()};".encode())
        return h.hexdigest()

    def snapshot_all(self) -> Dict[int, int]:
        with self._lock:
            members = sorted(self._members.items())
        return {sid: m.shard.save_snapshot() for sid, m in members}

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            members = sorted(self._members.items())
        return {"num_shards": self.num_shards, "dim": self.dim,
                "live_shards": len(members),
                "shards": {sid: m.shard.stats() for sid, m in members}}

    # ------------------------------------------------------------- lifecycle
    def stop(self):
        with self._lock:
            members = list(self._members.values())
            self._members.clear()
        for m in members:
            if m.registration is not None:
                m.registration.stop(leave=True)
            m.server.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
