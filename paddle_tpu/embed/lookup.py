"""RemoteLookup — route `paddle_tpu.layers.embedding` through the store.

The transparency contract: a model config that says
``layers.embedding(input=ids, size=64, remote=True)`` keeps its exact
layer graph, but the `[vocab, 64]` table never materializes on device.
Before each forward, :class:`RemoteLookup` reads the batch's ids
HOST-side from the feed, gathers just the touched rows from the sharded
store through an :class:`EmbeddingClient` (bounded-staleness cache and
failover included), and hands them to the forward as the same
``sparse_sub={param: (uids, rows)}`` row blocks the local row-sparse
trainer path already consumes (``ops.embedding.row_sub_lookup``). The
layer cannot tell a remote table from a prefetched local one.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

__all__ = ["RemoteLookup"]


class RemoteLookup:
    """Per-batch sparse_sub builder for every remote table in a topology.

    topology: a core.topology.Topology (or anything with
    ``remote_tables() -> {param_name: ids_layer_name}``).
    client: the :class:`EmbeddingClient` all tables share.
    """

    def __init__(self, topology, client):
        self.client = client
        self.tables: Dict[str, str] = topology.remote_tables()
        self.gathered_batches = 0

    def sparse_sub(self, feed: Dict[str, Any],
                   max_stale_s: Optional[float] = None) -> Dict[str, Any]:
        """Gather the row blocks this batch touches.

        feed: the feeder's name->array dict (ids may be [b] or [b, T];
        pad id -1 is skipped — `row_sub_lookup` maps it to a zero row).
        Returns {param_name: (uids [k], rows [k, dim])} as numpy — the
        jitted forward stages them in with the batch."""
        sub: Dict[str, Any] = {}
        for pname, src in sorted(self.tables.items()):
            ids = np.asarray(self._ids(feed[src])).reshape(-1)
            uids = np.unique(ids[ids >= 0]).astype(np.int64)
            rows = self.client.gather(uids, max_stale_s=max_stale_s)
            sub[pname] = (uids, rows)
        self.gathered_batches += 1
        return sub

    @staticmethod
    def _ids(value):
        # feeds may carry SequenceBatch-like wrappers; ids are the payload
        return getattr(value, "data", value)

    def push_grads(self, sub: Dict[str, Any],
                   grads: Dict[str, np.ndarray],
                   lr: Optional[float] = None):
        """Push the row-block gradients a training step produced back to
        the store: ``grads[param]`` is d(loss)/d(rows) aligned with the
        ``sub[param]`` uids — the async-SGD write half of the loop."""
        for pname, g in grads.items():
            uids, _ = sub[pname]
            self.client.push(uids, np.asarray(g), lr=lr)
