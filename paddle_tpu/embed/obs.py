"""Observability wiring for the sharded embedding service.

Same pattern as the coordinator's collector: live shards/clients are
tracked by WEAKREF — the scrape reads whatever is alive at scrape time,
nothing pushes gauges on the hot path, and a dead object silently drops
out of the catalog. The flight recorder gets an ``embed`` state
provider so a postmortem bundle dumped for ANY reason carries the
shard/client counters of the moment (docs/observability.md).

Gauge catalog (``paddle_tpu_embed_*``): see docs/observability.md.
"""

from __future__ import annotations

import weakref
from typing import Any, Dict

from paddle_tpu.analysis.lockdep import named_lock

_lock = named_lock("embed.obs")
_SHARDS: "weakref.WeakSet" = weakref.WeakSet()   # ptlint: guarded-by(embed.obs)
_CLIENTS: "weakref.WeakSet" = weakref.WeakSet()  # ptlint: guarded-by(embed.obs)


def track_shard(shard) -> None:
    """Register a live shard for scraping (called at construction)."""
    _install()
    with _lock:
        _SHARDS.add(shard)


def track_client(client) -> None:
    _install()
    with _lock:
        _CLIENTS.add(client)


def _live():
    with _lock:
        return list(_SHARDS), list(_CLIENTS)


_SHARD_GAUGES = (
    ("rows", "materialized (updated) rows held by the shard"),
    ("gathers", "row-gather RPCs served"),
    ("gathered_rows", "rows returned by gathers"),
    ("applied_updates", "scatter-update batches applied exactly once"),
    ("updated_rows", "rows mutated by applied updates"),
    ("dup_updates", "retried batches deduped by the applied-seq ledger"),
    ("replayed_wal", "WAL entries replayed at the last restore"),
    ("wal_seq", "write-ahead-log horizon"),
)

_CLIENT_GAUGES = (
    ("cached_rows", "rows in the bounded-staleness read cache"),
    ("gathers", "gather RPCs issued"),
    ("cache_hits", "rows served from cache within the staleness bound"),
    ("stale_serves", "rows served PAST the bound (journaled violations)"),
    ("pushes", "sparse update batches acked"),
    ("pushed_rows", "gradient rows acked"),
    ("dup_acks", "acks answered 'dup' (exactly-once retries absorbed)"),
    ("push_failures", "update batches lost past the retry deadline"),
    ("failovers", "transport failures that triggered re-resolution"),
)


def _embed_collector():
    from paddle_tpu.obs.metrics import SampleFamily
    shards, clients = _live()
    if not shards and not clients:
        return []
    out = []
    shard_stats = [s.stats() for s in shards]
    client_stats = [c.stats() for c in clients]
    for key, help_ in _SHARD_GAUGES:
        fam = SampleFamily(f"paddle_tpu_embed_shard_{key}", "gauge",
                           help_)
        for st in shard_stats:
            fam.add({"shard": str(st["shard_id"])}, float(st[key]))
        out.append(fam)
    for key, help_ in _CLIENT_GAUGES:
        fam = SampleFamily(f"paddle_tpu_embed_client_{key}", "gauge",
                           help_)
        for st in client_stats:
            fam.add({"client": st["client_id"]}, float(st[key]))
        out.append(fam)
    return out


def _flight_state() -> Dict[str, Any]:
    shards, clients = _live()
    return {"shards": [s.stats() for s in shards],
            "clients": [c.stats() for c in clients]}


def _install():
    """(Re-)install the registry collector + flight provider. Called on
    every track_* — both calls are idempotent dict/set writes, and the
    flight registration MUST repeat because between-tests hygiene
    (obs.reset_all -> FLIGHT.reset) clears all state providers; a
    once-per-process latch would leave later EmbedServices invisible
    to postmortem bundles."""
    try:
        from paddle_tpu.obs.flight import FLIGHT
        from paddle_tpu.obs.metrics import REGISTRY
        REGISTRY.register_collector(_embed_collector)
        FLIGHT.register_state_provider("embed", _flight_state)
    except Exception:  # noqa: BLE001 — obs must not break construction
        pass
