"""EmbeddingShard — one hash-partitioned slice of a row-sparse table.

The 2017 pserver reborn on the elastic plane: `ParameterServer2`
(paddle/pserver/ParameterServer2.cpp) held sparse parameter blocks and
served `sendParameter`/`getParameter`; the Go rewrite
(go/pserver/service.go) sharded them by key hash. This module is that
server side on this repo's own substrate:

- rows live in a host dict keyed by int64 id, lazily initialized from a
  DETERMINISTIC per-key seed — a row's initial value is a pure function
  of (key, seed, dim), so a replacement shard that never saw a key
  produces the same row the dead shard would have (digest stability
  across failover does not depend on which keys were ever gathered);
- every applied update batch is WAL-appended to a :class:`KVStore`
  BEFORE it mutates the table or acks — a SIGKILL between append and
  ack leaves an entry the replacement replays and a retry the
  per-client ``applied_seq`` map dedupes: exactly-once, both sides;
- :class:`EmbeddingShardServer` serves row-gather / scatter-update over
  the same threaded XML-RPC plane as the coordinator (handler threads
  ``pt-embed-rpc-*``), with a fault seam (``_rpc_interceptor``) the
  chaos family (o) drives and a ``kill()`` that tears connections
  without a response — the in-process twin of SIGKILL.

Updates reuse the :mod:`paddle_tpu.parallel.async_sgd` reconcile
semantics row-wise (`filter_finite_rows`): a poisoned gradient row is
dropped + counted instead of contaminating the shared table.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.analysis.lockdep import named_lock
from paddle_tpu.parallel.async_sgd import filter_finite_rows
from paddle_tpu.trainer.coordinator import KVStore, _ThreadingXMLRPCServer
from paddle_tpu.utils.stats import global_counters

__all__ = ["EmbeddingShard", "EmbeddingShardServer", "ShardKilled",
           "stable_hash64", "shard_of"]

#: header/payload separator inside WAL and snapshot frames
_SEP = b"\n\x00"


def stable_hash64(key: int) -> int:
    """splitmix64 — a process-independent key hash (python's builtin
    ``hash`` is salted per process; routing must agree across client,
    shard and replacement)."""
    z = (int(key) + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return z ^ (z >> 31)


def shard_of(key: int, num_shards: int) -> int:
    """Consistent hash routing: key -> owning shard id. Clients and
    shards must agree; this IS the partition function."""
    return stable_hash64(key) % int(num_shards)


def _emit_embed(kind: str, **fields):
    """Journal one ``embed/*`` event — never raises into the serving or
    update path (same discipline as the coordinator's ``_emit_coord``)."""
    try:
        from paddle_tpu.obs.events import emit
        emit("embed", kind, **fields)
    except Exception:  # noqa: BLE001 — obs must not break the data path
        pass


def _frame(header: Dict[str, Any], *arrays: np.ndarray) -> bytes:
    """json header + raw array payloads, lengths recorded in the header
    (keys/rows ride as raw little-endian bytes — compact, and immune to
    XML-RPC's 32-bit int limit)."""
    payloads = [np.ascontiguousarray(a).tobytes() for a in arrays]
    header = dict(header)
    header["payload_lens"] = [len(p) for p in payloads]
    return json.dumps(header).encode() + _SEP + b"".join(payloads)


def _unframe(blob: bytes):
    head, _, rest = blob.partition(_SEP)
    header = json.loads(head.decode())
    out, off = [], 0
    for n in header["payload_lens"]:
        out.append(rest[off:off + n])
        off += n
    return header, out


class ShardKilled(BaseException):
    """Raised by the chaos family (o) kill seam: a ``BaseException`` so
    the XML-RPC dispatch CANNOT turn it into a marshalled ``Fault`` —
    the connection tears with no response, exactly what the client of a
    SIGKILL'd process observes (and must retry through)."""


class EmbeddingShard:
    """One key-range slice of a hash-partitioned row-sparse table."""

    def __init__(self, shard_id: int, num_shards: int, dim: int, *,
                 seed: int = 0, init_std: float = 0.01,
                 store: Optional[KVStore] = None):
        assert 0 <= shard_id < num_shards
        self.shard_id = int(shard_id)
        self.num_shards = int(num_shards)
        self.dim = int(dim)
        self.seed = int(seed)
        self.init_std = float(init_std)
        self.store = store
        self._prefix = f"embed/shard{self.shard_id}"
        self._lock = named_lock("embed.shard")
        self._rows: Dict[int, np.ndarray] = {}   # ptlint: guarded-by(embed.shard)
        self._applied: Dict[str, int] = {}       # ptlint: guarded-by(embed.shard)
        self._wal_seq = 0                        # ptlint: guarded-by(embed.shard)
        self._gathers = 0                        # ptlint: guarded-by(embed.shard)
        self._gathered_rows = 0                  # ptlint: guarded-by(embed.shard)
        self._applied_updates = 0                # ptlint: guarded-by(embed.shard)
        self._updated_rows = 0                   # ptlint: guarded-by(embed.shard)
        self._dup_updates = 0                    # ptlint: guarded-by(embed.shard)
        self._replayed_wal = 0                   # ptlint: guarded-by(embed.shard)
        self.restored = False
        from paddle_tpu.embed.obs import track_shard
        track_shard(self)        # weakref: /metrics + flight bundles
        #: chaos family (o) seam — called under the shard lock AFTER the
        #: WAL append and BEFORE the table mutates/acks, i.e. inside the
        #: torn window a SIGKILL would hit; may raise :class:`ShardKilled`
        self._commit_interceptor: Optional[Callable[[int], None]] = None

    # ------------------------------------------------------------- routing
    def owns(self, key: int) -> bool:
        return shard_of(key, self.num_shards) == self.shard_id

    # ---------------------------------------------------------------- rows
    def _init_row(self, key: int) -> np.ndarray:
        """Deterministic lazy init: a pure function of (key, seed) — any
        shard (original or replacement) derives the same virgin row."""
        rng = np.random.default_rng(
            stable_hash64(int(key) ^ (self.seed * 0x5851F42D4C957F2D)))
        return rng.normal(0.0, self.init_std, self.dim).astype(np.float32)

    def gather(self, keys: Sequence[int]) -> np.ndarray:
        """Row block for ``keys`` ([n, dim] f32). Never-updated keys get
        their deterministic init WITHOUT materializing — the table holds
        only rows an update touched, so the digest covers exactly the
        mutated state."""
        keys = np.asarray(keys, np.int64)
        out = np.empty((len(keys), self.dim), np.float32)
        with self._lock:
            for i, k in enumerate(keys.tolist()):
                row = self._rows.get(k)
                out[i] = self._init_row(k) if row is None else row
            self._gathers += 1
            self._gathered_rows += len(keys)
        return out

    # -------------------------------------------------------------- updates
    def apply_updates(self, client_id: str, seq: int,
                      keys: Sequence[int], grads: np.ndarray,
                      lr: float) -> Dict[str, Any]:
        """Apply one sparse SGD batch exactly once.

        ``seq`` is the client's per-shard monotonic counter (1-based).
        A retry of an already-applied batch (the shard died after the
        WAL append but before the ack) dedupes via the per-client
        ``applied_seq`` map; a gap means the transport reordered or
        dropped an ack the client never retried — a protocol bug, so it
        raises instead of silently corrupting the exactly-once ledger.
        The WAL append happens BEFORE the mutation: a kill in between
        is replayed by the replacement and deduped on retry."""
        seq = int(seq)
        keys = np.asarray(keys, np.int64)
        grads = np.asarray(grads, np.float32).reshape(len(keys), self.dim)
        # reconcile guard, row-wise (AsyncSGDIsland semantics): poisoned
        # rows are dropped from the update, never from the ledger — seq
        # still advances so the stream stays gap-free
        keys, grads = filter_finite_rows(
            keys, grads, counter="embed/poisoned_rows")
        with self._lock:
            last = self._applied.get(client_id, 0)
            if seq <= last:
                self._dup_updates += 1
                global_counters.bump("embed/dup_updates")
                return {"applied": False, "dup": True, "seq": seq}
            if seq != last + 1:
                raise ValueError(
                    f"embed shard {self.shard_id}: client {client_id!r} "
                    f"update seq {seq} leaves a gap after {last} — "
                    "pushes must be applied in order")
            wal_seq = self._wal_seq + 1
            if self.store is not None:
                frame = _frame({"client_id": client_id, "seq": seq,
                                "lr": float(lr), "n": len(keys)},
                               keys, grads)
                self.store.put(f"{self._prefix}/wal/{wal_seq}", frame)
            self._wal_seq = wal_seq
            if self._commit_interceptor is not None:
                # the torn window: WAL durable, table not yet mutated,
                # ack not yet sent — where a real SIGKILL hurts most
                self._commit_interceptor(wal_seq)
            self._apply_rows_locked(keys, grads, float(lr))
            self._applied[client_id] = seq
            self._applied_updates += 1
            self._updated_rows += len(keys)
        return {"applied": True, "dup": False, "seq": seq}

    def _apply_rows_locked(self, keys: np.ndarray, grads: np.ndarray,
                           lr: float):
        for k, g in zip(keys.tolist(), grads):
            row = self._rows.get(k)
            if row is None:
                row = self._init_row(k)
            self._rows[k] = row - lr * g

    # ------------------------------------------------------------ integrity
    def digest(self) -> str:
        """Order-independent md5 over the mutated table state — equal
        across an uninterrupted run and a kill/restore/replay run iff
        every update landed exactly once."""
        with self._lock:
            items = sorted(self._rows.items())
        h = hashlib.md5()
        for k, row in items:
            h.update(np.int64(k).tobytes())
            h.update(np.ascontiguousarray(row, np.float32).tobytes())
        return h.hexdigest()

    def applied_seqs(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._applied)

    # ----------------------------------------------------------- durability
    def save_snapshot(self) -> int:
        """Write the full shard state (rows + applied ledger + the WAL
        horizon) to the store. Serialized under the lock, PUT outside it
        (multi-MB snapshots ride RpcStore's chunked path; updates that
        land mid-put stay replayable past ``wal_upto``). Returns the
        row count saved."""
        assert self.store is not None, "snapshot requires a store"
        with self._lock:
            keys = np.array(sorted(self._rows), np.int64)
            rows = (np.stack([self._rows[k] for k in keys.tolist()])
                    if len(keys) else np.empty((0, self.dim), np.float32))
            header = {"v": 1, "shard_id": self.shard_id,
                      "num_shards": self.num_shards, "dim": self.dim,
                      "seed": self.seed, "wal_upto": self._wal_seq,
                      "applied": dict(self._applied)}
        blob = _frame(header, keys, rows.astype(np.float32))
        self.store.put(f"{self._prefix}/snap", blob)
        _emit_embed("snapshot", shard_id=self.shard_id,
                    rows=int(len(keys)), wal_upto=header["wal_upto"])
        return int(len(keys))

    def restore_from_store(self) -> bool:
        """Recover this key range: load the last snapshot (absent is
        fine — a fresh shard), then replay WAL entries PAST its
        ``wal_upto`` horizon, deduping through the applied ledger the
        snapshot carried. This is what a replacement runs before it
        rejoins the membership plane."""
        assert self.store is not None, "restore requires a store"
        snap = self.store.get(f"{self._prefix}/snap")
        replayed = 0
        with self._lock:
            if snap is not None:
                header, payloads = _unframe(snap)
                assert header["dim"] == self.dim and \
                    header["num_shards"] == self.num_shards, \
                    "snapshot/shard geometry mismatch"
                keys = np.frombuffer(payloads[0], np.int64)
                rows = np.frombuffer(payloads[1], np.float32).reshape(
                    len(keys), self.dim)
                self._rows = {int(k): rows[i].copy()
                              for i, k in enumerate(keys)}
                self._applied = {str(c): int(s)
                                 for c, s in header["applied"].items()}
                self._wal_seq = int(header["wal_upto"])
            while True:
                frame = self.store.get(
                    f"{self._prefix}/wal/{self._wal_seq + 1}")
                if frame is None:
                    break
                header, payloads = _unframe(frame)
                self._wal_seq += 1
                cid, seq = str(header["client_id"]), int(header["seq"])
                if seq <= self._applied.get(cid, 0):
                    self._dup_updates += 1     # retried batch, WAL'd twice
                    continue
                keys = np.frombuffer(payloads[0], np.int64)
                grads = np.frombuffer(payloads[1], np.float32).reshape(
                    len(keys), self.dim)
                self._apply_rows_locked(keys, grads,
                                        float(header["lr"]))
                self._applied[cid] = seq
                replayed += 1
            self._replayed_wal = replayed
            self.restored = snap is not None or replayed > 0
        _emit_embed("restore", shard_id=self.shard_id,
                    from_snapshot=snap is not None, replayed=replayed)
        return self.restored

    # ---------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"shard_id": self.shard_id,
                    "num_shards": self.num_shards,
                    "dim": self.dim,
                    "rows": len(self._rows),
                    "gathers": self._gathers,
                    "gathered_rows": self._gathered_rows,
                    "applied_updates": self._applied_updates,
                    "updated_rows": self._updated_rows,
                    "dup_updates": self._dup_updates,
                    "replayed_wal": self._replayed_wal,
                    "wal_seq": self._wal_seq,
                    "clients": len(self._applied)}


class _EmbedRPCServer(_ThreadingXMLRPCServer):
    """An XML-RPC server whose handlers can DIE mid-request.

    The stdlib dispatcher marshals ANY escaping exception — including
    ``BaseException`` on current CPython — into a ``Fault`` response; a
    SIGKILL'd process answers NOTHING. So ``_marshaled_dispatch`` is
    re-implemented to let :class:`ShardKilled` propagate: the request
    thread unwinds, ``shutdown_request`` in socketserver's ``finally``
    closes the connection with no response written, and the client
    observes a transport error (the killed-process shape) instead of a
    Fault it could mistake for an answer. ``process_request_thread``
    then swallows the escape to keep the chaos suite's stderr clean."""

    def _marshaled_dispatch(self, data, dispatch_method=None, path=None):
        import xmlrpc.client as xc
        try:
            params, method = xc.loads(
                data, use_builtin_types=self.use_builtin_types)
            if dispatch_method is not None:
                response = dispatch_method(method, params)
            else:
                response = self._dispatch(method, params)
            response = xc.dumps((response,), methodresponse=1,
                                allow_none=self.allow_none,
                                encoding=self.encoding)
        except ShardKilled:
            raise              # tear the connection: NO response at all
        except xc.Fault as fault:
            response = xc.dumps(fault, allow_none=self.allow_none,
                                encoding=self.encoding)
        except Exception as exc:  # noqa: BLE001 — Fault, stdlib contract
            response = xc.dumps(xc.Fault(1, f"{type(exc)}:{exc}"),
                                allow_none=self.allow_none,
                                encoding=self.encoding)
        return response.encode(self.encoding, "xmlrpc")

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        except ShardKilled:
            pass


class EmbeddingShardServer:
    """Serve an :class:`EmbeddingShard` over threaded XML-RPC.

    Wire format: keys ride as ``Binary`` little-endian int64, rows and
    grads as ``Binary`` f32 — immune to XML-RPC's 32-bit int ceiling
    and ~4x smaller than marshalled arrays. Every RPC takes a
    ``trace_id`` (bound into the obs context while handling, so the
    per-RPC journal record and anything nested carries it end-to-end).
    """

    def __init__(self, shard: EmbeddingShard, host: str = "127.0.0.1",
                 port: int = 0):
        from xmlrpc.client import Binary
        self.shard = shard
        self.server = _EmbedRPCServer(
            (host, port), allow_none=True, logRequests=False,
            thread_prefix="pt-embed-rpc")
        self.host = host
        self.port = self.server.server_address[1]
        self.endpoint = f"{host}:{self.port}"
        self._dead = False
        self._seam_lock = named_lock("embed.rpcseam")
        self._rpc_index = 0                 # ptlint: guarded-by(embed.rpcseam)
        #: chaos family (o) seam — called at the TOP of every RPC with
        #: (method, 0-based index); may sleep (slow_shard) or raise
        #: :class:`ShardKilled` (kill_shard)
        self._rpc_interceptor: Optional[Callable[[str, int], None]] = None

        def _seam(method: str):
            with self._seam_lock:
                idx = self._rpc_index
                self._rpc_index += 1
                interceptor = self._rpc_interceptor
                dead = self._dead
            if dead:
                raise ShardKilled(f"shard {shard.shard_id} is killed")
            if interceptor is not None:
                interceptor(method, idx)

        def _bound(trace_id):
            from paddle_tpu.obs import context as obs_context
            return obs_context.bind(
                trace_id=trace_id or obs_context.new_trace_id())

        def ping():
            _seam("ping")
            return {"shard_id": shard.shard_id,
                    "num_shards": shard.num_shards, "dim": shard.dim}

        def gather(keys_blob, trace_id=None):
            _seam("gather")
            keys = np.frombuffer(keys_blob.data, "<i8")
            with _bound(trace_id):
                rows = shard.gather(keys)
                _emit_embed("gather", shard_id=shard.shard_id,
                            rows=len(keys))
            return {"rows": Binary(rows.astype("<f4").tobytes()),
                    "n": len(keys), "dim": shard.dim}

        def scatter_update(client_id, seq, keys_blob, grads_blob, lr,
                           trace_id=None):
            _seam("scatter_update")
            keys = np.frombuffer(keys_blob.data, "<i8")
            grads = np.frombuffer(grads_blob.data, "<f4").reshape(
                len(keys), shard.dim)
            with _bound(trace_id):
                res = shard.apply_updates(str(client_id), int(seq),
                                          keys, grads, float(lr))
                _emit_embed("update", shard_id=shard.shard_id,
                            rows=len(keys), seq=int(seq),
                            dup=bool(res["dup"]))
            return res

        def digest():
            _seam("digest")
            return shard.digest()

        def stats():
            _seam("stats")
            return shard.stats()

        def snapshot_now():
            _seam("snapshot_now")
            return shard.save_snapshot()

        for fn in (ping, gather, scatter_update, digest, stats,
                   snapshot_now):
            self.server.register_function(fn, fn.__name__)
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "EmbeddingShardServer":
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="pt-embed-rpc")
        self._thread.start()
        return self

    def _serve(self):
        try:
            self.server.serve_forever()
        except OSError:
            if not self._dead:       # killed: listening socket torn out
                raise

    def stop(self):
        """Graceful: finish in-flight requests, close the socket."""
        self.server.shutdown()
        self.server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def kill(self):
        """The SIGKILL twin: mark dead (every in-flight and future RPC
        dies mid-handling with no response) and tear the listening
        socket out so new connections are refused. No snapshot, no
        goodbye to the coordinator — its lease just lapses. The accept
        loop is reaped too (closing the socket alone leaves it spinning
        on an empty selector forever — an in-process-only corpse a real
        SIGKILL would have taken): ``shutdown()`` only stops NEW
        accepts; in-flight handlers still die un-answered on the dead
        flag."""
        with self._seam_lock:
            self._dead = True
        try:
            self.server.socket.close()
        except OSError:
            pass
        self.server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        _emit_embed("shard_killed", shard_id=self.shard.shard_id)
