"""paddle_tpu.embed — hash-partitioned embedding/parameter store.

PAPER.md layer 8 (`paddle/pserver/`: ParameterServer2/ParameterClient2,
the sharded KV store for sparse parameters behind 2017-era production
CTR ranking), rebuilt on this repo's elastic plane:

- :mod:`shard` — :class:`EmbeddingShard` (row-sparse slice, WAL +
  snapshot durability, exactly-once applied ledger) and its XML-RPC
  server; splitmix64 ``shard_of`` routing.
- :mod:`client` — :class:`EmbeddingClient`: consistent-hash routing,
  batched gather with a bounded-staleness cache (violations journaled),
  async-SGD sparse pushes with reconcile-guard semantics.
- :mod:`service` — membership-plane registration (leases + failover
  directory) and the in-process multi-shard harness.
- :mod:`lookup` — :class:`RemoteLookup`: `layers.embedding(remote=True)`
  routes through the store via the existing ``sparse_sub`` seam.
- :mod:`online` — continuous training: serving journal -> self-healing
  reader pipeline -> live sparse updates while lookups continue.
- :mod:`obs` — ``paddle_tpu_embed_*`` gauges + flight-bundle provider.

Chaos family (o) in :mod:`paddle_tpu.testing.faults` drives SIGKILL'd
shards, stale reads and slow shards against all of it
(tests/test_embed_faults.py; docs/robustness.md "Sharded embedding
service").
"""

from paddle_tpu.embed.client import EmbeddingClient, EmbedUnavailable
from paddle_tpu.embed.lookup import RemoteLookup
from paddle_tpu.embed.online import (OnlineTrainer, journal_sample_reader,
                                     log_sample, run_online,
                                     serving_sample_log)
from paddle_tpu.embed.service import EmbedService, ShardRegistration
from paddle_tpu.embed.shard import (EmbeddingShard, EmbeddingShardServer,
                                    ShardKilled, shard_of, stable_hash64)

__all__ = [
    "EmbeddingClient", "EmbedUnavailable", "RemoteLookup",
    "OnlineTrainer", "journal_sample_reader", "log_sample", "run_online",
    "serving_sample_log", "EmbedService", "ShardRegistration",
    "EmbeddingShard", "EmbeddingShardServer", "ShardKilled", "shard_of",
    "stable_hash64",
]
