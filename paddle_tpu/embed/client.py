"""EmbeddingClient — consistent-hash routing, bounded-staleness reads,
async-SGD sparse pushes.

The `ParameterClient2` side of the pserver pair: trainers (and the
serving path) talk to the sharded table through this one object.

- **Routing**: `shard_of(key)` — the same splitmix64 partition the
  shards use. Endpoints come either from a static list or from the
  coordinator MEMBERSHIP PLANE (`worker_info("embed/<sid>")`): a shard
  published its endpoint at join, a replacement re-publishes at rejoin,
  and the client re-resolves after any transport failure — failover is
  just "ask the directory again".
- **Bounded-staleness reads**: a row cache serves entries younger than
  `staleness_s` locally; older entries refetch. When a shard is DOWN
  past the retry deadline, a cached-but-stale row is served anyway —
  availability over freshness — and that VIOLATION is journaled
  (``embed/stale_read``) and counted: the 2017 pserver's
  `max_async_count` staleness bound, made observable.
- **Async push**: `push()` enqueues sparse (keys, grads); a worker
  thread (``pt-embed-push``) coalesces batches per shard and sends
  `scatter_update` with a per-shard monotonic ``seq``. The guard
  semantics of :meth:`AsyncSGDIsland.reconcile` apply row-wise at the
  source (`filter_finite_rows`); exactly-once lands at the shard: a
  transport failure retries the SAME seq against the re-resolved
  endpoint, and the shard's applied-seq ledger dedupes a batch whose
  WAL survived the kill.
"""

from __future__ import annotations

import threading
import time
import uuid
from http.client import HTTPException
from queue import Empty, Queue
from xmlrpc.client import ProtocolError
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.analysis.lockdep import named_lock
from paddle_tpu.parallel.async_sgd import filter_finite_rows
from paddle_tpu.utils.stats import global_counters

from paddle_tpu.embed.shard import _emit_embed, shard_of

__all__ = ["EmbeddingClient", "EmbedUnavailable"]


class EmbedUnavailable(RuntimeError):
    """A shard stayed unreachable past the retry deadline and no cached
    row could stand in."""


class EmbeddingClient:
    """Client for a hash-partitioned embedding store.

    num_shards/dim: table geometry (must match the shards').
    endpoints:     static ``{shard_id: "host:port"}`` map, or None to
                   resolve through ``coordinator``.
    coordinator:   a Coordinator (in-process) or CoordinatorServer proxy
                   (``connect(host, port)``) whose membership plane the
                   shards registered in.
    staleness_s:   the bounded-staleness read window — cached rows
                   younger than this serve locally; a DOWN shard makes
                   older rows serve anyway, journaled as violations.
    retry_deadline: seconds an RPC keeps retrying (with endpoint
                   re-resolution between attempts) before giving up.
    """

    def __init__(self, num_shards: int, dim: int, *,
                 endpoints: Optional[Dict[int, str]] = None,
                 coordinator: Any = None,
                 client_id: Optional[str] = None,
                 staleness_s: float = 30.0,
                 cache_capacity: int = 65536,
                 lr: float = 0.1,
                 retry_deadline: float = 10.0,
                 push_queue: int = 256):
        assert endpoints is not None or coordinator is not None, \
            "need a static endpoint map or a coordinator to resolve from"
        self.num_shards = int(num_shards)
        self.dim = int(dim)
        self.client_id = client_id or f"embc-{uuid.uuid4().hex[:8]}"
        self.staleness_s = float(staleness_s)
        self.cache_capacity = int(cache_capacity)
        self.lr = float(lr)
        self.retry_deadline = float(retry_deadline)
        self._coordinator = coordinator
        self._lock = named_lock("embed.client")
        self._endpoints: Dict[int, str] = dict(endpoints or {})  # ptlint: guarded-by(embed.client)
        self._cache: Dict[int, Tuple[np.ndarray, float]] = {}    # ptlint: guarded-by(embed.client)
        self._seq: Dict[int, int] = {}                           # ptlint: guarded-by(embed.client)
        self._inflight = 0                                       # ptlint: guarded-by(embed.client)
        self._gathers = 0                                        # ptlint: guarded-by(embed.client)
        self._gathered_rows = 0                                  # ptlint: guarded-by(embed.client)
        self._cache_hits = 0                                     # ptlint: guarded-by(embed.client)
        self._stale_serves = 0                                   # ptlint: guarded-by(embed.client)
        self._pushes = 0                                         # ptlint: guarded-by(embed.client)
        self._pushed_rows = 0                                    # ptlint: guarded-by(embed.client)
        self._dup_acks = 0                                       # ptlint: guarded-by(embed.client)
        self._push_failures = 0                                  # ptlint: guarded-by(embed.client)
        self._failovers = 0                                      # ptlint: guarded-by(embed.client)
        self._tls = threading.local()        # per-thread ServerProxy map
        from paddle_tpu.embed.obs import track_client
        track_client(self)       # weakref: /metrics + flight bundles
        self._queue: Queue = Queue(maxsize=int(push_queue))
        self._stop = threading.Event()
        self._push_thread = threading.Thread(
            target=self._push_loop, daemon=True, name="pt-embed-push")
        self._push_thread.start()

    # ------------------------------------------------------------ transport
    def _resolve(self, shard_id: int, refresh: bool = False) -> str:
        with self._lock:
            ep = None if refresh else self._endpoints.get(shard_id)
        if ep is not None:
            return ep
        if self._coordinator is None:
            with self._lock:      # static map: nothing to re-resolve
                ep = self._endpoints.get(shard_id)
            if ep is None:
                raise EmbedUnavailable(
                    f"no endpoint for shard {shard_id}")
            return ep
        info = self._coordinator.worker_info(f"embed/{shard_id}")
        ep = (info or {}).get("endpoint")
        if not ep:
            raise LookupError(
                f"shard {shard_id} has no live membership lease")
        with self._lock:
            self._endpoints[shard_id] = ep
        return ep

    def _proxy(self, endpoint: str):
        from xmlrpc.client import ServerProxy
        cache = getattr(self._tls, "conns", None)
        if cache is None:
            cache = self._tls.conns = {}
        proxy = cache.get(endpoint)
        if proxy is None:
            proxy = cache[endpoint] = ServerProxy(
                f"http://{endpoint}", allow_none=True)
        return proxy

    def _drop_proxy(self, endpoint: str):
        cache = getattr(self._tls, "conns", None)
        if cache is not None:
            cache.pop(endpoint, None)

    def _call(self, shard_id: int, method: str, *args):
        """One RPC with transport-failure retry + endpoint re-resolution
        (failover): an unreachable/torn shard is retried — the SAME
        arguments, so a retried ``scatter_update`` carries the SAME seq
        and the shard's ledger dedupes it — until ``retry_deadline``."""
        deadline = time.monotonic() + self.retry_deadline
        delay = 0.05
        refresh = False
        while True:
            endpoint = None
            try:
                endpoint = self._resolve(shard_id, refresh=refresh)
                return getattr(self._proxy(endpoint), method)(*args)
            except (OSError, HTTPException, ProtocolError,
                    LookupError) as err:
                # OSError: refused/reset; HTTPException (incl.
                # ProtocolError/BadStatusLine): connection torn with no
                # response — the killed-mid-commit shape. LookupError:
                # the lease lapsed and no replacement joined yet.
                if endpoint is not None:
                    self._drop_proxy(endpoint)
                with self._lock:
                    self._failovers += 1
                refresh = True
                if time.monotonic() + delay > deadline:
                    raise EmbedUnavailable(
                        f"shard {shard_id} unreachable past "
                        f"{self.retry_deadline}s: {err!r}") from err
                time.sleep(delay)
                delay = min(delay * 2.0, 1.0)

    def _trace_id(self) -> str:
        from paddle_tpu.obs import context as obs_context
        return obs_context.current().trace_id or obs_context.new_trace_id()

    # --------------------------------------------------------------- reads
    def gather(self, keys: Sequence[int],
               max_stale_s: Optional[float] = None) -> np.ndarray:
        """Batched row gather with the bounded-staleness cache.

        Returns [n, dim] f32 in key order. Rows cached within the
        staleness bound serve locally; the rest group into ONE RPC per
        owning shard. A shard down past the retry deadline serves from
        stale cache where possible (journaled violation, domain
        ``embed``), and raises :class:`EmbedUnavailable` only for keys
        with no cached row at all."""
        bound = self.staleness_s if max_stale_s is None else float(max_stale_s)
        keys = np.asarray(keys, np.int64)
        out = np.empty((len(keys), self.dim), np.float32)
        now = time.time()
        need: Dict[int, List[Tuple[int, int]]] = {}
        with self._lock:
            for i, k in enumerate(keys.tolist()):
                ent = self._cache.get(k)
                if ent is not None and now - ent[1] <= bound:
                    out[i] = ent[0]
                    self._cache_hits += 1
                else:
                    need.setdefault(
                        shard_of(k, self.num_shards), []).append((i, k))
        trace_id = self._trace_id()
        from xmlrpc.client import Binary
        for sid, items in sorted(need.items()):
            blob = Binary(np.array([k for _, k in items],
                                   "<i8").tobytes())
            try:
                resp = self._call(sid, "gather", blob, trace_id)
            except EmbedUnavailable:
                self._serve_stale(sid, items, out, bound, trace_id)
                continue
            rows = np.frombuffer(resp["rows"].data, "<f4").reshape(
                len(items), self.dim)
            fetched = time.time()
            with self._lock:
                for (i, k), row in zip(items, rows):
                    out[i] = row
                    self._cache[k] = (row.copy(), fetched)
                self._gathers += 1
                self._gathered_rows += len(items)
                self._evict_locked()
        return out

    def _serve_stale(self, shard_id: int, items, out, bound: float,
                     trace_id: str):
        """Availability over freshness: the shard is down — serve the
        stale cached rows we do have, journal the staleness-bound
        violation, and raise only for rows nobody ever cached."""
        now = time.time()
        ages: List[float] = []
        missing: List[int] = []
        with self._lock:
            for i, k in items:
                ent = self._cache.get(k)
                if ent is None:
                    missing.append(k)
                else:
                    out[i] = ent[0]
                    ages.append(now - ent[1])
            self._stale_serves += len(ages)
        if ages:
            global_counters.bump("embed/stale_serves", len(ages))
            _emit_embed("stale_read", shard_id=shard_id,
                        rows=len(ages), age_s=round(max(ages), 3),
                        bound_s=bound, trace_id=trace_id)
        if missing:
            raise EmbedUnavailable(
                f"shard {shard_id} is down and {len(missing)} row(s) "
                f"(e.g. key {missing[0]}) have no cached value")

    def _evict_locked(self):
        if len(self._cache) <= self.cache_capacity:
            return
        # drop the oldest ~12% by fetch time — cheap clock sweep
        n_drop = max(1, len(self._cache) // 8)
        for k in sorted(self._cache, key=lambda k: self._cache[k][1])[:n_drop]:
            del self._cache[k]

    # -------------------------------------------------------------- writes
    def push(self, keys: Sequence[int], grads: np.ndarray,
             lr: Optional[float] = None):
        """Queue one sparse gradient batch for async apply. Non-finite
        rows are dropped at the source (reconcile guard); cached copies
        of the pushed keys are invalidated so the next gather observes
        the update."""
        keys = np.asarray(keys, np.int64)
        grads = np.asarray(grads, np.float32).reshape(len(keys), self.dim)
        keys, grads = filter_finite_rows(
            keys, grads, counter="embed/poisoned_rows")
        lr = self.lr if lr is None else float(lr)
        with self._lock:
            for k in keys.tolist():
                self._cache.pop(k, None)
            self._inflight += 1
        self._queue.put((keys, grads, lr))

    def _push_loop(self):
        while not self._stop.is_set():
            try:
                item = self._queue.get(timeout=0.1)
            except Empty:
                continue
            batch = [item]
            while len(batch) < 32:           # coalesce what's already queued
                try:
                    batch.append(self._queue.get_nowait())
                except Empty:
                    break
            try:
                self._send_batch(batch)
            except Exception as err:  # noqa: BLE001 — a server Fault
                # (protocol bug, geometry mismatch) must not kill the
                # push worker; it is counted + journaled and the worker
                # lives on for the next batch
                with self._lock:
                    self._push_failures += 1
                global_counters.bump("embed/push_failures")
                _emit_embed("push_failed", error=repr(err)[:200])

    def _send_batch(self, batch):
        from xmlrpc.client import Binary
        # group rows by (owning shard, lr); concatenation preserves
        # duplicate keys — the shard applies row-by-row, so dup keys
        # accumulate exactly as separate pushes would
        groups: Dict[Tuple[int, float], List[Tuple[np.ndarray, np.ndarray]]] = {}
        for keys, grads, lr in batch:
            sids = np.array([shard_of(k, self.num_shards)
                             for k in keys.tolist()])
            for sid in np.unique(sids):
                m = sids == sid
                groups.setdefault((int(sid), lr), []).append(
                    (keys[m], grads[m]))
        trace_id = self._trace_id()
        try:
            for (sid, lr), parts in sorted(groups.items()):
                keys = np.concatenate([k for k, _ in parts])
                grads = np.concatenate([g for _, g in parts])
                with self._lock:
                    seq = self._seq.get(sid, 0) + 1
                try:
                    res = self._call(
                        sid, "scatter_update", self.client_id, int(seq),
                        Binary(keys.astype("<i8").tobytes()),
                        Binary(grads.astype("<f4").tobytes()),
                        float(lr), trace_id)
                except EmbedUnavailable:
                    with self._lock:
                        self._push_failures += 1
                    global_counters.bump("embed/push_failures")
                    _emit_embed("push_failed", shard_id=sid,
                                rows=int(len(keys)), seq=int(seq),
                                trace_id=trace_id)
                    continue
                with self._lock:
                    self._seq[sid] = seq
                    self._pushes += 1
                    self._pushed_rows += len(keys)
                    if res.get("dup"):
                        # the first attempt's WAL survived a kill; the
                        # retry deduped — exactly-once held
                        self._dup_acks += 1
        finally:
            with self._lock:
                self._inflight -= len(batch)

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every queued push has been acked (or failed
        terminally). True when drained."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                drained = self._inflight == 0
            if drained and self._queue.empty():
                return True
            time.sleep(0.01)
        return False

    # ------------------------------------------------------------ lifecycle
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"client_id": self.client_id,
                    "num_shards": self.num_shards,
                    "cached_rows": len(self._cache),
                    "gathers": self._gathers,
                    "gathered_rows": self._gathered_rows,
                    "cache_hits": self._cache_hits,
                    "stale_serves": self._stale_serves,
                    "pushes": self._pushes,
                    "pushed_rows": self._pushed_rows,
                    "dup_acks": self._dup_acks,
                    "push_failures": self._push_failures,
                    "failovers": self._failovers,
                    "inflight": self._inflight}

    def close(self, timeout: float = 5.0):
        """Drain and stop the push worker (R5 lifecycle)."""
        self.flush(timeout=timeout)
        self._stop.set()
        self._push_thread.join(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
