"""Reader creators & decorators — python/paddle/v2/reader parity.

Reference: python/paddle/v2/reader/{creator.py,decorator.py}: a *reader* is
a zero-arg callable returning an iterable of samples; decorators compose
(map_readers, buffered, shuffle, compose, chain, firstn, batched...).
`batch` (python/paddle/v2/minibatch.py) groups samples into lists.
"""

from __future__ import annotations

import itertools
import random as _random
import threading
import queue as _queue
from typing import Any, Callable, Iterable, List, Sequence

Reader = Callable[[], Iterable[Any]]

from paddle_tpu.reader.pipeline import (CheckpointableReader,  # noqa: E402
                                        ErrorBudget, ErrorBudgetExceeded,
                                        SupervisedReader, supervised)


class _CheckpointableBatches:
    """``batch()`` over a checkpointable sample reader (one exposing
    ``state()``/``set_state()`` — CheckpointableReader or an ordered
    SupervisedReader over one): records the source position at every
    batch boundary so the trainer can checkpoint mid-pass reader state.
    ``state_for(n)`` is the position after the n-th batch yielded by the
    CURRENT iteration (a bounded window of recent batches is kept)."""

    _KEEP = 256

    def __init__(self, reader, batch_size: int, drop_last: bool):
        self._src = reader
        self.batch_size = batch_size
        self.drop_last = drop_last
        self._states: dict = {}

    def set_state(self, st) -> None:
        self._src.set_state(st)

    def state_for(self, n: int):
        return self._states.get(n)

    def _mark(self, n: int) -> None:
        self._states[n] = self._src.state()
        while len(self._states) > self._KEEP:
            del self._states[min(self._states)]

    def __call__(self):
        self._states = {}
        n = 0
        buf: List[Any] = []
        for sample in self._src():
            buf.append(sample)
            if len(buf) == self.batch_size:
                self._mark(n)
                yield buf
                n += 1
                buf = []
        if buf and not self.drop_last:
            self._mark(n)
            yield buf


def batch(reader: Reader, batch_size: int, drop_last: bool = False) -> Reader:
    """paddle.batch parity: sample reader -> batch reader. A
    checkpointable sample reader yields a checkpointable batch reader
    (see _CheckpointableBatches / docs/robustness.md "Data pipeline")."""
    if hasattr(reader, "state") and hasattr(reader, "set_state") and \
            getattr(reader, "checkpointable", True):
        return _CheckpointableBatches(reader, batch_size, drop_last)

    def batch_reader():
        buf: List[Any] = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batch_reader


def shuffle(reader: Reader, buf_size: int, seed=None) -> Reader:
    def shuffled():
        rng = _random.Random(seed)
        buf: List[Any] = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                for s in buf:
                    yield s
                buf = []
        rng.shuffle(buf)
        for s in buf:
            yield s
    return shuffled


def map_readers(func, *readers: Reader) -> Reader:
    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)
    return reader


class ComposeNotAligned(ValueError):
    """Raised when composed readers yield different sample counts
    (python/paddle/v2/reader/decorator.py:90)."""


def compose(*readers: Reader, check_alignment: bool = True) -> Reader:
    """Zip several readers into tuple samples (reader.compose parity).

    With ``check_alignment`` (the default, as the reference), readers of
    unequal length raise ComposeNotAligned instead of silently truncating
    to the shortest (decorator.py:98 _check_input_not_empty zip)."""
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    _end = object()

    def reader():
        its = [r() for r in readers]
        if not check_alignment:
            for items in zip(*its):
                yield sum((make_tuple(i) for i in items), ())
            return
        for items in itertools.zip_longest(*its, fillvalue=_end):
            if any(i is _end for i in items):
                if not all(i is _end for i in items):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                return
            yield sum((make_tuple(i) for i in items), ())
    return reader


def chain(*readers: Reader) -> Reader:
    def reader():
        return itertools.chain(*[r() for r in readers])
    return reader


def firstn(reader: Reader, n: int) -> Reader:
    def limited():
        return itertools.islice(reader(), n)
    return limited


def _shutdown_put(q: "_queue.Queue", item, stop: threading.Event) -> bool:
    """Bounded-queue put that bails once the consumer shut the reader
    down — a fill thread must never block forever against a full queue
    after the consumer abandoned the generator."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except _queue.Full:
            continue
    return False


def buffered(reader: Reader, size: int) -> Reader:
    """Async prefetch via a background thread — the DoubleBuffer equivalent
    (paddle/gserver/dataproviders/DataProvider.h:249).

    Lifecycle (docs/robustness.md "Data pipeline"): a source exception
    re-raises in the CONSUMER at the point it occurred (never a silently
    truncated epoch), and abandoning the generator mid-epoch (break /
    close()) stops the fill thread instead of leaking it against a full
    queue. For supervision beyond that — watchdog, error budget, worker
    restarts — use reader.supervised()."""

    def buffered_reader():
        q: _queue.Queue = _queue.Queue(maxsize=size)
        stop = threading.Event()

        def fill():
            try:
                for sample in reader():
                    if not _shutdown_put(q, ("item", sample), stop):
                        return
                _shutdown_put(q, ("end", None), stop)
            except BaseException as e:    # re-raised by the consumer
                _shutdown_put(q, ("err", e), stop)

        t = threading.Thread(target=fill, daemon=True,
                             name="pt-data-buffered")
        t.start()
        try:
            while True:
                kind, val = q.get()
                if kind == "end":
                    return
                if kind == "err":
                    raise val
                yield val
        finally:
            stop.set()
            t.join(timeout=1.0)
    return buffered_reader


def xmap_readers(mapper, reader: Reader, process_num: int,
                 buffer_size: int, order: bool = False) -> Reader:
    """Apply `mapper` to samples with `process_num` worker threads
    (reader.decorator.xmap_readers parity, decorator.py:233 — the
    reference's "processes" are threads too). order=True preserves the
    input order; otherwise samples come out as workers finish.

    Lifecycle (docs/robustness.md "Data pipeline"): a worker/source
    exception re-raises in the consumer AT the failing sample — not
    after the whole epoch drains — and abandoning the generator early
    shuts the feed/worker threads down instead of deadlocking them on
    full queues. For quarantine/restart semantics use
    reader.supervised(mapper=...)."""

    def xreader():
        in_q: _queue.Queue = _queue.Queue(buffer_size)
        out_q: _queue.Queue = _queue.Queue(buffer_size)
        stop = threading.Event()

        def feed():
            try:
                for i, s in enumerate(reader()):
                    if not _shutdown_put(in_q, ("item", i, s), stop):
                        return
                for _ in range(process_num):
                    if not _shutdown_put(in_q, ("end",), stop):
                        return
            except BaseException as e:
                _shutdown_put(out_q, ("err", e), stop)

        def work():
            while not stop.is_set():
                try:
                    item = in_q.get(timeout=0.1)
                except _queue.Empty:
                    continue
                if item[0] == "end":
                    _shutdown_put(out_q, ("wend",), stop)
                    return
                _, i, s = item
                try:
                    v = mapper(s)
                except BaseException as e:   # surfaced NOW, not at drain
                    _shutdown_put(out_q, ("err", e), stop)
                    return
                if not _shutdown_put(out_q, ("item", i, v), stop):
                    return

        threads = [threading.Thread(target=feed, daemon=True,
                                    name="pt-data-xmap-feed")] + \
            [threading.Thread(target=work, daemon=True,
                              name=f"pt-data-xmap-w{w}")
             for w in range(process_num)]
        for t in threads:
            t.start()

        finished = 0
        pending = {}
        next_i = 0
        try:
            while finished < process_num:
                item = out_q.get()
                if item[0] == "wend":
                    finished += 1
                    continue
                if item[0] == "err":
                    raise item[1]
                _, i, v = item
                if not order:
                    yield v
                else:
                    pending[i] = v
                    while next_i in pending:
                        yield pending.pop(next_i)
                        next_i += 1
            # order mode: indices are dense, so nothing can stay pending
            assert not pending, "xmap_readers lost samples"
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=1.0)

    return xreader


def cache(reader: Reader) -> Reader:
    data: List[Any] = []
    filled = [False]

    def cached():
        if not filled[0]:
            data.extend(reader())
            filled[0] = True
        return iter(data)
    return cached


class creator:
    """reader.creator parity: build readers from arrays/files."""

    @staticmethod
    def np_array(arr) -> Reader:
        def reader():
            for row in arr:
                yield row
        return reader

    @staticmethod
    def text_file(path: str) -> Reader:
        def reader():
            with open(path) as f:
                for line in f:
                    yield line.rstrip("\n")
        return reader

    @staticmethod
    def recordio(paths, buf_size: int = 100) -> Reader:
        """Samples from RecordIO shard file(s) — the output of
        dataset.*.convert() (reader.creator.recordio parity,
        python/paddle/v2/reader/creator.py:60: buffered like the
        reference, background-prefetching buf_size samples). `paths` is
        a path, a comma-separated string, or a list. Records
        deserialize with the convert() pickling; see dataset/common.py
        for the trust note."""
        from paddle_tpu.dataset.common import record_deserializer
        from paddle_tpu.reader import recordio as rio
        if isinstance(paths, str):
            paths = paths.split(",")
        read = rio.chunk_reader(record_deserializer)

        def reader():
            for p in paths:
                for desc in rio.chunk_descriptors(p):
                    yield from read(desc)
        return buffered(reader, buf_size)

    @staticmethod
    def cloud_reader(host: str, port: int,
                     timeout_sec: float = 600.0) -> Reader:
        """Coordinator-dispatched samples (creator.cloud_reader parity,
        creator.py:91 — the etcd master endpoints become the coordinator
        address; the server side holds the shard chunk list). Chunks are
        handed out as fault-tolerant tasks; a crashed consumer's chunk
        re-queues on timeout."""
        from paddle_tpu.dataset.common import record_deserializer
        from paddle_tpu.reader import recordio as rio
        from paddle_tpu.trainer.coordinator import connect, task_reader

        def reader():
            coord = connect(host, port)
            yield from task_reader(
                coord, rio.chunk_reader(record_deserializer),
                idle_timeout=timeout_sec)()
        return reader
