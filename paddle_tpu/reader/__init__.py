"""Reader creators & decorators — python/paddle/v2/reader parity.

Reference: python/paddle/v2/reader/{creator.py,decorator.py}: a *reader* is
a zero-arg callable returning an iterable of samples; decorators compose
(map_readers, buffered, shuffle, compose, chain, firstn, batched...).
`batch` (python/paddle/v2/minibatch.py) groups samples into lists.
"""

from __future__ import annotations

import itertools
import random as _random
import threading
import queue as _queue
from typing import Any, Callable, Iterable, List, Sequence

Reader = Callable[[], Iterable[Any]]


def batch(reader: Reader, batch_size: int, drop_last: bool = False) -> Reader:
    """paddle.batch parity: sample reader -> batch reader."""
    def batch_reader():
        buf: List[Any] = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batch_reader


def shuffle(reader: Reader, buf_size: int, seed=None) -> Reader:
    def shuffled():
        rng = _random.Random(seed)
        buf: List[Any] = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                for s in buf:
                    yield s
                buf = []
        rng.shuffle(buf)
        for s in buf:
            yield s
    return shuffled


def map_readers(func, *readers: Reader) -> Reader:
    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)
    return reader


class ComposeNotAligned(ValueError):
    """Raised when composed readers yield different sample counts
    (python/paddle/v2/reader/decorator.py:90)."""


def compose(*readers: Reader, check_alignment: bool = True) -> Reader:
    """Zip several readers into tuple samples (reader.compose parity).

    With ``check_alignment`` (the default, as the reference), readers of
    unequal length raise ComposeNotAligned instead of silently truncating
    to the shortest (decorator.py:98 _check_input_not_empty zip)."""
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    _end = object()

    def reader():
        its = [r() for r in readers]
        if not check_alignment:
            for items in zip(*its):
                yield sum((make_tuple(i) for i in items), ())
            return
        for items in itertools.zip_longest(*its, fillvalue=_end):
            if any(i is _end for i in items):
                if not all(i is _end for i in items):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                return
            yield sum((make_tuple(i) for i in items), ())
    return reader


def chain(*readers: Reader) -> Reader:
    def reader():
        return itertools.chain(*[r() for r in readers])
    return reader


def firstn(reader: Reader, n: int) -> Reader:
    def limited():
        return itertools.islice(reader(), n)
    return limited


def buffered(reader: Reader, size: int) -> Reader:
    """Async prefetch via a background thread — the DoubleBuffer equivalent
    (paddle/gserver/dataproviders/DataProvider.h:249)."""
    end = object()

    def buffered_reader():
        q: _queue.Queue = _queue.Queue(maxsize=size)

        def fill():
            try:
                for sample in reader():
                    q.put(sample)
            finally:
                q.put(end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is end:
                break
            yield s
    return buffered_reader


def xmap_readers(mapper, reader: Reader, process_num: int,
                 buffer_size: int, order: bool = False) -> Reader:
    """Apply `mapper` to samples with `process_num` worker threads
    (reader.decorator.xmap_readers parity, decorator.py:233 — the
    reference's "processes" are threads too). order=True preserves the
    input order; otherwise samples come out as workers finish. Worker
    exceptions re-raise in the consumer."""
    import queue
    import threading

    end = object()

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)
        errors: List[BaseException] = []

        def feed():
            try:
                for i, s in enumerate(reader()):
                    in_q.put((i, s))
            except BaseException as e:   # surfaced below
                errors.append(e)
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, s = item
                try:
                    out_q.put((i, mapper(s)))
                except BaseException as e:
                    errors.append(e)
                    out_q.put(end)
                    return

        threads = [threading.Thread(target=feed, daemon=True)] + \
            [threading.Thread(target=work, daemon=True)
             for _ in range(process_num)]
        for t in threads:
            t.start()

        finished = 0
        pending = {}
        next_i = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            i, v = item
            if not order:
                yield v
            else:
                pending[i] = v
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
        if errors:
            raise errors[0]
        # order mode: indices are dense, so nothing can remain pending
        assert not pending, "xmap_readers lost samples"

    return xreader


def cache(reader: Reader) -> Reader:
    data: List[Any] = []
    filled = [False]

    def cached():
        if not filled[0]:
            data.extend(reader())
            filled[0] = True
        return iter(data)
    return cached


class creator:
    """reader.creator parity: build readers from arrays/files."""

    @staticmethod
    def np_array(arr) -> Reader:
        def reader():
            for row in arr:
                yield row
        return reader

    @staticmethod
    def text_file(path: str) -> Reader:
        def reader():
            with open(path) as f:
                for line in f:
                    yield line.rstrip("\n")
        return reader

    @staticmethod
    def recordio(paths, buf_size: int = 100) -> Reader:
        """Samples from RecordIO shard file(s) — the output of
        dataset.*.convert() (reader.creator.recordio parity,
        python/paddle/v2/reader/creator.py:60: buffered like the
        reference, background-prefetching buf_size samples). `paths` is
        a path, a comma-separated string, or a list. Records
        deserialize with the convert() pickling; see dataset/common.py
        for the trust note."""
        from paddle_tpu.dataset.common import record_deserializer
        from paddle_tpu.reader import recordio as rio
        if isinstance(paths, str):
            paths = paths.split(",")
        read = rio.chunk_reader(record_deserializer)

        def reader():
            for p in paths:
                for desc in rio.chunk_descriptors(p):
                    yield from read(desc)
        return buffered(reader, buf_size)

    @staticmethod
    def cloud_reader(host: str, port: int,
                     timeout_sec: float = 600.0) -> Reader:
        """Coordinator-dispatched samples (creator.cloud_reader parity,
        creator.py:91 — the etcd master endpoints become the coordinator
        address; the server side holds the shard chunk list). Chunks are
        handed out as fault-tolerant tasks; a crashed consumer's chunk
        re-queues on timeout."""
        from paddle_tpu.dataset.common import record_deserializer
        from paddle_tpu.reader import recordio as rio
        from paddle_tpu.trainer.coordinator import connect, task_reader

        def reader():
            coord = connect(host, port)
            yield from task_reader(
                coord, rio.chunk_reader(record_deserializer),
                idle_timeout=timeout_sec)()
        return reader
