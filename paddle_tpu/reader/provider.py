"""PyDataProvider2 compatibility — the v1 data-provider protocol.

Reference: python/paddle/trainer/PyDataProvider2.py:365 `@provider`
decorates a generator `process(settings, filename)` yielding per-sample
values; the trainer instantiates it per file from
`define_py_data_sources2` with optional shuffling pool and caching.

Here the decorated provider adapts onto the v2 reader protocol (the
framework's native path): `provider_reader(process, file_list)` returns a
zero-arg reader factory usable with paddle.reader.batch / SGD.train, with
CacheType.CACHE_PASS_IN_MEM materializing samples once and should_shuffle
mapped onto reader.shuffle's buffered pool.
"""

from __future__ import annotations

import random
from typing import Any, Callable, List, Optional, Sequence, Union

# re-export the v2 InputTypes under their PyDataProvider2 names so v1
# configs import everything from one place
from paddle_tpu.core.data_type import (InputType, SeqType,  # noqa: F401
                                       dense_vector, dense_vector_sequence,
                                       dense_vector_sub_sequence,
                                       integer_value, integer_value_sequence,
                                       integer_value_sub_sequence,
                                       sparse_binary_vector,
                                       sparse_float_vector)


class SequenceType:
    NO_SEQUENCE = SeqType(0)
    SEQUENCE = SeqType(1)
    SUB_SEQUENCE = SeqType(2)


class CacheType:
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


class _ProviderSettings:
    """The `settings` object handed to process(); init_hook may hang
    arbitrary state (slots, dictionaries) off it, as the reference allows."""

    def __init__(self, input_types, **kwargs):
        self.input_types = input_types
        self.slots = input_types
        for k, v in kwargs.items():
            setattr(self, k, v)


class DataProvider:
    """A decorated provider function plus its protocol options."""

    def __init__(self, generator, input_types, should_shuffle, pool_size,
                 cache, init_hook, check):
        self.generator = generator
        self.input_types = input_types
        self.should_shuffle = should_shuffle
        self.pool_size = pool_size
        self.cache = cache
        self.init_hook = init_hook
        self.check = check
        self.__name__ = getattr(generator, "__name__", "provider")

    def settings(self, **hook_kwargs) -> _ProviderSettings:
        s = _ProviderSettings(self.input_types, **hook_kwargs)
        if self.init_hook is not None:
            self.init_hook(s, **hook_kwargs)
        return s

    def __call__(self, settings, filename):
        return self.generator(settings, filename)


def provider(input_types=None, should_shuffle=None, pool_size=-1,
             min_pool_size=-1, can_over_batch_size=True,
             calc_batch_size=None, cache=CacheType.NO_CACHE,
             check=False, check_fail_continue=False, init_hook=None,
             **outter_kwargs):
    """`@provider(input_types=..., cache=...)` — PyDataProvider2.py:365.

    The decorated `process(settings, filename)` generator becomes a
    DataProvider; feed it to provider_reader() (or SGD.train via
    define_py_data_sources2) to train.
    """

    def wrapper(fn):
        return DataProvider(fn, input_types, should_shuffle,
                            pool_size if pool_size > 0 else min_pool_size,
                            cache, init_hook, check)

    return wrapper


def provider_reader(p: Union[DataProvider, Callable],
                    file_list: Union[str, Sequence[str]],
                    **hook_kwargs) -> Callable:
    """Adapt a @provider onto the v2 reader protocol.

    file_list: list of filenames, or a path to a text file with one
    filename per line (the reference's train.list / test.list contract).
    """
    assert isinstance(p, DataProvider), \
        "provider_reader needs an @provider-decorated function"
    if isinstance(file_list, str):
        with open(file_list) as f:
            files: List[str] = [ln.strip() for ln in f if ln.strip()]
    else:
        files = list(file_list)

    cached: Optional[List[Any]] = None

    def reader():
        nonlocal cached
        if cached is not None:
            samples = cached
            if p.should_shuffle in (None, True):
                samples = list(samples)
                random.shuffle(samples)
            yield from samples
            return
        settings = p.settings(**hook_kwargs)
        out: List[Any] = [] if p.cache == CacheType.CACHE_PASS_IN_MEM else None
        if p.should_shuffle in (None, True):
            # reference semantics: shuffle by default; pool_size <= 0 means
            # an UNBOUNDED pool (whole pass buffered then shuffled)
            pool_cap = p.pool_size if p.pool_size and p.pool_size > 0 \
                else float("inf")
            pool: List[Any] = []
            for fname in files:
                for sample in p(settings, fname):
                    pool.append(sample)
                    if len(pool) >= pool_cap:
                        random.shuffle(pool)
                        for s in pool:
                            if out is not None:
                                out.append(s)
                            yield s
                        pool = []
            random.shuffle(pool)
            for s in pool:
                if out is not None:
                    out.append(s)
                yield s
        else:
            for fname in files:
                for sample in p(settings, fname):
                    if out is not None:
                        out.append(sample)
                    yield sample
        if out is not None:
            cached = out

    return reader


def define_py_data_sources2(train_list, test_list, module, obj,
                            args=None) -> dict:
    """Config-level helper (reference config_parser define_py_data_sources2):
    resolve `module.obj` providers and return v2 readers for each split."""
    import importlib

    if isinstance(module, str):
        module = importlib.import_module(module)
    prov = getattr(module, obj) if isinstance(obj, str) else obj
    kwargs = dict(args or {})
    out = {}
    if train_list is not None:
        out["train"] = provider_reader(prov, train_list, **kwargs)
    if test_list is not None:
        out["test"] = provider_reader(prov, test_list, **kwargs)
    return out
