"""Supervised, self-healing data pipeline (docs/robustness.md).

The reference's ingestion path (PyDataProvider2 + the DoubleBuffer
prefetch thread, paddle/gserver/dataproviders/DataProvider.h:249) died
or silently truncated an epoch on the first bad sample, hung source, or
crashed worker. A production trainer is input-bound as often as it is
compute-bound, so the pipeline itself must supervise its workers and
budget its errors (the MapReduce "skip bad records" discipline, Dean &
Ghemawat OSDI'04) instead of propagating them. Three pieces:

  ErrorBudget          — the per-sample quarantine lane: a raising
                         mapper / corrupt record is skipped, logged and
                         counted into utils/stats
                         (``pipeline/quarantined``); past ``max_bad``
                         the budget emits a DataFaultEvent and, with
                         ``on_bad="raise"``, aborts the epoch with
                         ErrorBudgetExceeded.
  supervised()         — wrap any Reader (+ optional per-sample mapper)
                         in a worker pool with a real lifecycle:
                         bounded prefetch queues, clean shutdown when
                         the consumer abandons the generator (no leaked
                         threads — every thread is named ``pt-data-*``
                         and exits on a shared stop event), a hung-
                         source watchdog with per-sample timeout, and
                         crashed-worker restart (in-flight sample
                         requeued, never lost) with a bounded restart
                         budget.
  CheckpointableReader — recordio/`task_reader`-style sources with a
                         resumable position: (epoch, shard, chunk,
                         record-offset) advances exactly with consumed
                         records, so trainer/checkpoint.py can save it
                         alongside pass/batch/RNG state and a SIGKILL'd
                         run resumes MID-PASS without re-reading or
                         dropping records.

Thread-naming contract: every thread this module (and the reader
decorators) spawns is named ``pt-data-...``; tests/conftest.py fails any
test that leaks one.
"""

from __future__ import annotations

import collections
import queue as _queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

from paddle_tpu.utils.logging import get_logger
from paddle_tpu.analysis.lockdep import named_lock
from paddle_tpu.utils.stats import global_counters

__all__ = ["ErrorBudget", "ErrorBudgetExceeded", "supervised",
           "SupervisedReader", "CheckpointableReader", "THREAD_PREFIX"]

#: every pipeline thread name starts with this; the conftest leak
#: fixture keys on it
THREAD_PREFIX = "pt-data"

_STATE_KEYS = ("epoch", "shard", "chunk", "offset")


def _emit(on_event, kind: str, count: int, error=None, where=None):
    """Build + deliver a DataFaultEvent (lazy import: trainer.event must
    not be a hard import edge from the reader package). Every data
    fault also lands in the structured event journal
    (paddle_tpu/obs/events.py) regardless of handler."""
    from paddle_tpu.obs.events import emit_event
    from paddle_tpu.trainer.event import DataFaultEvent
    ev = DataFaultEvent(kind, count, error=error, where=where)
    emit_event(ev)
    if on_event is not None:
        on_event(ev)
    else:
        get_logger().warning("data pipeline fault: %r", ev)
    return ev


class ErrorBudgetExceeded(RuntimeError):
    """Raised (on_bad="raise") when quarantined samples exceed max_bad."""


class ErrorBudget:
    """The quarantine lane: count bad samples instead of propagating
    them, up to a budget.

    max_bad: quarantined samples tolerated. Exceeding it emits a
        DataFaultEvent(kind="data_budget") once and, with
        ``on_bad="raise"``, raises ErrorBudgetExceeded from the sample
        that crossed the line; ``on_bad="log"`` keeps skipping (the
        event/log is the alarm).
    stat: utils.stats.global_counters name each quarantined sample bumps
        (chaos tests diff it around an epoch).
    on_event: callable receiving the DataFaultEvent (e.g. the trainer's
        event handler); default logs.

    Thread-safe: source and worker threads record concurrently.
    """

    def __init__(self, max_bad: int = 100, on_bad: str = "log",
                 stat: str = "pipeline/quarantined",
                 on_event: Optional[Callable] = None):
        if on_bad not in ("log", "raise"):
            raise ValueError(f"on_bad must be 'log' or 'raise', "
                             f"got {on_bad!r}")
        if max_bad < 0:
            raise ValueError("max_bad must be >= 0")
        self.max_bad = max_bad
        self.on_bad = on_bad
        self.stat = stat
        self.on_event = on_event
        self._lock = named_lock("data.error_budget")
        self.bad = 0
        self.last_errors: collections.deque = collections.deque(maxlen=16)
        self._exhausted_emitted = False

    @property
    def exhausted(self) -> bool:
        return self.bad > self.max_bad

    def record(self, exc: BaseException, where: str = "") -> int:
        """Quarantine one bad sample. Returns the running bad count;
        raises ErrorBudgetExceeded when the budget is blown and
        on_bad="raise"."""
        with self._lock:
            self.bad += 1
            n = self.bad
            self.last_errors.append((where, repr(exc)))
            emit_exhausted = n > self.max_bad and not self._exhausted_emitted
            if emit_exhausted:
                self._exhausted_emitted = True
        global_counters.bump(self.stat)
        from paddle_tpu.obs.events import emit as journal_emit
        journal_emit("data", "quarantine", count=n, where=where,
                     error=repr(exc)[:400])
        if n <= 3 or n % 50 == 0:
            get_logger().warning(
                "quarantined bad sample #%d at %s: %r", n, where, exc)
        if emit_exhausted:
            _emit(self.on_event, "data_budget", n, error=exc, where=where)
        if n > self.max_bad and self.on_bad == "raise":
            raise ErrorBudgetExceeded(
                f"error budget exhausted: {n} bad samples "
                f"(max_bad={self.max_bad}); last at {where}: "
                f"{exc!r}") from exc
        return n


def _stop_put(q: "_queue.Queue", item, stop: threading.Event) -> bool:
    """Blocking put that gives up when the pipeline is shutting down —
    the reason an abandoned generator can never wedge a fill thread on a
    full queue."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except _queue.Full:
            continue
    return False


class SupervisedReader:
    """``supervised()``'s return value — a Reader with a lifecycle.

    Each call builds a fresh run: a source thread prefetching from
    ``reader()``, ``num_workers`` mapper threads (when a mapper is
    given), bounded queues, and a consumer-side watchdog. See
    :func:`supervised` for the knobs. When the source is checkpointable
    (CheckpointableReader-like) and delivery preserves source order
    (``order=True`` or no mapper), this reader is checkpointable too:
    ``state()`` tracks the position after the last *yielded* sample.
    """

    def __init__(self, reader: Callable, mapper: Optional[Callable] = None,
                 num_workers: int = 2, buffer_size: int = 16,
                 sample_timeout: Optional[float] = None,
                 error_budget: Optional[ErrorBudget] = None,
                 max_restarts: int = 4, on_stall: str = "warn",
                 stall_limit: int = 8, order: bool = False,
                 on_event: Optional[Callable] = None,
                 name: str = "pipeline"):
        if on_stall not in ("warn", "raise"):
            raise ValueError("on_stall must be 'warn' or 'raise'")
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self._reader = reader
        self._mapper = mapper
        self._num_workers = num_workers if mapper is not None else 0
        self._buffer_size = max(1, buffer_size)
        self._sample_timeout = sample_timeout
        self.error_budget = error_budget or ErrorBudget(on_event=on_event)
        if self.error_budget.on_event is None:
            self.error_budget.on_event = on_event
        self._max_restarts = max_restarts
        self._on_stall = on_stall
        self._stall_limit = stall_limit
        self._order = order
        self._on_event = on_event
        self._name = name
        # source-side quarantine: a CheckpointableReader without its own
        # budget shares this pipeline's, so decode errors and mapper
        # errors draw from ONE budget
        if getattr(reader, "error_budget", "missing") is None:
            reader.error_budget = self.error_budget
        self.checkpointable = (
            hasattr(reader, "state") and hasattr(reader, "set_state") and
            (order or mapper is None))
        self._cursor: Optional[Dict[str, int]] = None
        self.restarts = 0
        self.stalls = 0

    # -------------------------------------------------- checkpoint state
    def state(self) -> Dict[str, int]:
        """Position after the last yielded sample (delegates to the
        source before the first yield)."""
        if not self.checkpointable:
            raise TypeError(
                f"{self._name}: not checkpointable (source has no "
                "state()/set_state(), or order=False with a mapper)")
        return dict(self._cursor) if self._cursor is not None \
            else self._reader.state()

    def set_state(self, st: Dict[str, int]) -> None:
        if not self.checkpointable:
            raise TypeError(f"{self._name}: not checkpointable")
        self._reader.set_state(st)
        self._cursor = None

    # ------------------------------------------------------------- run
    def __call__(self) -> Iterable[Any]:
        return self._run()

    def _run(self):
        stop = threading.Event()
        out_q: "_queue.Queue" = _queue.Queue(self._buffer_size)
        in_q: "_queue.Queue" = _queue.Queue(self._buffer_size) \
            if self._mapper is not None else out_q
        src_busy: List[Optional[float]] = [None]
        budget = self.error_budget
        mapper = self._mapper
        track_pos = self.checkpointable
        name = self._name

        def source():
            try:
                it = iter(self._reader())
                i = 0
                while True:
                    src_busy[0] = time.monotonic()
                    try:
                        sample = next(it)
                    except StopIteration:
                        break
                    finally:
                        src_busy[0] = None
                    pos = self._reader.state() if track_pos else None
                    if mapper is None:
                        if not _stop_put(out_q, ("item", i, sample, pos),
                                         stop):
                            return
                    else:
                        if not _stop_put(in_q, ("item", i, sample, pos),
                                         stop):
                            return
                    i += 1
                _stop_put(out_q, ("send", i), stop)
            except BaseException as e:      # incl. ErrorBudgetExceeded
                _stop_put(out_q, ("err", e), stop)

        worker_busy: List[List[Optional[float]]] = []

        def work(wid: int, busy: List[Optional[float]]):
            while not stop.is_set():
                try:
                    msg = in_q.get(timeout=0.1)
                except _queue.Empty:
                    continue
                _, i, sample, pos = msg
                busy[0] = time.monotonic()
                try:
                    value = mapper(sample)
                except Exception as e:          # bad SAMPLE: quarantine
                    busy[0] = None
                    try:
                        budget.record(e, where=f"{name} sample #{i} "
                                               f"(mapper)")
                    except ErrorBudgetExceeded as bx:
                        _stop_put(out_q, ("err", bx), stop)
                        return
                    _stop_put(out_q, ("skip", i, pos), stop)
                    continue
                except BaseException as e:      # the WORKER crashed
                    busy[0] = None
                    # report death FIRST so the supervisor can spawn a
                    # replacement that drains in_q — requeueing first
                    # could deadlock a lone worker against a full queue
                    _stop_put(out_q, ("died", wid, e), stop)
                    _stop_put(in_q, ("item", i, sample, pos), stop)
                    return
                busy[0] = None
                if not _stop_put(out_q, ("item", i, value, pos), stop):
                    return

        threads = [threading.Thread(target=source, daemon=True,
                                    name=f"{THREAD_PREFIX}-{name}-src")]
        for w in range(self._num_workers):
            busy: List[Optional[float]] = [None]
            worker_busy.append(busy)
            threads.append(threading.Thread(
                target=work, args=(w, busy), daemon=True,
                name=f"{THREAD_PREFIX}-{name}-w{w}"))
        for t in threads:
            t.start()

        timeout = self._sample_timeout
        tick = min(max(timeout / 4.0, 0.05), 1.0) if timeout else 0.5
        stall_ticks = 0
        n_total = None
        completed = 0
        restarts = 0
        pending: Dict[int, Any] = {}
        skipped: Dict[int, Any] = {}   # idx -> pos (quarantined holes)
        next_i = 0
        self._cursor = None

        def stalled_where(now: float) -> List[str]:
            out = []
            b = src_busy[0]
            if b is not None and now - b > timeout:
                out.append(f"source ({now - b:.1f}s)")
            for w, busy in enumerate(worker_busy):
                b = busy[0]
                if b is not None and now - b > timeout:
                    out.append(f"worker {w} ({now - b:.1f}s)")
            return out

        try:
            while n_total is None or completed < n_total:
                try:
                    msg = out_q.get(timeout=tick)
                except _queue.Empty:
                    if timeout is None:
                        continue
                    where = stalled_where(time.monotonic())
                    if not where:
                        stall_ticks = 0
                        continue
                    stall_ticks += 1
                    self.stalls += 1
                    global_counters.bump("pipeline/stalls")
                    if stall_ticks == 1 or stall_ticks % 5 == 0:
                        get_logger().warning(
                            "%s: no sample for > %.2fs — stalled at %s "
                            "(tick %d)", name, timeout, ", ".join(where),
                            stall_ticks)
                        _emit(self._on_event, "source_stall", stall_ticks,
                              where=", ".join(where))
                    if self._on_stall == "raise" and \
                            stall_ticks >= self._stall_limit:
                        raise TimeoutError(
                            f"{name}: pipeline stalled for "
                            f"~{stall_ticks * tick:.1f}s at "
                            f"{', '.join(where)} (sample_timeout="
                            f"{timeout}s, on_stall='raise')")
                    continue
                stall_ticks = 0
                kind = msg[0]
                if kind == "send":
                    n_total = msg[1]
                elif kind == "err":
                    raise msg[1]
                elif kind == "died":
                    _, wid, exc = msg
                    restarts += 1
                    self.restarts = restarts
                    global_counters.bump("pipeline/worker_restarts")
                    get_logger().warning(
                        "%s: worker %d crashed (%r); in-flight sample "
                        "requeued; restart %d/%d", name, wid, exc,
                        restarts, self._max_restarts)
                    if restarts > self._max_restarts:
                        _emit(self._on_event, "restart_budget", restarts,
                              error=exc, where=f"{name} worker {wid}")
                        raise RuntimeError(
                            f"{name}: worker restart budget exhausted "
                            f"({restarts} > max_restarts="
                            f"{self._max_restarts})") from exc
                    _emit(self._on_event, "worker_restart", restarts,
                          error=exc, where=f"{name} worker {wid}")
                    busy = worker_busy[wid]
                    t = threading.Thread(
                        target=work, args=(wid, busy), daemon=True,
                        name=f"{THREAD_PREFIX}-{name}-w{wid}r{restarts}")
                    threads.append(t)
                    t.start()
                elif kind == "skip":
                    completed += 1
                    if self._order:
                        skipped[msg[1]] = msg[2]
                elif kind == "item":
                    _, i, value, pos = msg
                    completed += 1
                    if not self._order or mapper is None:
                        if track_pos:
                            self._cursor = pos
                        yield value
                    else:
                        pending[i] = (value, pos)
                # drain in-order deliveries (and skipped holes)
                if self._order and mapper is not None:
                    while True:
                        if next_i in skipped:
                            pos = skipped.pop(next_i)
                            if track_pos and pos is not None:
                                # the quarantined record is consumed:
                                # advance past it so a resume doesn't
                                # re-read (and re-count) it
                                self._cursor = pos
                            next_i += 1
                            continue
                        if next_i in pending:
                            value, pos = pending.pop(next_i)
                            next_i += 1
                            if track_pos:
                                self._cursor = pos
                            yield value
                            continue
                        break
            assert not pending, f"{name}: lost in-flight samples"
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=1.0)


def supervised(reader: Callable, mapper: Optional[Callable] = None,
               num_workers: int = 2, buffer_size: int = 16,
               sample_timeout: Optional[float] = None,
               error_budget: Optional[ErrorBudget] = None,
               max_restarts: int = 4, on_stall: str = "warn",
               stall_limit: int = 8, order: bool = False,
               on_event: Optional[Callable] = None,
               name: str = "pipeline") -> SupervisedReader:
    """Wrap ``reader`` (and an optional per-sample ``mapper``) in a
    supervised prefetch pipeline — the self-healing replacement for
    ``buffered``/``xmap_readers`` (docs/robustness.md "Data pipeline").

    reader: a v2 Reader (zero-arg callable -> iterable of samples).
    mapper: optional per-sample transform run by ``num_workers``
        threads. A mapper raising an ``Exception`` quarantines THAT
        sample through the error budget; a worker dying on any other
        ``BaseException`` has its in-flight sample requeued and the
        worker replaced, up to ``max_restarts``.
    buffer_size: bound of the prefetch queues — backpressure, and the
        shutdown guarantee: an abandoned generator stops the fill
        threads instead of leaking them against a full queue.
    sample_timeout: hung-source watchdog period (seconds per sample).
        A source/worker stuck past it logs, bumps the
        ``pipeline/stalls`` counter and emits
        DataFaultEvent(kind="source_stall"); with ``on_stall="raise"``
        the consumer gets a TimeoutError after ``stall_limit``
        consecutive stalled ticks instead of hanging forever. ``None``
        disables the watchdog.
    error_budget: shared ErrorBudget (default: a fresh
        ``ErrorBudget(max_bad=100, on_bad="log")``). A source with
        ``error_budget=None`` (CheckpointableReader) adopts it, so
        decode and mapper errors draw from one budget.
    order: deliver mapper outputs in source order (needed for
        checkpointability through a mapper).
    on_event: receives each DataFaultEvent (e.g. the trainer's event
        handler); default logs.
    """
    return SupervisedReader(
        reader, mapper=mapper, num_workers=num_workers,
        buffer_size=buffer_size, sample_timeout=sample_timeout,
        error_budget=error_budget, max_restarts=max_restarts,
        on_stall=on_stall, stall_limit=stall_limit, order=order,
        on_event=on_event, name=name)


class CheckpointableReader:
    """RecordIO sample reader with a resumable position.

    Yields (deserialized) records of ``paths`` (a path, comma-separated
    string, or list — the ``creator.recordio`` contract) while tracking
    the exact position (epoch, shard, chunk, record-offset) AFTER the
    last yielded sample: ``state()`` is always safe to save, and
    ``set_state()`` makes the next iteration resume mid-pass without
    re-reading or dropping consumed records. ``trainer/checkpoint.py``
    saves this state alongside pass/batch/RNG state when the train
    reader is checkpointable (``reader.batch`` propagates it).

    error_budget: quarantine lane for records that fail to deserialize
        (corrupt pickled records): counted + skipped, position still
        advances. ``None`` re-raises (strict mode) — ``supervised()``
        injects its own budget into a reader left at None.
    skip_corrupt_chunks: forward to recordio.read_chunk — crc-level
        corruption drops the chunk (counted separately in
        ``corrupt_chunks_skipped``), record-level corruption is this
        class's per-sample lane.
    """

    def __init__(self, paths, deserialize: Optional[Callable] = "pickle",
                 error_budget: Optional[ErrorBudget] = None,
                 skip_corrupt_chunks: bool = False):
        if isinstance(paths, str):
            paths = paths.split(",")
        self.paths = [p for p in paths if p]
        if not self.paths:
            raise ValueError("CheckpointableReader needs >= 1 shard path")
        if deserialize == "pickle":
            from paddle_tpu.dataset.common import record_deserializer
            deserialize = record_deserializer
        self._deserialize = deserialize
        self.error_budget = error_budget
        self._skip_corrupt_chunks = skip_corrupt_chunks
        self._epoch = 0
        self._pending: Optional[Dict[str, int]] = None
        self._cursor = {"epoch": 0, "shard": 0, "chunk": 0, "offset": 0}

    def state(self) -> Dict[str, int]:
        """Position of the next unconsumed record."""
        return dict(self._cursor)

    def set_state(self, st: Dict[str, int]) -> None:
        missing = [k for k in _STATE_KEYS if k not in st]
        if missing:
            raise ValueError(f"reader state missing keys {missing}; "
                             f"expected {list(_STATE_KEYS)}")
        pend = {k: int(st[k]) for k in _STATE_KEYS}
        if any(v < 0 for v in pend.values()):
            raise ValueError(f"reader state must be non-negative: {pend}")
        if pend["shard"] >= len(self.paths):
            raise ValueError(
                f"reader state shard {pend['shard']} out of range for "
                f"{len(self.paths)} shard(s) — was the shard list "
                "reordered or truncated since the checkpoint?")
        self._pending = pend
        self._cursor = dict(pend)   # state() reflects the seek at once

    def __call__(self) -> Iterable[Any]:
        start = self._pending or {"epoch": self._epoch, "shard": 0,
                                  "chunk": 0, "offset": 0}
        self._pending = None
        return self._iter(start)

    def _iter(self, start: Dict[str, int]):
        from paddle_tpu.reader import recordio as rio
        epoch = start["epoch"]
        self._epoch = epoch
        self._cursor = dict(start)
        s0, c0, o0 = start["shard"], start["chunk"], start["offset"]
        for s in range(s0, len(self.paths)):
            path = self.paths[s]
            for k in range(c0 if s == s0 else 0, rio.num_chunks(path)):
                recs = rio.read_chunk(
                    path, k, skip_corrupt=self._skip_corrupt_chunks)
                first = o0 if (s == s0 and k == c0) else 0
                for j in range(first, len(recs)):
                    nxt = {"epoch": epoch, "shard": s, "chunk": k,
                           "offset": j + 1}
                    if self._deserialize is None:
                        self._cursor = nxt
                        yield recs[j]
                        continue
                    try:
                        val = self._deserialize(recs[j])
                    except Exception as e:
                        # the record is consumed either way — quarantine
                        # advances the position so a resume cannot
                        # re-trip on it forever
                        self._cursor = nxt
                        if self.error_budget is None:
                            raise
                        self.error_budget.record(
                            e, where=f"{path} chunk {k} record {j}")
                        continue
                    self._cursor = nxt
                    yield val
        self._epoch = epoch + 1
        self._cursor = {"epoch": self._epoch, "shard": 0, "chunk": 0,
                        "offset": 0}
