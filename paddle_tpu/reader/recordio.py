"""PTRecordIO — chunked record files; the elastic data plane's format.

Reference role: the Go master partitioned RecordIO chunks into tasks
(go/master/service.go:106) and trainers streamed records per task; the
C++ DataProviders did the disk IO. Here:

- the native codec is `paddle_tpu/native/recordio.cc` (C ABI, built
  on demand with the system compiler and loaded via ctypes);
- this module holds a byte-identical pure-Python twin (used when no
  compiler exists) and the user-facing API:

      write_records(path, records_iter)
      num_chunks(path) / read_chunk(path, k) -> [bytes]
      chunk_reader(path)     -> the Coordinator's chunk_reader callable
      chunk_descriptors(path) -> chunk list for Coordinator(chunks=...)

Layout (little-endian u32): chunk := magic "PTRC" | num_records |
payload_len | crc32(payload) | payload; payload := (len | bytes)*.

Robustness (docs/robustness.md):
- writes land in ``path + ".tmp"`` then ``os.replace`` — a crash
  mid-write never leaves a torn shard at the final path (the checkpoint
  atomicity protocol);
- a truncated/torn TAIL (bad magic or a chunk running past EOF) ends
  the index with a warning instead of killing the job;
- ``skip_corrupt=True`` on read_chunk/chunk_reader logs and SKIPS a
  crc-mismatched chunk (counted in ``corrupt_chunks_skipped()``)
  instead of aborting mid-epoch. Same semantics on the native path.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import tempfile
import zlib
from typing import Iterable, List, Optional

from paddle_tpu.utils.logging import get_logger

_MAGIC = 0x50545243
_HDR = struct.Struct("<IIII")

#: chunks dropped by skip_corrupt across this process (all shards)
_CORRUPT_SKIPPED = [0]


def corrupt_chunks_skipped() -> int:
    """How many crc-mismatched chunks skip_corrupt dropped (process-wide
    counter; chaos tests diff it around an epoch)."""
    return _CORRUPT_SKIPPED[0]

# --------------------------------------------------------------- native

_lib = None
_lib_tried = False


def _native() -> Optional[ctypes.CDLL]:
    """Build (once) and load the native codec; None if no compiler."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native", "recordio.cc")
    if not os.path.exists(src):
        return None
    import shutil
    cc = shutil.which("g++") or shutil.which("gcc") or shutil.which("cc")
    if cc is None:
        return None
    so = os.path.join(tempfile.gettempdir(),
                      f"libptrecordio_{os.getuid()}.so")
    try:
        if (not os.path.exists(so) or
                os.path.getmtime(so) < os.path.getmtime(src)):
            subprocess.run([cc, "-O2", "-shared", "-fPIC", "-o", so, src],
                           check=True, capture_output=True, timeout=120)
        lib = ctypes.CDLL(so)
    except Exception:
        return None
    lib.pt_writer_open.restype = ctypes.c_void_p
    lib.pt_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
    lib.pt_writer_write.restype = ctypes.c_int
    lib.pt_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint32]
    lib.pt_writer_close.restype = ctypes.c_int
    lib.pt_writer_close.argtypes = [ctypes.c_void_p]
    lib.pt_reader_open.restype = ctypes.c_void_p
    lib.pt_reader_open.argtypes = [ctypes.c_char_p]
    lib.pt_reader_num_chunks.restype = ctypes.c_uint32
    lib.pt_reader_num_chunks.argtypes = [ctypes.c_void_p]
    lib.pt_reader_seek_chunk.restype = ctypes.c_int
    lib.pt_reader_seek_chunk.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.pt_reader_next.restype = ctypes.c_int64
    lib.pt_reader_next.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.POINTER(
                                       ctypes.c_uint8))]
    lib.pt_reader_close.restype = None
    lib.pt_reader_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


# --------------------------------------------------------------- writing


def write_records(path: str, records: Iterable[bytes],
                  max_chunk_bytes: int = 1 << 20,
                  use_native: Optional[bool] = None) -> None:
    """Write an iterable of byte records as a PTRecordIO file.
    Atomic: bytes land in ``path + ".tmp"`` and are renamed into place
    only after a successful flush/close — a crash mid-write leaves the
    previous shard (or nothing) at ``path``, never a torn file that
    passes ``os.path.exists`` (the checkpoint atomicity protocol)."""
    lib = _native() if use_native in (None, True) else None
    if use_native is True and lib is None:
        raise RuntimeError("native recordio codec unavailable")
    tmp = path + ".tmp"
    try:
        if lib is not None:
            w = lib.pt_writer_open(tmp.encode(), max_chunk_bytes)
            if not w:
                raise OSError(f"cannot open {tmp!r} for writing")
            try:
                for rec in records:
                    if lib.pt_writer_write(w, rec, len(rec)) != 0:
                        raise OSError("recordio write failed")
            finally:
                if lib.pt_writer_close(w) != 0:
                    raise OSError("recordio flush/close failed")
        else:
            # pure-python twin
            with open(tmp, "wb") as f:
                payload = bytearray()
                n = 0

                def flush():
                    nonlocal payload, n
                    if not n:
                        return
                    f.write(_HDR.pack(
                        _MAGIC, n, len(payload),
                        zlib.crc32(bytes(payload)) & 0xFFFFFFFF))
                    f.write(payload)
                    payload = bytearray()
                    n = 0

                for rec in records:
                    payload += struct.pack("<I", len(rec)) + rec
                    n += 1
                    if len(payload) >= max_chunk_bytes:
                        flush()
                flush()
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    os.replace(tmp, path)


# --------------------------------------------------------------- reading


#: path -> ((mtime_ns, size), index) — reading chunk k re-walked every
#: chunk header before it (O(chunks^2) over a full shard sweep); shards
#: are immutable once written, so cache the index per file identity
_INDEX_CACHE: dict = {}


def _py_index(path: str) -> List[tuple]:
    import os
    st = os.stat(path)
    ident = (st.st_mtime_ns, st.st_size)
    hit = _INDEX_CACHE.get(path)
    if hit is not None and hit[0] == ident:
        return hit[1]
    chunks = []
    with open(path, "rb") as f:
        while True:
            off = f.tell()
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                break
            magic, n, plen, crc = _HDR.unpack(hdr)
            if magic != _MAGIC:
                # a torn/truncated tail (crash mid-append, partial copy):
                # the shard ends here — salvage the intact prefix instead
                # of killing the whole job
                get_logger().warning(
                    "%s: bad chunk magic at byte %d — treating as "
                    "end-of-file (torn shard tail?); %d intact chunks "
                    "indexed", path, off, len(chunks))
                break
            if off + _HDR.size + plen > st.st_size:
                # header intact but the payload runs past EOF: a chunk
                # whose write never completed — same salvage semantics
                get_logger().warning(
                    "%s: chunk at byte %d declares %d payload bytes but "
                    "the file ends at %d — dropping the torn tail chunk "
                    "(%d intact chunks indexed)", path, off, plen,
                    st.st_size, len(chunks))
                break
            chunks.append((off, n, plen, crc))
            f.seek(plen, 1)
    if len(_INDEX_CACHE) > 256:      # bound the cache
        _INDEX_CACHE.clear()
    _INDEX_CACHE[path] = (ident, chunks)
    return chunks


def num_chunks(path: str, use_native: Optional[bool] = None) -> int:
    lib = _native() if use_native in (None, True) else None
    if lib is not None:
        r = lib.pt_reader_open(path.encode())
        if not r:
            raise OSError(f"cannot open {path!r}")
        try:
            return int(lib.pt_reader_num_chunks(r))
        finally:
            lib.pt_reader_close(r)
    return len(_py_index(path))


def _skip_corrupt_chunk(path: str, k: int) -> List[bytes]:
    """Shared skip_corrupt tail: log, count, return an empty chunk."""
    _CORRUPT_SKIPPED[0] += 1
    get_logger().warning(
        "%s: chunk %d crc mismatch — skipping its records "
        "(skip_corrupt; %d corrupt chunks skipped so far)",
        path, k, _CORRUPT_SKIPPED[0])
    return []


def read_chunk(path: str, k: int,
               use_native: Optional[bool] = None,
               skip_corrupt: bool = False) -> List[bytes]:
    """All records of chunk k (crc-validated). A crc mismatch raises
    ValueError — or, with ``skip_corrupt=True``, logs, bumps the
    ``corrupt_chunks_skipped()`` counter and returns [] so an epoch
    completes with just that chunk's records missing."""
    lib = _native() if use_native in (None, True) else None
    if use_native is True and lib is None:
        raise RuntimeError("native recordio codec unavailable")
    if lib is not None:
        r = lib.pt_reader_open(path.encode())
        if not r:
            raise OSError(f"cannot open {path!r}")
        try:
            rc = lib.pt_reader_seek_chunk(r, k)
            if rc == -2:
                if skip_corrupt:
                    return _skip_corrupt_chunk(path, k)
                raise ValueError(f"{path}: chunk {k} crc mismatch")
            if rc != 0:
                raise IndexError(f"{path}: no chunk {k}")
            out = []
            ptr = ctypes.POINTER(ctypes.c_uint8)()
            while True:
                ln = lib.pt_reader_next(r, ctypes.byref(ptr))
                if ln < 0:
                    break
                out.append(ctypes.string_at(ptr, ln))
            return out
        finally:
            lib.pt_reader_close(r)
    chunks = _py_index(path)
    if k >= len(chunks):
        raise IndexError(f"{path}: no chunk {k}")
    off, n, plen, crc = chunks[k]
    with open(path, "rb") as f:
        f.seek(off + _HDR.size)
        payload = f.read(plen)
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        if skip_corrupt:
            return _skip_corrupt_chunk(path, k)
        raise ValueError(f"{path}: chunk {k} crc mismatch")
    out = []
    cur = 0
    while cur + 4 <= plen:
        (ln,) = struct.unpack_from("<I", payload, cur)
        out.append(bytes(payload[cur + 4:cur + 4 + ln]))
        cur += 4 + ln
    return out


# ------------------------------------------------------- coordinator glue


def chunk_descriptors(path: str) -> List[tuple]:
    """[(path, k)] — the opaque chunk list for Coordinator(chunks=...)."""
    return [(path, k) for k in range(num_chunks(path))]


def chunk_reader(deserialize=None, skip_corrupt: bool = False):
    """Returns the Coordinator-side chunk_reader: takes a (path, k)
    descriptor, yields (deserialized) records of that chunk. With
    ``skip_corrupt=True`` a crc-mismatched chunk is logged + counted
    and yields nothing instead of aborting the epoch."""
    def read(desc):
        path, k = desc
        for rec in read_chunk(path, k, skip_corrupt=skip_corrupt):
            yield deserialize(rec) if deserialize else rec
    return read
