"""Exactly-once audits over the structured event journal.

The settle audit existed three times before this module: twice in
tests/test_fleet_faults.py (the replica-SIGKILL and router-SIGKILL
suites both asserted "one fleet/settle per trace_id, every sent trace
covered") and once, shape-shifted, in tests/test_embed_faults.py (the
applied-seq ledger equivalent). The soak verdict engine
(paddle_tpu/loadgen/verdict.py) needs the same audit a fourth time —
so it lives here once, in two layers:

- :func:`audit_exactly_once` — the NON-RAISING core: count settles
  per trace_id across one or many journals and report duplicates /
  losses / strays as data. The verdict engine folds this dict into
  the machine-readable soak report.
- :func:`assert_exactly_once` — the pytest-facing wrapper that turns
  the same dict into one readable assertion failure.

``journals`` is deliberately polymorphic: a journal path, a list of
paths (merged via obs/merge.py so cross-process ordering holds), or
an already-merged/parsed list of record dicts — the chaos tests hold
paths, the verdict engine holds merged records.

The embedding plane's exactly-once is ledger-based, not journal-based
(WAL-before-ack; digest equality is the proof), so it gets its own
helper: :func:`assert_exactly_once_applied` checks per-shard
``applied_seqs()`` ledgers against an expected map — the shared shape
under tests/test_embed_faults.py's digest comparisons.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Union

__all__ = ["audit_exactly_once", "assert_exactly_once",
           "assert_exactly_once_applied"]

Journals = Union[str, Sequence[str], Sequence[dict]]


def _load_records(journals: Journals) -> List[dict]:
    """Normalize the polymorphic ``journals`` argument to a record
    list. Multiple paths go through ``merge_journals`` so the records
    carry ``mseq`` and a cross-process total order; raw record lists
    pass through untouched (the caller already merged)."""
    if isinstance(journals, str):
        journals = [journals]
    journals = list(journals)
    if not journals:
        return []
    if isinstance(journals[0], dict):
        return journals                      # already parsed/merged
    from paddle_tpu.obs.merge import merge_journals
    if len(journals) == 1:
        # single journal: plain read (no clock adjustment to do), but
        # tolerate a torn final line the same way read_journal does
        recs = []
        with open(journals[0], encoding="utf-8") as fh:
            lines = fh.read().splitlines()
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    break                    # torn final line
                raise
        return recs
    return merge_journals([os.fspath(p) for p in journals])


def audit_exactly_once(journals: Journals,
                       expected_traces: Iterable[str],
                       domain: str = "fleet",
                       kind: str = "settle") -> dict:
    """Audit that every expected trace settled EXACTLY once.

    Returns a report dict (never raises):

    - ``ok``          True iff zero duplicates and zero losses
    - ``expected``    number of expected trace_ids
    - ``settled``     distinct trace_ids with >= 1 settle record
    - ``duplicates``  {trace_id: settle_count} for counts > 1
    - ``lost``        expected trace_ids with NO settle record
    - ``strays``      settled trace_ids outside the expected set
                      (informational: a prime/control request is
                      legitimate — strays do NOT fail the audit)
    """
    expected = {str(t) for t in expected_traces}
    counts: Dict[str, int] = {}
    for rec in _load_records(journals):
        if rec.get("domain") != domain or rec.get("kind") != kind:
            continue
        tid = rec.get("trace_id")
        if tid is None:
            continue
        counts[str(tid)] = counts.get(str(tid), 0) + 1
    dups = {t: n for t, n in counts.items() if n > 1}
    lost = sorted(expected - set(counts))
    strays = sorted(set(counts) - expected)
    return {"ok": not dups and not lost,
            "domain": domain, "kind": kind,
            "expected": len(expected),
            "settled": len(counts),
            "duplicates": dups,
            "lost": lost,
            "strays": strays}


def assert_exactly_once(journals: Journals,
                        expected_traces: Iterable[str],
                        domain: str = "fleet",
                        kind: str = "settle") -> dict:
    """Raise AssertionError unless every expected trace settled
    exactly once; returns the :func:`audit_exactly_once` report so a
    test can keep asserting on strays/counts."""
    report = audit_exactly_once(journals, expected_traces,
                                domain=domain, kind=kind)
    assert report["ok"], (
        f"exactly-once violated for {domain}/{kind}: "
        f"{len(report['duplicates'])} duplicated trace(s) "
        f"{report['duplicates']!r}, {len(report['lost'])} lost "
        f"trace(s) {report['lost']!r} "
        f"(expected {report['expected']}, settled {report['settled']})")
    return report


def assert_exactly_once_applied(
        shards, expected_seqs: Dict[int, dict],
        dup_acks: Optional[int] = None,
        min_dup_acks: int = 0) -> None:
    """The embedding plane's exactly-once: each shard's applied-seq
    ledger must equal the reference run's — a retried seq that
    re-applied would show a doubled high-water mark, a lost WAL replay
    a missing one. ``shards`` maps shard_id -> object with
    ``applied_seqs()`` (EmbeddingShard), or is an EmbedService (its
    ``.shard(sid)`` accessor is used). With ``dup_acks`` given, also
    require at least ``min_dup_acks`` deduped retries — the proof the
    torn window was actually exercised, not skipped."""
    for sid, want in expected_seqs.items():
        shard = shards.shard(sid) if hasattr(shards, "shard") \
            else shards[sid]
        got = shard.applied_seqs()
        assert got == want, (
            f"shard {sid} applied-seq ledger diverged from the "
            f"uninterrupted reference: got {got!r}, want {want!r} — "
            "a retry re-applied (doubled) or a WAL replay was lost")
    if dup_acks is not None:
        assert dup_acks >= min_dup_acks, (
            f"expected >= {min_dup_acks} deduped same-seq retr"
            f"{'y' if min_dup_acks == 1 else 'ies'} (dup_acks), got "
            f"{dup_acks} — the torn-window retry path never ran")
