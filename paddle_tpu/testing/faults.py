"""Deterministic fault injection for chaos-testing the training loop.

The reference stack's fault tolerance was proven by hope: the Go master
re-queued tasks and the pserver checkpointed, but nothing in the tree
could *inject* a disk-full mid-checkpoint or a dropped RPC on demand.
This module is that missing harness: a seedable :class:`FaultPlan` that
can

  (a) raise ``OSError`` (ENOSPC by default) inside a checkpoint write at
      a chosen save index and byte offset — including TORN writes that
      leave a truncated artifact on disk;
  (b) drop or delay chosen coordinator RPCs (by method name and 0-based
      call index, or at a seeded random rate);
  (c) poison chosen training batches so the loss goes NaN/Inf at exact
      step indices;
  (d) SIGKILL a subprocess trainer when its stdout reaches a chosen
      step marker.

Everything is deterministic given the seed and the schedule, so a chaos
test that fails replays exactly. See ``tests/test_faults.py`` for the
tests that drive all four against the real loop, and
``docs/robustness.md`` for the recipe.
"""

from __future__ import annotations

import contextlib
import errno
import os
import random
import re
import signal
import threading
import time
from typing import Callable, Dict, Iterable, Optional, Sequence, Set

import numpy as np

__all__ = ["FaultPlan", "FlakyCoordinator"]


class FlakyCoordinator:
    """Proxy over a coordinator (in-process or RPC) that injects
    transport faults on chosen calls.

    drop: {method: iterable of 0-based call indices} — those calls raise
        ConnectionError WITHOUT reaching the target (the request is
        lost on the wire).
    delay: {method: {call index: seconds}} — those calls sleep first,
        then go through (a slow network / GC-paused server).
    drop_rate: additionally drop each call with this seeded probability.

    Counters are per method name. Attributes that aren't callable (an
    in-process Coordinator's `epoch` property) pass straight through."""

    def __init__(self, target, drop: Optional[Dict[str, Iterable[int]]] = None,
                 delay: Optional[Dict[str, Dict[int, float]]] = None,
                 drop_rate: float = 0.0, seed: int = 0):
        self._target = target
        self._drop = {m: set(v) for m, v in (drop or {}).items()}
        self._delay = {m: dict(v) for m, v in (delay or {}).items()}
        self._drop_rate = drop_rate
        self._rng = random.Random(seed)
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.faults_injected = 0

    def __getattr__(self, name):
        val = getattr(self._target, name)
        if not callable(val):
            return val

        def call(*args, **kw):
            with self._lock:
                i = self._counts.get(name, 0)
                self._counts[name] = i + 1
                dropped = i in self._drop.get(name, ()) or (
                    self._drop_rate and
                    self._rng.random() < self._drop_rate)
                wait = self._delay.get(name, {}).get(i, 0.0)
                if dropped or wait:
                    self.faults_injected += 1
            if wait:
                time.sleep(wait)
            if dropped:
                raise ConnectionError(
                    f"injected drop: {name}() call #{i}")
            return val(*args, **kw)
        return call


class FaultPlan:
    """A seedable schedule of faults to drive against the real loop."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)

    # ------------------------------------------------- (a) checkpoint IO
    @contextlib.contextmanager
    def checkpoint_write_failure(self, at_save: int = 0,
                                 at_byte: Optional[int] = None,
                                 errnum: int = errno.ENOSPC):
        """Within the context, the ``at_save``-th checkpoint state write
        (0-based, counting every CheckpointManager.save in the process)
        raises OSError(errnum). With ``at_byte``, that many bytes are
        written FIRST — the torn artifact stays in the .tmp directory,
        exactly what a crash mid-write leaves; the atomic-rename design
        must keep the previous checkpoint as the newest intact one."""
        from paddle_tpu.trainer import checkpoint as ck
        real = ck._savez
        count = [0]

        def savez(path, flat):
            i = count[0]
            count[0] += 1
            if i != at_save:
                return real(path, flat)
            if at_byte is None:
                raise OSError(errnum, os.strerror(errnum))
            # serialize fully in memory, land only the first at_byte
            # bytes on disk — the torn artifact a crash mid-write leaves
            import io
            buf = io.BytesIO()
            np.savez(buf, **flat)
            with open(path, "wb") as f:
                f.write(buf.getvalue()[:at_byte])
            raise OSError(errnum, os.strerror(errnum))

        ck._savez = savez
        try:
            yield count
        finally:
            ck._savez = real

    @staticmethod
    def corrupt_newest_checkpoint(directory: str,
                                  payload: bytes = b"garbage") -> int:
        """Overwrite the newest checkpoint's state file (bit-rot / a
        torn copy), returning its step — restore must fall back to the
        one before it via the md5 check."""
        from paddle_tpu.trainer.checkpoint import CheckpointManager
        mgr = CheckpointManager(directory)
        steps = mgr.all_steps()
        if not steps:
            raise FileNotFoundError(f"no checkpoints under {directory}")
        newest = steps[-1]
        with open(os.path.join(directory, f"ckpt-{newest:010d}",
                               "state.npz"), "wb") as f:
            f.write(payload)
        return newest

    # -------------------------------------------------- (b) RPC faults
    def flaky_coordinator(self, target,
                          drop: Optional[Dict[str, Iterable[int]]] = None,
                          delay: Optional[Dict[str, Dict[int, float]]] = None,
                          drop_rate: float = 0.0) -> FlakyCoordinator:
        """Wrap a coordinator (in-process or connect() proxy) so chosen
        RPCs are dropped (ConnectionError) or delayed — see
        FlakyCoordinator. Randomized drops use this plan's seed."""
        return FlakyCoordinator(target, drop=drop, delay=delay,
                                drop_rate=drop_rate, seed=self.seed)

    # ------------------------------------------------ (c) NaN injection
    def poison_batches(self, reader: Callable, steps: Sequence[int],
                       value: float = float("nan"),
                       column: int = 0) -> Callable:
        """Wrap a BATCH reader (yields lists of sample tuples): at the
        given 0-based batch indices, the ``column``-th field of every
        sample is replaced with ``value`` (NaN or Inf) — the loss and
        gradients of that step go non-finite, which is what the guarded
        train step must absorb. Other batches pass through untouched, so
        a comparison run that simply skips the poisoned indices defines
        the expected parameters bit-for-bit."""
        bad: Set[int] = set(int(s) for s in steps)

        def poisoned():
            for i, batch in enumerate(reader()):
                if i in bad:
                    batch = [
                        tuple(np.full_like(
                            np.asarray(f, np.float32), value)
                            if j == column else f
                            for j, f in enumerate(sample))
                        for sample in batch]
                yield batch
        return poisoned

    # --------------------------------------------- (d) process murder
    @staticmethod
    def kill_at_marker(proc, step: int, pattern: str = r"STEP (\d+)",
                       timeout: float = 120.0,
                       sig: int = signal.SIGKILL) -> int:
        """Read ``proc.stdout`` lines until the marker regex reports a
        step >= ``step``, then deliver ``sig`` (SIGKILL: the TPU
        preemption / OOM-killer case — no cleanup handlers run). The
        worker prints markers like 'STEP 7'. Returns the step it died
        at; raises TimeoutError if the marker never appears (after
        killing the process so no orphan survives the test)."""
        rx = re.compile(pattern)
        deadline = time.time() + timeout
        try:
            for line in proc.stdout:
                if isinstance(line, bytes):
                    line = line.decode("utf-8", "replace")
                m = rx.search(line)
                if m and int(m.group(1)) >= step:
                    proc.send_signal(sig)
                    proc.wait(timeout=30)
                    return int(m.group(1))
                if time.time() > deadline:
                    break
        except ValueError:            # stream closed under us
            pass
        proc.kill()
        proc.wait(timeout=30)
        raise TimeoutError(
            f"marker {pattern!r} never reached step {step} "
            f"within {timeout}s")
