"""Deterministic fault injection for chaos-testing training AND serving.

The reference stack's fault tolerance was proven by hope: the Go master
re-queued tasks and the pserver checkpointed, but nothing in the tree
could *inject* a disk-full mid-checkpoint or a dropped RPC on demand.
This module is that missing harness: a seedable :class:`FaultPlan` that
can

  (a) raise ``OSError`` (ENOSPC by default) inside a checkpoint write at
      a chosen save index and byte offset — including TORN writes that
      leave a truncated artifact on disk;
  (b) drop or delay chosen coordinator RPCs (by method name and 0-based
      call index, or at a seeded random rate);
  (c) poison chosen training batches so the loss goes NaN/Inf at exact
      step indices;
  (d) SIGKILL a subprocess trainer when its stdout reaches a chosen
      step marker;

and, for memory pressure (docs/robustness.md "Memory pressure"):

  (i) raise a realistic ``XlaRuntimeError: RESOURCE_EXHAUSTED`` from the
      jitted train step at a chosen optimizer step, ``n`` consecutive
      attempts (``oom_at`` — drives the adaptive microbatcher's bisect +
      re-run path), or model a device with a FIXED row capacity so every
      dispatch above it fails (``memory_pressure`` — the
      allocation-pressure mode that drives ``plan_memory()``'s binary
      search and runtime adaptation deterministically);

and, for the serving path (docs/robustness.md "Serving"):

  (e) make chosen forward calls SLOW, FAIL, or HANG on an event
      (``flaky_forward`` — drives the InferenceServer's deadline and
      circuit-breaker machinery);
  (f) POISON request byte payloads deterministically
      (``poison_bytes`` — the capi_host fuzz inputs);
  (g) destroy a C-ABI handle mid-request (``destroy_during``) and fire
      request BURSTS from a thread pool (``burst``) for overload tests;

and, for the continuous-batching decode engine (docs/robustness.md
"Decode engine"):

  (j) run a deterministic SCHEDULE of scheduler events against a live
      engine — join/cancel/evict/shutdown at exact engine-step indices
      (``decode_script`` over the engine's ``_step_interceptor`` seam,
      so the event lands between two jitted dispatches exactly where a
      concurrent client's action would) — and CANCEL a generation
      request once it has streamed a chosen number of tokens from
      another thread (``disconnect_after`` — the
      client-disconnect-during-generation fault). The invariant every
      one of these must preserve: KV pages ALWAYS return to the pool
      (engine.page_accounting()["leaked"] == 0);

and, for the data pipeline (docs/robustness.md "Data pipeline"):

  (h) HANG or SLOW a source at chosen sample indices (``hung_reader`` —
      drives the supervised pipeline's watchdog), make a mapper RAISE at
      chosen calls (``raising_mapper`` — the quarantine lane), CRASH the
      worker thread running a mapper (``crashing_mapper`` raises
      :class:`WorkerCrash`, a BaseException — the restart path), and
      CORRUPT chosen pickled records before they land in a RecordIO
      shard (``corrupt_records`` — per-record corruption that passes the
      chunk crc but fails deserialization);

and, for performance observability (docs/observability.md "Profiling &
SLOs"):

  (l) make training or decode steps SLOW on demand — ``slow_step``
      injects a (factor-1)x-baseline stall into chosen optimizer steps
      INSIDE the jitted-dispatch scope (the profiler's "compute"
      phase), and ``slow_phase`` slows a chosen engine phase by a fixed
      number of milliseconds inside that phase's timer — the
      deterministic stragglers the SLO watchdog's step-regression
      detector and phase attribution must catch
      (tests/test_profile.py chaos acceptance);

and, for elastic membership (docs/robustness.md "Elastic training"):

  (k) run a deterministic SCHEDULE of membership events against a live
      coordinator — join/leave/kill at exact task-grant indices
      (``membership_script`` over the coordinator's
      ``_grant_interceptor`` seam, so a reshape lands between two
      grants exactly where a real scale-out/in would). The invariants
      every script must preserve: per-record read counts stay
      exactly-once across the reshape, and completions from superseded
      grants are REJECTED (coordinator ``stale_grants``);

and, for lock discipline (docs/static_analysis.md "Lock discipline"):

  (m) GRAB a named instrumented lock from inside the step path
      (``hold_lock`` — resolves the witness name via
      ``analysis.lockdep.find_lock`` and holds it for ``ms``
      milliseconds at chosen interceptor firings) — the deterministic
      twin of a background thread contending on a hot shared lock, so
      contention/hold-time telemetry and the lockdep order graph can be
      driven on demand.

and, for prefix-cache / speculative decoding (docs/robustness.md
"Prefix reuse & speculation"):

  (n) drive COPY-ON-WRITE and trie-eviction churn against the prefix-
      cached engine — ``divergent_twins`` submits request pairs whose
      prompts share a prefix but diverge INSIDE a KV page (every
      admission after the first takes the CoW path),
      ``prefix_evict_storm`` joins waves of distinct-prefix requests
      until admission must reclaim LRU trie leaves (journaled
      ``engine/prefix_evict``), and ``cancel_mid_verify`` is a
      decode_script fragment cancelling a request between a draft
      proposal round and its verify dispatch. The invariants every
      storm must preserve: zero page leaks AND zero refcount
      underflows (``page_accounting()``), and every surviving request
      token-exact vs the dense reference
      (tests/test_serving_faults.py family (n) acceptance);

and, for the sharded embedding service (docs/robustness.md "Sharded
embedding service"):

  (o) SIGKILL an embedding shard at a chosen point — ``kill_shard``
      with ``window="commit"`` dies inside a scatter-update's TORN
      window (WAL durable, ack never sent: the replacement must replay
      it and the client's same-seq retry must dedupe to ``dup``), or
      ``window="rpc"`` dies before any side effect; ``stale_read``
      ages the client's bounded-staleness cache so reads cross the
      bound deterministically (stale serves against a dead shard must
      journal ``embed/stale_read`` violations); ``slow_shard`` stalls
      chosen shard RPCs by a fixed number of milliseconds (the hot-
      shard straggler). The invariant every kill must preserve: the
      final table digest equals the uninterrupted run's
      (tests/test_embed_faults.py chaos acceptance);

and, for the serving fleet (docs/robustness.md "Serving fleet"):

  (p) kill/drain/lapse fleet replicas under routed load —
      ``kill_replica`` fires a caller-supplied kill (SIGKILL a
      subprocess, or the in-process ``httpd.kill()`` tear) the moment
      the router's stream interceptor has relayed ``at`` tokens from
      the victim (``mid_stream=True``), or right before dispatch to
      it (``mid_stream=False``); ``lease_lapse`` pauses a replica's
      membership heartbeats WITHOUT leaving, so its lease expires
      (the implicit drain) and resumes them on exit (the rejoin);
      ``drain_during_burst`` triggers ``router.drain(replica)`` from
      a side thread once the router has dispatched ``after``
      requests. The invariants every storm must preserve: every
      in-flight request settles EXACTLY ONCE (completed on a sibling
      or typed-rejected), survivors show zero KV-page leaks, and
      ``paddle_tpu trace merge`` over the router's + replicas'
      journals reconstructs each victim's hop chain from its
      trace_id alone (tests/test_fleet_faults.py chaos acceptance);

and, for the fleet CONTROL plane (docs/robustness.md "Fleet
autopilot"):

  (q) kill routers and coordinators out from under the fleet —
      ``kill_router`` fires a caller-supplied kill (SIGKILL a router
      subprocess, or the in-process router ``httpd.kill()`` tear) the
      moment THE ROUTER ITSELF has relayed ``at`` tokens of any
      stream (``mid_stream=True``) or right before its next dispatch
      (``mid_stream=False``) — the client's stream tears before the
      terminal record and it retries the SAME trace_id on a sibling
      router; ``coordinator_outage`` makes a registry's coordinator
      proxy raise ``OSError`` on every RPC for the context's duration
      (the registry must serve its last-known view with bounded
      staleness, NOT mass-expire the fleet); ``bursty_trace`` is the
      seeded quiet→spike→quiet per-tick request-count shape the
      autoscaler chaos test replays. The invariants: exactly one
      ``fleet/settle`` per trace_id across ALL routers' merged
      journals (the replica-side hop journal is the dedupe witness),
      zero KV-page leaks, and a coordinator outage shorter than the
      staleness bound sheds NOTHING (tests/test_autopilot.py +
      tests/test_fleet_faults.py family (q) acceptance);

and, for the two-tier KV plane (docs/robustness.md "Two-tier KV
cache"):

  (s) drive spill/restore churn against the two-tier engine —
      ``spill_storm`` joins waves of distinct-prefix requests THEN
      revisits earlier prompts, so pool pressure spills cold trie
      pages host-ward (journaled ``engine/page_spill``) and the
      revisits restore them (``engine/page_restore``);
      ``corrupt_spilled_page`` bit-flips or torn-truncates one stored
      entry WITHOUT touching its CRC (the restore must journal
      ``engine/spill_integrity`` and degrade to a prefix miss — a
      torn page is never restored); ``kill_during_spill`` raises
      :class:`WorkerCrash` inside the engine's ``_spill_interceptor``
      seam at the "read" or "commit" stage of the spill ordering —
      the SIGKILL-mid-spill twin. The invariants every storm must
      preserve: ``page_accounting()`` balanced across BOTH tiers
      (zero device leaks AND host-tier conservation:
      puts == restores + lru + integrity-drops + cleared + resident),
      and every surviving request token-exact vs the single-tier
      reference (tests/test_serving_faults.py TestTwoTierChaos);

Everything is deterministic given the seed and the schedule, so a chaos
test that fails replays exactly. See ``tests/test_faults.py`` and
``tests/test_serving_faults.py`` for the tests that drive these against
the real loop/server, and ``docs/robustness.md`` for the recipe.
"""

from __future__ import annotations

import contextlib
import errno
import os
import random
import re
import signal
import threading
import time
from typing import Callable, Dict, Iterable, Optional, Sequence, Set

import numpy as np

__all__ = ["FaultPlan", "FlakyCoordinator", "WorkerCrash"]


class WorkerCrash(BaseException):
    """A simulated worker-thread death (segfaulting native op, stack
    overflow, interpreter teardown). Deliberately NOT an Exception: the
    supervised pipeline quarantines mapper ``Exception``s as bad
    samples, but a BaseException means the WORKER died — its in-flight
    sample is requeued and the worker restarted (reader/pipeline.py)."""


class FlakyCoordinator:
    """Proxy over a coordinator (in-process or RPC) that injects
    transport faults on chosen calls.

    drop: {method: iterable of 0-based call indices} — those calls raise
        ConnectionError WITHOUT reaching the target (the request is
        lost on the wire).
    delay: {method: {call index: seconds}} — those calls sleep first,
        then go through (a slow network / GC-paused server).
    drop_rate: additionally drop each call with this seeded probability.

    Counters are per method name. Attributes that aren't callable (an
    in-process Coordinator's `epoch` property) pass straight through."""

    def __init__(self, target, drop: Optional[Dict[str, Iterable[int]]] = None,
                 delay: Optional[Dict[str, Dict[int, float]]] = None,
                 drop_rate: float = 0.0, seed: int = 0):
        self._target = target
        self._drop = {m: set(v) for m, v in (drop or {}).items()}
        self._delay = {m: dict(v) for m, v in (delay or {}).items()}
        self._drop_rate = drop_rate
        self._rng = random.Random(seed)
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.faults_injected = 0

    def __getattr__(self, name):
        val = getattr(self._target, name)
        if not callable(val):
            return val

        def call(*args, **kw):
            with self._lock:
                i = self._counts.get(name, 0)
                self._counts[name] = i + 1
                dropped = i in self._drop.get(name, ()) or (
                    self._drop_rate and
                    self._rng.random() < self._drop_rate)
                wait = self._delay.get(name, {}).get(i, 0.0)
                if dropped or wait:
                    self.faults_injected += 1
            if wait:
                time.sleep(wait)
            if dropped:
                raise ConnectionError(
                    f"injected drop: {name}() call #{i}")
            return val(*args, **kw)
        return call


class FaultPlan:
    """A seedable schedule of faults to drive against the real loop."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)

    # ------------------------------------------------- (a) checkpoint IO
    @contextlib.contextmanager
    def checkpoint_write_failure(self, at_save: int = 0,
                                 at_byte: Optional[int] = None,
                                 errnum: int = errno.ENOSPC):
        """Within the context, the ``at_save``-th checkpoint state write
        (0-based, counting every CheckpointManager.save in the process)
        raises OSError(errnum). With ``at_byte``, that many bytes are
        written FIRST — the torn artifact stays in the .tmp directory,
        exactly what a crash mid-write leaves; the atomic-rename design
        must keep the previous checkpoint as the newest intact one."""
        from paddle_tpu.trainer import checkpoint as ck
        real = ck._savez
        count = [0]

        def savez(path, flat):
            i = count[0]
            count[0] += 1
            if i != at_save:
                return real(path, flat)
            if at_byte is None:
                raise OSError(errnum, os.strerror(errnum))
            # serialize fully in memory, land only the first at_byte
            # bytes on disk — the torn artifact a crash mid-write leaves
            import io
            buf = io.BytesIO()
            np.savez(buf, **flat)
            with open(path, "wb") as f:
                f.write(buf.getvalue()[:at_byte])
            raise OSError(errnum, os.strerror(errnum))

        ck._savez = savez
        try:
            yield count
        finally:
            ck._savez = real

    @staticmethod
    def corrupt_newest_checkpoint(directory: str,
                                  payload: bytes = b"garbage") -> int:
        """Overwrite the newest checkpoint's state file (bit-rot / a
        torn copy), returning its step — restore must fall back to the
        one before it via the md5 check."""
        from paddle_tpu.trainer.checkpoint import CheckpointManager
        mgr = CheckpointManager(directory)
        steps = mgr.all_steps()
        if not steps:
            raise FileNotFoundError(f"no checkpoints under {directory}")
        newest = steps[-1]
        with open(os.path.join(directory, f"ckpt-{newest:010d}",
                               "state.npz"), "wb") as f:
            f.write(payload)
        return newest

    # -------------------------------------------------- (b) RPC faults
    def flaky_coordinator(self, target,
                          drop: Optional[Dict[str, Iterable[int]]] = None,
                          delay: Optional[Dict[str, Dict[int, float]]] = None,
                          drop_rate: float = 0.0) -> FlakyCoordinator:
        """Wrap a coordinator (in-process or connect() proxy) so chosen
        RPCs are dropped (ConnectionError) or delayed — see
        FlakyCoordinator. Randomized drops use this plan's seed."""
        return FlakyCoordinator(target, drop=drop, delay=delay,
                                drop_rate=drop_rate, seed=self.seed)

    # ------------------------------------------------ (c) NaN injection
    def poison_batches(self, reader: Callable, steps: Sequence[int],
                       value: float = float("nan"),
                       column: int = 0) -> Callable:
        """Wrap a BATCH reader (yields lists of sample tuples): at the
        given 0-based batch indices, the ``column``-th field of every
        sample is replaced with ``value`` (NaN or Inf) — the loss and
        gradients of that step go non-finite, which is what the guarded
        train step must absorb. Other batches pass through untouched, so
        a comparison run that simply skips the poisoned indices defines
        the expected parameters bit-for-bit."""
        bad: Set[int] = set(int(s) for s in steps)

        def poisoned():
            for i, batch in enumerate(reader()):
                if i in bad:
                    batch = [
                        tuple(np.full_like(
                            np.asarray(f, np.float32), value)
                            if j == column else f
                            for j, f in enumerate(sample))
                        for sample in batch]
                yield batch
        return poisoned

    # --------------------------------------------- (i) memory pressure
    @staticmethod
    @contextlib.contextmanager
    def oom_at(trainer, step: int, n: int = 1, nbytes: int = 2 << 30):
        """Within the context, the trainer's jitted train step raises a
        realistic ``XlaRuntimeError: RESOURCE_EXHAUSTED`` on its first
        ``n`` dispatch attempts of optimizer step ``step`` (0-based,
        ``trainer._step_count`` at dispatch time) — the adaptive
        microbatcher must bisect ``n`` times and then complete the SAME
        batch with zero lost samples (trainer/memory.py). Yields a stats
        dict (``injected``). Uses the trainer's ``_step_interceptor``
        seam, so the exception comes from exactly where a real device
        allocator failure would: the step dispatch."""
        from paddle_tpu.trainer.memory import resource_exhausted_error
        stats = {"injected": 0}
        remaining = [int(n)]
        prev = trainer._step_interceptor

        def intercept(k, mb):
            if prev is not None:
                prev(k, mb)
            if trainer._step_count == step and remaining[0] > 0:
                remaining[0] -= 1
                stats["injected"] += 1
                raise resource_exhausted_error(
                    nbytes, where=f"oom_at(step={step})")

        trainer._step_interceptor = intercept
        try:
            yield stats
        finally:
            trainer._step_interceptor = prev

    @staticmethod
    @contextlib.contextmanager
    def memory_pressure(trainer, max_rows: int, nbytes: int = 2 << 30):
        """Model a device whose memory fits at most ``max_rows``
        microbatch rows: within the context, EVERY dispatch (train step
        or warmup-probe trial) whose per-microbatch row count exceeds
        ``max_rows`` raises ``RESOURCE_EXHAUSTED``. Deterministic
        allocation pressure — ``plan_memory()``'s binary search and the
        runtime bisect must both converge to a microbatch <= max_rows.
        Yields a stats dict (``injected``)."""
        from paddle_tpu.trainer.memory import resource_exhausted_error
        stats = {"injected": 0}
        prev = trainer._step_interceptor

        def intercept(k, mb):
            if prev is not None:
                prev(k, mb)
            if mb > max_rows:
                stats["injected"] += 1
                raise resource_exhausted_error(
                    nbytes,
                    where=f"memory_pressure(max_rows={max_rows}), "
                          f"microbatch={mb}")

        trainer._step_interceptor = intercept
        try:
            yield stats
        finally:
            trainer._step_interceptor = prev

    # ------------------------------------------- (e) serving: forward
    @contextlib.contextmanager
    def flaky_forward(self, inference, fail: Iterable[int] = (),
                      delay: Optional[Dict[int, float]] = None,
                      hang: Optional[Dict[int, threading.Event]] = None,
                      fail_rate: float = 0.0):
        """Within the context, the target Inference's jitted forward is
        wrapped so chosen 0-based call indices

          - raise RuntimeError (a poisoned request / kernel abort)
            — ``fail`` indices, plus ``fail_rate`` seeded-random drops;
          - sleep ``delay[i]`` seconds first (a slow device);
          - block on ``hang[i]`` (an Event) until the TEST releases it
            — a deterministic hung forward, the case deadlines +
            the circuit breaker must absorb.

        Yields a stats dict (``injected`` count). Thread-safe: serving
        workers may call concurrently."""
        real = inference._fwd
        fail_set: Set[int] = set(int(i) for i in fail)
        delays = dict(delay or {})
        hangs = dict(hang or {})
        rng = random.Random(self.seed)
        lock = threading.Lock()
        count = [0]
        stats = {"injected": 0, "calls": 0}

        def fwd(*args, **kw):
            with lock:
                i = count[0]
                count[0] += 1
                stats["calls"] += 1
                bad = i in fail_set or (
                    fail_rate and rng.random() < fail_rate)
                wait = delays.get(i, 0.0)
                ev = hangs.get(i)
                if bad or wait or ev is not None:
                    stats["injected"] += 1
            if ev is not None:
                ev.wait()
            if wait:
                time.sleep(wait)
            if bad:
                raise RuntimeError(f"injected forward fault: call #{i}")
            return real(*args, **kw)

        inference._fwd = fwd
        try:
            yield stats
        finally:
            inference._fwd = real

    # ------------------------------------------- (f) serving: payloads
    def poison_bytes(self, data: bytes, flips: int = 4,
                     truncate: Optional[int] = None) -> bytes:
        """A deterministically corrupted copy of ``data``: ``flips``
        seeded byte-flips, optionally truncated to ``truncate`` bytes —
        the malformed payloads the C-ABI fuzz feeds every entry point."""
        buf = bytearray(data if truncate is None else data[:truncate])
        for _ in range(flips):
            if not buf:
                break
            buf[self._rng.randrange(len(buf))] ^= 0xFF
        return bytes(buf)

    # --------------------------------------- (g) serving: concurrency
    @staticmethod
    def destroy_during(destroy: Callable[[int], int], handle: int,
                       delay_s: float = 0.005) -> threading.Thread:
        """Destroy ``handle`` from another thread after ``delay_s`` —
        the mid-request-destroy race the refcounted registry must make
        safe. Returns the (started) thread; join it."""
        def run():
            time.sleep(delay_s)
            destroy(handle)
        t = threading.Thread(target=run, daemon=True,
                             name="pt-fault-destroy")
        t.start()
        return t

    @staticmethod
    def burst(fn: Callable[[int], object], n: int, threads: int = 8,
              timeout: float = 60.0):
        """Fire ``fn(i)`` for i in range(n) from a pool of ``threads`` —
        the burst-overload fault. Returns (results, errors): per-index
        return values and caught exceptions (None where the other
        applies). Raises TimeoutError if the burst doesn't settle —
        i.e. a deadlock in the system under test."""
        from concurrent.futures import ThreadPoolExecutor
        from concurrent.futures import TimeoutError as _FutTimeout
        results = [None] * n
        errors: list = [None] * n

        def run(i):
            try:
                results[i] = fn(i)
            except Exception as e:       # typed errors are the data
                errors[i] = e

        pool = ThreadPoolExecutor(max_workers=threads)
        futs = [pool.submit(run, i) for i in range(n)]
        try:
            for f in futs:
                try:
                    f.result(timeout=timeout)
                except _FutTimeout:
                    # don't wait on the wedged worker — that would turn
                    # a detected deadlock into a hung test
                    pool.shutdown(wait=False, cancel_futures=True)
                    raise TimeoutError(
                        f"burst did not settle within {timeout}s "
                        f"(deadlock in the system under test?)")
        finally:
            pool.shutdown(wait=False)
        return results, errors

    # ------------------------------------------ (j) decode engine
    @staticmethod
    @contextlib.contextmanager
    def decode_script(engine, at: Dict[int, Callable]):
        """Within the context, run ``at[i]()`` immediately BEFORE the
        engine's ``i``-th step dispatches (0-based, counted from
        entering the context — a warmed engine replays the same script
        at the same offsets) — the deterministic twin of a client
        submitting/cancelling mid-decode or an operator forcing an
        eviction. Actions run on the engine's stepping thread via the
        ``_step_interceptor`` seam, so they interleave with the jitted
        step exactly like real scheduler events: between dispatches,
        never during one. Yields a stats dict (``fired``: indices that
        ran)."""
        actions = {int(i): fn for i, fn in at.items()}
        stats = {"fired": []}
        prev = engine._step_interceptor
        base = engine._steps

        def intercept(step):
            if prev is not None:
                prev(step)
            fn = actions.get(step - base)
            if fn is not None:
                stats["fired"].append(step - base)
                fn()

        engine._step_interceptor = intercept
        try:
            yield stats
        finally:
            engine._step_interceptor = prev

    @staticmethod
    def disconnect_after(request, n_tokens: int,
                         poll_s: float = 0.002,
                         timeout: float = 60.0) -> threading.Thread:
        """Cancel ``request`` from another thread once it has streamed
        ``n_tokens`` generated tokens — a client that consumed part of
        the stream and disconnected mid-generation. The engine must
        observe the cancellation at its next step, return every page to
        the pool, and leave the other in-flight sequences token-exact.
        Returns the (started) thread; join it."""
        def run():
            deadline = time.time() + timeout
            while (request.num_generated < n_tokens
                   and not request.done.is_set()
                   and time.time() < deadline):
                time.sleep(poll_s)
            request.cancel()

        t = threading.Thread(target=run, daemon=True,
                             name="pt-fault-disconnect")
        t.start()
        return t

    # ------------------------------------- (n) prefix-cache / CoW chaos
    def divergent_twins(self, engine, *, diverge_at: Optional[int] = None,
                        tail: int = 3, max_new: int = 4,
                        pairs: int = 2, vocab: int = 32):
        """Submit ``pairs`` request pairs sharing a ``diverge_at``-token
        prompt prefix that splits INSIDE a KV page (default: mid-page
        of the engine's second page) — every admission after the first
        walks the radix index and exercises the copy-on-write path.
        Returns ``[(request, prompt), ...]``; drive the engine, then
        assert each settled output token-exact vs the dense reference
        and ``page_accounting()`` zero leaks / zero underflows."""
        rng = np.random.RandomState(self.seed)
        ps = engine.page_size
        if diverge_at is None:
            diverge_at = ps + max(1, ps // 2)   # mid-page, page 1
        shared = [int(t) for t in rng.randint(0, vocab, diverge_at)]
        out = []
        for _ in range(2 * pairs):
            prompt = shared + [int(t)
                               for t in rng.randint(0, vocab, tail)]
            out.append((engine.submit(prompt, max_new), prompt))
        return out

    def prefix_evict_storm(self, engine, *, waves: int = 4,
                           per_wave: int = 2, gap: int = 3,
                           prompt_len: int = 8, max_new: int = 3,
                           vocab: int = 32):
        """Join ``per_wave`` requests with DISTINCT prompts every
        ``gap`` engine steps: finished requests stack their pages into
        the radix index until admission must reclaim LRU trie leaves
        (journaled ``engine/prefix_evict``) before any slot preemption.
        The first wave submits immediately (so ``run()`` has work);
        later waves are a decode_script schedule. Returns
        ``(schedule, submitted)`` — ``submitted`` fills with
        ``(request, prompt)`` as waves fire; run the engine under
        ``decode_script(engine, schedule)``."""
        rng = np.random.RandomState(self.seed + 1)
        submitted: list = []

        def fire():
            for _ in range(per_wave):
                prompt = [int(t)
                          for t in rng.randint(0, vocab, prompt_len)]
                submitted.append((engine.submit(prompt, max_new),
                                  prompt))

        schedule: Dict[int, Callable] = {
            w * gap: fire for w in range(1, waves)}
        fire()
        return schedule, submitted

    # ------------------------------------- (s) two-tier KV spill chaos
    def spill_storm(self, engine, *, waves: int = 5, per_wave: int = 2,
                    gap: int = 4, prompt_len: int = 8, max_new: int = 3,
                    vocab: int = 32, revisit_from: int = 2):
        """``prefix_evict_storm``'s two-tier twin: join ``per_wave``
        DISTINCT-prefix requests every ``gap`` engine steps so pool
        pressure spills cold trie leaves to the host store
        (``engine/page_spill``) — and, from wave ``revisit_from`` on,
        each wave ALSO re-submits one of the earliest prompts, whose
        pages are by then the coldest and most likely spilled: the
        revisit's admission walks the same token path and must restore
        them (``engine/page_restore``) before prefill is charged.
        Returns ``(schedule, submitted)`` in the evict-storm shape —
        run the engine under ``decode_script(engine, schedule)``, then
        assert both-tier balance and token identity."""
        rng = np.random.RandomState(self.seed + 2)
        prompts = [[int(t) for t in rng.randint(0, vocab, prompt_len)]
                   for _ in range(waves * per_wave)]
        submitted: list = []
        wave_no = [0]

        def fire():
            w = wave_no[0]
            wave_no[0] += 1
            for j in range(per_wave):
                prompt = prompts[(w * per_wave + j) % len(prompts)]
                submitted.append((engine.submit(prompt, max_new),
                                  prompt))
            if w >= revisit_from:
                prompt = prompts[w % revisit_from]
                submitted.append((engine.submit(prompt, max_new),
                                  prompt))

        schedule: Dict[int, Callable] = {
            w * gap: fire for w in range(1, waves)}
        fire()
        return schedule, submitted

    def corrupt_spilled_page(self, engine,
                             mode: str = "bitflip") -> Optional[tuple]:
        """Corrupt ONE entry in the engine's spill store in place —
        ``mode="bitflip"`` (seeded single-byte flip: bit-rot) or
        ``"truncate"`` (zero the tail: a torn write) — WITHOUT
        touching its recorded CRC. The next restore of that key must
        fail verification, journal ``engine/spill_integrity``
        (``reason="crc_mismatch"``), drop the entry, and degrade to a
        prefix miss: the request recomputes and stays token-exact.
        Returns the corrupted key (a token path), or None if the
        store is empty. Use as a decode_script action to land the
        corruption between two exact steps."""
        if engine.spill is None:
            raise ValueError("engine has no spill store "
                             "(kv_spill_pages=0)")
        return engine.spill.corrupt_one(mode, rng=self._rng)

    @staticmethod
    @contextlib.contextmanager
    def kill_during_spill(engine, at: int = 0, stage: str = "commit"):
        """Within the context, raise :class:`WorkerCrash` from the
        engine's ``_spill_interceptor`` seam at the ``at``-th firing
        of the named ``stage`` — the SIGKILL-mid-spill twin, landing
        at an exact point of the crash-safety ordering
        (serving/spill.py):

        - ``stage="read"``: before the device page is read — nothing
          has changed; the trie still owns the page and the store has
          no entry;
        - ``stage="commit"``: after the trie node is evicted and the
          device page freed, before ``put()`` commits — the page is
          simply free and the store has no entry (cache contents
          lost, accounting intact).

        Either way the ordering contract guarantees no page is both
        device-owned and host-stored, and ``page_accounting()`` on
        the survivor stays balanced across both tiers. Yields a stats
        dict (``fired``, ``stage``, ``path``)."""
        if stage not in ("read", "commit"):
            raise ValueError(f"unknown spill stage {stage!r}")
        stats = {"fired": 0, "stage": stage, "path": None}
        count = [0]
        prev = engine._spill_interceptor

        def seam(point, path, page):
            if prev is not None:
                prev(point, path, page)
            if point != stage:
                return
            i = count[0]
            count[0] += 1
            if i == at:
                stats["fired"] += 1
                stats["path"] = path
                raise WorkerCrash(
                    f"kill_during_spill: {stage} #{i} page={page}")

        engine._spill_interceptor = seam
        try:
            yield stats
        finally:
            engine._spill_interceptor = prev

    @staticmethod
    def cancel_mid_verify(request, at: int = 2) -> Dict[int, Callable]:
        """A decode_script fragment cancelling ``request`` immediately
        before engine step ``at`` dispatches — with speculation on, the
        cancel lands BETWEEN a draft proposal round and the target's
        verify of those proposals: the engine must reap it before the
        next dispatch, return every page (and shared-prefix ref) to
        the pool, and leave the other slots' outputs token-exact.
        Merge into a larger schedule or pass straight to
        ``decode_script``."""
        return {int(at): request.cancel}

    # ------------------------------------- (l) performance stragglers
    @staticmethod
    @contextlib.contextmanager
    def slow_step(trainer, step: int, factor: float = 5.0, n: int = 4):
        """Within the context, optimizer steps [step, step+n) run
        ~``factor``x slower: a sleep of (factor-1)x the measured
        per-step baseline is injected through the trainer's
        ``_step_interceptor`` seam, INSIDE the jitted-dispatch scope —
        so the continuous profiler books the stall under its "compute"
        phase and the SLO watchdog's regression detector must both fire
        AND attribute it there (the deterministic twin of a straggling
        device / thermal throttling). The baseline is the median
        inter-dispatch gap over the healthy steps before ``step``
        (fallback 20 ms when the stall lands first). The seam fires on
        the microbatcher path — train with ``microbatch=`` set (e.g.
        "auto"). Yields a stats dict (``injected``, ``baseline_ms``,
        ``slept_ms``)."""
        stats = {"injected": 0, "baseline_ms": None, "slept_ms": 0.0}
        dts: list = []
        t_last = [None]
        prev = trainer._step_interceptor

        def intercept(k, mb):
            if prev is not None:
                prev(k, mb)
            now = time.perf_counter()
            sc = trainer._step_count
            if step <= sc < step + n:
                base = sorted(dts)[len(dts) // 2] if dts else 0.020
                stats["baseline_ms"] = round(base * 1e3, 3)
                pause = max(factor - 1.0, 0.0) * base
                stats["injected"] += 1
                stats["slept_ms"] += pause * 1e3
                time.sleep(pause)
                t_last[0] = None     # stalled gaps are not baseline
                return
            if t_last[0] is not None:
                dts.append(now - t_last[0])
            t_last[0] = now

        trainer._step_interceptor = intercept
        try:
            yield stats
        finally:
            trainer._step_interceptor = prev

    @staticmethod
    @contextlib.contextmanager
    def slow_phase(engine, phase: str = "decode_step", ms: float = 50.0,
                   at: int = 0, n: Optional[int] = None):
        """Within the context, the engine's ``phase`` runs ``ms``
        milliseconds slow from its ``at``-th step after entry (0-based,
        the decode_script convention) for ``n`` steps (None: until
        exit). ``decode_step`` — the jitted dispatch — is slowed INSIDE
        the ``serving/decode_step`` timer by a sleeping proxy over
        ``engine.paged``, so the profiler's per-phase breakdown books
        the stall there and the watchdog's attribution must name it;
        any other name sleeps under a ``serving/<phase>`` timer via the
        ``_step_interceptor`` seam. Yields a stats dict
        (``injected``)."""
        stats = {"injected": 0}
        base = engine._steps
        lo = base + int(at)
        hi = lo + (int(n) if n is not None else (1 << 62))
        pause = ms / 1e3

        if phase == "decode_step":
            real = engine.paged

            class _SlowPaged:
                def __getattr__(self, name):
                    return getattr(real, name)

                def step(self, *a, **kw):
                    if lo <= engine._steps < hi:
                        stats["injected"] += 1
                        time.sleep(pause)
                    return real.step(*a, **kw)

            engine.paged = _SlowPaged()
            try:
                yield stats
            finally:
                engine.paged = real
            return

        from paddle_tpu.utils.stats import stat_timer
        prev = engine._step_interceptor

        def intercept(step_idx):
            if prev is not None:
                prev(step_idx)
            if lo <= step_idx < hi:
                stats["injected"] += 1
                with stat_timer(f"serving/{phase}"):
                    time.sleep(pause)

        engine._step_interceptor = intercept
        try:
            yield stats
        finally:
            engine._step_interceptor = prev

    # --------------------------------------------- (m) lock discipline
    @staticmethod
    @contextlib.contextmanager
    def hold_lock(target, name: str, at: int = 0, ms: float = 50.0,
                  n: int = 1):
        """Within the context, grab the named instrumented lock (e.g.
        ``"coord.state"``, ``"obs.flight"`` — any live
        :func:`paddle_tpu.analysis.lockdep.named_lock`) from inside
        ``target``'s ``_step_interceptor`` seam and HOLD it for ``ms``
        milliseconds, starting at the ``at``-th firing after entry
        (0-based) for ``n`` firings. The deterministic twin of a
        background thread squatting on a hot shared lock: every other
        thread contending on it stalls for the full hold, which the
        lockdep witness books as contention + hold-time telemetry
        (``paddle_tpu_lockdep_contentions_total`` /
        ``_hold_time_ms``) and, when the step path itself holds
        another lock, as an order-graph edge. The lock must already
        exist (``find_lock`` raises KeyError otherwise, so a typo'd
        name fails loudly instead of silently holding nothing). Yields
        a stats dict (``injected``, ``held_ms``)."""
        from paddle_tpu.analysis.lockdep import find_lock
        lock = find_lock(name)
        if lock is None:
            raise KeyError(f"no live instrumented lock named {name!r}")
        stats = {"injected": 0, "held_ms": 0.0}
        fired = [0]
        pause = ms / 1e3
        prev = target._step_interceptor

        def intercept(*args, **kw):
            if prev is not None:
                prev(*args, **kw)
            idx = fired[0]
            fired[0] += 1
            if at <= idx < at + n:
                t0 = time.perf_counter()
                with lock:
                    # ptlint: disable=R9(deliberate: this fault injector EXISTS to stall a hot lock on demand)
                    time.sleep(pause)
                stats["injected"] += 1
                stats["held_ms"] += (time.perf_counter() - t0) * 1e3

        target._step_interceptor = intercept
        try:
            yield stats
        finally:
            target._step_interceptor = prev

    # ----------------------------------------- (k) elastic membership
    @staticmethod
    @contextlib.contextmanager
    def membership_script(coordinator, at: Dict[int, Callable]):
        """Within the context, run ``at[i]()`` immediately AFTER the
        coordinator's ``i``-th task grant commits (0-based, counted
        from entering the context) — the deterministic twin of a worker
        joining, leaving, or dying at an exact point in the dispatch
        schedule. Actions run on the granting thread via the
        coordinator's ``_grant_interceptor`` seam, OUTSIDE its lock, so
        an action may itself call ``join()``/``leave()`` (or SIGKILL a
        subprocess) without deadlocking — and the grant the action
        follows was already stamped with the PRE-action generation,
        which is exactly the stale-grant race the elastic tests must
        provoke on demand. Yields a stats dict (``fired``: indices that
        ran)."""
        actions = {int(i): fn for i, fn in at.items()}
        stats = {"fired": []}
        prev = coordinator._grant_interceptor
        base = coordinator._grants

        def intercept(idx, grant):
            if prev is not None:
                prev(idx, grant)
            fn = actions.get(idx - base)
            if fn is not None:
                stats["fired"].append(idx - base)
                fn()

        coordinator._grant_interceptor = intercept
        try:
            yield stats
        finally:
            coordinator._grant_interceptor = prev

    # --------------------------------------------- (h) data pipeline
    @staticmethod
    def hung_reader(reader: Callable, hang: Optional[Dict[int, float]] = None,
                    release: Optional[Dict[int, threading.Event]] = None
                    ) -> Callable:
        """Wrap a sample Reader so chosen 0-based sample indices HANG
        before being yielded: ``hang[i]`` seconds (a finite hang — a
        stuck disk/NFS read that eventually completes), or until the
        test sets ``release[i]`` (a deterministic indefinite hang). The
        supervised pipeline's watchdog must detect the stall; no sample
        is lost — delivery is late, not absent. Indices reset per
        epoch (per ``reader()`` call), so a resumed/second pass replays
        the same schedule."""
        hangs = dict(hang or {})
        events = dict(release or {})

        def rdr():
            for i, s in enumerate(reader()):
                if i in events:
                    events[i].wait()
                if i in hangs:
                    time.sleep(hangs[i])
                yield s
        return rdr

    def raising_mapper(self, mapper: Callable, at: Iterable[int],
                       exc_type=ValueError) -> Callable:
        """Wrap a mapper so the given 0-based CALL indices raise
        ``exc_type`` — the per-sample fault the quarantine lane must
        absorb. The call counter is shared across worker threads
        (lock-protected), so exactly len(at) calls fail."""
        bad = set(int(i) for i in at)
        lock = threading.Lock()
        count = [0]

        def m(sample):
            with lock:
                i = count[0]
                count[0] += 1
            if i in bad:
                raise exc_type(f"injected mapper fault: call #{i}")
            return mapper(sample)
        return m

    def crashing_mapper(self, mapper: Callable,
                        at: Iterable[int]) -> Callable:
        """Wrap a mapper so the given 0-based call indices raise
        :class:`WorkerCrash` (a BaseException): the worker THREAD dies
        mid-sample. The pipeline must requeue the in-flight sample and
        restart the worker — zero records lost. Call counter shared
        across threads, so the requeued retry (a later call index)
        succeeds."""
        bad = set(int(i) for i in at)
        lock = threading.Lock()
        count = [0]

        def m(sample):
            with lock:
                i = count[0]
                count[0] += 1
            if i in bad:
                raise WorkerCrash(f"injected worker crash: call #{i}")
            return mapper(sample)
        return m

    def corrupt_records(self, records: Iterable[bytes],
                        at: Iterable[int]) -> Iterable[bytes]:
        """Yield ``records`` with the chosen 0-based indices replaced by
        garbage that can NEVER unpickle (leading 0xFF is no pickle
        opcode) — per-record corruption inside an otherwise crc-valid
        chunk. Feed the result to recordio.write_records to build a
        shard with exactly len(at) bad records."""
        bad = set(int(i) for i in at)
        for i, rec in enumerate(records):
            if i in bad:
                filler = bytes(self._rng.randrange(256)
                               for _ in range(max(len(rec) - 1, 4)))
                yield b"\xff" + filler
            else:
                yield rec

    # ------------------------------------------ (o) sharded embeddings
    @staticmethod
    @contextlib.contextmanager
    def kill_shard(server, at: int = 0, window: str = "commit"):
        """Within the context, SIGKILL-twin an embedding shard at a
        chosen point (:meth:`EmbeddingShardServer.kill`: every in-flight
        and future RPC tears its connection with NO response; new
        connections are refused; no snapshot, no leave — the membership
        lease just lapses).

        window="commit": die inside the ``at``-th scatter-update's TORN
        WINDOW — after the WAL append is durable, before the table
        mutates or the ack is sent (the shard's ``_commit_interceptor``
        seam). This is the worst-case kill for exactly-once accounting:
        the replacement must REPLAY the entry and the client's retry of
        the same seq must come back ``dup``.

        window="rpc": die at the ``at``-th RPC of any kind (the
        server's ``_rpc_interceptor`` seam) — the request dies BEFORE
        any side effect; the retry applies cleanly on the replacement.

        Yields a stats dict (``killed_at``: the index it fired on, or
        None if never reached)."""
        from paddle_tpu.embed.shard import ShardKilled
        stats = {"killed_at": None}
        if window == "commit":
            shard = server.shard
            prev = shard._commit_interceptor
            count = [0]

            def commit_seam(wal_seq):
                if prev is not None:
                    prev(wal_seq)
                i = count[0]
                count[0] += 1
                if i == at:
                    stats["killed_at"] = i
                    server.kill()
                    raise ShardKilled(
                        f"kill_shard: commit #{i} (WAL {wal_seq} "
                        "durable, ack never sent)")

            shard._commit_interceptor = commit_seam
            try:
                yield stats
            finally:
                shard._commit_interceptor = prev
        elif window == "rpc":
            prev = server._rpc_interceptor

            def rpc_seam(method, idx):
                if prev is not None:
                    prev(method, idx)
                if idx == at:
                    stats["killed_at"] = idx
                    server.kill()
                    raise ShardKilled(
                        f"kill_shard: rpc #{idx} ({method})")

            server._rpc_interceptor = rpc_seam
            try:
                yield stats
            finally:
                server._rpc_interceptor = prev
        else:
            raise ValueError(f"unknown kill window {window!r}")

    @staticmethod
    @contextlib.contextmanager
    def stale_read(client, age_s: float):
        """Within the context, every row in the client's bounded-
        staleness cache (present now or fetched later) reads as
        ``age_s`` seconds OLDER than it is — rows age past the bound
        deterministically instead of waiting wall-clock time. Against a
        LIVE shard this forces refetches (the bound doing its job);
        against a killed shard it forces stale SERVES, which must be
        journaled as ``embed/stale_read`` violations. Yields a stats
        dict (``aged``: entries rewritten so far)."""
        stats = {"aged": 0}
        lock = client._lock
        real_gather = client.gather

        def age_now():
            with lock:
                for k, (row, ts) in list(client._cache.items()):
                    client._cache[k] = (row, ts - age_s)
                    stats["aged"] += 1

        def gather(keys, max_stale_s=None):
            out = real_gather(keys, max_stale_s=max_stale_s)
            age_now()            # rows fetched by THIS call age too
            return out

        age_now()
        client.gather = gather
        try:
            yield stats
        finally:
            client.gather = real_gather

    @staticmethod
    @contextlib.contextmanager
    def slow_shard(server, ms: float, at: Iterable[int] = (),
                   every: bool = False):
        """Within the context, the shard's RPCs STALL ``ms``
        milliseconds before handling — chosen 0-based RPC indices, or
        every RPC (``every=True``): the deterministic straggler/hot-
        shard twin for tail-latency and timeout tests. Yields a stats
        dict (``slowed``: indices that stalled)."""
        indices = set(int(i) for i in at)
        stats = {"slowed": []}
        prev = server._rpc_interceptor

        def seam(method, idx):
            if prev is not None:
                prev(method, idx)
            if every or idx in indices:
                stats["slowed"].append(idx)
                time.sleep(ms / 1000.0)

        server._rpc_interceptor = seam
        try:
            yield stats
        finally:
            server._rpc_interceptor = prev

    # --------------------------------------------- (d) process murder
    @staticmethod
    def kill_at_marker(proc, step: int, pattern: str = r"STEP (\d+)",
                       timeout: float = 120.0,
                       sig: int = signal.SIGKILL) -> int:
        """Read ``proc.stdout`` lines until the marker regex reports a
        step >= ``step``, then deliver ``sig`` (SIGKILL: the TPU
        preemption / OOM-killer case — no cleanup handlers run). The
        worker prints markers like 'STEP 7'. Returns the step it died
        at; raises TimeoutError if the marker never appears (after
        killing the process so no orphan survives the test)."""
        rx = re.compile(pattern)
        deadline = time.time() + timeout
        try:
            for line in proc.stdout:
                if isinstance(line, bytes):
                    line = line.decode("utf-8", "replace")
                m = rx.search(line)
                if m and int(m.group(1)) >= step:
                    proc.send_signal(sig)
                    proc.wait(timeout=30)
                    return int(m.group(1))
                if time.time() > deadline:
                    break
        except ValueError:            # stream closed under us
            pass
        proc.kill()
        proc.wait(timeout=30)
        raise TimeoutError(
            f"marker {pattern!r} never reached step {step} "
            f"within {timeout}s")

    # --------------------------------------------- (p) fleet chaos
    @staticmethod
    @contextlib.contextmanager
    def kill_replica(router, replica_id: str, kill: Callable[[], None],
                     at: int = 2, mid_stream: bool = True):
        """Arm a one-shot replica kill on the router's chaos seams:
        with ``mid_stream`` the caller's ``kill()`` fires the moment
        the router has relayed ``at`` tokens of any request streaming
        off ``replica_id`` (the SIGKILL-mid-generation fault — the
        victim connection tears before its terminal record, which is
        the router's failover trigger); without it, ``kill()`` fires
        right before the router's next dispatch TO that replica (the
        request dies on connect and fails over with zero streamed
        tokens). ``kill`` is a subprocess SIGKILL or the in-process
        ``httpd.kill()`` tear — the seam doesn't care. Yields a stats
        dict (``fired``: kill count, ``at_tokens``: stream position
        it fired at, ``victim_traces``: trace_ids that were streaming
        off the victim when it died)."""
        stats = {"fired": 0, "at_tokens": None, "victim_traces": []}
        lock = threading.Lock()
        prev_stream = router._stream_interceptor
        prev_route = router._route_interceptor

        def fire(trace_id, n):
            with lock:
                if stats["fired"]:
                    return
                stats["fired"] = 1
                stats["at_tokens"] = n
            if trace_id is not None:
                stats["victim_traces"].append(trace_id)
            kill()

        def stream_seam(trace_id, rid, n):
            if prev_stream is not None:
                prev_stream(trace_id, rid, n)
            if mid_stream and rid == replica_id and n >= at:
                fire(trace_id, n)

        def route_seam(trace_id, rid, hop):
            if prev_route is not None:
                prev_route(trace_id, rid, hop)
            if not mid_stream and rid == replica_id:
                fire(trace_id, 0)

        router._stream_interceptor = stream_seam
        router._route_interceptor = route_seam
        try:
            yield stats
        finally:
            router._stream_interceptor = prev_stream
            router._route_interceptor = prev_route

    @staticmethod
    @contextlib.contextmanager
    def lease_lapse(registration, wait_s: Optional[float] = None):
        """Pause a replica's membership heartbeats WITHOUT leaving —
        the long-GC-pause / wedged-process fault. The lease expires
        (``worker_info`` goes None: the router treats it as an
        implicit drain and stops routing there) while the replica
        keeps serving whatever it already holds. On exit the
        heartbeats resume; the next tick re-joins (the registration's
        ``rejoins`` counter bumps) and the router re-admits. With
        ``wait_s`` the context sleeps that long after pausing so the
        lapse is guaranteed by the time the body runs."""
        registration.pause()
        if wait_s:
            time.sleep(wait_s)
        try:
            yield registration
        finally:
            registration.unpause()

    @staticmethod
    @contextlib.contextmanager
    def drain_during_burst(router, replica_id: str, after: int = 3,
                           timeout: Optional[float] = None):
        """Arm a drain-under-load: once the router has dispatched
        ``after`` requests (any replica), a side thread calls
        ``router.drain(replica_id)`` — new admissions shift to
        siblings while the drained replica's in-flight requests
        settle. Yields a stats dict (``drained``: the drain() result,
        set once it completes; ``dispatches``: dispatch count seen).
        Join happens on exit."""
        stats = {"drained": None, "dispatches": 0}
        fired = threading.Event()
        prev_route = router._route_interceptor

        def do_drain():
            stats["drained"] = router.drain(replica_id,
                                            timeout=timeout)

        thread = threading.Thread(target=do_drain, daemon=True,
                                  name="pt-fault-drain")

        def route_seam(trace_id, rid, hop):
            if prev_route is not None:
                prev_route(trace_id, rid, hop)
            stats["dispatches"] += 1
            if stats["dispatches"] >= after and not fired.is_set():
                fired.set()
                thread.start()

        router._route_interceptor = route_seam
        try:
            yield stats
        finally:
            router._route_interceptor = prev_route
            if fired.is_set():
                thread.join(timeout=30)

    # ------------------------------------------- (q) control-plane chaos
    @staticmethod
    @contextlib.contextmanager
    def kill_router(router, kill: Callable[[], None], at: int = 2,
                    mid_stream: bool = True):
        """Arm a one-shot kill of the ROUTER ITSELF — family (p)'s
        ``kill_replica`` one level up the plane. With ``mid_stream``
        the caller's ``kill()`` (the in-process router
        ``httpd.kill()`` tear, or a subprocess SIGKILL) fires the
        moment this router has relayed ``at`` tokens of ANY stream;
        without it, right before its next dispatch. Streaming clients
        see a torn NDJSON stream (no terminal record) and retry the
        same trace_id on a sibling router — the replica-side hop
        journal dedupes fleet-wide. Yields the same stats dict shape
        as ``kill_replica`` (``fired``, ``at_tokens``,
        ``victim_traces``)."""
        stats = {"fired": 0, "at_tokens": None, "victim_traces": []}
        lock = threading.Lock()
        prev_stream = router._stream_interceptor
        prev_route = router._route_interceptor

        def fire(trace_id, n):
            with lock:
                if stats["fired"]:
                    return
                stats["fired"] = 1
                stats["at_tokens"] = n
            if trace_id is not None:
                stats["victim_traces"].append(trace_id)
            kill()

        def stream_seam(trace_id, rid, n):
            if prev_stream is not None:
                prev_stream(trace_id, rid, n)
            if mid_stream and n >= at:
                fire(trace_id, n)

        def route_seam(trace_id, rid, hop):
            if prev_route is not None:
                prev_route(trace_id, rid, hop)
            if not mid_stream:
                fire(trace_id, 0)

        router._stream_interceptor = stream_seam
        router._route_interceptor = route_seam
        try:
            yield stats
        finally:
            router._stream_interceptor = prev_stream
            router._route_interceptor = prev_route

    @staticmethod
    @contextlib.contextmanager
    def coordinator_outage(target, for_s: Optional[float] = None):
        """Take the coordinator away WITHOUT touching the replicas —
        every directory RPC raises ``OSError`` until the context
        exits. ``target`` is a ``ReplicaRegistry`` or anything with a
        ``.registry`` (a Router). The registry's contract under this
        fault (fleet/registry.py): keep serving the last-known
        routable view, journal ``fleet/stale_view`` with the bounded
        staleness age, and journal ``fleet/view_recovered`` on the
        first successful poll after exit — NOT a mass leave. With
        ``for_s`` the context sleeps that long after cutting the wire
        so at least one poll has failed by the time the body runs
        (``lease_lapse``'s ``wait_s`` shape)."""
        registry = getattr(target, "registry", target)
        if registry.coordinator is None:
            raise ValueError("static registry has no coordinator to "
                             "take down")

        class _DownCoordinator:
            def __getattr__(self, name):
                def _down(*args, **kwargs):
                    raise OSError(
                        f"coordinator outage (injected): {name}")
                return _down

        real = registry.coordinator
        registry.coordinator = _DownCoordinator()
        if for_s:
            time.sleep(for_s)
        try:
            yield registry
        finally:
            registry.coordinator = real

    # -------------------------------------------- (r) warm-start artifacts
    @staticmethod
    @contextlib.contextmanager
    def corrupt_artifact(store, name: Optional[str] = None,
                         mode: str = "payload"):
        """Damage one on-disk artifact — the torn-write / bit-rot /
        partial-copy fault the warm-start plane must DETECT and
        degrade past, never crash on (docs/robustness.md "Warm start
        & artifact integrity"). ``name`` picks the artifact (default:
        the newest); ``mode``:

        - ``payload``: flip one payload byte (crc catches it),
        - ``torn``: truncate mid-payload (a writer died without the
          atomic rename discipline — or the volume did),
        - ``magic``: clobber the frame magic (not an artifact at all).

        The contract under this fault: ``store.get`` returns None,
        counts a fallback, journals ``artifacts/fallback`` with
        ``reason="corrupt"`` — and the caller serves via JIT,
        token-identically. Yields ``{"path", "mode"}``; the original
        bytes are restored on exit."""
        paths = [r["path"] for r in store.entries()
                 if name is None or r["name"] == f"{name}.ptaf"]
        if name is None and paths:
            paths = [max(paths, key=os.path.getmtime)]
        if not paths:
            raise ValueError(f"no artifact to corrupt "
                             f"(name={name!r}) in {store.root}")
        path = paths[0]
        with open(path, "rb") as f:
            original = f.read()
        if mode == "payload":
            blob = original[:-5] + bytes([original[-5] ^ 0xFF]) + \
                original[-4:]
        elif mode == "torn":
            blob = original[:max(9, len(original) // 2)]
        elif mode == "magic":
            blob = b"XXXX" + original[4:]
        else:
            raise ValueError(f"unknown mode {mode!r}")
        with open(path, "wb") as f:
            f.write(blob)
        try:
            yield {"path": path, "mode": mode}
        finally:
            with open(path, "wb") as f:
                f.write(original)

    @staticmethod
    @contextlib.contextmanager
    def stale_fingerprint(store, name: Optional[str] = None):
        """Rewrite one artifact as an INTERNALLY-CONSISTENT frame
        built for a different environment — the stale-artifact fault
        (the store survived a jax upgrade / model change; every byte
        is intact, the executable is just for the wrong world). The
        frame passes magic/crc/digest re-derivation, so only the
        fingerprint comparison can catch it: ``store.get`` must
        return None with ``reason="stale"`` in the
        ``artifacts/fallback`` journal record. Yields ``{"path",
        "doctored_digest"}``; restored on exit."""
        import json as _json
        import struct as _struct
        import zlib as _zlib

        from paddle_tpu.artifacts.fingerprint import Fingerprint
        from paddle_tpu.artifacts.store import MAGIC

        paths = [r["path"] for r in store.entries()
                 if name is None or r["name"] == f"{name}.ptaf"]
        if name is None and paths:
            paths = [max(paths, key=os.path.getmtime)]
        if not paths:
            raise ValueError(f"no artifact to doctor "
                             f"(name={name!r}) in {store.root}")
        path = paths[0]
        with open(path, "rb") as f:
            original = f.read()
        (hlen,) = _struct.unpack("<I", original[4:8])
        header = _json.loads(original[8:8 + hlen])
        payload = original[8 + hlen:]
        fields = dict(header["fingerprint"])
        env = dict(fields.get("env") or {})
        env["jax"] = "0.0.0-doctored"
        fields["env"] = env
        doctored = Fingerprint(fields)
        header["fingerprint"] = doctored.fields
        header["digest"] = doctored.digest
        hbytes = _json.dumps(header, sort_keys=True).encode()
        blob = MAGIC + _struct.pack("<I", len(hbytes)) + hbytes + \
            payload
        assert _zlib.crc32(payload) & 0xFFFFFFFF == \
            header["payload_crc"]
        with open(path, "wb") as f:
            f.write(blob)
        try:
            yield {"path": path, "doctored_digest": doctored.digest}
        finally:
            with open(path, "wb") as f:
                f.write(original)

    @staticmethod
    def cache_race(store, name: str, fp, payloads, threads: int = 8,
                   timeout: float = 60.0) -> dict:
        """N writers publish the SAME artifact name concurrently — the
        fleet-cold-start thundering herd (every replica of a fresh
        rollout finishes its build at once and races to backfill).
        The atomic tmp+rename discipline must leave exactly one
        COMPLETE frame under the final name — readers never observe a
        partial file — and no writer may raise. Returns ``{"writes",
        "errors", "winner"}`` where ``winner`` is the surviving
        frame's inspect() row (``winner["ok"]`` is the assertion)."""
        results, errors = FaultPlan.burst(
            lambda i: store.put(name, fp, payloads[i % len(payloads)],
                                meta={"writer": i}),
            len(payloads), threads=threads, timeout=timeout)
        return {"writes": sum(1 for r in results if r is not None),
                "errors": [e for e in errors if e is not None],
                "winner": store.inspect(store.path(name))}

    @staticmethod
    def bursty_trace(seed: int = 0, ticks: int = 30, base: int = 1,
                     peak: int = 12, burst_start: int = 8,
                     burst_len: int = 8) -> list:
        """The canonical autoscaler chaos load shape: a per-tick
        request-count list — quiet (``base``±1), a hard spike to
        ``peak``±2 for ``burst_len`` ticks starting at
        ``burst_start``, then quiet again (the scale-DOWN window).
        Seeded jitter keeps it deterministic: same seed, same trace,
        same scaling decisions (tests/test_autopilot.py replays
        this)."""
        rng = random.Random(seed)
        out = []
        for t in range(int(ticks)):
            if burst_start <= t < burst_start + burst_len:
                lo, hi = max(1, peak - 2), peak + 2
            else:
                lo, hi = max(0, base - 1), base + 1
            out.append(rng.randint(lo, hi))
        return out
