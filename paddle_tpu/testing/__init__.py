"""Testing utilities — deterministic fault injection for chaos tests
(docs/robustness.md)."""

from paddle_tpu.testing.faults import FaultPlan

__all__ = ["FaultPlan"]
