"""Testing utilities — deterministic fault injection for chaos tests
and the shared exactly-once audits (docs/robustness.md)."""

from paddle_tpu.testing.audit import (assert_exactly_once,
                                      assert_exactly_once_applied,
                                      audit_exactly_once)
from paddle_tpu.testing.faults import FaultPlan, WorkerCrash

__all__ = ["FaultPlan", "WorkerCrash", "audit_exactly_once",
           "assert_exactly_once", "assert_exactly_once_applied"]
