"""Testing utilities — deterministic fault injection for chaos tests
(docs/robustness.md)."""

from paddle_tpu.testing.faults import FaultPlan, WorkerCrash

__all__ = ["FaultPlan", "WorkerCrash"]
