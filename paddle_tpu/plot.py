"""Training-curve plotting — python/paddle/v2/plot/plot.py parity.

Ploter collects (step, value) series per title and renders them with
matplotlib when available; `DISABLE_PLOT=True` (the reference's escape
hatch for headless test runs) or a missing matplotlib degrades to a
silent data collector, so scripts written against the reference run
unchanged."""

from __future__ import annotations

import os
from typing import Dict, List


class PlotData:
    def __init__(self):
        self.step: List[float] = []
        self.value: List[float] = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(float(value))

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    def __init__(self, *args: str):
        self.__args__ = args
        self.__plot_data__: Dict[str, PlotData] = {t: PlotData()
                                                   for t in args}
        self.__disable_plot__ = os.environ.get("DISABLE_PLOT") == "True"
        self._plt = None
        if not self.__disable_plot__:
            try:
                import matplotlib
                matplotlib.use("Agg")
                import matplotlib.pyplot as plt
                self._plt = plt
            except Exception:
                self.__disable_plot__ = True

    def append(self, title: str, step, value):
        assert title in self.__plot_data__, f"unknown series {title!r}"
        self.__plot_data__[title].append(step, value)

    def data(self, title: str) -> PlotData:
        return self.__plot_data__[title]

    def plot(self, path: str = None):
        if self.__disable_plot__ or self._plt is None:
            return
        titles = []
        for title in self.__args__:
            d = self.__plot_data__[title]
            if d.step:
                titles.append(title)
                self._plt.plot(d.step, d.value)
        self._plt.legend(titles, loc="upper left")
        if path:
            self._plt.savefig(path)
        self._plt.gcf().clear()

    def reset(self):
        for d in self.__plot_data__.values():
            d.reset()
