"""Incremental (KV-cache) decoding for the transformer LM.

The recurrent zoo generates through `beam_search` (the dynamic
RecurrentGradientMachine parity path); the transformer needs the modern
equivalent: a jit-compiled autoregressive loop that carries per-layer
K/V caches instead of re-running the prefix every step. This module
reimplements `models.transformer.transformer_lm`'s forward functionally
over the SAME parameter table (the DSL fixes parameter names, so a
trained `Parameters` dict drops straight in); `tests/test_decode.py`
pins step-wise logits against the training graph token for token.

TPU shape discipline: one compilation per (batch, prompt_len, max_len,
temperature) combination — the prompt prefills in a single batched
causal pass (one big MXU matmul chain), then `lax.scan` extends one
token at a time with `dynamic_update_slice` into fixed-size caches.
Parameters are a jit argument (not trace constants), so one decoder
serves updated parameter tables without retracing.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.ops import moe as moe_ops


def _ln(x, g, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.maximum(jnp.mean(xf * xf, axis=-1, keepdims=True)
                      - mean * mean, 0.0)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * g + b).astype(x.dtype)


def _heads(x, h):
    return x.reshape(x.shape[:-1] + (h, x.shape[-1] // h))


class TransformerDecoder:
    """Greedy / temperature sampling with per-layer KV caches.

    params: the training-side parameter dict (Parameters.raw or
    Topology.init_params output). Config args mirror transformer_lm."""

    def __init__(self, params, *, n_layers: int, n_heads: int,
                 name: str = "tfm", moe_k: int = 2,
                 moe_capacity_factor: Optional[float] = None):
        prefix = f"_{name}"
        self.p = {k: jnp.asarray(v) for k, v in params.items()
                  if k.startswith(prefix)}
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.name = name
        # MoE blocks are auto-detected from the parameter table (and
        # expert_num comes from the gate's shape), but k is NOT
        # recoverable from it: moe_k MUST match the training config or
        # decode silently diverges. moe_capacity_factor=None (the
        # default) routes DROP-FREE at inference — capacity = each
        # call's full token count, so decode matches the training
        # forward whenever training itself dropped nothing (the
        # capacity limit only buys memory/balance at training scale).
        # Set a float to reproduce a training capacity limit exactly.
        self.moe_k = moe_k
        self.moe_capacity_factor = moe_capacity_factor
        self._jitted = {}

    # ---------------------------------------------------------------- core
    @staticmethod
    def _use_flash_prefill(t, pos, dh) -> bool:
        """Flash-prefill gate: a long (>=256) prompt on TPU with a
        tile-friendly head dim, and the cache empty before this call
        (pos is the static int 0 at prefill; decode steps pass traced
        scalars and fall through to the einsum path)."""
        from paddle_tpu.config import global_config
        from paddle_tpu.ops import pallas_attention as flash
        probe = jax.ShapeDtypeStruct((1, t, 1, dh), jnp.float32)
        return (isinstance(pos, int) and pos == 0 and t >= 256
                and flash.flash_supported(probe, probe)
                and global_config().use_flash_attention
                and jax.default_backend() == "tpu")

    def _embed(self, p, ids, pos):
        n = self.name
        return (p[f"_{n}_tok_emb.w0"][ids]
                + p[f"_{n}_pos_emb.w0"][pos])

    def _block(self, p, i, x, k_cache, v_cache, pos, kv_len):
        """One decoder block over a [b, t, d] slice; reads/extends the
        [b, T, h, dh] caches at positions [pos, pos+t)."""
        n, h = self.name, self.n_heads
        ln1 = _ln(x, p[f"_{n}_l{i}_ln1.w0"], p[f"_{n}_l{i}_ln1.wbias"])
        q = _heads(ln1 @ p[f"_{n}_l{i}_q.w0"], h)
        dh = q.shape[-1]
        kv_h = k_cache.shape[2]
        k = _heads(ln1 @ p[f"_{n}_l{i}_k.w0"], kv_h)
        v = _heads(ln1 @ p[f"_{n}_l{i}_v.w0"], kv_h)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, pos, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, pos, 0, 0))
        t = x.shape[1]
        T = k_cache.shape[1]
        scale = dh ** -0.5
        rep = h // kv_h
        if self._use_flash_prefill(t, pos, dh):
            # LONG-prompt prefill: the einsum path materializes a
            # [b,g,rep,t,t] score tensor (quadratic HBM); the flash
            # kernel streams K/V blocks instead. Only valid when the
            # cache holds nothing before this call (pos == 0), i.e.
            # attention is causal over exactly these t positions. GQA
            # repeats K/V here — a one-time prefill cost, never paid
            # per decode step.
            from paddle_tpu.ops import pallas_attention as flash
            kq = k if rep == 1 else jnp.repeat(k, rep, axis=2)
            vq = v if rep == 1 else jnp.repeat(v, rep, axis=2)
            lens = jnp.minimum(jnp.full((x.shape[0],), t, jnp.int32),
                               kv_len)
            attn = flash.flash_attention(
                q.astype(x.dtype), kq.astype(x.dtype),
                vq.astype(x.dtype), q_lens=lens, kv_lens=lens,
                causal=True, scale=scale,
                interpret=jax.default_backend() == "cpu")
            attn = attn.reshape(x.shape)
        else:
            # grouped-query: q [b,t,(kv_h, rep),dh] against kv_h-head
            # caches — the cache is read at stored width, never repeated
            q5 = q.reshape(q.shape[0], t, kv_h, rep, dh)
            logits = jnp.einsum("bqgrd,bkgd->bgrqk", q5,
                                k_cache.astype(q.dtype)) * scale
            # causal against absolute positions: query row j is at pos+j
            qpos = pos + jnp.arange(t)[:, None]
            kpos = jnp.arange(T)[None, :]
            mask = (kpos <= qpos) & (kpos < kv_len)
            logits = jnp.where(mask[None, None, None], logits, -1e30)
            w = jax.nn.softmax(logits, axis=-1)
            attn = jnp.einsum("bgrqk,bkgd->bqgrd", w,
                              v_cache.astype(q.dtype))
            attn = attn.reshape(x.shape)
        x = x + attn @ p[f"_{n}_l{i}_proj.w0"]
        return self._ffn(p, i, x), k_cache, v_cache

    def _ffn(self, p, i, x):
        """ln2 + FFN (dense or MoE) + residual over [b, t, d] — shared
        between the dense-cache block and the paged step (PagedDecoder),
        so the two paths cannot drift numerically."""
        n = self.name
        ln2 = _ln(x, p[f"_{n}_l{i}_ln2.w0"], p[f"_{n}_l{i}_ln2.wbias"])
        if f"_{n}_l{i}_moe.gate" in p:
            b_, t_, d_ = ln2.shape
            cf = self.moe_capacity_factor
            cap = None
            if cf is None:
                gate = p[f"_{n}_l{i}_moe.gate"]
                cap = b_ * t_
                # drop-free routing materializes [n, E, C=n] dispatch
                # tensors — quadratic in tokens. Cheap for the per-step
                # call (n = batch); for a LARGE prefill fall back to a
                # generous factor instead of OOMing the chip.
                if cap * cap * gate.shape[-1] > (1 << 27):
                    import warnings
                    warnings.warn(
                        f"moe prefill with {cap} tokens: drop-free "
                        "routing would need a "
                        f"[{cap},{gate.shape[-1]},{cap}] dispatch "
                        "tensor; falling back to capacity_factor=2.0 "
                        "(set moe_capacity_factor explicitly to "
                        "choose)", stacklevel=2)
                    cap, cf = None, 2.0
            y2d, _ = moe_ops.moe_ffn(
                ln2.reshape(b_ * t_, d_), None,
                p[f"_{n}_l{i}_moe.gate"], p[f"_{n}_l{i}_moe.moe_up"],
                p[f"_{n}_l{i}_moe.moe_down"], k=self.moe_k,
                capacity_factor=cf if cf is not None else 1.25,
                capacity=cap, dispatch_mode="auto")
            x = x + y2d.reshape(b_, t_, d_)
        else:
            up = jax.nn.relu(ln2 @ p[f"_{n}_l{i}_up.w0"]
                             + p[f"_{n}_l{i}_up.wbias"])
            x = x + up @ p[f"_{n}_l{i}_down.w0"]
        return x

    def _logits(self, p, x):
        n = self.name
        x = _ln(x, p[f"_{n}_lnf.w0"], p[f"_{n}_lnf.wbias"])
        if f"_{n}_head.w0" in p:
            logits = x @ p[f"_{n}_head.w0"]
        else:  # tie_embeddings: the head IS the token table, transposed
            logits = x @ p[f"_{n}_tok_emb.w0"].T
        if f"_{n}_head.wbias" in p:  # older checkpoints carried a bias
            logits = logits + p[f"_{n}_head.wbias"]
        return logits

    def _forward(self, p, ids, pos, caches, cache_pos, kv_len):
        """ids [b, t] -> (logits [b, t, V], caches')."""
        x = self._embed(p, ids, pos)
        new_caches = []
        for i, (kc, vc) in enumerate(caches):
            x, kc, vc = self._block(p, i, x, kc, vc, cache_pos, kv_len)
            new_caches.append((kc, vc))
        return self._logits(p, x), new_caches

    def _prefill(self, p, prompt, plen, max_len):
        """Allocate the fixed-size caches and run the one batched causal
        pass over the prompt. -> (last-position logits path input, caches)."""
        n, h = self.name, self.n_heads
        b = prompt.shape[0]
        d = p[f"_{n}_tok_emb.w0"].shape[1]
        dtype = p[f"_{n}_tok_emb.w0"].dtype
        # kv head count from the k projection's width (grouped-query
        # attention stores kv_h-sized caches — THE decode win of GQA)
        dh = d // h
        kv_h = p[f"_{n}_l0_k.w0"].shape[1] // dh
        caches = [(jnp.zeros((b, max_len, kv_h, dh), dtype),
                   jnp.zeros((b, max_len, kv_h, dh), dtype))
                  for _ in range(self.n_layers)]
        pos = jnp.arange(plen)[None, :].repeat(b, 0)
        return self._forward(p, prompt, pos, caches, 0, plen)

    def _validate(self, prompt, max_len):
        plen = int(prompt.shape[1])
        assert max_len > plen, f"max_len {max_len} <= prompt length {plen}"
        pos_rows = self.p[f"_{self.name}_pos_emb.w0"].shape[0]
        assert max_len <= pos_rows, (
            f"max_len {max_len} exceeds the position table ({pos_rows} "
            "rows) — jit gathers clamp silently, so positions past the "
            "table would all reuse its last row")
        return plen

    # ------------------------------------------------------------- generate
    def _build(self, plen: int, max_len: int,
               temperature: Optional[float]):
        def sample(lg, key):
            if temperature is None:
                return jnp.argmax(lg, axis=-1).astype(jnp.int32)
            return jax.random.categorical(
                key, lg.astype(jnp.float32) / temperature).astype(jnp.int32)

        def run(p, prompt, rng):
            b = prompt.shape[0]
            logits, caches = self._prefill(p, prompt, plen, max_len)
            k0, rng = jax.random.split(rng)
            first = sample(logits[:, -1], k0)

            def step(carry, key):
                caches, tok, pp = carry
                lg, caches = self._forward(
                    p, tok[:, None], jnp.full((b, 1), pp, jnp.int32),
                    caches, pp, pp + 1)
                return (caches, sample(lg[:, -1], key), pp + 1), tok

            n_steps = max_len - plen - 1
            keys = jax.random.split(rng, n_steps) if n_steps > 0 else \
                jnp.zeros((0, 2), jnp.uint32)
            (_, last_tok, _), toks = jax.lax.scan(
                step, (caches, first, jnp.int32(plen)), keys)
            return jnp.concatenate(
                [toks.transpose(1, 0), last_tok[:, None]], axis=1)

        return jax.jit(run)

    # ---------------------------------------------------------- beam search
    def _build_beam_gnmt(self, plen: int, max_len: int, beam_size: int,
                         eos_id: int, alpha: float):
        """Full GNMT beam semantics: a hypothesis that emits EOS leaves
        the beam and is BANKED with its length-penalized score
        (raw / len^alpha) inside the scan, freeing its lane for live
        continuations — a short high-scoring hypothesis can therefore
        never be pruned mid-search by longer raw-sum rivals (the
        limitation of the raw-sum path below, which length_penalty=0
        keeps). Returns (tokens [b,K,L], penalized scores [b,K]),
        best first."""
        n = self.name
        K = beam_size
        L = max_len - plen

        def run(p, prompt):
            b = prompt.shape[0]
            V = p[f"_{n}_head.w0"].shape[1] if f"_{n}_head.w0" in p \
                else p[f"_{n}_tok_emb.w0"].shape[0]
            # live lanes exclude EOS, so K live continuations need K
            # non-EOS tokens to exist (the raw-sum path has no such
            # restriction — its EOS lanes freeze in place)
            assert K < V, \
                f"gnmt beam needs beam_size={K} < vocab_size={V}"
            vmask = jnp.arange(V) == eos_id
            logits, caches = self._prefill(p, prompt, plen, max_len)
            lp0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))
            # the bank: top-K finished hypotheses, penalized scores
            bank_s = jnp.full((b, K), -1e30, jnp.float32)
            bank_t = jnp.full((b, K, L), eos_id, jnp.int32)
            # immediate-EOS is the first banked candidate (length 1)
            bank_s = bank_s.at[:, 0].set(lp0[:, eos_id] / 1.0 ** alpha)
            # live lanes seed from the top-K NON-eos first tokens
            lp0m = jnp.where(vmask[None], -1e30, lp0)
            scores, tok0 = jax.lax.top_k(lp0m, K)
            caches = [(jnp.repeat(kc, K, axis=0), jnp.repeat(vc, K, axis=0))
                      for kc, vc in caches]
            tokens = jnp.full((b, K, L), eos_id, jnp.int32)
            tokens = tokens.at[:, :, 0].set(tok0)

            def merge_bank(bank_s, bank_t, cand_s, cand_t):
                all_s = jnp.concatenate([bank_s, cand_s], axis=1)
                all_t = jnp.concatenate([bank_t, cand_t], axis=1)
                top_s, idx = jax.lax.top_k(all_s, K)
                top_t = jnp.take_along_axis(all_t, idx[:, :, None], axis=1)
                return top_s, top_t

            def step(carry, t):
                caches, tokens, scores, bank_s, bank_t = carry
                last = tokens[:, :, t - 1].reshape(b * K)
                lg, caches2 = self._forward(
                    p, last[:, None],
                    jnp.full((b * K, 1), plen + t - 1, jnp.int32),
                    caches, plen + t - 1, plen + t)
                lp = jax.nn.log_softmax(
                    lg[:, -1].astype(jnp.float32)).reshape(b, K, V)
                # bank each lane's EOS continuation (length t+1 with eos)
                eos_raw = scores + lp[:, :, eos_id]
                eos_pen = eos_raw / (t + 1.0) ** alpha
                cand_t = tokens.at[:, :, t].set(eos_id)
                bank_s, bank_t = merge_bank(bank_s, bank_t, eos_pen,
                                            cand_t)
                # live lanes continue over non-EOS tokens only
                lp = jnp.where(vmask[None, None], -1e30, lp)
                total = scores[:, :, None] + lp
                scores2, flat = jax.lax.top_k(total.reshape(b, K * V), K)
                parent = flat // V
                tok = (flat % V).astype(jnp.int32)
                tokens2 = jnp.take_along_axis(
                    tokens, parent[:, :, None], axis=1).at[:, :, t].set(tok)
                pflat = (jnp.arange(b)[:, None] * K + parent).reshape(-1)
                caches2 = [(kc[pflat], vc[pflat]) for kc, vc in caches2]
                return (caches2, tokens2, scores2, bank_s, bank_t), 0

            (caches, tokens, scores, bank_s, bank_t), _ = jax.lax.scan(
                step, (caches, tokens, scores, bank_s, bank_t),
                jnp.arange(1, L))
            # drain: still-live lanes compete at their full length L
            bank_s, bank_t = merge_bank(bank_s, bank_t,
                                        scores / float(L) ** alpha, tokens)
            return bank_t, bank_s

        return jax.jit(run)

    def _build_beam(self, plen: int, max_len: int, beam_size: int,
                    eos_id: int):
        n = self.name
        K = beam_size

        def run(p, prompt):
            b = prompt.shape[0]
            V = p[f"_{n}_head.w0"].shape[1] if f"_{n}_head.w0" in p \
                else p[f"_{n}_tok_emb.w0"].shape[0]
            logits, caches = self._prefill(p, prompt, plen, max_len)
            lp0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))
            # seed K lanes with the top-K first tokens
            scores, tok0 = jax.lax.top_k(lp0, K)          # [b, K]
            caches = [(jnp.repeat(kc, K, axis=0), jnp.repeat(vc, K, axis=0))
                      for kc, vc in caches]               # [b*K, ...]
            tokens = jnp.full((b, K, max_len - plen), eos_id, jnp.int32)
            tokens = tokens.at[:, :, 0].set(tok0)
            alive = tok0 != eos_id                        # [b, K]

            def step(carry, t):
                caches, tokens, scores, alive = carry
                last = tokens[:, :, t - 1].reshape(b * K)
                lg, caches2 = self._forward(
                    p, last[:, None],
                    jnp.full((b * K, 1), plen + t - 1, jnp.int32),
                    caches, plen + t - 1, plen + t)
                lp = jax.nn.log_softmax(
                    lg[:, -1].astype(jnp.float32)).reshape(b, K, V)
                # finished beams: only the eos continuation, at no cost —
                # the lane's score freezes and it keeps emitting eos
                frozen = jnp.full((V,), -1e30).at[eos_id].set(0.0)
                lp = jnp.where(alive[:, :, None], lp, frozen[None, None])
                total = scores[:, :, None] + lp           # [b, K, V]
                scores2, flat = jax.lax.top_k(total.reshape(b, K * V), K)
                parent = flat // V                        # [b, K]
                tok = (flat % V).astype(jnp.int32)
                # reorder histories + caches to follow the winning parents
                gather = lambda a: jnp.take_along_axis(a, parent[..., None],
                                                       axis=1)
                tokens2 = jnp.take_along_axis(
                    tokens, parent[:, :, None], axis=1).at[:, :, t].set(tok)
                pflat = (jnp.arange(b)[:, None] * K + parent).reshape(-1)
                caches2 = [(kc[pflat], vc[pflat]) for kc, vc in caches2]
                alive2 = gather(alive[..., None])[..., 0] & (tok != eos_id)
                return (caches2, tokens2, scores2, alive2), 0

            n_steps = max_len - plen - 1
            (caches, tokens, scores, alive), _ = jax.lax.scan(
                step, (caches, tokens, scores, alive),
                jnp.arange(1, n_steps + 1))
            return tokens, scores

        return jax.jit(run)

    def beam_search(self, prompt, max_len: int, beam_size: int = 4,
                    eos_id: int = 0, num_results: Optional[int] = None,
                    length_penalty: float = 0.0):
        """prompt [b, P] -> per-sample n-best [(score, tokens), ...],
        best first — the transformer analogue of the recurrent zoo's
        `beam_search` layer (scores are summed token log-probs; finished
        beams freeze at their EOS). Rows are trimmed at the first EOS.

        length_penalty alpha > 0 runs FULL GNMT semantics in-device
        (_build_beam_gnmt): a hypothesis that emits EOS is banked with
        its penalized score score/len^alpha inside the search, freeing
        its lane — so short high-scoring hypotheses survive the beam,
        and the returned scores are the penalized ones. alpha = 0 keeps
        the raw-sum search."""
        import numpy as np
        prompt = jnp.asarray(prompt, jnp.int32)
        plen = self._validate(prompt, max_len)
        n_keep = num_results if num_results is not None else beam_size
        assert 1 <= n_keep <= beam_size, (
            f"num_results={num_results} must be in [1, beam_size]")
        assert length_penalty >= 0.0, length_penalty
        key = ("beam", plen, int(max_len), beam_size, eos_id,
               float(length_penalty))
        if key not in self._jitted:
            if length_penalty > 0.0:
                self._jitted[key] = self._build_beam_gnmt(
                    plen, int(max_len), beam_size, eos_id,
                    float(length_penalty))
            else:
                self._jitted[key] = self._build_beam(plen, int(max_len),
                                                     beam_size, eos_id)
        toks, scores = self._jitted[key](self.p, prompt)
        toks, scores = np.asarray(toks), np.asarray(scores)
        out = []
        for bi in range(toks.shape[0]):
            rows = []
            for ki in range(toks.shape[1]):
                row = list(map(int, toks[bi, ki]))
                if eos_id in row:
                    row = row[:row.index(eos_id) + 1]
                # gnmt path returns penalized scores already
                rows.append((float(scores[bi, ki]), row))
            out.append(rows[:n_keep])
        return out

    def paged(self, *, num_slots: int, page_size: int,
              num_pages: int, max_pages_per_slot: int,
              temperature: Optional[float] = None,
              window: int = 1,
              attention: str = "auto",
              warm_start: bool = True,
              kv_quant: Optional[str] = None) -> "PagedDecoder":
        """A fixed-shape paged-KV decode step over this decoder's
        parameter table (the serving engine's hot path)."""
        return PagedDecoder(self, num_slots=num_slots,
                            page_size=page_size, num_pages=num_pages,
                            max_pages_per_slot=max_pages_per_slot,
                            temperature=temperature, window=window,
                            attention=attention, warm_start=warm_start,
                            kv_quant=kv_quant)

    def generate(self, prompt, max_len: int,
                 temperature: Optional[float] = None,
                 rng: Optional[jax.Array] = None,
                 eos_id: Optional[int] = None):
        """prompt [b, P] int32 -> per-row generated ids (length
        max_len - P, trimmed at eos_id when given).

        temperature None = greedy argmax; otherwise categorical at the
        given temperature. max_len bounds prompt + generation (the KV
        cache size)."""
        import numpy as np
        prompt = jnp.asarray(prompt, jnp.int32)
        plen = self._validate(prompt, max_len)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        key = (plen, int(max_len), temperature)
        if key not in self._jitted:
            self._jitted[key] = self._build(plen, int(max_len), temperature)
        out = np.asarray(self._jitted[key](self.p, prompt, rng))
        if eos_id is None:
            return [list(map(int, row)) for row in out]
        rows = []
        for row in out:
            hit = np.where(row == eos_id)[0]
            rows.append(list(map(int, row[:hit[0] + 1] if len(hit) else row)))
        return rows


class PagedDecoder:
    """One fixed-shape, slot-batched decode step over a PAGED KV cache.

    The dense-cache decoder above allocates a [b, max_len, g, dh] cache
    PER REQUEST BATCH and marches the whole batch in lockstep — padding
    every sequence's cache read to the longest, and recompiling per
    (batch, prompt_len) combination. This class is the serving
    replacement: K/V live in a shared preallocated POOL of fixed-size
    pages ([L, n_pages, page_size, g, dh]); each slot of a fixed-size
    slot batch owns a page-table row mapping its logical positions to
    physical pages. Requests join and leave mid-flight by editing the
    small int32 inputs (tokens / positions / page tables / active mask)
    — the jitted step's shapes NEVER change, so continuous batching
    costs zero recompiles (pinned by @recompile_budget in
    tests/test_paged_decode.py).

    Numerics are the dense path's, by construction: token embedding,
    per-layer ln/q/k/v, the grouped-query einsum attention
    (ops/pallas_decode.paged_attention runs the exact dense einsum over
    the gathered page view), and the SHARED ``_ffn`` — so greedy paged
    decode is token-identical to ``TransformerDecoder.generate``
    (tests/test_paged_decode.py pins this on ragged,
    page-boundary-straddling batches).

    Scheduling (which slot holds which request, page alloc/free,
    eviction) is host-side policy and lives in serving/engine.py; this
    class is only the device step. Physical page 0 is RESERVED as the
    null page: inactive slots write their (discarded) K/V there and
    unassigned page-table entries point at it, which keeps the scatter
    and gather unconditional — no shape-changing branches.

    ``window`` > 1 widens the step to W tokens PER SLOT per dispatch —
    one fixed [S, W] shape that serves three schedules with zero extra
    compiles: multi-token prompt teacher-forcing, the speculative
    verify window (feed the pending token + k draft proposals, read W
    argmaxes, accept the token-identical prefix — serving/engine.py),
    and the classic one-token step (W = 1, or masked columns).
    In-window causality holds because every window token's K/V is
    scattered into the pool BEFORE attention and each token's kv_len
    masks later positions. ``attention`` selects the cache-read path:
    "gather" (the exact einsum over the full page view), "kernel" (the
    allocated-pages Pallas kernel — ops/pallas_decode.py), or "auto"
    (kernel on TPU when supported, gather elsewhere).

    ``kv_quant="int8"`` switches the pools to the two-tier INT8 layout:
    each pool becomes a pytree ``{"q": int8 [L, N, ps, g, dh],
    "s": float32 [L, N, ps, g]}`` — the scatter quantizes each K/V row
    per (token, kv-head) with ops/pallas_decode.quantize_kv (a pure
    function of the row, so prefix-shared pages stay bit-identical
    across owners) and attention reads through the dequant-fused
    kernel or the dequantizing gather fallback. ~4x pages per HBM
    byte at fp32 base dtype; greedy output is prefix-identical to the
    fp path under the pinned INT8_KV_* contract."""

    def __init__(self, dense: TransformerDecoder, *, num_slots: int,
                 page_size: int, num_pages: int,
                 max_pages_per_slot: int,
                 temperature: Optional[float] = None,
                 window: int = 1, attention: str = "auto",
                 warm_start: bool = True,
                 kv_quant: Optional[str] = None):
        assert num_pages >= 2, "need at least the null page + one real"
        assert max_pages_per_slot * page_size <= \
            dense.p[f"_{dense.name}_pos_emb.w0"].shape[0], (
            "slot capacity exceeds the position table — positions past "
            "it would silently clamp to its last row")
        assert window >= 1, window
        assert attention in ("auto", "kernel", "gather"), attention
        assert kv_quant in (None, "int8"), kv_quant
        self.kv_quant = kv_quant
        self.dense = dense
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_pages_per_slot = int(max_pages_per_slot)
        self.temperature = temperature
        self.window = int(window)
        n, h = dense.name, dense.n_heads
        d = dense.p[f"_{n}_tok_emb.w0"].shape[1]
        self.head_dim = d // h
        self.kv_heads = dense.p[f"_{n}_l0_k.w0"].shape[1] // self.head_dim
        self.dtype = dense.p[f"_{n}_tok_emb.w0"].dtype
        from paddle_tpu.ops import pallas_decode as paged_ops
        probe_q = jax.ShapeDtypeStruct(
            (self.num_slots, self.window, h, self.head_dim), self.dtype)
        kv_dtype = jnp.int8 if self.kv_quant == "int8" else self.dtype
        probe_k = jax.ShapeDtypeStruct(
            (self.num_pages, self.page_size, self.kv_heads,
             self.head_dim), kv_dtype)
        probe_s = jax.ShapeDtypeStruct(
            (self.num_pages, self.page_size, self.kv_heads),
            jnp.float32) if self.kv_quant == "int8" else None
        on_tpu = jax.default_backend() == "tpu"
        if attention == "kernel":
            self.use_kernel = True
        elif attention == "gather":
            self.use_kernel = False
        else:
            self.use_kernel = on_tpu and \
                paged_ops.paged_kernel_supported(probe_q, probe_k,
                                                 probe_s)
        self.kernel_interpret = self.use_kernel and not on_tpu
        # donating the pools lets XLA update pages in place (the pools
        # ARE the device memory budget); the CPU backend has no donation
        # and would warn on every dispatch
        donate = () if jax.default_backend() == "cpu" else (1, 2)
        self._step = jax.jit(self._step_impl, donate_argnums=donate)
        self._copy = jax.jit(self._copy_page_impl,
                             donate_argnums=() if not donate else (0, 1))
        # warm-start plane (paddle_tpu/artifacts): both jitted
        # functions resolve through the executable ladder on first
        # dispatch — an artifact hit (in-process or on-disk) makes the
        # engine's startup zero-compile. Fingerprints capture every
        # knob that changes the compiled program.
        self.warm_start = bool(warm_start)
        from paddle_tpu.artifacts import fingerprint
        plan = {"num_slots": self.num_slots,
                "page_size": self.page_size,
                "num_pages": self.num_pages,
                "max_pages_per_slot": self.max_pages_per_slot,
                "window": self.window,
                "temperature": self.temperature,
                "use_kernel": self.use_kernel,
                "kernel_interpret": self.kernel_interpret,
                "kv_quant": self.kv_quant}
        self._step_fp = fingerprint("paged_step", dense.p, plan=plan)
        page_plan = {"num_pages": self.num_pages,
                     "page_size": self.page_size,
                     "n_layers": dense.n_layers,
                     "kv_heads": self.kv_heads,
                     "head_dim": self.head_dim,
                     "dtype": str(jnp.dtype(self.dtype)),
                     "kv_quant": self.kv_quant}
        self._copy_fp = fingerprint("paged_copy", dense.p,
                                    plan=page_plan)
        self._read_fp = fingerprint("paged_read", dense.p,
                                    plan=page_plan)
        self._write_fp = fingerprint("paged_write", dense.p,
                                     plan=page_plan)
        self._read = jax.jit(self._read_page_impl)
        self._write = jax.jit(self._write_page_impl,
                              donate_argnums=() if not donate
                              else (0, 1))
        self._step_exe = None
        self._copy_exe = None
        self._read_exe = None
        self._write_exe = None

    def init_pools(self):
        """Zeroed (k_pool, v_pool): each [L, n_pages, page_size, g, dh]
        arrays at the base dtype, or — under ``kv_quant="int8"`` — the
        two-tier pytrees ``{"q": int8 values, "s": float32 per-row
        scales [L, n_pages, page_size, g]}``."""
        shape = (self.dense.n_layers, self.num_pages, self.page_size,
                 self.kv_heads, self.head_dim)
        if self.kv_quant == "int8":
            def one():
                return {"q": jnp.zeros(shape, jnp.int8),
                        "s": jnp.zeros(shape[:-1], jnp.float32)}
            return one(), one()
        return jnp.zeros(shape, self.dtype), jnp.zeros(shape, self.dtype)

    def pool_bytes(self) -> int:
        rows = self.dense.n_layers * self.num_pages * \
            self.page_size * self.kv_heads
        if self.kv_quant == "int8":
            # 1 byte/element + one float32 scale per row, per pool
            return 2 * rows * (self.head_dim + 4)
        return 2 * int(jnp.dtype(self.dtype).itemsize) * rows * \
            self.head_dim

    def _paged_block(self, p, i, x, k_pool, v_pool, page_idx, offs,
                     page_tables, kv_lens):
        from paddle_tpu.ops import pallas_decode as paged_ops
        d0 = self.dense
        n, h = d0.name, d0.n_heads
        S, W = x.shape[0], x.shape[1]
        ln1 = _ln(x, p[f"_{n}_l{i}_ln1.w0"], p[f"_{n}_l{i}_ln1.wbias"])
        q = _heads(ln1 @ p[f"_{n}_l{i}_q.w0"], h)       # [S, W, h, dh]
        g = self.kv_heads
        k = _heads(ln1 @ p[f"_{n}_l{i}_k.w0"], g)        # [S, W, g, dh]
        v = _heads(ln1 @ p[f"_{n}_l{i}_v.w0"], g)
        # unconditional scatter: every window token writes its K/V at
        # (physical page, in-page offset) — BEFORE attention, so later
        # window tokens attend to earlier ones (in-window causality via
        # each token's kv_len). Masked tokens were routed to the null
        # page by the caller.
        rows_p = page_idx.reshape(-1)
        rows_o = offs.reshape(-1)
        if self.kv_quant == "int8":
            kq, ks = paged_ops.quantize_kv(k.reshape(S * W, g, -1))
            vq, vs = paged_ops.quantize_kv(v.reshape(S * W, g, -1))
            k_pool = {"q": k_pool["q"].at[i, rows_p, rows_o].set(kq),
                      "s": k_pool["s"].at[i, rows_p, rows_o].set(ks)}
            v_pool = {"q": v_pool["q"].at[i, rows_p, rows_o].set(vq),
                      "s": v_pool["s"].at[i, rows_p, rows_o].set(vs)}
            attn = paged_ops.paged_window_attention(
                q, k_pool["q"][i], v_pool["q"][i], page_tables,
                kv_lens, use_kernel=self.use_kernel,
                interpret=self.kernel_interpret,
                k_scales=k_pool["s"][i], v_scales=v_pool["s"][i])
        else:
            k_pool = k_pool.at[i, rows_p, rows_o
                               ].set(k.reshape(S * W, g, -1)
                                     .astype(k_pool.dtype))
            v_pool = v_pool.at[i, rows_p, rows_o
                               ].set(v.reshape(S * W, g, -1)
                                     .astype(v_pool.dtype))
            attn = paged_ops.paged_window_attention(
                q, k_pool[i], v_pool[i], page_tables, kv_lens,
                use_kernel=self.use_kernel,
                interpret=self.kernel_interpret)
        x = x + attn.reshape(x.shape) @ p[f"_{n}_l{i}_proj.w0"]
        return d0._ffn(p, i, x), k_pool, v_pool

    def _step_impl(self, p, k_pool, v_pool, tokens, positions,
                   page_tables, active, key):
        """tokens/positions/active [S, W]; page_tables [S, P] int32 ->
        (next_tokens [S, W] int32, k_pool', v_pool'). Output column w
        is the model's next-token choice after feeding window tokens
        0..w — the teacher-forced continuation AND the speculative
        verify verdict in one read."""
        d0 = self.dense
        ps = self.page_size
        x = d0._embed(p, tokens, positions)             # [S, W, d]
        page_idx = jnp.take_along_axis(
            page_tables, positions // ps, axis=1)       # [S, W]
        page_idx = jnp.where(active, page_idx, 0)       # null the dead
        offs = jnp.where(active, positions % ps, 0)
        kv_lens = positions + 1
        for i in range(d0.n_layers):
            x, k_pool, v_pool = self._paged_block(
                p, i, x, k_pool, v_pool, page_idx, offs, page_tables,
                kv_lens)
        logits = d0._logits(p, x)                       # [S, W, V]
        if self.temperature is None:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            nxt = jax.random.categorical(
                key, logits.astype(jnp.float32) /
                self.temperature).astype(jnp.int32)
        return nxt, k_pool, v_pool

    @staticmethod
    def _page_slice(leaf, page):
        """[L, 1, ...] view of one physical page — rank-generic so it
        covers both the value leaves [L, N, ps, g, dh] and the int8
        layout's scale leaves [L, N, ps, g]."""
        start = (0, page) + (0,) * (leaf.ndim - 2)
        return jax.lax.dynamic_slice(
            leaf, start, (leaf.shape[0], 1) + leaf.shape[2:])

    @staticmethod
    def _page_update(leaf, data, page):
        start = (0, page) + (0,) * (leaf.ndim - 2)
        return jax.lax.dynamic_update_slice(
            leaf, data.astype(leaf.dtype), start)

    def _copy_page_impl(self, k_pool, v_pool, src, dst):
        """Device-side page copy (all layers) — the copy-on-write step
        behind partial-page prefix reuse (serving/prefix.py). src/dst
        are TRACED int32 scalars, so every (src, dst) pair shares ONE
        compilation. tree_map'd over the pool pytree, so the int8
        layout copies values AND scales."""
        def cp(pool):
            return jax.tree_util.tree_map(
                lambda leaf: self._page_update(
                    leaf, self._page_slice(leaf, src), dst), pool)

        return cp(k_pool), cp(v_pool)

    def _read_page_impl(self, k_pool, v_pool, page):
        """Device -> host leg of page spill (serving/spill.py): one
        physical page of both pools as [L, 1, ...] leaves. ``page`` is
        a traced scalar — one compilation covers every spill."""
        rd = lambda pool: jax.tree_util.tree_map(
            lambda leaf: self._page_slice(leaf, page), pool)
        return rd(k_pool), rd(v_pool)

    def _write_page_impl(self, k_pool, v_pool, k_page, v_page, page):
        """Host -> device leg of page restore: the inverse of
        :meth:`_read_page_impl`."""
        wr = lambda pool, data: jax.tree_util.tree_map(
            lambda leaf, d: self._page_update(leaf, d, page),
            pool, data)
        return wr(k_pool, k_page), wr(v_pool, v_page)

    def copy_page(self, k_pool, v_pool, src: int, dst: int):
        """Copy physical page ``src`` -> ``dst`` in both pools."""
        args = (k_pool, v_pool, jnp.int32(src), jnp.int32(dst))
        if self._copy_exe is None:
            from paddle_tpu.artifacts import resolve
            self._copy_exe = resolve(self._copy_fp, self._copy, args,
                                     warm=self.warm_start)
        return self._copy_exe(*args)

    def read_page(self, k_pool, v_pool, page: int):
        """One physical page of both pools as [L, 1, ...] pytrees —
        the spill store's device->host read (serving/engine.py)."""
        args = (k_pool, v_pool, jnp.int32(page))
        if self._read_exe is None:
            from paddle_tpu.artifacts import resolve
            self._read_exe = resolve(self._read_fp, self._read, args,
                                     warm=self.warm_start)
        return self._read_exe(*args)

    def write_page(self, k_pool, v_pool, k_page, v_page, page: int):
        """Write [L, 1, ...] page pytrees back into physical ``page``
        of both pools — the restore leg of page spill."""
        args = (k_pool, v_pool, k_page, v_page, jnp.int32(page))
        if self._write_exe is None:
            from paddle_tpu.artifacts import resolve
            self._write_exe = resolve(self._write_fp, self._write,
                                      args, warm=self.warm_start)
        return self._write_exe(*args)

    def step(self, k_pool, v_pool, tokens, positions, page_tables,
             active, key=None):
        """Dispatch one decode step. Accepts the classic [S] one-token
        arrays (returns next tokens [S]) or the [S, W] window contract
        (returns [S, W]). Compiles exactly once for the engine's
        lifetime — joins/evictions/window occupancy only change
        VALUES."""
        if key is None:
            key = jax.random.PRNGKey(0)
        tokens = jnp.asarray(tokens, jnp.int32)
        squeeze = tokens.ndim == 1
        if squeeze:
            assert self.window == 1, (
                "one-token [S] arrays only drive a window=1 decoder")
            tokens = tokens[:, None]
            positions = jnp.asarray(positions, jnp.int32)[:, None]
            active = jnp.asarray(active, jnp.bool_)[:, None]
        args = (self.dense.p, k_pool, v_pool, tokens,
                jnp.asarray(positions, jnp.int32),
                jnp.asarray(page_tables, jnp.int32),
                jnp.asarray(active, jnp.bool_), key)
        if self._step_exe is None:
            from paddle_tpu.artifacts import resolve
            self._step_exe = resolve(self._step_fp, self._step, args,
                                     warm=self.warm_start)
        nxt, k_pool, v_pool = self._step_exe(*args)
        if squeeze:
            nxt = nxt[:, 0]
        return nxt, k_pool, v_pool


class DraftDecoder:
    """The DRAFT side of speculative decoding: a small decoder over
    slot-PRIVATE dense caches, window-batched like PagedDecoder.

    The draft never shares the paged pool or the prefix trie — each
    slot owns a [T+1]-row dense cache lane (row T is the null row,
    mirroring the paged null page), and the engine teacher-forces the
    slot's committed tokens through it before asking for proposals.
    That keeps draft-cache coherence trivially correct under prefix
    hits, CoW, eviction and rejected speculation: the engine only
    tracks how many committed tokens the draft has FED (draft_pos),
    rolls it back past rejected proposals, and re-feeds — every cache
    row is rewritten before any query's kv_len can reach it. Greedy
    argmax only: proposals must be deterministic for the target's
    token-identity acceptance rule to compose (serving/engine.py).

    ONE jitted [S, W] step serves catch-up (feed up to W committed
    tokens) and proposal (feed 1 token, read its argmax) — zero extra
    compiles under churn, same contract as the target step."""

    def __init__(self, dense: TransformerDecoder, *, num_slots: int,
                 max_seq_len: int, window: int = 1,
                 warm_start: bool = True):
        pos_rows = dense.p[f"_{dense.name}_pos_emb.w0"].shape[0]
        assert max_seq_len <= pos_rows, (max_seq_len, pos_rows)
        self.dense = dense
        self.num_slots = int(num_slots)
        self.max_seq_len = int(max_seq_len)
        self.window = int(window)
        n, h = dense.name, dense.n_heads
        d = dense.p[f"_{n}_tok_emb.w0"].shape[1]
        self.head_dim = d // h
        self.kv_heads = dense.p[f"_{n}_l0_k.w0"].shape[1] // self.head_dim
        self.dtype = dense.p[f"_{n}_tok_emb.w0"].dtype
        donate = () if jax.default_backend() == "cpu" else (1, 2)
        self._step = jax.jit(self._step_impl, donate_argnums=donate)
        self.warm_start = bool(warm_start)
        from paddle_tpu.artifacts import fingerprint
        self._step_fp = fingerprint(
            "draft_step", dense.p,
            plan={"num_slots": self.num_slots,
                  "max_seq_len": self.max_seq_len,
                  "window": self.window})
        self._step_exe = None

    def init_caches(self):
        """Zeroed (k, v), each [L, S, T+1, g, dh] — row T is the null
        row masked tokens write to (never read: kv_len <= T)."""
        shape = (self.dense.n_layers, self.num_slots,
                 self.max_seq_len + 1, self.kv_heads, self.head_dim)
        return jnp.zeros(shape, self.dtype), jnp.zeros(shape, self.dtype)

    def cache_bytes(self) -> int:
        return 2 * int(jnp.dtype(self.dtype).itemsize) * \
            self.dense.n_layers * self.num_slots * \
            (self.max_seq_len + 1) * self.kv_heads * self.head_dim

    def _step_impl(self, p, kc, vc, tokens, positions, active):
        """tokens/positions/active [S, W] -> (argmax [S, W], kc', vc')."""
        d0 = self.dense
        n, h, g = d0.name, d0.n_heads, self.kv_heads
        S, W = tokens.shape
        T1 = self.max_seq_len + 1
        rep = h // g
        rows = jnp.arange(S)[:, None]
        wpos = jnp.where(active, positions, self.max_seq_len)
        x = d0._embed(p, tokens, jnp.where(active, positions, 0))
        kv_lens = positions + 1                          # [S, W]
        tpos = jnp.arange(T1)
        mask = tpos[None, None, :] < kv_lens[:, :, None]  # [S, W, T1]
        for i in range(d0.n_layers):
            ln1 = _ln(x, p[f"_{n}_l{i}_ln1.w0"],
                      p[f"_{n}_l{i}_ln1.wbias"])
            q = _heads(ln1 @ p[f"_{n}_l{i}_q.w0"], h)    # [S, W, h, dh]
            k = _heads(ln1 @ p[f"_{n}_l{i}_k.w0"], g)
            v = _heads(ln1 @ p[f"_{n}_l{i}_v.w0"], g)
            kc = kc.at[i, rows, wpos].set(k.astype(kc.dtype))
            vc = vc.at[i, rows, wpos].set(v.astype(vc.dtype))
            dh = q.shape[-1]
            q5 = q.reshape(S, W, g, rep, dh)
            logits = jnp.einsum("swgrd,stgd->sgrwt", q5,
                                kc[i].astype(q.dtype)) * (dh ** -0.5)
            logits = jnp.where(mask[:, None, None], logits, -1e30)
            w_ = jax.nn.softmax(logits, axis=-1)
            attn = jnp.einsum("sgrwt,stgd->swgrd", w_,
                              vc[i].astype(q.dtype))
            x = x + attn.reshape(x.shape) @ p[f"_{n}_l{i}_proj.w0"]
            x = d0._ffn(p, i, x)
        logits = d0._logits(p, x)                        # [S, W, V]
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), kc, vc

    def step(self, kc, vc, tokens, positions, active):
        args = (self.dense.p, kc, vc,
                jnp.asarray(tokens, jnp.int32),
                jnp.asarray(positions, jnp.int32),
                jnp.asarray(active, jnp.bool_))
        if self._step_exe is None:
            from paddle_tpu.artifacts import resolve
            self._step_exe = resolve(self._step_fp, self._step, args,
                                     warm=self.warm_start)
        return self._step_exe(*args)
