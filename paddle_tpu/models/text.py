"""Text model zoo: IMDB stacked-LSTM classifier (the RNN benchmark),
quick-start text CNN, and the word-embedding language model.

Reference: benchmark/paddle/rnn/rnn.py (embedding -> N x simple_lstm ->
last_seq -> softmax, the 83 ms/batch headline), v1_api_demo/quick_start
(text conv), demo imikolov N-gram LM (python/paddle/v2/dataset/imikolov.py
consumers). TPU-first: the LSTM runs as one lax.scan whose cell matmuls hit
the MXU; masks come from SequenceBatch lengths (no SequenceToBatch
repacking needed).
"""

from __future__ import annotations

from paddle_tpu import activation as act
from paddle_tpu import layers as layer
from paddle_tpu import networks
from paddle_tpu import pooling
from paddle_tpu.core.data_type import integer_value, integer_value_sequence
from paddle_tpu.core.registry import ParamAttr
from paddle_tpu.models.image import ModelSpec


def stacked_lstm_net(vocab_size: int = 30000, emb_size: int = 128,
                     hidden_size: int = 128, lstm_num: int = 1,
                     num_classes: int = 2) -> ModelSpec:
    """benchmark/paddle/rnn/rnn.py parity (IMDB text classification)."""
    data = layer.data("word", integer_value_sequence(vocab_size))
    lbl = layer.data("label", integer_value(num_classes))
    t = layer.embedding(data, size=emb_size, name="sln_emb")
    for i in range(lstm_num):
        t = networks.simple_lstm(t, size=hidden_size, name=f"sln_lstm{i}")
    t = layer.last_seq(t, name="sln_last")
    out = layer.fc(t, size=num_classes, act=act.Softmax(), name="sln_out")
    cost = layer.classification_cost(out, lbl, name="sln_cost")
    err = layer.classification_error(out, lbl, name="sln_error")
    return ModelSpec("stacked_lstm_net", data, lbl, out, cost, err)


def bidi_lstm_net(vocab_size: int = 30000, emb_size: int = 128,
                  hidden_size: int = 128, num_classes: int = 2) -> ModelSpec:
    """Bidirectional variant (networks.py bidirectional_lstm consumer)."""
    data = layer.data("word", integer_value_sequence(vocab_size))
    lbl = layer.data("label", integer_value(num_classes))
    emb = layer.embedding(data, size=emb_size, name="bln_emb")
    t = networks.bidirectional_lstm(emb, size=hidden_size, name="bln_bilstm")
    out = layer.fc(t, size=num_classes, act=act.Softmax(), name="bln_out")
    cost = layer.classification_cost(out, lbl, name="bln_cost")
    err = layer.classification_error(out, lbl, name="bln_error")
    return ModelSpec("bidi_lstm_net", data, lbl, out, cost, err)


def convolution_net(vocab_size: int = 30000, emb_size: int = 128,
                    hidden_size: int = 128, num_classes: int = 2) -> ModelSpec:
    """quick_start text CNN: two context-window conv-pools, concat, softmax
    (v1_api_demo/quick_start/trainer_config.cnn.py shape)."""
    data = layer.data("word", integer_value_sequence(vocab_size))
    lbl = layer.data("label", integer_value(num_classes))
    emb = layer.embedding(data, size=emb_size, name="cn_emb")
    conv3 = networks.sequence_conv_pool(emb, context_len=3,
                                        hidden_size=hidden_size,
                                        name="cn_conv3")
    conv4 = networks.sequence_conv_pool(emb, context_len=4,
                                        hidden_size=hidden_size,
                                        name="cn_conv4")
    merged = layer.concat([conv3, conv4], name="cn_concat")
    out = layer.fc(merged, size=num_classes, act=act.Softmax(), name="cn_out")
    cost = layer.classification_cost(out, lbl, name="cn_cost")
    err = layer.classification_error(out, lbl, name="cn_error")
    return ModelSpec("convolution_net", data, lbl, out, cost, err)


def ngram_lm(vocab_size: int = 2000, emb_size: int = 32,
             hidden_size: int = 256, context: int = 4) -> ModelSpec:
    """imikolov N-gram LM: N-1 embedded context words -> fc -> softmax
    (doc/tutorials word2vec-style demo the imikolov dataset feeds)."""
    words = [layer.data(f"w{i}", integer_value(vocab_size))
             for i in range(context)]
    nxt = layer.data("next_word", integer_value(vocab_size))
    embs = [layer.embedding(w, size=emb_size, name=f"lm_emb{i}",
                            param_attr=ParamAttr(name="lm_emb_shared"))
            for i, w in enumerate(words)]
    ctx = layer.concat(embs, name="lm_concat")
    h = layer.fc(ctx, size=hidden_size, act=act.Relu(), name="lm_h")
    out = layer.fc(h, size=vocab_size, act=act.Softmax(), name="lm_out")
    cost = layer.classification_cost(out, nxt, name="lm_cost")
    err = layer.classification_error(out, nxt, name="lm_error")
    spec = ModelSpec("ngram_lm", words[0], nxt, out, cost, err)
    spec.words = words
    return spec
