"""Attention seq2seq NMT — the stacked-GRU encoder-decoder with Bahdanau
attention (BASELINE.json config #3; reference shape: demo seqToseq /
book machine-translation config built on recurrent_group + simple_attention,
trainer_config_helpers/networks.py:1298, beam_search layers.py:4101).

TPU-first: the whole encoder and the unrolled decoder are lax.scans inside
one jit; generation runs the beam as a batched lax.while/scan with top-k
re-indexing (RecurrentGradientMachine::beamSearch parity without the
per-path dynamic bookkeeping).
"""

from __future__ import annotations

from paddle_tpu import activation as act
from paddle_tpu import layers as layer
from paddle_tpu import networks
from paddle_tpu.core.data_type import integer_value_sequence
from paddle_tpu.core.registry import LayerOutput, ParamAttr
from paddle_tpu.models.image import ModelSpec


def _encoder(src_ids: LayerOutput, vocab: int, emb_size: int, enc_size: int,
             name: str = "enc"):
    emb = layer.embedding(src_ids, size=emb_size, name=f"{name}_emb",
                          param_attr=ParamAttr(name=f"_{name}_emb_w"))
    fwd = networks.simple_gru(emb, size=enc_size, name=f"{name}_fw")
    bwd = networks.simple_gru(emb, size=enc_size, name=f"{name}_bw",
                              reverse=True)
    enc = layer.concat([fwd, bwd], name=f"{name}_concat")       # [b,T,2h]
    proj = layer.fc(enc, size=enc_size, act=None, bias_attr=False,
                    name=f"{name}_proj", param_attr=ParamAttr(
                        name=f"_{name}_proj_w"))
    boot = layer.fc(layer.first_seq(bwd, name=f"{name}_bwd_first"),
                    size=enc_size, act=act.Tanh(), name=f"{name}_boot",
                    param_attr=ParamAttr(name=f"_{name}_boot_w"))
    return enc, proj, boot


def _decoder_step_factory(dec_size: int, trg_vocab: int, name: str = "dec",
                          boot=None):
    """Returns step(cur_emb, enc_seq, enc_proj) for recurrent_group /
    beam_search. Parameter names are FIXED via ParamAttr so training and
    generation graphs share weights."""

    def step(cur_emb, enc_seq, enc_proj):
        mem = layer.memory(name=f"{name}_state", size=dec_size,
                           boot_layer=boot)
        context = networks.simple_attention(
            encoded_sequence=enc_seq, encoded_proj=enc_proj,
            decoder_state=mem, name=f"{name}_attn",
            softmax_param_attr=ParamAttr(name=f"_{name}_attn_w"))
        # Only the input projection feeds gru_step: the recurrent (h,3h)
        # contribution is owned by GruStepLayer itself (reference decoder
        # passes just the input projection — gru_unit, networks.py:1298).
        inputs = layer.fc(layer.concat([context, cur_emb],
                                       name=f"{name}_in_concat"),
                          size=dec_size * 3, act=None, bias_attr=False,
                          name=f"{name}_in_proj",
                          param_attr=ParamAttr(name=f"_{name}_inproj_w"))
        nxt = layer.gru_step(inputs, output_mem=mem, size=dec_size,
                             name=f"{name}_state",
                             param_attr=ParamAttr(name=f"_{name}_gru_w"),
                             bias_attr=ParamAttr(name=f"_{name}_gru_b"))
        out = layer.fc(nxt, size=trg_vocab, act=act.Softmax(),
                       name=f"{name}_prob",
                       param_attr=ParamAttr(name=f"_{name}_out_w"),
                       bias_attr=ParamAttr(name=f"_{name}_out_b"))
        return out
    return step


def nmt_attention(src_vocab: int = 30000, trg_vocab: int = 30000,
                  emb_size: int = 512, enc_size: int = 512,
                  dec_size: int = 512) -> ModelSpec:
    """Training graph: teacher-forced decoder over the target sequence."""
    src = layer.data("source_words", integer_value_sequence(src_vocab))
    trg = layer.data("target_words", integer_value_sequence(trg_vocab))
    trg_next = layer.data("target_next_words",
                          integer_value_sequence(trg_vocab))
    enc, proj, boot = _encoder(src, src_vocab, emb_size, enc_size)

    trg_emb = layer.embedding(trg, size=emb_size, name="dec_emb",
                              param_attr=ParamAttr(name="_dec_emb_w"))
    step = _decoder_step_factory(dec_size, trg_vocab, boot=boot)

    def group_step(cur_emb, enc_seq, enc_proj):
        return step(cur_emb, enc_seq, enc_proj)

    probs = layer.recurrent_group(
        step=group_step,
        input=[trg_emb,
               layer.StaticInput(enc, is_seq=True),
               layer.StaticInput(proj, is_seq=True)],
        name="decoder_group")
    cost = layer.classification_cost(probs, trg_next, name="nmt_cost")
    err = layer.classification_error(probs, trg_next, name="nmt_error")
    return ModelSpec("nmt_attention", src, trg_next, probs, cost, err)


def nmt_generator(src_vocab: int = 30000, trg_vocab: int = 30000,
                  emb_size: int = 512, enc_size: int = 512,
                  dec_size: int = 512, bos_id: int = 0, eos_id: int = 1,
                  beam_size: int = 4, max_length: int = 50) -> LayerOutput:
    """Generation graph: beam search sharing the training parameters."""
    src = layer.data("source_words", integer_value_sequence(src_vocab))
    enc, proj, boot = _encoder(src, src_vocab, emb_size, enc_size)
    step = _decoder_step_factory(dec_size, trg_vocab, boot=boot)

    def gen_step(cur_ids, enc_seq, enc_proj):
        cur_emb = layer.embedding(cur_ids, size=emb_size, name="dec_emb_gen",
                                  param_attr=ParamAttr(name="_dec_emb_w"))
        return step(cur_emb, enc_seq, enc_proj)

    return layer.beam_search(
        step=gen_step,
        input=[layer.GeneratedInput(size=trg_vocab, embedding_name="_dec_emb_w",
                                    embedding_size=emb_size),
               layer.StaticInput(enc, is_seq=True),
               layer.StaticInput(proj, is_seq=True)],
        bos_id=bos_id, eos_id=eos_id, beam_size=beam_size,
        max_length=max_length, name="nmt_beam")
