"""Image classification model zoo.

Benchmark-parity builders (reference: benchmark/paddle/image/{alexnet,
googlenet,smallnet_mnist_cifar}.py, plus the VGG group helper in
trainer_config_helpers/networks.py:465) and the ResNet-50 north-star from
BASELINE.json (no ResNet existed in the reference tree — this is the added
flagship). All builders:

  - take an image `data` layer named "image" (flat channel-major
    [b, c*h*w], the paddle feed convention) and a `label` layer,
  - return a ModelSpec with cost/output/error nodes so one helper drives
    training, the bench harness, and the graft entry.

TPU-first notes: convs run NHWC through lax.conv (MXU); batch-norm is fused
by XLA; image tensors never round-trip to NCHW.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from paddle_tpu import activation as act
from paddle_tpu import layers as layer
from paddle_tpu import networks
from paddle_tpu import pooling
from paddle_tpu.core.data_type import dense_vector, integer_value
from paddle_tpu.core.registry import LayerOutput


@dataclasses.dataclass
class ModelSpec:
    """A built model: feed via .data/.label, train on .cost, eval .error.

    `output` is the inference head. It is usually inside the cost graph,
    but may be a side branch the training graph deliberately skips
    (transformer_lm's probs node — its cost trains on logits); build
    inference topologies from `output` itself (`Topology(spec.output)`,
    what trainer/inference.Inference does), or pass
    `extra_outputs=[spec.output]` when one topology must serve both."""
    name: str
    data: LayerOutput
    label: LayerOutput
    output: LayerOutput
    cost: LayerOutput
    error: Optional[LayerOutput] = None

    def __post_init__(self):
        # tag the cost node(s) with the declared inference head so
        # Topology(spec.cost) can WARN when the head is a side branch
        # the cost graph excludes (instead of relying on the builder
        # remembering this docstring)
        costs = self.cost if isinstance(self.cost, (list, tuple)) \
            else [self.cost]
        for c in costs:
            c.declared_output = self.output.name

    @property
    def extra_layers(self):
        return [self.error] if self.error is not None else []


def _image_inputs(height: int, width: int, channels: int, num_classes: int):
    img = layer.data("image", dense_vector(height * width * channels),
                     height=height, width=width)
    lbl = layer.data("label", integer_value(num_classes))
    return img, lbl


def _close(name, img, out, lbl) -> ModelSpec:
    cost = layer.classification_cost(out, lbl, name=f"{name}_cost")
    err = layer.classification_error(out, lbl, name=f"{name}_error")
    return ModelSpec(name=name, data=img, label=lbl, output=out, cost=cost,
                     error=err)


# ---------------------------------------------------------------------------


def mnist_mlp(num_classes: int = 10) -> ModelSpec:
    """784 -> 128 -> 64 -> softmax. v1_api_demo/mnist parity."""
    img = layer.data("image", dense_vector(784))
    lbl = layer.data("label", integer_value(num_classes))
    h1 = layer.fc(img, size=128, act=act.Relu(), name="mlp_h1")
    h2 = layer.fc(h1, size=64, act=act.Relu(), name="mlp_h2")
    out = layer.fc(h2, size=num_classes, act=act.Softmax(), name="mlp_out")
    return _close("mnist_mlp", img, out, lbl)


def smallnet(height: int = 32, width: int = 32, channels: int = 3,
             num_classes: int = 10) -> ModelSpec:
    """CIFAR-quick net (benchmark/paddle/image/smallnet_mnist_cifar.py)."""
    img, lbl = _image_inputs(height, width, channels, num_classes)
    t = layer.img_conv(img, filter_size=5, num_filters=32, num_channels=channels,
                       stride=1, padding=2, act=act.Relu(), name="sn_conv1")
    t = layer.img_pool(t, pool_size=3, stride=2, padding=1, name="sn_pool1")
    t = layer.img_conv(t, filter_size=5, num_filters=32, stride=1, padding=2,
                       act=act.Relu(), name="sn_conv2")
    t = layer.img_pool(t, pool_size=3, stride=2, padding=1,
                       pool_type=pooling.Avg(), name="sn_pool2")
    t = layer.img_conv(t, filter_size=3, num_filters=64, stride=1, padding=1,
                       act=act.Relu(), name="sn_conv3")
    t = layer.img_pool(t, pool_size=3, stride=2, padding=1,
                       pool_type=pooling.Avg(), name="sn_pool3")
    t = layer.fc(t, size=64, act=act.Relu(), name="sn_fc1")
    out = layer.fc(t, size=num_classes, act=act.Softmax(), name="sn_out")
    return _close("smallnet", img, out, lbl)


def alexnet(height: int = 227, width: int = 227, channels: int = 3,
            num_classes: int = 1000) -> ModelSpec:
    """AlexNet (benchmark/paddle/image/alexnet.py — the headline bench)."""
    img, lbl = _image_inputs(height, width, channels, num_classes)
    t = layer.img_conv(img, filter_size=11, num_filters=96,
                       num_channels=channels, stride=4, padding=1,
                       act=act.Relu(), name="an_conv1")
    t = layer.img_cmrnorm(t, size=5, scale=0.0001, power=0.75, name="an_norm1")
    t = layer.img_pool(t, pool_size=3, stride=2, name="an_pool1")
    t = layer.img_conv(t, filter_size=5, num_filters=256, stride=1, padding=2,
                       act=act.Relu(), name="an_conv2")
    t = layer.img_cmrnorm(t, size=5, scale=0.0001, power=0.75, name="an_norm2")
    t = layer.img_pool(t, pool_size=3, stride=2, name="an_pool2")
    t = layer.img_conv(t, filter_size=3, num_filters=384, stride=1, padding=1,
                       act=act.Relu(), name="an_conv3")
    t = layer.img_conv(t, filter_size=3, num_filters=384, stride=1, padding=1,
                       act=act.Relu(), name="an_conv4")
    t = layer.img_conv(t, filter_size=3, num_filters=256, stride=1, padding=1,
                       act=act.Relu(), name="an_conv5")
    t = layer.img_pool(t, pool_size=3, stride=2, name="an_pool5")
    t = layer.fc(t, size=4096, act=act.Relu(), name="an_fc6")
    t = layer.dropout(t, 0.5, name="an_drop6")
    t = layer.fc(t, size=4096, act=act.Relu(), name="an_fc7")
    t = layer.dropout(t, 0.5, name="an_drop7")
    out = layer.fc(t, size=num_classes, act=act.Softmax(), name="an_out")
    return _close("alexnet", img, out, lbl)


def vgg16(height: int = 224, width: int = 224, channels: int = 3,
          num_classes: int = 1000) -> ModelSpec:
    img, lbl = _image_inputs(height, width, channels, num_classes)
    out = networks.vgg_16_network(img, num_channels=channels,
                                  num_classes=num_classes)
    return _close("vgg16", img, out, lbl)


# ---------------------------------------------------------------------------
# GoogleNet (inception v1, benchmark/paddle/image/googlenet.py shapes)


def _inception(name, input, f1, f3r, f3, f5r, f5, proj):
    # the three 1x1 branches (direct, 3x3-reducer, 5x5-reducer) merge
    # into ONE wide 1x1 conv + channel slices: same math, but the block
    # input is read from HBM once instead of three times and the merged
    # matmul has 3x the N dim for the MXU (inception blocks are
    # bandwidth-bound at these channel counts)
    c1x1 = layer.img_conv(input, filter_size=1, num_filters=f1 + f3r + f5r,
                          act=act.Relu(), name=f"{name}_1x1s")
    c1 = layer.slice_projection(c1x1, 0, f1, channel_slice=True)
    c3r = layer.slice_projection(c1x1, f1, f1 + f3r, channel_slice=True)
    c5r = layer.slice_projection(c1x1, f1 + f3r, f1 + f3r + f5r,
                                 channel_slice=True)
    c3 = layer.img_conv(c3r, filter_size=3, num_filters=f3, padding=1,
                        act=act.Relu(), name=f"{name}_3x3")
    c5 = layer.img_conv(c5r, filter_size=5, num_filters=f5, padding=2,
                        act=act.Relu(), name=f"{name}_5x5")
    mp = layer.img_pool(input, pool_size=3, stride=1, padding=1,
                        name=f"{name}_maxpool")
    cp = layer.img_conv(mp, filter_size=1, num_filters=proj, act=act.Relu(),
                        name=f"{name}_proj")
    return layer.concat([c1, c3, c5, cp], name=f"{name}_concat")


def googlenet(height: int = 224, width: int = 224, channels: int = 3,
              num_classes: int = 1000) -> ModelSpec:
    img, lbl = _image_inputs(height, width, channels, num_classes)
    t = layer.img_conv(img, filter_size=7, num_filters=64,
                       num_channels=channels, stride=2, padding=3,
                       act=act.Relu(), name="gn_conv1")
    t = layer.img_pool(t, pool_size=3, stride=2, padding=1, name="gn_pool1")
    t = layer.img_conv(t, filter_size=1, num_filters=64, act=act.Relu(),
                       name="gn_conv2r")
    t = layer.img_conv(t, filter_size=3, num_filters=192, padding=1,
                       act=act.Relu(), name="gn_conv2")
    t = layer.img_pool(t, pool_size=3, stride=2, padding=1, name="gn_pool2")
    t = _inception("gn_i3a", t, 64, 96, 128, 16, 32, 32)
    t = _inception("gn_i3b", t, 128, 128, 192, 32, 96, 64)
    t = layer.img_pool(t, pool_size=3, stride=2, padding=1, name="gn_pool3")
    t = _inception("gn_i4a", t, 192, 96, 208, 16, 48, 64)
    t = _inception("gn_i4b", t, 160, 112, 224, 24, 64, 64)
    t = _inception("gn_i4c", t, 128, 128, 256, 24, 64, 64)
    t = _inception("gn_i4d", t, 112, 144, 288, 32, 64, 64)
    t = _inception("gn_i4e", t, 256, 160, 320, 32, 128, 128)
    t = layer.img_pool(t, pool_size=3, stride=2, padding=1, name="gn_pool4")
    t = _inception("gn_i5a", t, 256, 160, 320, 32, 128, 128)
    t = _inception("gn_i5b", t, 384, 192, 384, 48, 128, 128)
    # global average pool
    t = layer.global_img_pool(t, pool_type=pooling.Avg(), name="gn_gap")
    t = layer.dropout(t, 0.4, name="gn_drop")
    out = layer.fc(t, size=num_classes, act=act.Softmax(), name="gn_out")
    return _close("googlenet", img, out, lbl)


# ---------------------------------------------------------------------------
# ResNet (v1.5-style: stride-2 in the 3x3 of the bottleneck) — the
# BASELINE.json north-star model; no reference config exists, designed
# TPU-first (NHWC, BN+ReLU fused by XLA, large MXU matmuls).

_RESNET_BLOCKS = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def _conv_bn(name, x, k, nf, stride=1, padding=0, relu=True,
             num_channels=None):
    # deliberately the PLAIN two-layer composition: the fused
    # alternatives (layer.conv_bn with fuse_stats, ops/fused.py) all
    # measured SLOWER end-to-end — XLA already fuses conv+BN optimally;
    # see docs/perf.md "BN backward: the epilogue lever, measured and
    # rejected"
    c = layer.img_conv(x, filter_size=k, num_filters=nf, stride=stride,
                       padding=padding, bias_attr=False, act=None,
                       num_channels=num_channels, name=f"{name}_conv")
    return layer.batch_norm(c, act=act.Relu() if relu else None,
                            name=f"{name}_bn")


def _basic_block(name, x, nf, stride):
    t = _conv_bn(f"{name}_a", x, 3, nf, stride=stride, padding=1)
    t = _conv_bn(f"{name}_b", t, 3, nf, padding=1, relu=False)
    if stride != 1 or x.meta.channels != nf:
        x = _conv_bn(f"{name}_sc", x, 1, nf, stride=stride, relu=False)
    return layer.addto([t, x], act=act.Relu(), name=f"{name}_add")


def _bottleneck_block(name, x, nf, stride):
    t = _conv_bn(f"{name}_a", x, 1, nf)
    t = _conv_bn(f"{name}_b", t, 3, nf, stride=stride, padding=1)
    t = _conv_bn(f"{name}_c", t, 1, nf * 4, relu=False)
    if stride != 1 or x.meta.channels != nf * 4:
        x = _conv_bn(f"{name}_sc", x, 1, nf * 4, stride=stride, relu=False)
    return layer.addto([t, x], act=act.Relu(), name=f"{name}_add")


def resnet(depth: int = 50, height: int = 224, width: int = 224,
           channels: int = 3, num_classes: int = 1000,
           tpu_stem: bool = False) -> ModelSpec:
    kind, reps = _RESNET_BLOCKS[depth]
    block = _basic_block if kind == "basic" else _bottleneck_block
    img, lbl = _image_inputs(height, width, channels, num_classes)
    if tpu_stem:
        # space-to-depth stem (the MLPerf-era TPU trick): fold 2x2 blocks
        # into channels so the stem conv contracts over 12 channels at
        # 112x112 instead of 3 at 224x224 — same downsampling, a 10x10
        # effective receptive field covering the default 7x7, and an
        # implicit GEMM that tiles onto the MXU. A model VARIANT, not the
        # default (weights are not interchangeable with the 7x7 stem).
        t = layer.space_to_depth(img, factor=2, num_channels=channels)
        t = _conv_bn("rn_stem", t, 5, 64, stride=1, padding=2)
    else:
        t = _conv_bn("rn_stem", img, 7, 64, stride=2, padding=3,
                     num_channels=channels)
    # floor pooling (ceil_mode=False) keeps the canonical 56/28/14/7
    # feature-map chain — divisible by the TPU's 8-sublane tiling, where
    # caffe ceil's 57/29/15 chain pads every map by ~12%
    t = layer.img_pool(t, pool_size=3, stride=2, padding=1,
                       ceil_mode=False, name="rn_pool1")
    nf = 64
    for si, n in enumerate(reps):
        for bi in range(n):
            stride = 2 if (si > 0 and bi == 0) else 1
            t = block(f"rn_s{si}b{bi}", t, nf, stride)
        nf *= 2
    t = layer.global_img_pool(t, pool_type=pooling.Avg(), name="rn_gap")
    out = layer.fc(t, size=num_classes, act=act.Softmax(), name="rn_out")
    return _close(f"resnet{depth}", img, out, lbl)


def resnet50(**kw) -> ModelSpec:
    return resnet(50, **kw)
