"""Model zoo — benchmark-parity network builders (populated per
SURVEY.md §6: MNIST MLP, SmallNet/VGG/AlexNet/GoogleNet/ResNet CNNs,
stacked-LSTM text classification, seq2seq NMT, Wide&Deep CTR, CRF tagger)."""
