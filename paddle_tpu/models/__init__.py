"""Model zoo — benchmark-parity network builders (SURVEY.md §6,
BASELINE.json configs): image CNNs (SmallNet/AlexNet/GoogleNet/VGG/ResNet),
IMDB stacked-LSTM, attention seq2seq NMT, Wide&Deep CTR, CRF taggers.

Every builder returns a ModelSpec (cost/output/error LayerOutputs) so the
trainer, the bench harness, and __graft_entry__ drive them uniformly.
"""

from paddle_tpu.models.image import (ModelSpec, mnist_mlp, smallnet, alexnet,
                                     vgg16, googlenet, resnet, resnet50)
from paddle_tpu.models.text import (stacked_lstm_net, bidi_lstm_net,
                                    convolution_net, ngram_lm)
from paddle_tpu.models.seq2seq import nmt_attention, nmt_generator
from paddle_tpu.models.recommender import wide_and_deep, movielens_regression
from paddle_tpu.models.tagger import crf_tagger, rnn_crf_tagger

__all__ = [
    "ModelSpec", "mnist_mlp", "smallnet", "alexnet", "vgg16", "googlenet",
    "resnet", "resnet50", "stacked_lstm_net", "bidi_lstm_net",
    "convolution_net", "ngram_lm", "nmt_attention", "nmt_generator",
    "wide_and_deep", "movielens_regression", "crf_tagger", "rnn_crf_tagger",
    "transformer_lm", "transformer_encoder", "transformer_classifier",
    "TransformerDecoder", "PagedDecoder",
]
from paddle_tpu.models.transformer import (transformer_lm,  # noqa: F401
                                           transformer_classifier,
                                           transformer_encoder)
from paddle_tpu.models.decode import (PagedDecoder,  # noqa: F401
                                      TransformerDecoder)
