"""Decoder-only transformer language model — the long-context flagship.

Not a 2017-reference model (the reference predates transformers); this is
the "don't stop at parity" entry that exercises the framework's TPU-era
spine end to end: flash attention (Pallas fwd+bwd kernels,
ops/pallas_attention.py) through the `dot_product_attention` layer,
ring attention when the mesh has an `sp` axis, layer_norm, and the
mixed-precision policy. Pre-norm GPT-style blocks:

    x = x + MHA(LN(x));  x = x + FFN(LN(x))

with learned token + position embeddings and a weight-tied-free softmax
head, trained on next-token cross entropy over the sequence.
"""

from __future__ import annotations

from paddle_tpu import activation as act
from paddle_tpu import layers as layer
from paddle_tpu import pooling
from paddle_tpu.core.data_type import (dense_vector_sequence, integer_value,
                                       integer_value_sequence)
from paddle_tpu.models.image import ModelSpec


def transformer_lm(vocab_size: int = 32000, d_model: int = 512,
                   n_heads: int = 8, n_layers: int = 6,
                   d_ff: int = 2048, max_len: int = 2048,
                   moe_experts: int = 0, moe_k: int = 2,
                   moe_aux_coeff: float = 0.01,
                   moe_capacity_factor: float = 1.25,
                   dropout: float = 0.0, label_smoothing: float = 0.0,
                   tie_embeddings: bool = False, n_kv_heads=None,
                   name: str = "tfm") -> ModelSpec:
    """tokens + positions -> N pre-norm blocks -> next-token CE.

    Feed contract: (token_ids, position_ids, next_token_ids) — three
    integer sequences of equal length (positions are just 0..T-1; a data
    input keeps the graph free of iota-on-ragged-length corner cases).

    dropout > 0 adds residual-branch dropout after the attention
    projection and the FFN (train mode only; the KV-cache decoder and
    test mode see the deterministic graph).

    moe_experts > 0 swaps every block's dense FFN for a top-`moe_k`
    capacity-routed mixture of `moe_experts` experts (layers.moe); the
    router load-balance losses join the CE as extra cost nodes
    (spec.cost becomes a list — SGD takes it as-is), and the expert
    tables shard over the mesh's `ep` axis when one exists.

    n_kv_heads < n_heads is grouped-query attention (MQA at 1): the
    k/v projections emit n_kv_heads heads, each shared by
    n_heads/n_kv_heads query heads — the decoder then stores and reads
    kv-sized caches (measured 1.96x decode throughput at batch 32 with
    n_kv_heads=2; docs/perf.md). tie_embeddings shares the token table
    as the transposed head weight.
    """
    toks = layer.data(f"{name}_tokens", integer_value_sequence(vocab_size))
    pos = layer.data(f"{name}_positions", integer_value_sequence(max_len))
    nxt = layer.data(f"{name}_labels", integer_value_sequence(vocab_size))

    x = layer.addto([
        layer.embedding(toks, size=d_model, name=f"{name}_tok_emb"),
        layer.embedding(pos, size=d_model, name=f"{name}_pos_emb"),
    ], name=f"{name}_emb")
    aux_costs = []

    kv_h = n_kv_heads or n_heads
    kv_dim = (d_model // n_heads) * kv_h
    for i in range(n_layers):
        ln1 = layer.layer_norm(x, name=f"{name}_l{i}_ln1")
        q = layer.fc(ln1, size=d_model, bias_attr=False,
                     name=f"{name}_l{i}_q")
        k = layer.fc(ln1, size=kv_dim, bias_attr=False,
                     name=f"{name}_l{i}_k")
        v = layer.fc(ln1, size=kv_dim, bias_attr=False,
                     name=f"{name}_l{i}_v")
        attn = layer.dot_product_attention(q, k, v, num_heads=n_heads,
                                           num_kv_heads=n_kv_heads,
                                           causal=True,
                                           name=f"{name}_l{i}_attn")
        proj = layer.fc(attn, size=d_model, bias_attr=False,
                        name=f"{name}_l{i}_proj")
        if dropout > 0:
            proj = layer.dropout(proj, dropout, name=f"{name}_l{i}_drop1")
        x = layer.addto([x, proj], name=f"{name}_l{i}_res1")

        ln2 = layer.layer_norm(x, name=f"{name}_l{i}_ln2")
        if moe_experts > 0:
            ffn = layer.moe(ln2, expert_num=moe_experts,
                            expert_hidden=d_ff, k=moe_k,
                            capacity_factor=moe_capacity_factor,
                            name=f"{name}_l{i}_moe")
            aux_costs.append(layer.moe_aux_cost(
                ln2, ffn, coeff=moe_aux_coeff, name=f"{name}_l{i}_aux"))
        else:
            up = layer.fc(ln2, size=d_ff, act=act.Relu(),
                          name=f"{name}_l{i}_up")
            ffn = layer.fc(up, size=d_model, bias_attr=False,
                           name=f"{name}_l{i}_down")
        if dropout > 0:
            ffn = layer.dropout(ffn, dropout, name=f"{name}_l{i}_drop2")
        x = layer.addto([x, ffn], name=f"{name}_l{i}_res2")

    xf = layer.layer_norm(x, name=f"{name}_lnf")
    # the head emits LOGITS and the CE runs from_logits (logsumexp +
    # gather — no vocab-sized softmax tensor materializes in the training
    # forward); the softmax probs are a separate paramless SIDE branch:
    # Topology(spec.cost) does not contain it by design — build inference
    # topologies from spec.output (see ModelSpec docstring)
    # no bias on the vocab projection (the modern LM convention): a
    # 32k-wide bias adds nothing measurable to the fit but costs a
    # vocab-sized gradient reduction + optimizer slots every step.
    # tie_embeddings shares the token embedding table as the head
    # weight (applied transposed — fc(tied_transpose=True)): halves
    # the vocab-sized parameters and their optimizer state/update.
    from paddle_tpu.core.registry import ParamAttr
    head_attr = ParamAttr(name=f"_{name}_tok_emb.w0") \
        if tie_embeddings else None
    logits = layer.fc(xf, size=vocab_size, act=None, bias_attr=False,
                      param_attr=head_attr,
                      tied_transpose=tie_embeddings,
                      name=f"{name}_head")
    probs = layer.addto([logits], act=act.Softmax(), name=f"{name}_probs")
    cost = layer.cross_entropy_cost(logits, nxt, from_logits=True,
                                    label_smoothing=label_smoothing,
                                    name=f"{name}_cost")
    spec = ModelSpec(name="transformer_lm", data=toks, label=nxt,
                     output=probs,
                     cost=[cost] + aux_costs if aux_costs else cost)
    spec.positions = pos
    return spec


def _encoder_trunk(toks, pos, *, name, d_model, n_heads, n_layers, d_ff,
                   dropout):
    """Embeddings + N bidirectional pre-norm blocks + final layer norm —
    shared by the MLM encoder and the sequence classifier."""
    x = layer.addto([
        layer.embedding(toks, size=d_model, name=f"{name}_tok_emb"),
        layer.embedding(pos, size=d_model, name=f"{name}_pos_emb"),
    ], name=f"{name}_emb")
    for i in range(n_layers):
        ln1 = layer.layer_norm(x, name=f"{name}_l{i}_ln1")
        q = layer.fc(ln1, size=d_model, bias_attr=False,
                     name=f"{name}_l{i}_q")
        k = layer.fc(ln1, size=d_model, bias_attr=False,
                     name=f"{name}_l{i}_k")
        v = layer.fc(ln1, size=d_model, bias_attr=False,
                     name=f"{name}_l{i}_v")
        attn = layer.dot_product_attention(q, k, v, num_heads=n_heads,
                                           causal=False,
                                           name=f"{name}_l{i}_attn")
        proj = layer.fc(attn, size=d_model, bias_attr=False,
                        name=f"{name}_l{i}_proj")
        if dropout > 0:
            proj = layer.dropout(proj, dropout, name=f"{name}_l{i}_drop1")
        x = layer.addto([x, proj], name=f"{name}_l{i}_res1")

        ln2 = layer.layer_norm(x, name=f"{name}_l{i}_ln2")
        up = layer.fc(ln2, size=d_ff, act=act.Relu(),
                      name=f"{name}_l{i}_up")
        ffn = layer.fc(up, size=d_model, bias_attr=False,
                       name=f"{name}_l{i}_down")
        if dropout > 0:
            ffn = layer.dropout(ffn, dropout, name=f"{name}_l{i}_drop2")
        x = layer.addto([x, ffn], name=f"{name}_l{i}_res2")
    return layer.layer_norm(x, name=f"{name}_lnf")


def transformer_classifier(vocab_size: int = 32000, num_classes: int = 2,
                           d_model: int = 512, n_heads: int = 8,
                           n_layers: int = 6, d_ff: int = 2048,
                           max_len: int = 512, dropout: float = 0.0,
                           name: str = "enc") -> ModelSpec:
    """Sequence classification over the bidirectional trunk (the
    BERT-family fine-tune head): mean-pool the final hidden states over
    valid positions, project to `num_classes`. The default name matches
    `transformer_encoder`'s, so the trunk's parameter names are
    identical and MLM-pretrained Parameters load directly (the head
    params are fresh); param loading matches BY NAME, so keep the two
    specs' `name` equal when fine-tuning."""
    toks = layer.data(f"{name}_tokens", integer_value_sequence(vocab_size))
    pos = layer.data(f"{name}_positions", integer_value_sequence(max_len))
    lbl = layer.data(f"{name}_label", integer_value(num_classes))
    xf = _encoder_trunk(toks, pos, name=name, d_model=d_model,
                        n_heads=n_heads, n_layers=n_layers, d_ff=d_ff,
                        dropout=dropout)
    pooled = layer.pooling(xf, pooling_type=pooling.Avg(),
                           name=f"{name}_pool")
    out = layer.fc(pooled, size=num_classes, act=act.Softmax(),
                   name=f"{name}_out")
    cost = layer.classification_cost(out, lbl, name=f"{name}_cost")
    err = layer.classification_error(out, lbl, name=f"{name}_error")
    spec = ModelSpec(name="transformer_classifier", data=toks, label=lbl,
                     output=out, cost=cost, error=err)
    spec.positions = pos
    return spec


def transformer_encoder(vocab_size: int = 32000, d_model: int = 512,
                        n_heads: int = 8, n_layers: int = 6,
                        d_ff: int = 2048, max_len: int = 512,
                        dropout: float = 0.0,
                        name: str = "enc") -> ModelSpec:
    """Bidirectional encoder trained on the masked-LM objective (the
    BERT-family pretraining recipe) — same pre-norm blocks as
    `transformer_lm` but with causal=False attention, so every token
    attends to the whole (unpadded) sequence.

    Feed contract: (masked_ids, position_ids, label_ids, mlm_weight) —
    three integer sequences plus a FLOAT sequence that is 1.0 exactly
    on the masked positions. The cost is cross entropy over the vocab
    logits weighted PER TOKEN by mlm_weight: unmasked positions
    contribute nothing, the standard MLM objective. The builder does
    not pick the mask — the data pipeline does (mask ~15% of tokens,
    feed the corrupted ids + original labels + the 0/1 weight), which
    keeps the graph static and the masking policy user-owned.

    spec.output is the probs side branch (same contract as the LM:
    build inference topologies from it, Topology(spec.cost) warns).
    """
    toks = layer.data(f"{name}_tokens", integer_value_sequence(vocab_size))
    pos = layer.data(f"{name}_positions", integer_value_sequence(max_len))
    lbls = layer.data(f"{name}_labels", integer_value_sequence(vocab_size))
    mlm_w = layer.data(f"{name}_mlm_weight", dense_vector_sequence(1))

    xf = _encoder_trunk(toks, pos, name=name, d_model=d_model,
                        n_heads=n_heads, n_layers=n_layers, d_ff=d_ff,
                        dropout=dropout)
    logits = layer.fc(xf, size=vocab_size, act=None, bias_attr=False,
                      name=f"{name}_head")
    probs = layer.addto([logits], act=act.Softmax(), name=f"{name}_probs")
    cost = layer.cross_entropy_cost(logits, lbls, weight=mlm_w,
                                    from_logits=True,
                                    name=f"{name}_cost")
    spec = ModelSpec(name="transformer_encoder", data=toks, label=lbls,
                     output=probs, cost=cost)
    spec.positions = pos
    spec.mlm_weight = mlm_w
    return spec
