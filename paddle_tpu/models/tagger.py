"""CRF sequence tagging — v1_api_demo/sequence_tagging parity
(BASELINE.json config #5; reference layers: CRFLayer/CRFDecoding,
linear_chain_crf over a context-window + fc emission stack).

TPU-first: the linear-chain forward algorithm is a lax.scan over time with
batched [b, L, L] logsumexp transitions (layers/crf_layers.py); decoding is
a Viterbi scan, all inside jit.
"""

from __future__ import annotations

from paddle_tpu import activation as act
from paddle_tpu import layers as layer
from paddle_tpu.core.data_type import integer_value_sequence
from paddle_tpu.core.registry import ParamAttr
from paddle_tpu.models.image import ModelSpec


def crf_tagger(vocab_size: int = 20000, num_labels: int = 45,
               emb_size: int = 128, hidden_size: int = 256,
               context_len: int = 5) -> ModelSpec:
    words = layer.data("words", integer_value_sequence(vocab_size))
    labels = layer.data("labels", integer_value_sequence(num_labels))
    emb = layer.embedding(words, size=emb_size, name="crf_emb")
    ctx = layer.context_projection(emb, context_len=context_len,
                                   name="crf_ctx")
    hidden = layer.fc(ctx, size=hidden_size, act=act.Tanh(), name="crf_h")
    emission = layer.fc(hidden, size=num_labels, act=None,
                        name="crf_emission")
    # decode shares the SAME transition parameter as the training CRF
    # (reference: CRFDecodingLayer reuses the CRFLayer weight by name)
    crf_w = ParamAttr(name="_crf_trans_w")
    cost = layer.crf(emission, labels, size=num_labels, name="crf_cost",
                     param_attr=crf_w)
    decoded = layer.crf_decoding(emission, size=num_labels,
                                 name="crf_decode", param_attr=crf_w)
    spec = ModelSpec("crf_tagger", words, labels, emission, cost, None)
    spec.decoded = decoded
    return spec


def rnn_crf_tagger(vocab_size: int = 20000, num_labels: int = 45,
                   emb_size: int = 128, hidden_size: int = 128) -> ModelSpec:
    """Bidirectional-GRU emissions under a CRF (sequence_tagging rnn_crf)."""
    from paddle_tpu import networks
    words = layer.data("words", integer_value_sequence(vocab_size))
    labels = layer.data("labels", integer_value_sequence(num_labels))
    emb = layer.embedding(words, size=emb_size, name="rcrf_emb")
    fwd = networks.simple_gru(emb, size=hidden_size, name="rcrf_fw")
    bwd = networks.simple_gru(emb, size=hidden_size, name="rcrf_bw",
                              reverse=True)
    merged = layer.concat([fwd, bwd], name="rcrf_concat")
    emission = layer.fc(merged, size=num_labels, act=None,
                        name="rcrf_emission")
    crf_w = ParamAttr(name="_rcrf_trans_w")
    cost = layer.crf(emission, labels, size=num_labels, name="rcrf_cost",
                     param_attr=crf_w)
    decoded = layer.crf_decoding(emission, size=num_labels,
                                 name="rcrf_decode", param_attr=crf_w)
    spec = ModelSpec("rnn_crf_tagger", words, labels, emission, cost, None)
    spec.decoded = decoded
    return spec
