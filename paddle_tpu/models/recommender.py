"""Wide&Deep CTR model — the large-sparse-embedding config (BASELINE.json
config #4).

Reference capability: the sparse remote-update path (embedding rows on
pservers, trainers prefetch touched rows — MultiGradientMachine.h:99-166,
SparseRemoteParameterUpdater, doc/design/cluster_train/
large_model_dist_train.md). TPU-native: tables are dense-at-rest arrays
whose ROWS are sharded over the mesh's `mp` axis via pjit sharding rules
(paddle_tpu/parallel/tensor_parallel.py marks `*emb*` params row-sharded);
XLA turns the gathers into all-to-all-style collective lookups — no pserver.
"""

from __future__ import annotations

from typing import Sequence

from paddle_tpu import activation as act
from paddle_tpu import layers as layer
from paddle_tpu.core.data_type import (dense_vector, integer_value,
                                       integer_value_sequence)
from paddle_tpu.core.registry import ParamAttr
from paddle_tpu.models.image import ModelSpec


def wide_and_deep(sparse_dims: Sequence[int] = (100000, 100000, 10000),
                  dense_dim: int = 13, emb_size: int = 64,
                  hidden_sizes: Sequence[int] = (256, 128, 64)) -> ModelSpec:
    """Wide (linear over sparse ids) + Deep (embeddings -> MLP) CTR net."""
    dense = layer.data("dense_features", dense_vector(dense_dim))
    sparse_inputs = [layer.data(f"sparse_{i}", integer_value(dim))
                     for i, dim in enumerate(sparse_dims)]
    lbl = layer.data("label", integer_value(2))

    # deep: one embedding table per sparse slot (row-shardable over mp)
    embs = [layer.embedding(s, size=emb_size, name=f"wd_emb{i}",
                            param_attr=ParamAttr(name=f"_wd_emb{i}_w",
                                                 sparse=True))
            for i, s in enumerate(sparse_inputs)]
    deep = layer.concat(embs + [dense], name="wd_deep_concat")
    for j, h in enumerate(hidden_sizes):
        deep = layer.fc(deep, size=h, act=act.Relu(), name=f"wd_deep_fc{j}")

    # wide: direct 1-dim "linear" embeddings of the ids + dense passthrough
    wides = [layer.embedding(s, size=1, name=f"wd_wide{i}",
                             param_attr=ParamAttr(name=f"_wd_wide{i}_w",
                                                  sparse=True))
             for i, s in enumerate(sparse_inputs)]
    wide = layer.concat(wides + [dense], name="wd_wide_concat")

    merged = layer.concat([wide, deep], name="wd_merge")
    out = layer.fc(merged, size=2, act=act.Softmax(), name="wd_out")
    cost = layer.classification_cost(out, lbl, name="wd_cost")
    err = layer.classification_error(out, lbl, name="wd_error")
    spec = ModelSpec("wide_and_deep", dense, lbl, out, cost, err)
    spec.sparse_inputs = sparse_inputs
    return spec


def movielens_regression(user_dim: int = 6040, movie_dim: int = 3952,
                         emb_size: int = 64) -> ModelSpec:
    """MovieLens rating regression (demo/recommendation parity): user and
    movie towers -> cos_sim scaled to [0,5]."""
    uid = layer.data("user_id", integer_value(user_dim))
    mid = layer.data("movie_id", integer_value(movie_dim))
    score = layer.data("score", dense_vector(1))
    uvec = layer.fc(layer.embedding(uid, size=emb_size, name="ml_uemb"),
                    size=emb_size, act=act.Relu(), name="ml_ufc")
    mvec = layer.fc(layer.embedding(mid, size=emb_size, name="ml_memb"),
                    size=emb_size, act=act.Relu(), name="ml_mfc")
    sim = layer.cos_sim(uvec, mvec, scale=5.0, name="ml_sim")
    cost = layer.square_error_cost(sim, score, name="ml_cost")
    return ModelSpec("movielens_regression", uid, score, sim, cost, None)
