"""paddle.v2.pooling-compatible pooling descriptors.

Reference: python/paddle/trainer_config_helpers/poolings.py (MaxPooling,
AvgPooling, SumPooling, SqrtAvgPooling for sequence pooling; Max/Avg for
image pooling).
"""

from __future__ import annotations


class BasePoolingType:
    name = "average"


class Max(BasePoolingType):
    name = "max"

    def __init__(self, output_max_index: bool = False):
        self.output_max_index = output_max_index


class Avg(BasePoolingType):
    name = "average"

    def __init__(self, strategy: str = "average"):
        self.strategy = strategy


class Sum(BasePoolingType):
    name = "sum"


class SqrtAvg(BasePoolingType):
    name = "sqrt"


class First(BasePoolingType):
    name = "first"


class Last(BasePoolingType):
    name = "last"


MaxPooling = Max
AvgPooling = Avg
SumPooling = Sum
SqrtAvgPooling = SqrtAvg


def to_name(p) -> str:
    if p is None:
        return "average"
    if isinstance(p, str):
        return p
    if isinstance(p, type) and issubclass(p, BasePoolingType):
        return p.name
    if isinstance(p, BasePoolingType):
        return p.name
    raise TypeError(f"bad pooling type: {p!r}")
