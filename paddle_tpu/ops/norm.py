"""Normalization ops.

Reference: paddle/gserver/layers/BatchNormalizationLayer (+Cudnn twin,
BatchNormBaseLayer keeps moving mean/var as MOVING_AVERAGE parameters),
CrossMapNormalLayer (LRN, paddle/function/CrossMapNormalOp), DataNormLayer,
CrossChannelNormLayer, L2 row norm (NormLayer 'l2' type).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax


def batch_norm_train(x: jnp.ndarray, gamma, beta, moving_mean, moving_var,
                     *, momentum: float = 0.9, eps: float = 1e-5,
                     axes: Optional[Tuple[int, ...]] = None):
    """Training-mode batch norm over all axes but the last (feature) axis.

    Returns (y, new_moving_mean, new_moving_var). Moving stats update matches
    the reference's movingAvgFraction semantics
    (BatchNormBaseLayer: moving = moving*m + batch*(1-m)).
    """
    if axes is None:
        axes = tuple(range(x.ndim - 1))
    # statistics in f32 (the reduction is cheap); the big elementwise map
    # stays in x.dtype by folding (gamma, beta, mean, var) into ONE
    # per-channel scale/shift pair cast down first — otherwise f32 params
    # promote the whole [b,h,w,c] activation to f32, doubling HBM traffic
    # (dominant cost of BN on TPU; seen as 30% loop-fusion time in traces)
    xf = x.astype(jnp.float32)
    # E[x^2]-E[x]^2 instead of jnp.var: both reductions happen in ONE
    # pass over the activation (XLA fuses them), where var's
    # subtract-then-square needs a second full HBM read after the mean
    mean = jnp.mean(xf, axis=axes)
    var = jnp.maximum(jnp.mean(xf * xf, axis=axes) - mean * mean, 0.0)
    inv = lax.rsqrt(var + eps) * gamma
    scale = inv.astype(x.dtype)
    shift = (beta - mean * inv).astype(x.dtype)
    y = x * scale + shift
    new_mean = moving_mean * momentum + mean * (1.0 - momentum)
    new_var = moving_var * momentum + var * (1.0 - momentum)
    return y, new_mean, new_var


def batch_norm_infer(x: jnp.ndarray, gamma, beta, moving_mean, moving_var,
                     *, eps: float = 1e-5):
    inv = lax.rsqrt(moving_var + eps) * gamma
    scale = inv.astype(x.dtype)
    shift = (beta - moving_mean * inv).astype(x.dtype)
    return x * scale + shift


def lrn_cross_map(x: jnp.ndarray, size: int = 5, scale: float = 1e-4,
                  power: float = 0.75) -> jnp.ndarray:
    """Local response norm across channels, x: [N,H,W,C].

    Reference CrossMapNormalOp: denom = 1 + scale/size * sum_{window} x^2;
    y = x * denom^-power (config_parser img_norm defaults scale=0.0128/size).
    """
    sq = jnp.square(x)
    half = size // 2
    c = x.shape[-1]
    # channel-window sum as a banded [C, C] matmul: the padded-shifted-
    # slices formulation re-reads the squared activation `size` times
    # from HBM (measured ~3.4 ms/step on AlexNet's [512,55,55,96] stage,
    # round-5 trace); the MXU band-matmul reads it once and the window
    # addition is free FLOPs
    ch = jnp.arange(c)
    band = ((ch[:, None] >= ch[None, :] - half) &
            (ch[:, None] <= ch[None, :] + size - 1 - half)).astype(x.dtype)
    window = jnp.dot(sq, band, preferred_element_type=jnp.float32) \
        .astype(x.dtype)
    base = 1.0 + (scale / size) * window
    # base^-power via hardware rsqrt/sqrt for the universal exponents:
    # generic pow lowers to a log2+exp2 transcendental pair per element,
    # which on the [N,55,55,96] AlexNet stage was ~17% of the whole
    # train step (round-5 trace); -0.75 = rsqrt * sqrt(rsqrt) and -0.5 =
    # rsqrt are exact identities, not approximations
    if power == 0.75:
        r = lax.rsqrt(base)
        return x * (r * jnp.sqrt(r))
    if power == 0.5:
        return x * lax.rsqrt(base)
    return x / base ** power


def cross_channel_l2_norm(x: jnp.ndarray, scale, eps: float = 1e-10) -> jnp.ndarray:
    """CrossChannelNormLayer (SSD): L2-normalize each pixel across channels,
    multiply per-channel learned scale. x: [N,H,W,C], scale: [C]."""
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True) + eps)
    return x / norm * scale


def l2_normalize(x: jnp.ndarray, axis: int = -1, eps: float = 1e-12) -> jnp.ndarray:
    return x * lax.rsqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)


def layer_norm(x: jnp.ndarray, gamma, beta, eps: float = 1e-5) -> jnp.ndarray:
    """Modern extra (not in the 2017 reference) used by the transformer zoo."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * lax.rsqrt(var + eps) * gamma + beta
