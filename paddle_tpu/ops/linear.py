"""Dense linear algebra ops with TPU dtype policy.

Replaces the GEMM paths of paddle/math (Matrix::mul over cuBLAS,
hl_matrix_mul) and paddle/function/MulOp. On TPU all matmuls go through one
helper that casts to the configured compute dtype (bfloat16 keeps the MXU
fed) while accumulating/returning float32.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.config import global_config


def compute_dtype():
    return jnp.dtype(global_config().compute_dtype)


def matmul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """MXU-friendly matmul with f32 accumulation.

    compute_dtype float32 -> full-precision MXU passes (precision=highest;
    TPUs otherwise default to bf16 passes even for f32 inputs);
    compute_dtype bfloat16 -> cast inputs, single fast MXU pass.
    """
    cd = compute_dtype()
    if cd != jnp.float32:
        # mixed precision: activations stay in the compute dtype — f32
        # master weights must NOT promote the output (a bf16 x @ f32 w
        # promoting to f32 silently ran every elementwise chain after
        # every fc in f32, doubling HBM traffic; see docs/perf.md)
        out_dtype = cd
        a = a.astype(cd)
        b = b.astype(cd)
        prec = None
    else:
        out_dtype = jnp.promote_types(a.dtype, b.dtype)
        prec = jax.lax.Precision.HIGHEST
    return jnp.matmul(a, b, precision=prec,
                      preferred_element_type=jnp.float32).astype(out_dtype)


def fc(x: jnp.ndarray, w: jnp.ndarray, b: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """x: [..., in], w: [in, out], b: [out]."""
    y = matmul(x, w)
    if b is not None:
        y = y + b.astype(y.dtype)   # f32 master bias must not promote y
    return y


def dot(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(a * b, axis=-1)


def outer(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Row-wise outer product [b, m], [b, n] -> [b, m*n] (OuterProdLayer)."""
    o = a[..., :, None] * b[..., None, :]
    return o.reshape(o.shape[:-2] + (o.shape[-2] * o.shape[-1],))


def cos_sim(a: jnp.ndarray, b: jnp.ndarray, scale: float = 1.0,
            eps: float = 1e-8) -> jnp.ndarray:
    """Row-wise cosine similarity (paddle/function/CosSimOp, CosSimLayer)."""
    num = jnp.sum(a * b, axis=-1)
    den = jnp.sqrt(jnp.sum(a * a, axis=-1) * jnp.sum(b * b, axis=-1))
    return scale * num / jnp.maximum(den, eps)


def interpolation(w: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """w*a + (1-w)*b with per-row scalar w [batch, 1] (InterpolationLayer)."""
    return w * a + (1.0 - w) * b


def slope_intercept(x: jnp.ndarray, slope: float, intercept: float) -> jnp.ndarray:
    return slope * x + intercept


def sum_to_one_norm(x: jnp.ndarray, eps: float = 1e-8) -> jnp.ndarray:
    """Row-normalize to sum 1 (SumToOneNormLayer)."""
    return x / jnp.maximum(jnp.sum(x, axis=-1, keepdims=True), eps)
