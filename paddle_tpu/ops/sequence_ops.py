"""Sequence ops over padded+masked SequenceBatch.

Reference: gserver/layers/{SequencePoolLayer (max/avg/sum pooling over
sequences), SequenceLastInstanceLayer, SequenceConcatLayer,
SequenceReshapeLayer, SequenceSliceLayer, ExpandLayer, SubSequenceLayer,
ContextProjection (paddle/function/ContextProjectionOp)}. All of these
consumed the ragged Argument layout; here each is a masked dense op.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch

_NEG = -1e30


def seq_pool(seq: SequenceBatch, pool_type: str = "average") -> jnp.ndarray:
    """Pool over time -> [batch, d]. pool_type: average|sum|max|sqrt|last|first."""
    x = seq.data
    m = seq.mask(x.dtype)
    while m.ndim < x.ndim:
        m = m[..., None]
    if pool_type in ("average", "avg"):
        s = jnp.sum(x * m, axis=1)
        return s / jnp.maximum(jnp.sum(m, axis=1), 1.0)
    if pool_type == "sum":
        return jnp.sum(x * m, axis=1)
    if pool_type == "sqrt":
        s = jnp.sum(x * m, axis=1)
        return s / jnp.sqrt(jnp.maximum(jnp.sum(m, axis=1), 1.0))
    if pool_type == "max":
        return jnp.max(jnp.where(m > 0, x, _NEG), axis=1)
    if pool_type == "last":
        return last_instance(seq)
    if pool_type == "first":
        return first_instance(seq)
    raise ValueError(f"unknown pool_type {pool_type!r}")


def last_instance(seq: SequenceBatch) -> jnp.ndarray:
    """SequenceLastInstanceLayer: x[i, len_i - 1]."""
    idx = jnp.maximum(seq.lengths - 1, 0)
    return jnp.take_along_axis(
        seq.data, idx.reshape((-1,) + (1,) * (seq.data.ndim - 1)), axis=1)[:, 0]


def first_instance(seq: SequenceBatch) -> jnp.ndarray:
    return seq.data[:, 0]


def expand_to_sequence(x: jnp.ndarray, like: SequenceBatch) -> SequenceBatch:
    """ExpandLayer: broadcast per-sample [b, d] to every timestep of `like`."""
    data = jnp.broadcast_to(x[:, None], (x.shape[0], like.max_len) + x.shape[1:])
    return like.with_data(data)


def seq_concat(a: SequenceBatch, b: SequenceBatch) -> SequenceBatch:
    """SequenceConcatLayer: concatenate along time per sample (a_i ++ b_i).

    Static-shape implementation: allocate max_a+max_b and scatter b after
    a's valid prefix via a gather index computation.
    """
    la, lb = a.lengths, b.lengths
    total = a.max_len + b.max_len
    t = jnp.arange(total, dtype=jnp.int32)[None, :]        # [1, T]
    in_a = t < la[:, None]
    idx_a = jnp.clip(t, 0, a.max_len - 1)
    idx_b = jnp.clip(t - la[:, None], 0, b.max_len - 1)
    ga = jnp.take_along_axis(
        a.data, idx_a.reshape(idx_a.shape + (1,) * (a.data.ndim - 2)), axis=1) \
        if a.data.ndim > 2 else jnp.take_along_axis(a.data, idx_a, axis=1)
    gb = jnp.take_along_axis(
        b.data, idx_b.reshape(idx_b.shape + (1,) * (b.data.ndim - 2)), axis=1) \
        if b.data.ndim > 2 else jnp.take_along_axis(b.data, idx_b, axis=1)
    cond = in_a.reshape(in_a.shape + (1,) * (a.data.ndim - 2))
    return SequenceBatch(jnp.where(cond, ga, gb), la + lb)


def seq_slice(seq: SequenceBatch, starts: jnp.ndarray,
              ends: jnp.ndarray) -> SequenceBatch:
    """SequenceSliceLayer: per-sample [start, end) window, re-packed at t=0."""
    t = jnp.arange(seq.max_len, dtype=jnp.int32)[None, :]
    src = jnp.clip(t + starts[:, None], 0, seq.max_len - 1)
    gathered = jnp.take_along_axis(
        seq.data, src.reshape(src.shape + (1,) * (seq.data.ndim - 2)), axis=1) \
        if seq.data.ndim > 2 else jnp.take_along_axis(seq.data, src, axis=1)
    new_len = jnp.clip(jnp.minimum(ends, seq.lengths) - starts, 0, seq.max_len)
    return SequenceBatch(gathered, new_len.astype(jnp.int32))


def seq_reverse(seq: SequenceBatch) -> SequenceBatch:
    """Reverse each sequence within its valid length (for reverse RNNs —
    the reference's GatedRecurrentLayer(reversed=True))."""
    t = jnp.arange(seq.max_len, dtype=jnp.int32)[None, :]
    src = jnp.clip(seq.lengths[:, None] - 1 - t, 0, seq.max_len - 1)
    data = jnp.take_along_axis(
        seq.data, src.reshape(src.shape + (1,) * (seq.data.ndim - 2)), axis=1) \
        if seq.data.ndim > 2 else jnp.take_along_axis(seq.data, src, axis=1)
    # positions beyond length are garbage; zero them via mask
    out = SequenceBatch(data, seq.lengths)
    return out.with_data(out.masked_data())


def context_projection(seq: SequenceBatch, context_len: int,
                       context_start: int,
                       pad_weights: Optional[jnp.ndarray] = None) -> SequenceBatch:
    """ContextProjection: concat a sliding window of neighbors per timestep.

    [b, T, d] -> [b, T, d*context_len]. Out-of-range positions use zeros or
    trainable pad rows (paddle/function/ContextProjectionOp trainable_padding).
    pad_weights: [pad_rows, d] where pad_rows = (#left oob)+(#right oob).
    """
    x = seq.masked_data()
    b, T = x.shape[0], x.shape[1]
    d = x.shape[-1]
    outs = []
    n_left = max(0, -context_start)
    for i in range(context_len):
        off = context_start + i
        sh = jnp.roll(x, -off, axis=1)
        t = jnp.arange(T, dtype=jnp.int32)[None, :]
        pos = t + off
        valid = (pos >= 0) & (pos < seq.lengths[:, None])
        validf = valid.astype(x.dtype)[..., None]
        part = sh * validf
        if pad_weights is not None:
            if off < 0:  # left out-of-range -> pad row (n_left + off) ... rows 0..n_left-1
                row = pad_weights[i]
                part = part + (pos < 0).astype(x.dtype)[..., None] * row
            elif off > 0:
                row = pad_weights[n_left + context_len - 1 - i] if \
                    pad_weights.shape[0] > n_left else pad_weights[i]
                oob = (pos >= seq.lengths[:, None]) & (t < seq.lengths[:, None])
                part = part + oob.astype(x.dtype)[..., None] * row
        outs.append(part)
    return seq.with_data(jnp.concatenate(outs, axis=-1))


def sub_seq_pool(seq: SequenceBatch, pool_type: str = "average",
                 max_segments: Optional[int] = None) -> SequenceBatch:
    """Pool each inner (sub-)sequence of a nested batch -> sequence of
    pooled vectors [b, max_segments, d] (SequencePoolLayer at sub-seq level).

    max_segments must be static under jit; defaults to max_len (safe bound).
    """
    assert seq.is_nested, "sub_seq_pool needs a nested SequenceBatch"
    x = seq.data
    b, T = x.shape[0], x.shape[1]
    xs = x.reshape(b, T, -1)
    seg = seq.segment_ids
    max_segs = max_segments if max_segments is not None else T
    # one-hot segment matrix [b, T, S]
    s_ids = jnp.arange(max_segs, dtype=jnp.int32)
    onehot = (seg[..., None] == s_ids[None, None, :]).astype(xs.dtype)
    sums = jnp.einsum("btd,bts->bsd", xs, onehot)
    counts = jnp.sum(onehot, axis=1)[..., None]
    if pool_type in ("average", "avg"):
        pooled = sums / jnp.maximum(counts, 1.0)
    elif pool_type == "sum":
        pooled = sums
    elif pool_type == "max":
        big = jnp.where(onehot[..., None] > 0, xs[:, :, None, :], _NEG)
        pooled = jnp.max(big, axis=1)
    elif pool_type == "last":
        # index of last position of each segment
        tidx = jnp.arange(T, dtype=jnp.int32)[None, :, None]
        last_t = jnp.max(jnp.where(onehot > 0, tidx, -1), axis=1)  # [b, S]
        pooled = jnp.take_along_axis(xs, jnp.maximum(last_t, 0)[..., None],
                                     axis=1)
    elif pool_type == "first":
        tidx = jnp.arange(T, dtype=jnp.int32)[None, :, None]
        first_t = jnp.min(jnp.where(onehot > 0, tidx, T + 1), axis=1)
        pooled = jnp.take_along_axis(xs, jnp.clip(first_t, 0, T - 1)[..., None],
                                     axis=1)
    else:
        raise ValueError(pool_type)
    return SequenceBatch(pooled, seq.num_segments)


def nested_to_padded(seq: SequenceBatch, max_segments=None, max_sub_len=None):
    """Nested ragged layout -> dense per-subsequence view.

    [b, T, d] + segment_ids -> (data [b, S, L, d], inner_len [b, S]) where
    S/L default to T (bounded by it). This is the RecurrentGradientMachine
    createInFrameInfo reorganization (RecurrentGradientMachine.cpp) done as
    one static-shape scatter instead of per-sample index vectors.
    """
    assert seq.is_nested, "nested_to_padded needs segment_ids"
    T = seq.max_len
    S = int(max_segments or T)
    Lm = int(max_sub_len or T)
    d_shape = seq.data.shape[2:]

    def per_row(data, segs):
        t_idx = jnp.arange(T, dtype=jnp.int32)
        valid = (segs >= 0) & (segs < S)
        seg_safe = jnp.clip(segs, 0, S - 1)
        # first position of each segment (segments are contiguous, ascending)
        eq = seg_safe[None, :] == jnp.arange(S, dtype=jnp.int32)[:, None]
        eq = eq & valid[None, :]
        first = jnp.argmax(eq, axis=1).astype(jnp.int32)      # [S]
        # count only positions that fit the [S, Lm] view — lengths must
        # agree with the (possibly truncated) data
        inner_len = jnp.minimum(jnp.sum(eq, axis=1), Lm).astype(jnp.int32)
        rank = t_idx - first[seg_safe]
        flat_pos = jnp.where(valid & (rank < Lm),
                             seg_safe * Lm + rank, S * Lm)
        buf = jnp.zeros((S * Lm,) + d_shape, seq.data.dtype)
        buf = buf.at[flat_pos].set(data, mode="drop")
        return buf.reshape((S, Lm) + d_shape), inner_len

    return jax.vmap(per_row)(seq.data, seq.segment_ids)


def padded_to_nested(data: jnp.ndarray, inner_len: jnp.ndarray,
                     n_segments: jnp.ndarray, out_len: int) -> SequenceBatch:
    """Inverse of nested_to_padded: [b, S, L, d] + [b, S] -> nested
    SequenceBatch with max_len out_len."""
    b, S, Lm = data.shape[:3]
    d_shape = data.shape[3:]

    def per_row(dat, ilen, nseg):
        s_ids = jnp.arange(S, dtype=jnp.int32)
        ilen = jnp.where(s_ids < nseg, ilen, 0)
        offset = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             jnp.cumsum(ilen)[:-1].astype(jnp.int32)])
        l_idx = jnp.arange(Lm, dtype=jnp.int32)[None, :]
        pos = offset[:, None] + l_idx                          # [S, L]
        keep = (l_idx < ilen[:, None]) & (s_ids[:, None] < nseg)
        pos = jnp.where(keep, pos, out_len)
        buf = jnp.zeros((out_len,) + d_shape, data.dtype)
        buf = buf.at[pos.reshape(-1)].set(
            dat.reshape((S * Lm,) + d_shape), mode="drop")
        seg_buf = jnp.full((out_len,), -1, jnp.int32).at[
            pos.reshape(-1)].set(
            jnp.broadcast_to(s_ids[:, None], (S, Lm)).reshape(-1),
            mode="drop")
        return buf, seg_buf, jnp.sum(ilen).astype(jnp.int32)

    out, segs, lengths = jax.vmap(per_row)(data, inner_len, n_segments)
    return SequenceBatch(out, lengths, segs, n_segments)
