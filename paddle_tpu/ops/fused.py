"""Fused 1x1-conv + batch-norm (the BN-epilogue lever) — MEASURED AND
REJECTED as a default: end-to-end ResNet-50 trains ~9% slower through
this op than through XLA's own conv+BN fusion (50.9 vs 46.7 ms/step);
the trace shows XLA re-materializes the stats-pass conv output anyway
and the z-reconstruction backward loses to XLA's autodiff backward.
Kept working + tested behind conv_bn(fuse_stats=True) for future
compiler/hardware revisits; full writeup in docs/perf.md.

Batch norm's batch statistics create a two-pass dependency: the
normalize cannot run until the stats over the WHOLE conv output exist,
so XLA must materialize the conv output y, read it for the stats, read
it again for the affine, and write z — three activation-sized HBM
passes beyond what frozen-stats BN pays (measured: ResNet-50 with
use_global_stats trains 19% faster, the full cost of the machinery).

For 1x1 convs (a matmul over [b*h*w, Cin]) the matmul is far cheaper
than the y traffic (Cin=64: ~0.07 ms of MXU vs ~0.5 ms of HBM for one
stage-1 tensor), so this op RECOMPUTES instead of materializing:

- pass 1: y = x@w feeding ONLY the stats reductions (XLA fuses the
  reduction into the matmul consumer; y is never written to HBM);
- pass 2: a CSE-blocked second x@w (lax.optimization_barrier on x
  keeps XLA from deduplicating it) whose only consumer is the folded
  scale/shift affine — the conv fuses with its epilogue and writes z
  directly.

Measured on the ResNet-50 stage-1 expand shape ([401408,64]@[64,256]):
recompute 3.01 ms vs materialize 4.01 ms. A Pallas matmul with an
in-kernel stats accumulator was also tried and measured SLOWER than
XLA's own matmul+reduce fusion (3.05 vs 2.76 ms) — XLA already fuses
the epilogue; the win is in the recompute structure, not the kernel.

The custom_vjp keeps the training backward from hoarding residuals:
it saves only (x, w, gamma, beta, mean, var) and recomputes y-hat in
the backward with one extra conv; dx/dw delegate to jax.vjp of the
conv so XLA's native conv-grad lowerings apply. Reference analogue:
the fused hl_batch_norm* CUDA kernels (paddle/cuda/src/hl_cuda_cudnn.cc)
via cudnnBatchNormalization*, which fuse the same reductions.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.linear import compute_dtype


def _conv(x, w):
    from paddle_tpu.ops import conv as conv_ops
    return conv_ops.conv2d(x, w, stride=1, padding=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def conv_bn_train(x, w, gamma, beta, eps):
    """1x1 conv (x [b,h,w,Cin], w [1,1,Cin,C]) + training batch norm ->
    (z [b,h,w,C], batch mean, batch var). Numerics match
    conv2d + batch_norm_train exactly (same fold, same dtypes).

    Everything stays NHWC conv-land: a first version that reshaped to
    [b*h*w, Cin] and used jnp.matmul measured 2.2x SLOWER end-to-end on
    ResNet-50 — XLA assigns matmuls and convs different layouts, and the
    reshapes at the op boundary became 37 ms/step of physical
    transposes ('data formatting' in the trace)."""
    (z, mean, var), _ = _conv_bn_fwd(x, w, gamma, beta, eps)
    return z, mean, var


def _conv_bn_fwd(x, w, gamma, beta, eps):
    y1 = _conv(x, w)                     # stats pass — never hits HBM
    yf = y1.astype(jnp.float32)
    axes = tuple(range(y1.ndim - 1))
    mean = jnp.mean(yf, axis=axes)
    var = jnp.maximum(jnp.mean(yf * yf, axis=axes) - mean * mean, 0.0)
    inv = lax.rsqrt(var + eps) * gamma
    scale = inv.astype(y1.dtype)
    shift = (beta - mean * inv).astype(y1.dtype)
    y2 = _conv(lax.optimization_barrier(x), w)   # CSE-blocked 2nd pass
    z = y2 * scale + shift
    return (z, mean, var), (x, w, gamma, beta, mean, var)


def _conv_bn_bwd(eps, res, cts):
    x, w, gamma, beta, mean, var = res
    dz, dmean_ct, dvar_ct = cts
    m = dz.size // dz.shape[-1]
    rstd = lax.rsqrt(var + eps)
    inv = rstd * gamma
    # y-hat by RECOMPUTE (one extra conv): reconstructing it from the
    # output as (z - beta) / gamma is cheaper but silently wrong at
    # gamma == 0 (a pruned channel's dgamma would read 0 and could
    # never un-prune); this op is correctness-first since it is not the
    # default path anyway.
    y3 = _conv(lax.optimization_barrier(x), w)
    yhat = (y3.astype(jnp.float32) - mean) * rstd
    dzf = dz.astype(jnp.float32)
    axes = tuple(range(dz.ndim - 1))
    dbeta = jnp.sum(dzf, axis=axes)
    dgamma = jnp.sum(dzf * yhat, axis=axes)
    dy = inv * (dzf - dbeta / m - yhat * dgamma / m)
    # cotangents of the (mean, var) outputs (zero in a plain train step;
    # kept for correctness): mean = E[y], var = E[y^2] - E[y]^2 clamped
    # at zero (no gradient through the clamp)
    dvar_live = jnp.where(var > 0, dvar_ct, 0.0)
    dy = dy + dmean_ct / m + dvar_live * 2.0 * (yhat / rstd) / m
    dyb = dy.astype(dz.dtype)
    # conv grads through jax.vjp of the conv itself: XLA's native
    # transposed-conv / weight-grad lowerings, no hand-rolled layouts
    _, conv_vjp = jax.vjp(_conv, x, w)
    dx, dw = conv_vjp(dyb)
    return (dx.astype(x.dtype), dw.astype(w.dtype),
            dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype))


conv_bn_train.defvjp(_conv_bn_fwd, _conv_bn_bwd)
