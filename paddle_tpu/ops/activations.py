"""Activation functions.

Reference: paddle/gserver/activations/ActivationFunction.cpp:94-438 registers
16 activations (sigmoid, softmax, sequence_softmax, relu, brelu, tanh, stanh,
hard_sigmoid?, linear, exponential, log, square, sqrt, reciprocal, abs,
softrelu). Each had hand-written forward+backward; here backward is jax.grad.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_REGISTRY = {}


def register(name):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def get(name: str):
    if name not in _REGISTRY:
        raise KeyError(f"unknown activation {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def names():
    return sorted(_REGISTRY)


@register("linear")
def linear(x):
    return x


identity = linear
_REGISTRY["identity"] = linear


@register("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@register("tanh")
def tanh(x):
    return jnp.tanh(x)


@register("stanh")
def stanh(x):
    # scaled tanh: 1.7159 * tanh(2/3 x) (ActivationFunction.cpp STanh)
    return 1.7159 * jnp.tanh(2.0 / 3.0 * x)


@register("relu")
def relu(x):
    return jax.nn.relu(x)


@register("brelu")
def brelu(x):
    # bounded relu: min(max(x, 0), 24) (reference BRelu default bound 24)
    return jnp.clip(x, 0.0, 24.0)


@register("softrelu")
def softrelu(x):
    # log(1 + exp(x)), input clipped to [-40, 40] like the reference
    return jnp.log1p(jnp.exp(jnp.clip(x, -40.0, 40.0)))


@register("leaky_relu")
def leaky_relu(x):
    return jax.nn.leaky_relu(x)


@register("exponential")
def exponential(x):
    return jnp.exp(x)


@register("log")
def log_act(x):
    return jnp.log(x)


@register("square")
def square(x):
    return jnp.square(x)


@register("sqrt")
def sqrt_act(x):
    return jnp.sqrt(x)


@register("reciprocal")
def reciprocal(x):
    return 1.0 / x


@register("abs")
def abs_act(x):
    return jnp.abs(x)


@register("softmax")
def softmax(x):
    # always normalize in f32: bf16 exp/sum under mixed precision loses
    # probability mass and destabilizes the CE loss right above it
    return jax.nn.softmax(x.astype(jnp.float32), axis=-1)


@register("sequence_softmax")
def sequence_softmax(x, mask=None):
    """Softmax across the time axis of a [batch, time, 1]-ish sequence score,
    honoring the padding mask (reference: SequenceSoftmaxActivation operates
    per-sequence over the ragged layout)."""
    if mask is not None:
        while mask.ndim < x.ndim:
            mask = mask[..., None]
        x = jnp.where(mask > 0, x, -1e30)
    return jax.nn.softmax(x, axis=1)
