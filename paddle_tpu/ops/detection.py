"""SSD-style detection ops: prior boxes, bbox encode/decode, IoU, NMS.

Reference: paddle/gserver/layers/PriorBox.cpp (forward:34-106, init:19-33),
paddle/gserver/layers/DetectionUtil.cpp (decodeBBox, encodeBBoxWithVar,
matchBBox semantics inside MultiBoxLossLayer), DetectionOutputLayer.cpp.

TPU design: the reference builds dynamic per-class vectors on the CPU and
runs greedy NMS over them; here everything is fixed-shape and vectorized so
the whole detection head stays on-device under jit. NMS is a static-length
greedy pass (`lax.fori_loop` over a top-k candidate list with an O(N^2)
IoU suppression matrix) — padded slots carry score 0 / label -1.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax.numpy as jnp
from jax import lax


def prior_boxes(layer_h: int, layer_w: int, image_h: int, image_w: int,
                min_sizes: Sequence[float], max_sizes: Sequence[float],
                aspect_ratios: Sequence[float], variance: Sequence[float],
                clip: bool = True) -> jnp.ndarray:
    """Generate SSD prior boxes for one feature map.

    Returns [layer_h * layer_w * num_priors, 8] — each row is
    (xmin, ymin, xmax, ymax, var0, var1, var2, var3), normalized to [0, 1],
    matching the reference's interleaved box/variance layout
    (PriorBox.cpp:49-67: 4 coords then 4 variances per prior).

    Prior order per cell mirrors the reference loop exactly
    (PriorBox.cpp:103-130): for EACH min_size, the aspect-1 box followed
    immediately by its sqrt(min*max) boxes (one per max_size), then one
    box per flipped aspect ratio (r and 1/r) at the last min_size.
    """
    assert len(variance) == 4
    step_w = image_w / layer_w
    step_h = image_h / layer_h

    # per-cell (w, h) box shapes in pixels, in reference emission order
    shapes = []
    for s in min_sizes:
        shapes.append((s, s))
        for m in max_sizes:
            d = math.sqrt(s * m)
            shapes.append((d, d))
    base = min_sizes[-1]
    for r in aspect_ratios:
        if abs(r - 1.0) < 1e-6:
            continue
        for ar in (r, 1.0 / r):
            shapes.append((base * math.sqrt(ar), base / math.sqrt(ar)))
    shapes = jnp.asarray(shapes, jnp.float32)          # [np, 2]
    n_priors = shapes.shape[0]

    cx = (jnp.arange(layer_w, dtype=jnp.float32) + 0.5) * step_w
    cy = (jnp.arange(layer_h, dtype=jnp.float32) + 0.5) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)                    # [h, w]
    cxg = cxg[..., None]                               # [h, w, 1]
    cyg = cyg[..., None]
    bw = shapes[None, None, :, 0]                      # [1, 1, np]
    bh = shapes[None, None, :, 1]
    xmin = (cxg - bw / 2.0) / image_w
    ymin = (cyg - bh / 2.0) / image_h
    xmax = (cxg + bw / 2.0) / image_w
    ymax = (cyg + bh / 2.0) / image_h
    boxes = jnp.stack([xmin, ymin, xmax, ymax], axis=-1)  # [h, w, np, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                           boxes.shape)
    out = jnp.concatenate([boxes, var], axis=-1)       # [h, w, np, 8]
    return out.reshape(layer_h * layer_w * n_priors, 8)


def _center_form(boxes: jnp.ndarray):
    """(xmin,ymin,xmax,ymax) -> (cx, cy, w, h)."""
    w = boxes[..., 2] - boxes[..., 0]
    h = boxes[..., 3] - boxes[..., 1]
    cx = (boxes[..., 0] + boxes[..., 2]) * 0.5
    cy = (boxes[..., 1] + boxes[..., 3]) * 0.5
    return cx, cy, w, h


def decode_boxes(loc: jnp.ndarray, priors: jnp.ndarray) -> jnp.ndarray:
    """Decode predicted offsets against priors (DetectionUtil decodeBBox).

    loc:    [..., P, 4] predicted (dx, dy, dw, dh)
    priors: [P, 8] boxes + variances from prior_boxes
    returns [..., P, 4] corner-form boxes.
    """
    pcx, pcy, pw, ph = _center_form(priors[..., :4])
    var = priors[..., 4:]
    cx = var[..., 0] * loc[..., 0] * pw + pcx
    cy = var[..., 1] * loc[..., 1] * ph + pcy
    w = jnp.exp(jnp.clip(var[..., 2] * loc[..., 2], -10.0, 10.0)) * pw
    h = jnp.exp(jnp.clip(var[..., 3] * loc[..., 3], -10.0, 10.0)) * ph
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


def encode_boxes(gt: jnp.ndarray, priors: jnp.ndarray) -> jnp.ndarray:
    """Encode ground-truth corner boxes into regression targets (inverse of
    decode_boxes; DetectionUtil encodeBBoxWithVar)."""
    pcx, pcy, pw, ph = _center_form(priors[..., :4])
    var = priors[..., 4:]
    gcx, gcy, gw, gh = _center_form(gt)
    eps = 1e-8
    dx = (gcx - pcx) / jnp.maximum(pw, eps) / var[..., 0]
    dy = (gcy - pcy) / jnp.maximum(ph, eps) / var[..., 1]
    dw = jnp.log(jnp.maximum(gw, eps) / jnp.maximum(pw, eps)) / var[..., 2]
    dh = jnp.log(jnp.maximum(gh, eps) / jnp.maximum(ph, eps)) / var[..., 3]
    return jnp.stack([dx, dy, dw, dh], axis=-1)


def iou_matrix(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Pairwise IoU. a: [N, 4], b: [M, 4] corner boxes -> [N, M]."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0.0) * jnp.clip(a[:, 3] - a[:, 1], 0.0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0.0) * jnp.clip(b[:, 3] - b[:, 1], 0.0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-12), 0.0)


def match_priors(priors: jnp.ndarray, gt_boxes: jnp.ndarray,
                 gt_valid: jnp.ndarray,
                 overlap_threshold: float = 0.5
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Match priors to ground truth (MultiBoxLossLayer matchBBox semantics).

    Two-phase: (1) per-prior argmax matching when IoU > overlap_threshold,
    (2) bipartite override — every valid gt claims its best prior so no gt
    goes unmatched. Returns (match_idx [P] int32, -1 = unmatched;
    match_iou [P] float32).
    """
    P = priors.shape[0]
    iou = iou_matrix(priors[:, :4], gt_boxes)            # [P, G]
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)  # [P]
    best_iou = jnp.max(iou, axis=1)
    match_idx = jnp.where(best_iou > overlap_threshold, best_gt, -1)
    # bipartite: gt g claims prior argmax_p iou[p, g]; invalid gt slots are
    # routed to index P so the drop-mode scatter ignores them entirely
    best_prior = jnp.argmax(iou, axis=0).astype(jnp.int32)  # [G]
    g_ids = jnp.arange(gt_boxes.shape[0], dtype=jnp.int32)
    scatter_idx = jnp.where(gt_valid, best_prior, P)
    claimed = jnp.full((P,), -1, jnp.int32).at[scatter_idx].set(
        g_ids, mode="drop")
    match_idx = jnp.where(claimed >= 0, claimed, match_idx)
    match_iou = jnp.where(
        claimed >= 0,
        iou[jnp.arange(P), jnp.clip(claimed, 0)],
        best_iou)
    return match_idx, match_iou


def nms(boxes: jnp.ndarray, scores: jnp.ndarray, *,
        iou_threshold: float = 0.45, score_threshold: float = 0.01,
        top_k: int = 400) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Greedy NMS with static shapes (DetectionUtil applyNMSFast).

    boxes [N, 4], scores [N] -> (boxes [K, 4], scores [K], keep_mask [K])
    where K = min(top_k, N); suppressed/padded slots have score 0.
    """
    k = min(top_k, boxes.shape[0])
    scores = jnp.where(scores >= score_threshold, scores, 0.0)
    top_scores, order = lax.top_k(scores, k)
    cand = boxes[order]                                   # [K, 4]
    iou = iou_matrix(cand, cand)                          # [K, K]
    valid = top_scores > 0.0

    def body(i, keep):
        sup = jnp.any((iou[i] > iou_threshold) & keep &
                      (jnp.arange(k) < i))
        return keep.at[i].set(valid[i] & ~sup)

    keep = lax.fori_loop(0, k, body, jnp.zeros((k,), bool))
    return cand, jnp.where(keep, top_scores, 0.0), keep


def smooth_l1(x: jnp.ndarray) -> jnp.ndarray:
    """Elementwise smooth-L1 (huber with delta=1), as SSD's loc loss uses."""
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0, 0.5 * x * x, ax - 0.5)
