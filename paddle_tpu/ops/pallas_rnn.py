"""Fused LSTM/GRU sequence kernels in Pallas.

Reference parity: the hand-fused CUDA recurrences hl_cuda_lstm.cu /
hl_gpu_gru.cuh — the one place the reference found XLA-era fusion
insufficient and wrote kernels by hand. Same story on TPU: a lax.scan
LSTM re-reads h/c from HBM every step; this kernel keeps the recurrent
weight AND state resident in VMEM across the whole sequence (grid over
time — v5e has ~100+ MB of usable VMEM, so even h=1280's [1280,5120]
weight stays resident), and each step is one MXU matmul [b,h]x[h,4h]
plus VPU gate math with zero HBM traffic for the carry.

Training is fused end-to-end for the LSTM (hl_cuda_lstm.cu does both
directions; so do we): the forward kernel streams out the activated
gates and cell sequence as residuals, and a reverse-time backward kernel
carries dh/dc in VMEM while emitting dz — the pre-activation cotangent —
from which the weight/bias/peephole grads fall out as ONE large
MXU-friendly matmul outside the kernel (sum_t h_{t-1}^T dz_t), instead
of T tiny rank-updates.

MXU passes run in the global compute dtype (bf16 under mixed precision,
f32 otherwise) with f32 accumulation; gate math and carries are always
f32. Semantics match ops/recurrent.lstm_scan/gru_scan exactly (tests
assert forward AND gradient parity): padded steps freeze the carry and
zero the output; final state is the last VALID step's state.

Kernels are used on the TPU backend when shapes are tile-friendly
(h % 128 == 0, batch % 8 == 0) and activations are the defaults;
`interpret=True` runs them on CPU for tests.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.core.sequence import SequenceBatch

# jax renamed pltpu.TPUCompilerParams -> CompilerParams across releases;
# accept whichever this jax ships.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def _sigmoid(x):
    return jax.nn.sigmoid(x)


def _mxu_dtype():
    from paddle_tpu.ops.linear import compute_dtype
    cd = compute_dtype()
    return jnp.bfloat16 if cd == jnp.bfloat16 else jnp.float32


# ---------------------------------------------------------------------------
# LSTM — forward kernel


def _lstm_kernel(save_res, lens_ref, x4_ref, w_ref, b_ref, peep_ref,
                 *refs):
    # residual streams (c sequence + activated gates) exist only on the
    # training path; the primal/inference call skips them so its HBM
    # write traffic stays one h-stream wide
    if save_res:
        (out_ref, cseq_ref, gates_ref, hT_ref, cT_ref,
         h_scr, c_scr) = refs
    else:
        out_ref, hT_ref, cT_ref, h_scr, c_scr = refs
        cseq_ref = gates_ref = None
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)
        c_scr[:] = jnp.zeros_like(c_scr)

    x4 = x4_ref[0].astype(jnp.float32)                # [b, 4h]
    h = h_scr[:]
    c = c_scr[:]
    hdim = h.shape[-1]

    z = x4 + jnp.dot(h.astype(w_ref.dtype), w_ref[:],
                     preferred_element_type=jnp.float32) \
        + b_ref[0]
    zi = z[:, :hdim]
    zf = z[:, hdim:2 * hdim]
    zc = z[:, 2 * hdim:3 * hdim]
    zo = z[:, 3 * hdim:]
    pi = peep_ref[0:1, :]
    pf = peep_ref[1:2, :]
    po = peep_ref[2:3, :]
    i_g = _sigmoid(zi + pi * c)
    f_g = _sigmoid(zf + pf * c)
    cand = jnp.tanh(zc)
    c_new = f_g * c + i_g * cand
    o_g = _sigmoid(zo + po * c_new)
    h_new = o_g * jnp.tanh(c_new)

    valid = (lens_ref[:] > t)                         # [b, 1] bool
    h_keep = jnp.where(valid, h_new, h)
    c_keep = jnp.where(valid, c_new, c)
    h_scr[:] = h_keep
    c_scr[:] = c_keep
    out_ref[0] = jnp.where(valid, h_new,
                           jnp.zeros_like(h_new)).astype(out_ref.dtype)
    if save_res:
        cseq_ref[0] = c_keep.astype(cseq_ref.dtype)
        gates_ref[0] = jnp.concatenate([i_g, f_g, cand, o_g],
                                       axis=-1).astype(gates_ref.dtype)
    hT_ref[:] = h_keep
    cT_ref[:] = c_keep


# ---------------------------------------------------------------------------
# LSTM — backward kernel (reverse time; dh/dc carried in VMEM)


def _lstm_bwd_kernel(T, lens_ref, w_ref, peep_ref, gates_ref, cseq_ref,
                     cprev_ref, dhseq_ref, dhT_ref, dcT_ref,
                     dz_ref, dh_scr, dc_scr):
    idx = pl.program_id(0)
    t = T - 1 - idx

    @pl.when(idx == 0)
    def _init():
        dh_scr[:] = dhT_ref[:]
        dc_scr[:] = dcT_ref[:]

    g4 = gates_ref[0].astype(jnp.float32)             # [b, 4h]
    hdim = dh_scr.shape[-1]
    i_g = g4[:, :hdim]
    f_g = g4[:, hdim:2 * hdim]
    cand = g4[:, 2 * hdim:3 * hdim]
    o_g = g4[:, 3 * hdim:]
    c_t = cseq_ref[0].astype(jnp.float32)
    c_prev = cprev_ref[0].astype(jnp.float32)
    c_prev = jnp.where(t > 0, c_prev, jnp.zeros_like(c_prev))
    pi = peep_ref[0:1, :]
    pf = peep_ref[1:2, :]
    po = peep_ref[2:3, :]

    valid = (lens_ref[:] > t)                         # [b, 1]
    dh_t = dh_scr[:] + jnp.where(valid, dhseq_ref[0].astype(jnp.float32),
                                 0.0)
    tc = jnp.tanh(c_t)
    do = dh_t * tc
    dzo = do * o_g * (1.0 - o_g)
    dc_t = dc_scr[:] + dh_t * o_g * (1.0 - tc * tc) + dzo * po
    di = dc_t * cand
    dzi = di * i_g * (1.0 - i_g)
    df = dc_t * c_prev
    dzf = df * f_g * (1.0 - f_g)
    dg = dc_t * i_g
    dzc = dg * (1.0 - cand * cand)
    dz = jnp.concatenate([dzi, dzf, dzc, dzo], axis=-1)
    dz = jnp.where(valid, dz, jnp.zeros_like(dz))

    # dh_{t-1} = dz @ w^T (contract the 4h dim of both)
    dh_prev = jax.lax.dot_general(
        dz.astype(w_ref.dtype), w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dc_prev = dc_t * f_g + dzi * pi + dzf * pf

    dh_scr[:] = jnp.where(valid, dh_prev, dh_scr[:])
    dc_scr[:] = jnp.where(valid, dc_prev, dc_scr[:])
    dz_ref[0] = dz.astype(dz_ref.dtype)


# ---------------------------------------------------------------------------
# LSTM — lax reference (semantics oracle; CPU / odd-shape fallback path)


def _lstm_ref(x4, lens2d, w, bias2d, peep2d):
    """Pure-lax implementation with identical semantics — what the
    fused kernel is tested against (tests/test_pallas_rnn.py pins both
    forward and gradient parity)."""
    b, T, four_h = x4.shape
    h = four_h // 4
    lens = lens2d.reshape(b)
    xt = jnp.moveaxis(x4, 1, 0)

    def body(carry, inp):
        t, x_t = inp
        hh, cc = carry
        z = x_t + hh @ w + bias2d[0]
        zi, zf, zc, zo = (z[:, :h], z[:, h:2*h], z[:, 2*h:3*h], z[:, 3*h:])
        i_g = _sigmoid(zi + peep2d[0] * cc)
        f_g = _sigmoid(zf + peep2d[1] * cc)
        cand = jnp.tanh(zc)
        c_new = f_g * cc + i_g * cand
        o_g = _sigmoid(zo + peep2d[2] * c_new)
        h_new = o_g * jnp.tanh(c_new)
        valid = (t < lens)[:, None]
        h_keep = jnp.where(valid, h_new, hh)
        c_keep = jnp.where(valid, c_new, cc)
        return (h_keep, c_keep), jnp.where(valid, h_new, 0.0)

    init = (jnp.zeros((b, h), x4.dtype), jnp.zeros((b, h), x4.dtype))
    (hT, cT), outs = jax.lax.scan(
        body, init, (jnp.arange(T, dtype=jnp.int32), xt))
    return jnp.moveaxis(outs, 0, 1), hT, cT


# ---------------------------------------------------------------------------
# LSTM — custom-vjp wrapper: fused forward AND fused backward


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _lstm_pallas(x4, lens2d, w, bias2d, peep2d, interpret):
    out, hT, cT = _lstm_fwd_call(x4, lens2d, w, bias2d, peep2d,
                                 interpret, save_res=False)
    return out, hT, cT


def _lstm_fwd_call(x4, lens2d, w, bias2d, peep2d, interpret,
                   save_res=True):
    b, T, four_h = x4.shape
    h = four_h // 4
    mxu = _mxu_dtype()
    xt = jnp.moveaxis(x4, 1, 0).astype(mxu)
    res_out_specs = [
        pl.BlockSpec((1, b, h), lambda t: (t, 0, 0),
                     memory_space=pltpu.VMEM),             # c seq
        pl.BlockSpec((1, b, four_h), lambda t: (t, 0, 0),
                     memory_space=pltpu.VMEM),             # gates
    ] if save_res else []
    res_out_shapes = [
        jax.ShapeDtypeStruct((T, b, h), mxu),
        jax.ShapeDtypeStruct((T, b, four_h), mxu),
    ] if save_res else []
    outs = pl.pallas_call(
        functools.partial(_lstm_kernel, save_res),
        grid=(T,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),            # lens [b,1]
            pl.BlockSpec((1, b, four_h), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),            # x4 block
            pl.BlockSpec(memory_space=pltpu.VMEM),            # w [h,4h]
            pl.BlockSpec(memory_space=pltpu.VMEM),            # bias [1,4h]
            pl.BlockSpec(memory_space=pltpu.VMEM),            # peep [3,h]
        ],
        out_specs=[
            pl.BlockSpec((1, b, h), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),            # h seq
        ] + res_out_specs + [
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, b, h), mxu),     # h stream
        ] + res_out_shapes + [
            jax.ShapeDtypeStruct((b, h), jnp.float32),
            jax.ShapeDtypeStruct((b, h), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, h), jnp.float32),
            pltpu.VMEM((b, h), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(lens2d, xt, w.astype(mxu), bias2d, peep2d)
    if save_res:
        out, cseq, gates, hT, cT = outs
        return jnp.moveaxis(out, 0, 1), hT, cT, cseq, gates
    out, hT, cT = outs
    return jnp.moveaxis(out, 0, 1), hT, cT


def _lstm_fwd(x4, lens2d, w, bias2d, peep2d, interpret):
    out, hT, cT, cseq, gates = _lstm_fwd_call(x4, lens2d, w, bias2d, peep2d,
                                              interpret, save_res=True)
    res = (lens2d, w, peep2d, cseq, gates,
           jnp.moveaxis(out, 1, 0), jnp.zeros((0,), x4.dtype))
    return (out, hT, cT), res


def _lstm_bwd(interpret, res, ct):
    lens2d, w, peep2d, cseq, gates, hseq_tb, x4_token = res
    x4_dtype = x4_token.dtype
    d_out, d_hT, d_cT = ct
    T, b, h = cseq.shape
    four_h = 4 * h
    mxu = _mxu_dtype()
    d_out_tb = jnp.moveaxis(d_out, 1, 0)

    rev = lambda t: (T - 1 - t, 0, 0)                  # noqa: E731
    rev_prev = lambda t: (jnp.maximum(T - 2 - t, 0), 0, 0)  # noqa: E731
    dz = pl.pallas_call(
        functools.partial(_lstm_bwd_kernel, T),
        grid=(T,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),             # lens
            pl.BlockSpec(memory_space=pltpu.VMEM),             # w
            pl.BlockSpec(memory_space=pltpu.VMEM),             # peep
            pl.BlockSpec((1, b, four_h), rev,
                         memory_space=pltpu.VMEM),             # gates
            pl.BlockSpec((1, b, h), rev, memory_space=pltpu.VMEM),   # c_t
            pl.BlockSpec((1, b, h), rev_prev,
                         memory_space=pltpu.VMEM),             # c_{t-1}
            pl.BlockSpec((1, b, h), rev, memory_space=pltpu.VMEM),   # dh_seq
            pl.BlockSpec(memory_space=pltpu.VMEM),             # dhT
            pl.BlockSpec(memory_space=pltpu.VMEM),             # dcT
        ],
        out_specs=[
            pl.BlockSpec((1, b, four_h), rev, memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((T, b, four_h), mxu)],
        scratch_shapes=[
            pltpu.VMEM((b, h), jnp.float32),
            pltpu.VMEM((b, h), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(lens2d, w.astype(mxu), peep2d, gates, cseq, cseq, d_out_tb,
      d_hT.astype(jnp.float32), d_cT.astype(jnp.float32))[0]

    # Parameter grads as single large contractions (MXU work, not T tiny
    # rank-1 updates): dw = sum_t h_{t-1}^T dz_t over (t, b).
    hprev = jnp.concatenate(
        [jnp.zeros((1, b, h), hseq_tb.dtype), hseq_tb[:-1]], axis=0)
    dw = jax.lax.dot_general(
        hprev.reshape(T * b, h).astype(mxu),
        dz.reshape(T * b, four_h).astype(mxu),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    # f32-ACCUMULATING reductions over the bf16 stream (dtype=f32 keeps
    # the bf16 multiply fused into the reduce; an explicit .astype would
    # materialize a full f32 copy of dz — 0.6 ms at h=1280 in traces)
    dbias = jnp.sum(dz, axis=(0, 1), dtype=jnp.float32).reshape(1, four_h)
    cprev = jnp.concatenate(
        [jnp.zeros((1, b, h), cseq.dtype), cseq[:-1]], axis=0)
    dpi = jnp.sum(dz[..., :h] * cprev, axis=(0, 1), dtype=jnp.float32)
    dpf = jnp.sum(dz[..., h:2 * h] * cprev, axis=(0, 1), dtype=jnp.float32)
    dpo = jnp.sum(dz[..., 3 * h:] * cseq, axis=(0, 1), dtype=jnp.float32)
    dpeep = jnp.stack([dpi, dpf, dpo])
    dx4 = jnp.moveaxis(dz, 0, 1).astype(x4_dtype)
    glens = jnp.zeros(lens2d.shape, jax.dtypes.float0)
    return dx4, glens, dw.astype(w.dtype), dbias, dpeep


_lstm_pallas.defvjp(_lstm_fwd, _lstm_bwd)


def lstm_sequence(x4: jnp.ndarray, lengths: jnp.ndarray, w: jnp.ndarray,
                  bias: Optional[jnp.ndarray],
                  peep: Optional[jnp.ndarray], *,
                  interpret: bool = False
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """x4: [b, T, 4h]; returns (h_seq [b,T,h] f32, hT [b,h], cT [b,h]).
    Differentiable: fused Pallas kernels both directions."""
    b, T, four_h = x4.shape
    h = four_h // 4
    lens = lengths.astype(jnp.int32).reshape(b, 1)
    b_arr = (bias if bias is not None
             else jnp.zeros((four_h,), jnp.float32)).reshape(1, four_h) \
        .astype(jnp.float32)
    p_arr = (peep.reshape(3, h) if peep is not None
             else jnp.zeros((3, h), jnp.float32)).astype(jnp.float32)
    return _lstm_pallas(x4, lens, w, b_arr, p_arr, interpret)


# ---------------------------------------------------------------------------
# GRU


def _gru_kernel(lens_ref, x3_ref, wg_ref, wc_ref, b_ref,
                out_ref, hT_ref, h_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)

    x3 = x3_ref[0].astype(jnp.float32)                # [b, 3h]
    h = h_scr[:]
    hdim = h.shape[-1]

    zr = x3[:, :2 * hdim] + jnp.dot(h.astype(wg_ref.dtype), wg_ref[:],
                                    preferred_element_type=jnp.float32) \
        + b_ref[0, :2 * hdim]
    z = _sigmoid(zr[:, :hdim])
    r = _sigmoid(zr[:, hdim:])
    cand = x3[:, 2 * hdim:] + jnp.dot((r * h).astype(wc_ref.dtype), wc_ref[:],
                                      preferred_element_type=jnp.float32) \
        + b_ref[0, 2 * hdim:]
    c = jnp.tanh(cand)
    h_new = (1.0 - z) * h + z * c

    valid = (lens_ref[:] > t)
    h_keep = jnp.where(valid, h_new, h)
    h_scr[:] = h_keep
    out_ref[0] = jnp.where(valid, h_new, jnp.zeros_like(h_new))
    hT_ref[:] = h_keep


def _gru_ref(x3, lens2d, w, bias2d):
    b, T, three_h = x3.shape
    h = three_h // 3
    lens = lens2d.reshape(b)
    xt = jnp.moveaxis(x3, 1, 0)

    def body(carry, inp):
        t, x_t = inp
        hh = carry
        zr = x_t[:, :2*h] + hh @ w[:, :2*h] + bias2d[0, :2*h]
        z = _sigmoid(zr[:, :h])
        r = _sigmoid(zr[:, h:])
        cand = x_t[:, 2*h:] + (r * hh) @ w[:, 2*h:] + bias2d[0, 2*h:]
        h_new = (1.0 - z) * hh + z * jnp.tanh(cand)
        valid = (t < lens)[:, None]
        h_keep = jnp.where(valid, h_new, hh)
        return h_keep, jnp.where(valid, h_new, 0.0)

    hT, outs = jax.lax.scan(
        body, jnp.zeros((b, h), x3.dtype),
        (jnp.arange(T, dtype=jnp.int32), xt))
    return jnp.moveaxis(outs, 0, 1), hT


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _gru_pallas(x3, lens2d, w, bias2d, interpret):
    b, T, three_h = x3.shape
    h = three_h // 3
    xt = jnp.moveaxis(x3, 1, 0)
    out, hT = _gru_call(xt, lens2d, w, bias2d, b, T, three_h, h, interpret)
    return jnp.moveaxis(out, 0, 1), hT


def _gru_fwd(x3, lens2d, w, bias2d, interpret):
    # GRU training keeps the lax vjp (one forward + one backward, same
    # cost as the plain scan); only the LSTM has the full fused backward.
    out, vjp = jax.vjp(_gru_ref, x3, lens2d, w, bias2d)
    return out, (vjp, lens2d.shape)


def _gru_bwd(interpret, res, ct):
    vjp, lens_shape = res
    gx3, _, gw, gb = vjp(ct)
    glens = jnp.zeros(lens_shape, jax.dtypes.float0)
    return gx3, glens, gw, gb


_gru_pallas.defvjp(_gru_fwd, _gru_bwd)


def gru_sequence(x3: jnp.ndarray, lengths: jnp.ndarray, w: jnp.ndarray,
                 bias: Optional[jnp.ndarray], *,
                 interpret: bool = False
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x3: [b, T, 3h]; w: [h, 3h] (gates [h,2h] | cand [h,h]).
    Returns (h_seq [b,T,h], hT [b,h])."""
    b, T, three_h = x3.shape
    lens = lengths.astype(jnp.int32).reshape(b, 1)
    b_arr = (bias if bias is not None
             else jnp.zeros((three_h,), jnp.float32)).reshape(1, three_h) \
        .astype(jnp.float32)
    return _gru_pallas(x3.astype(jnp.float32), lens, w.astype(jnp.float32),
                       b_arr, interpret)


def _gru_call(xt, lens, w, b_arr, b, T, three_h, h, interpret):
    mxu = _mxu_dtype()
    return pl.pallas_call(
        _gru_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),            # lens
            pl.BlockSpec((1, b, three_h), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),            # wg [h,2h]
            pl.BlockSpec(memory_space=pltpu.VMEM),            # wc [h,h]
            pl.BlockSpec(memory_space=pltpu.VMEM),            # bias [1,3h]
        ],
        out_specs=[
            pl.BlockSpec((1, b, h), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, b, h), jnp.float32),
            jax.ShapeDtypeStruct((b, h), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((b, h), jnp.float32)],
        interpret=interpret,
    )(lens, xt.astype(mxu), w[:, :2 * h].astype(mxu),
      w[:, 2 * h:].astype(mxu), b_arr)


# ---------------------------------------------------------------------------
# dispatch


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# v5e-class chips expose ~128 MB of VMEM (measured: a 120 MB scratch
# compiles and runs); leave headroom for double-buffered stream blocks
_VMEM_BUDGET = 96 * 1024 * 1024


def _vmem_bytes(b: int, h: int, gates: int) -> int:
    """Rough VMEM residency of the fused kernel: resident weights + the
    double-buffered per-step stream blocks + state scratches."""
    gh = gates * h
    mxu_bytes = 2 if _mxu_dtype() == jnp.bfloat16 else 4
    return (mxu_bytes * h * gh          # recurrent weight (resident)
            + 2 * mxu_bytes * b * gh    # x block (double-buffered)
            + 2 * mxu_bytes * b * gh    # gates block
            + 4 * gh + 12 * h           # bias + peephole
            + 4 * b * h * 8)            # h/c stream blocks + scratches


def pallas_ok(b: int, h: int, act: str, gate_act: str,
              state_act: str = "tanh", gates: int = 4) -> bool:
    """Use the fused kernel only for tile-friendly shapes that FIT in VMEM
    and default activations (everything else keeps the lax.scan path)."""
    import os
    if os.environ.get("PADDLE_TPU_NO_PALLAS"):
        return False
    return (_on_tpu() and act == "tanh" and gate_act == "sigmoid"
            and state_act == "tanh" and h % 128 == 0 and b % 8 == 0
            and _vmem_bytes(b, h, gates) <= _VMEM_BUDGET)
