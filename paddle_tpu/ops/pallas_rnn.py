"""Fused LSTM/GRU sequence kernels in Pallas.

Reference parity: the hand-fused CUDA recurrences hl_cuda_lstm.cu /
hl_gpu_gru.cuh — the one place the reference found XLA-era fusion
insufficient and wrote kernels by hand. Same story on TPU: a lax.scan
LSTM re-reads h/c from HBM every step; this kernel keeps the recurrent
state in VMEM scratch across the whole sequence (grid over time), so each
step is one MXU matmul [b,h]x[h,4h] plus VPU gate math with zero HBM
traffic for the carry.

Semantics match ops/recurrent.lstm_scan/gru_scan exactly (tests assert
parity): padded steps freeze the carry and zero the output; final state
is the last VALID step's state. The kernel is the PRIMAL (inference)
path; under jax.grad the custom_vjp runs the lax reference once forward
and once backward — identical cost to the plain scan, so training never
pays a duplicate forward.

Kernels are used on the TPU backend when shapes are tile-friendly
(h % 128 == 0, batch % 8 == 0) and activations are the defaults;
`interpret=True` runs them on CPU for tests.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.core.sequence import SequenceBatch


def _sigmoid(x):
    return jax.nn.sigmoid(x)


# ---------------------------------------------------------------------------
# LSTM


def _lstm_kernel(lens_ref, x4_ref, w_ref, b_ref, peep_ref,
                 out_ref, hT_ref, cT_ref, h_scr, c_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)
        c_scr[:] = jnp.zeros_like(c_scr)

    x4 = x4_ref[0]                                    # [b, 4h]
    h = h_scr[:]
    c = c_scr[:]
    hdim = h.shape[-1]

    z = x4 + jnp.dot(h, w_ref[:], preferred_element_type=jnp.float32) \
        + b_ref[0]
    zi = z[:, :hdim]
    zf = z[:, hdim:2 * hdim]
    zc = z[:, 2 * hdim:3 * hdim]
    zo = z[:, 3 * hdim:]
    pi = peep_ref[0:1, :]
    pf = peep_ref[1:2, :]
    po = peep_ref[2:3, :]
    i_g = _sigmoid(zi + pi * c)
    f_g = _sigmoid(zf + pf * c)
    cand = jnp.tanh(zc)
    c_new = f_g * c + i_g * cand
    o_g = _sigmoid(zo + po * c_new)
    h_new = o_g * jnp.tanh(c_new)

    valid = (lens_ref[:] > t)                         # [b, 1] bool
    h_keep = jnp.where(valid, h_new, h)
    c_keep = jnp.where(valid, c_new, c)
    h_scr[:] = h_keep
    c_scr[:] = c_keep
    out_ref[0] = jnp.where(valid, h_new, jnp.zeros_like(h_new))
    hT_ref[:] = h_keep
    cT_ref[:] = c_keep


def _lstm_ref(x4, lens2d, w, bias2d, peep2d):
    """Pure-lax reference with identical semantics — the backward pass
    (pallas forward + lax-vjp backward via custom_vjp below)."""
    b, T, four_h = x4.shape
    h = four_h // 4
    lens = lens2d.reshape(b)
    xt = jnp.moveaxis(x4, 1, 0)

    def body(carry, inp):
        t, x_t = inp
        hh, cc = carry
        z = x_t + hh @ w + bias2d[0]
        zi, zf, zc, zo = (z[:, :h], z[:, h:2*h], z[:, 2*h:3*h], z[:, 3*h:])
        i_g = _sigmoid(zi + peep2d[0] * cc)
        f_g = _sigmoid(zf + peep2d[1] * cc)
        cand = jnp.tanh(zc)
        c_new = f_g * cc + i_g * cand
        o_g = _sigmoid(zo + peep2d[2] * c_new)
        h_new = o_g * jnp.tanh(c_new)
        valid = (t < lens)[:, None]
        h_keep = jnp.where(valid, h_new, hh)
        c_keep = jnp.where(valid, c_new, cc)
        return (h_keep, c_keep), jnp.where(valid, h_new, 0.0)

    init = (jnp.zeros((b, h), x4.dtype), jnp.zeros((b, h), x4.dtype))
    (hT, cT), outs = jax.lax.scan(
        body, init, (jnp.arange(T, dtype=jnp.int32), xt))
    return jnp.moveaxis(outs, 0, 1), hT, cT


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _lstm_pallas(x4, lens2d, w, bias2d, peep2d, interpret):
    b, T, four_h = x4.shape
    h = four_h // 4
    xt = jnp.moveaxis(x4, 1, 0)
    out, hT, cT = _lstm_call(xt, lens2d, w, bias2d, peep2d, b, T, four_h, h,
                             interpret)
    return jnp.moveaxis(out, 0, 1), hT, cT


def _lstm_fwd(x4, lens2d, w, bias2d, peep2d, interpret):
    # Under differentiation (training), run the lax reference ONCE and keep
    # its vjp closure as the residual: same total cost as the plain scan
    # path (one forward + one backward), no kernel re-execution. The fused
    # kernel is the inference/primal path.
    out, vjp = jax.vjp(_lstm_ref, x4, lens2d, w, bias2d, peep2d)
    return out, (vjp, lens2d.shape)


def _lstm_bwd(interpret, res, ct):
    vjp, lens_shape = res
    gx4, _, gw, gb, gp = vjp(ct)
    glens = jnp.zeros(lens_shape, jax.dtypes.float0)
    return gx4, glens, gw, gb, gp


_lstm_pallas.defvjp(_lstm_fwd, _lstm_bwd)


def lstm_sequence(x4: jnp.ndarray, lengths: jnp.ndarray, w: jnp.ndarray,
                  bias: Optional[jnp.ndarray],
                  peep: Optional[jnp.ndarray], *,
                  interpret: bool = False
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """x4: [b, T, 4h] f32; returns (h_seq [b,T,h], hT [b,h], cT [b,h]).
    Differentiable: forward runs the fused kernel, backward the lax vjp."""
    b, T, four_h = x4.shape
    h = four_h // 4
    lens = lengths.astype(jnp.int32).reshape(b, 1)
    b_arr = (bias if bias is not None
             else jnp.zeros((four_h,), jnp.float32)).reshape(1, four_h) \
        .astype(jnp.float32)
    p_arr = (peep.reshape(3, h) if peep is not None
             else jnp.zeros((3, h), jnp.float32)).astype(jnp.float32)
    return _lstm_pallas(x4.astype(jnp.float32), lens, w.astype(jnp.float32),
                        b_arr, p_arr, interpret)


def _lstm_call(xt, lens, w, b_arr, p_arr, b, T, four_h, h, interpret):
    return pl.pallas_call(
        _lstm_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),            # lens [b,1]
            pl.BlockSpec((1, b, four_h), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),            # x4 block
            pl.BlockSpec(memory_space=pltpu.VMEM),            # w [h,4h]
            pl.BlockSpec(memory_space=pltpu.VMEM),            # bias [1,4h]
            pl.BlockSpec(memory_space=pltpu.VMEM),            # peep [3,h]
        ],
        out_specs=[
            pl.BlockSpec((1, b, h), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, b, h), jnp.float32),
            jax.ShapeDtypeStruct((b, h), jnp.float32),
            jax.ShapeDtypeStruct((b, h), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, h), jnp.float32),
            pltpu.VMEM((b, h), jnp.float32),
        ],
        interpret=interpret,
    )(lens, xt, w, b_arr, p_arr)


# ---------------------------------------------------------------------------
# GRU


def _gru_kernel(lens_ref, x3_ref, wg_ref, wc_ref, b_ref,
                out_ref, hT_ref, h_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)

    x3 = x3_ref[0]                                    # [b, 3h]
    h = h_scr[:]
    hdim = h.shape[-1]

    zr = x3[:, :2 * hdim] + jnp.dot(h, wg_ref[:],
                                    preferred_element_type=jnp.float32) \
        + b_ref[0, :2 * hdim]
    z = _sigmoid(zr[:, :hdim])
    r = _sigmoid(zr[:, hdim:])
    cand = x3[:, 2 * hdim:] + jnp.dot(r * h, wc_ref[:],
                                      preferred_element_type=jnp.float32) \
        + b_ref[0, 2 * hdim:]
    c = jnp.tanh(cand)
    h_new = (1.0 - z) * h + z * c

    valid = (lens_ref[:] > t)
    h_keep = jnp.where(valid, h_new, h)
    h_scr[:] = h_keep
    out_ref[0] = jnp.where(valid, h_new, jnp.zeros_like(h_new))
    hT_ref[:] = h_keep


def _gru_ref(x3, lens2d, w, bias2d):
    b, T, three_h = x3.shape
    h = three_h // 3
    lens = lens2d.reshape(b)
    xt = jnp.moveaxis(x3, 1, 0)

    def body(carry, inp):
        t, x_t = inp
        hh = carry
        zr = x_t[:, :2*h] + hh @ w[:, :2*h] + bias2d[0, :2*h]
        z = _sigmoid(zr[:, :h])
        r = _sigmoid(zr[:, h:])
        cand = x_t[:, 2*h:] + (r * hh) @ w[:, 2*h:] + bias2d[0, 2*h:]
        h_new = (1.0 - z) * hh + z * jnp.tanh(cand)
        valid = (t < lens)[:, None]
        h_keep = jnp.where(valid, h_new, hh)
        return h_keep, jnp.where(valid, h_new, 0.0)

    hT, outs = jax.lax.scan(
        body, jnp.zeros((b, h), x3.dtype),
        (jnp.arange(T, dtype=jnp.int32), xt))
    return jnp.moveaxis(outs, 0, 1), hT


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _gru_pallas(x3, lens2d, w, bias2d, interpret):
    b, T, three_h = x3.shape
    h = three_h // 3
    xt = jnp.moveaxis(x3, 1, 0)
    out, hT = _gru_call(xt, lens2d, w, bias2d, b, T, three_h, h, interpret)
    return jnp.moveaxis(out, 0, 1), hT


def _gru_fwd(x3, lens2d, w, bias2d, interpret):
    out, vjp = jax.vjp(_gru_ref, x3, lens2d, w, bias2d)
    return out, (vjp, lens2d.shape)


def _gru_bwd(interpret, res, ct):
    vjp, lens_shape = res
    gx3, _, gw, gb = vjp(ct)
    glens = jnp.zeros(lens_shape, jax.dtypes.float0)
    return gx3, glens, gw, gb


_gru_pallas.defvjp(_gru_fwd, _gru_bwd)


def gru_sequence(x3: jnp.ndarray, lengths: jnp.ndarray, w: jnp.ndarray,
                 bias: Optional[jnp.ndarray], *,
                 interpret: bool = False
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x3: [b, T, 3h]; w: [h, 3h] (gates [h,2h] | cand [h,h]).
    Returns (h_seq [b,T,h], hT [b,h])."""
    b, T, three_h = x3.shape
    lens = lengths.astype(jnp.int32).reshape(b, 1)
    b_arr = (bias if bias is not None
             else jnp.zeros((three_h,), jnp.float32)).reshape(1, three_h) \
        .astype(jnp.float32)
    return _gru_pallas(x3.astype(jnp.float32), lens, w.astype(jnp.float32),
                       b_arr, interpret)


def _gru_call(xt, lens, w, b_arr, b, T, three_h, h, interpret):
    return pl.pallas_call(
        _gru_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),            # lens
            pl.BlockSpec((1, b, three_h), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),            # wg [h,2h]
            pl.BlockSpec(memory_space=pltpu.VMEM),            # wc [h,h]
            pl.BlockSpec(memory_space=pltpu.VMEM),            # bias [1,3h]
        ],
        out_specs=[
            pl.BlockSpec((1, b, h), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, b, h), jnp.float32),
            jax.ShapeDtypeStruct((b, h), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((b, h), jnp.float32)],
        interpret=interpret,
    )(lens, xt, w[:, :2 * h], w[:, 2 * h:], b_arr)


# ---------------------------------------------------------------------------
# dispatch


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


_VMEM_BUDGET = 12 * 1024 * 1024   # ~16 MB/core minus headroom


def _vmem_bytes(b: int, h: int, gates: int) -> int:
    """Rough VMEM residency of the fused kernel: weights + one x block +
    out block + state scratches/outputs, all f32."""
    gh = gates * h
    return 4 * (h * gh          # recurrent weight
                + b * gh        # x4/x3 time block
                + gh            # bias
                + 3 * h         # peephole
                + b * h * 4)    # out block + final states + scratches


def pallas_ok(b: int, h: int, act: str, gate_act: str,
              state_act: str = "tanh", gates: int = 4) -> bool:
    """Use the fused kernel only for tile-friendly shapes that FIT in VMEM
    and default activations (everything else keeps the lax.scan path)."""
    import os
    if os.environ.get("PADDLE_TPU_NO_PALLAS"):
        return False
    return (_on_tpu() and act == "tanh" and gate_act == "sigmoid"
            and state_act == "tanh" and h % 128 == 0 and b % 8 == 0
            and _vmem_bytes(b, h, gates) <= _VMEM_BUDGET)
