"""Pooling ops — reference: paddle/gserver/layers/PoolLayer (max/avg,
CudnnPoolLayer), SpatialPyramidPoolLayer, MaxOutLayer; hl_cnn.h pooling
kernels. lax.reduce_window lowers these onto the VPU."""

from __future__ import annotations

from typing import Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import lax


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _ceil_pads(in_size: int, kernel: int, stride: int, padding: int,
               ceil_mode: bool = True):
    """Caffe ceil-mode window arithmetic (reference PoolLayer /
    config_parser pooling output size): out = ceil((in - k + 2p)/s) + 1,
    clipped so the last window starts inside in+p; returns (out,
    (left_pad, right_pad)) with the asymmetric right pad that makes
    reduce_window produce exactly `out` windows. ceil_mode=False is the
    img_pool_layer ceil_mode flag (floor arithmetic — and on TPU the
    floor chain 56/28/14/7 tiles the 8-sublane register file exactly,
    where ceil's 57/29/15 pads every map ~12%)."""
    out = pool_out_size(in_size, kernel, stride, padding, ceil_mode)
    right = (out - 1) * stride + kernel - in_size - padding
    return out, (padding, max(right, 0))


def max_pool2d(x: jnp.ndarray, kernel, stride=None, padding=0,
               ceil_mode: bool = True) -> jnp.ndarray:
    """x: [N,H,W,C]. Ceil-mode (caffe) window arithmetic like the
    reference's PoolLayer."""
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride if stride is not None else kernel)
    ph, pw = _pair(padding)
    _, pads_h = _ceil_pads(x.shape[1], kh, sh, ph, ceil_mode)
    _, pads_w = _ceil_pads(x.shape[2], kw, sw, pw, ceil_mode)
    return lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max, (1, kh, kw, 1), (1, sh, sw, 1),
        ((0, 0), pads_h, pads_w, (0, 0)))


def avg_pool2d(x: jnp.ndarray, kernel, stride=None, padding=0,
               exclude_padding: bool = True,
               ceil_mode: bool = True) -> jnp.ndarray:
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride if stride is not None else kernel)
    ph, pw = _pair(padding)
    _, pads_h = _ceil_pads(x.shape[1], kh, sh, ph, ceil_mode)
    _, pads_w = _ceil_pads(x.shape[2], kw, sw, pw, ceil_mode)
    dims, strides = (1, kh, kw, 1), (1, sh, sw, 1)
    pads = ((0, 0), pads_h, pads_w, (0, 0))
    sums = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
    if exclude_padding and any(p != (0, 0) for p in pads):
        ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
        counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
        return sums / jnp.maximum(counts, 1.0)
    return sums / float(kh * kw)


def global_avg_pool(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(x, axis=(1, 2))


def pool_out_size(in_size: int, kernel: int, stride: int, padding: int,
                  ceil_mode: bool = True) -> int:
    """config_parser.py pooling output size (caffe ceil mode + clip: the
    last window must start inside in+p)."""
    if ceil_mode:
        out = int(np.ceil((in_size - kernel + 2 * padding) / stride)) + 1
    else:
        out = (in_size - kernel + 2 * padding) // stride + 1
    if padding > 0 and (out - 1) * stride >= in_size + padding:
        out -= 1
    return out


def maxout(x: jnp.ndarray, groups: int) -> jnp.ndarray:
    """MaxOutLayer: [N,H,W,C] -> max over `groups` channel groups ->
    [N,H,W,C//groups]."""
    n, h, w, c = x.shape
    assert c % groups == 0
    return jnp.max(x.reshape(n, h, w, c // groups, groups), axis=-1)


def spatial_pyramid_pool(x: jnp.ndarray, pyramid_height: int,
                         pool_type: str = "max") -> jnp.ndarray:
    """SPP (SpatialPyramidPoolLayer): levels 1x1, 2x2, ... 2^(h-1) bins,
    concatenated. Output [N, C * sum(4^l)].

    Adaptive binning (bin boundaries computed per level from the static
    spatial dims) so the output size ALWAYS matches C * sum(4^l), even when
    a level has more bins than pixels — bins then overlap/repeat pixels,
    matching reference behavior of degenerate windows.
    """
    n, h, w, c = x.shape
    outs = []
    for lvl in range(pyramid_height):
        bins = 2 ** lvl
        hb = np.linspace(0, h, bins + 1)
        wb = np.linspace(0, w, bins + 1)
        for bi in range(bins):
            h0, h1 = int(np.floor(hb[bi])), int(np.ceil(hb[bi + 1]))
            h1 = max(h1, h0 + 1)
            h0 = min(h0, h - 1)
            for bj in range(bins):
                w0, w1 = int(np.floor(wb[bj])), int(np.ceil(wb[bj + 1]))
                w1 = max(w1, w0 + 1)
                w0 = min(w0, w - 1)
                region = x[:, h0:h1, w0:w1, :]
                if pool_type == "max":
                    outs.append(jnp.max(region, axis=(1, 2)))
                else:
                    outs.append(jnp.mean(region, axis=(1, 2)))
    return jnp.concatenate(outs, axis=-1)


def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


def max_pool3d(x: jnp.ndarray, kernel, stride=None, padding=0) -> jnp.ndarray:
    """x: [N,D,H,W,C] (Pool3DLayer); same ceil-mode arithmetic as 2D."""
    kd, kh, kw = _triple(kernel)
    sd, sh, sw = _triple(stride if stride is not None else kernel)
    pd, ph, pw = _triple(padding)
    _, pads_d = _ceil_pads(x.shape[1], kd, sd, pd)
    _, pads_h = _ceil_pads(x.shape[2], kh, sh, ph)
    _, pads_w = _ceil_pads(x.shape[3], kw, sw, pw)
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, kd, kh, kw, 1), (1, sd, sh, sw, 1),
        ((0, 0), pads_d, pads_h, pads_w, (0, 0)))


def avg_pool3d(x: jnp.ndarray, kernel, stride=None, padding=0) -> jnp.ndarray:
    kd, kh, kw = _triple(kernel)
    sd, sh, sw = _triple(stride if stride is not None else kernel)
    pd, ph, pw = _triple(padding)
    _, pads_d = _ceil_pads(x.shape[1], kd, sd, pd)
    _, pads_h = _ceil_pads(x.shape[2], kh, sh, ph)
    _, pads_w = _ceil_pads(x.shape[3], kw, sw, pw)
    dims, strides = (1, kd, kh, kw, 1), (1, sd, sh, sw, 1)
    pads = ((0, 0), pads_d, pads_h, pads_w, (0, 0))
    sums = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
    ones = jnp.ones(x.shape[:4] + (1,), x.dtype)
    counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
    return sums / jnp.maximum(counts, 1.0)
