"""Convolution ops.

Replaces the reference's conv stack — paddle/function/{GemmConvOp,Im2Col,
DepthwiseConvOp,NaiveConvOp}, gserver ExpandConvLayer/CudnnConvLayer and the
hl_cnn.h CUDA kernels — with lax.conv_general_dilated, which XLA lowers
straight onto the MXU. Data layout is NHWC (TPU-preferred), weights HWIO.
The reference's NCHW<->NHWC SwitchOp is unnecessary internally; feeds arrive
flat [batch, c*h*w] (paddle image convention, channel-major) and are reshaped
at the data boundary.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.linear import compute_dtype


def _prec():
    import jax
    return None if compute_dtype() != jnp.float32 else jax.lax.Precision.HIGHEST


def _pair(v: Union[int, Sequence[int]]) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def conv2d(x: jnp.ndarray, w: jnp.ndarray, *, stride=1, padding=0,
           dilation=1, groups: int = 1) -> jnp.ndarray:
    """x: [N,H,W,C], w: [kh,kw,C//groups,OC] -> [N,H',W',OC]."""
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)
    cd = compute_dtype()
    # mixed precision: output follows the compute dtype, not a (possibly
    # f32) input — same policy as ops/linear.matmul
    out_dtype = x.dtype if cd == jnp.float32 else cd
    # On the bf16 path we must NOT pass preferred_element_type: the conv
    # VJP rule can't transpose mixed (bf16 operand, f32 cotangent) convs.
    # The MXU accumulates bf16 passes in f32 internally either way.
    pet = jnp.float32 if cd == jnp.float32 else None
    if cd != jnp.float32:
        x = x.astype(cd)
        w = w.astype(cd)
    y = lax.conv_general_dilated(
        x, w,
        window_strides=(sh, sw),
        padding=((ph, ph), (pw, pw)),
        rhs_dilation=(dh, dw),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
        precision=_prec(),
        preferred_element_type=pet,
    )
    return y.astype(out_dtype)


def conv2d_transpose(x: jnp.ndarray, w: jnp.ndarray, *, stride=1, padding=0) -> jnp.ndarray:
    """Deconv / transposed conv (ExpandConvTransLayer). w: [kh,kw,OC,IC]
    stored like forward conv with in/out swapped."""
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    kh, kw = w.shape[0], w.shape[1]
    cd = compute_dtype()
    # mixed precision: output follows the compute dtype, not a (possibly
    # f32) input — same policy as ops/linear.matmul
    out_dtype = x.dtype if cd == jnp.float32 else cd
    pet = jnp.float32 if cd == jnp.float32 else None
    if cd != jnp.float32:
        x = x.astype(cd)
        w = w.astype(cd)
    y = lax.conv_transpose(
        x, w,
        strides=(sh, sw),
        padding=((kh - 1 - ph, kh - 1 - ph), (kw - 1 - pw, kw - 1 - pw)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        precision=_prec(),
        preferred_element_type=pet,
    )
    return y.astype(out_dtype)


def conv3d(x: jnp.ndarray, w: jnp.ndarray, *, stride=1, padding=0) -> jnp.ndarray:
    """x: [N,D,H,W,C], w: [kd,kh,kw,IC,OC] (Conv3DLayer)."""
    if isinstance(stride, int):
        stride = (stride,) * 3
    if isinstance(padding, int):
        padding = (padding,) * 3
    pads = tuple((p, p) for p in padding)
    cd = compute_dtype()
    # mixed precision: output follows the compute dtype, not a (possibly
    # f32) input — same policy as ops/linear.matmul
    out_dtype = x.dtype if cd == jnp.float32 else cd
    pet = jnp.float32 if cd == jnp.float32 else None
    if cd != jnp.float32:
        x = x.astype(cd)
        w = w.astype(cd)
    y = lax.conv_general_dilated(
        x, w, window_strides=tuple(stride), padding=pads,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        precision=_prec(),
        preferred_element_type=pet)
    return y.astype(out_dtype)


def conv_out_size(in_size: int, kernel: int, stride: int, padding: int,
                  dilation: int = 1, caffe_mode: bool = True) -> int:
    """Output spatial size. Reference: config_parser.py cnn_output_size —
    caffe_mode floor((i + 2p - k)/s) + 1; else ceil variant."""
    eff_k = dilation * (kernel - 1) + 1
    if caffe_mode:
        return (in_size + 2 * padding - eff_k) // stride + 1
    return (in_size + 2 * padding - eff_k + stride - 1) // stride + 1


def im2col(x: jnp.ndarray, kernel, stride=1, padding=0) -> jnp.ndarray:
    """Patch extraction (BlockExpandLayer / Im2Col) -> [N, H', W', kh*kw*C]."""
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    return lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), ((ph, ph), (pw, pw)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def row_conv(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """Lookahead row convolution (paddle/function/RowConvOp, RowConvLayer).

    x: [batch, time, d]; w: [context, d]. y[t] = sum_{i<context} x[t+i] * w[i].
    """
    context = w.shape[0]
    d = x.shape[-1]
    # depthwise conv over time with right-side (future) context; HWIO layout
    # for feature_group_count=d is [kh, kw, 1, d]
    xt = x[:, :, None, :]                      # [N, T, 1, d]
    wt = w[:, None, None, :]                   # [context, 1, 1, d]
    y = lax.conv_general_dilated(
        xt, wt, window_strides=(1, 1),
        padding=((0, context - 1), (0, 0)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=d)
    return y[:, :, 0, :]


def conv3d_transpose(x: jnp.ndarray, w: jnp.ndarray, *, stride=1,
                     padding=0) -> jnp.ndarray:
    """x: [N,D,H,W,C], w: [kd,kh,kw,IC,OC] (DeConv3DLayer) — same
    fractionally-strided form as conv2d_transpose above."""
    if isinstance(stride, int):
        stride = (stride,) * 3
    if isinstance(padding, int):
        padding = (padding,) * 3
    k = w.shape[:3]
    pads = tuple((k[i] - 1 - padding[i], k[i] - 1 - padding[i])
                 for i in range(3))
    cd = compute_dtype()
    # mixed precision: output follows the compute dtype, not a (possibly
    # f32) input — same policy as ops/linear.matmul
    out_dtype = x.dtype if cd == jnp.float32 else cd
    pet = jnp.float32 if cd == jnp.float32 else None
    if cd != jnp.float32:
        x = x.astype(cd)
        w = w.astype(cd)
    y = lax.conv_transpose(
        x, w, strides=tuple(stride), padding=pads,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        precision=_prec(),
        preferred_element_type=pet)
    return y.astype(out_dtype)
