"""Pure functional ops — the compute substrate.

Replaces paddle/math (25k LoC) + paddle/cuda (20k LoC) + paddle/function
(11k LoC): every hand-written CUDA/SSE kernel family becomes a jnp/lax
expression XLA fuses and tiles onto MXU/VPU; the few genuinely hot fused
loops (LSTM cell, top-k beam step) get Pallas kernels in ops/pallas_kernels.py.
"""

from paddle_tpu.ops import activations, linear, conv, pool, norm, cost
from paddle_tpu.ops import sequence_ops, embedding, recurrent
