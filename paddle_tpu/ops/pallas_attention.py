"""Fused flash attention in Pallas — the TPU hot-loop for attention.

Like ops/pallas_rnn.py, this is the one-hop-beyond-XLA fusion: plain
attention materializes the [b, h, Tq, Tk] score matrix in HBM (the
quadratic term that kills long sequences); this kernel streams K/V blocks
through VMEM with online softmax, computing the padding/causal mask
IN-KERNEL from per-row lengths, so primal HBM traffic is linear in
sequence length. Single-chip counterpart of
parallel/sequence_parallel.py's ring attention (the same online-softmax
update run across chips).

Semantics match parallel/sequence_parallel.attention with a
lengths+causal mask exactly (tests assert parity): padded K/V positions
are ignored, q rows at/past their length return 0. Training is fused
both directions (FlashAttention-2 style): the forward saves only the
per-row logsumexp; the backward kernels recompute each block's softmax
from it while streaming dq per q-block and dk/dv per k-block, so HBM
stays linear in T in BOTH passes (~2.8x XLA on the T=4096 train step
with the round-5 exp2 softmax — see docs/perf.md; the round-2 version
fell back to the quadratic XLA vjp). Beyond one chip, ring attention
over the `sp` mesh axis shards the same math.

Used automatically by the attention layer on TPU for tile-friendly
shapes (head_dim % 8 == 0); `interpret=True` runs on CPU for tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LOG2E = 1.4426950408889634     # log2(e): fold into the dot scale so the
LN2 = 0.6931471805599453       # online softmax runs in exp2 (one fewer
                               # VPU pass per tile than exp)


def _flash_kernel(lens_ref, q_ref, k_ref, v_ref, out_ref, *refs,
                  scale, nk, block_q, block_k, causal, save_lse):
    # the logsumexp residual is only written on the training path; the
    # primal/inference call skips the [bh, Tq, 128] f32 stream entirely
    if save_lse:
        lse_ref, acc_scr, m_scr, l_scr = refs
    else:
        acc_scr, m_scr, l_scr = refs
        lse_ref = None
    j = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # block-skip: nothing to do when this K block is entirely past the
    # row's kv_len, or (causal) entirely above the diagonal
    i = pl.program_id(0)
    q_len = lens_ref[i, 0]
    kv_len = lens_ref[i, 1]
    needed = kk * block_k < kv_len
    if causal:
        needed = needed & (kk * block_k <= j * block_q + block_q - 1)

    # an interior tile needs NO mask at all: every row is under q_len,
    # every col under kv_len, and (causal) the whole tile sits at or
    # below the diagonal — skipping the iota/compare/where VPU work there
    # is the standard flash fast path (most tiles are interior)
    interior = (j * block_q + block_q <= q_len) & \
        (kk * block_k + block_k <= kv_len)
    if causal:
        interior = interior & (kk * block_k + block_k - 1 <= j * block_q)

    def _online_update(s2, p_mask, prec, v):
        """s2 is in BASE-2 units (the dot scale carries log2(e)), so the
        softmax runs on exp2 — the multiply by log2e rides the matmul
        epilogue instead of costing a VPU pass over every [bq, bk] tile.
        m/l scratches hold base-2 running max / exp2-sum; _finish
        converts the logsumexp back to natural units for the backward.
        (A deferred any-row-changed rescale was also tried here and
        REJECTED: the per-tile scalar branch costs more than the two
        rescale passes it saves — numbers in docs/perf.md.)"""
        m_old = m_scr[:]                              # [bq, 128] (bcast)
        s_max = jnp.max(s2, axis=-1, keepdims=True)   # [bq, 1]
        m_new = jnp.maximum(m_old, s_max)             # [bq, 128]
        alpha = jnp.exp2(m_old[:, 0:1] - m_new[:, 0:1])
        p = jnp.exp2(s2 - m_new[:, 0:1])              # [bq, bk]
        if p_mask is not None:
            # explicit zero on masked entries: with a finite NEG_INF, a
            # row masked in EVERY block would otherwise see
            # exp2(s - m) == 1 junk
            p = jnp.where(p_mask, p, 0.0)
        l_new = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32,
            precision=prec)
        m_scr[:] = m_new
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(needed & interior)
    def _fast_block():
        q = q_ref[0]                                  # [bq, d]
        k = k_ref[0]                                  # [bk, d]
        v = v_ref[0]                                  # [bk, d]
        prec = jax.lax.Precision.HIGHEST if q.dtype == jnp.float32 else None
        s2 = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=prec) * (scale * LOG2E)
        _online_update(s2, None, prec, v)

    @pl.when(needed & ~interior)
    def _masked_block():
        q = q_ref[0]                                  # [bq, d]
        k = k_ref[0]                                  # [bk, d]
        v = v_ref[0]                                  # [bk, d]
        # dots in the input dtype (bf16 rides the MXU single-pass), f32
        # accumulation; HIGHEST keeps f32 inputs full-precision
        # (ops/linear convention — default truncates even f32 operands)
        # but is only legal on f32 operands
        prec = jax.lax.Precision.HIGHEST if q.dtype == jnp.float32 else None
        s2 = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=prec) * (scale * LOG2E)

        # in-kernel mask from lengths (+causal) — nothing quadratic in HBM
        rows = j * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = kk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = (rows < q_len) & (cols < kv_len)
        if causal:
            valid = valid & (cols <= rows)
        s2 = jnp.where(valid, s2, NEG_INF)            # [bq, bk]
        _online_update(s2, valid, prec, v)

    @pl.when(kk == nk - 1)
    def _finish():
        l = l_scr[:][:, 0:1]
        out_ref[0] = jnp.where(l > 0.0, acc_scr[:] / jnp.maximum(l, 1e-30),
                               0.0).astype(out_ref.dtype)
        if save_lse:
            # logsumexp per row in NATURAL units (the backward contract):
            # m is base-2, l is an exp2 sum -> lse = m*ln2 + ln(l)
            m = m_scr[:][:, 0:1]
            lse = jnp.where(l > 0.0,
                            m * LN2 + jnp.log(jnp.maximum(l, 1e-30)),
                            NEG_INF)
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _flash_call(q3, k3, v3, lens2, *, scale, block_q, block_k, causal,
                interpret, save_lse=True):
    """q3: [bh, Tq, d]; k3/v3: [bh, Tk, d]; lens2: [bh, 2] int32
    (q_len, kv_len per row). Returns (out, lse[bh, Tq, 128]) with
    save_lse, else just out."""
    bh, tq, d = q3.shape
    tk = k3.shape[1]
    nq = tq // block_q
    nk = tk // block_k

    kernel = functools.partial(_flash_kernel, scale=scale, nk=nk,
                               block_q=block_q, block_k=block_k,
                               causal=causal, save_lse=save_lse)
    lse_specs = [pl.BlockSpec((1, block_q, 128),
                              lambda i, j, kk: (i, j, 0))] if save_lse else []
    lse_shapes = [jax.ShapeDtypeStruct((bh, tq, 128), jnp.float32)] \
        if save_lse else []
    outs = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),    # lens [bh, 2], whole
            pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, kk, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
        ] + lse_specs,
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q3.dtype),
        ] + lse_shapes,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(lens2, q3, k3, v3)
    return outs if save_lse else (outs[0], None)


# ---------------------------------------------------------------------------
# backward kernels (FlashAttention-2 style): recompute the block softmax
# from the saved logsumexp, stream dq per q-block and dk/dv per k-block —
# HBM stays linear in T, replacing the quadratic XLA vjp


def _recompute_p(q, k, lens_row, lse, jq, kk, *, scale, block_q, block_k,
                 causal):
    """exp(S - lse) for one (q block, k block) tile, fully masked.
    Computed as exp2((S - lse) * log2e) with log2e folded into the dot
    scale — the same VPU-pass saving as the forward; lse (natural units)
    scales by log2e on its cheap [bq, 1] column only."""
    prec = jax.lax.Precision.HIGHEST if q.dtype == jnp.float32 else None
    s2 = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32,
                             precision=prec) * (scale * LOG2E)
    rows = jq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = kk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = (rows < lens_row[0]) & (cols < lens_row[1])
    if causal:
        valid = valid & (cols <= rows)
    p = jnp.where(valid, jnp.exp2(s2 - lse * LOG2E), 0.0)
    return p, valid, prec


def _flash_bwd_dq_kernel(lens_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         dd_ref, dq_ref, dq_scr, *, scale, nk, block_q,
                         block_k, causal):
    i = pl.program_id(0)
    j = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    needed = kk * block_k < lens_ref[i, 1]
    if causal:
        needed = needed & (kk * block_k <= j * block_q + block_q - 1)

    @pl.when(needed)
    def _block():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0:1]
        dd = dd_ref[0][:, 0:1]
        p, valid, prec = _recompute_p(
            q, k, (lens_ref[i, 0], lens_ref[i, 1]), lse, j, kk, scale=scale,
            block_q=block_q, block_k=block_k, causal=causal)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=prec)
        ds = p * (dp - dd) * scale
        dq_scr[:] += jax.lax.dot(ds.astype(k.dtype), k,
                                 preferred_element_type=jnp.float32,
                                 precision=prec)

    @pl.when(kk == nk - 1)
    def _done():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(lens_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                          dd_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, scale,
                          nq, block_q, block_k, causal):
    i = pl.program_id(0)
    kk = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    needed = j * block_q < lens_ref[i, 0]
    if causal:
        needed = needed & (j * block_q + block_q - 1 >= kk * block_k)

    @pl.when(needed)
    def _block():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0:1]
        dd = dd_ref[0][:, 0:1]
        p, valid, prec = _recompute_p(
            q, k, (lens_ref[i, 0], lens_ref[i, 1]), lse, j, kk, scale=scale,
            block_q=block_q, block_k=block_k, causal=causal)
        # dV += P^T dO ; dK += dS^T Q
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=prec)
        ds = p * (dp - dd) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)

    @pl.when(j == nq - 1)
    def _done():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_grads(q3, k3, v3, do3, out3, lse, lens2, *, scale, block_q,
                 block_k, causal, interpret):
    bh, tq, d = q3.shape
    tk = k3.shape[1]
    nq = tq // block_q
    nk = tk // block_k
    dd = jnp.sum(do3.astype(jnp.float32) * out3.astype(jnp.float32),
                 axis=-1, keepdims=True)                      # [bh, tq, 1]
    dd = jnp.broadcast_to(dd, (bh, tq, 128))

    common_in = [
        pl.BlockSpec(memory_space=pltpu.SMEM),                # lens
    ]
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, nk=nk,
                          block_q=block_q, block_k=block_k, causal=causal),
        grid=(bh, nq, nk),
        in_specs=common_in + [
            pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_q, 128), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_q, 128), lambda i, j, kk: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(lens2, q3, k3, v3, do3, lse, dd)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale, nq=nq,
                          block_q=block_q, block_k=block_k, causal=causal),
        grid=(bh, nk, nq),
        in_specs=common_in + [
            pl.BlockSpec((1, block_q, d), lambda i, kk, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kk, j: (i, kk, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kk, j: (i, kk, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, kk, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 128), lambda i, kk, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 128), lambda i, kk, j: (i, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, kk, j: (i, kk, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kk, j: (i, kk, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(lens2, q3, k3, v3, do3, lse, dd)
    return dq, dk, dv


def _lens_mask(q_lens, kv_lens, tq, tk, causal):
    """[b, Tq, Tk] bool mask equivalent to the in-kernel computation."""
    rows = jnp.arange(tq, dtype=jnp.int32)
    cols = jnp.arange(tk, dtype=jnp.int32)
    m = (rows[None, :, None] < q_lens[:, None, None]) & \
        (cols[None, None, :] < kv_lens[:, None, None])
    if causal:
        m = m & (cols[None, None, :] <= rows[None, :, None])
    return m


def _reference(q, k, v, mask, scale):
    """XLA attention — also the custom_vjp backward (see module docstring)."""
    prec = jax.lax.Precision.HIGHEST if q.dtype == jnp.float32 else None
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32,
                        precision=prec) * scale
    if mask is not None:
        logits = jnp.where(mask[:, None, :, :], logits, NEG_INF)
    w = jax.nn.softmax(logits, axis=-1)
    if mask is not None:
        # fully-masked rows: softmax over all -inf is uniform; zero them
        any_valid = jnp.any(mask, axis=-1)[:, None, :, None]
        w = jnp.where(any_valid, w, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v,
                      preferred_element_type=jnp.float32,
                      precision=prec).astype(q.dtype)


def _to_heads(x):
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _from_heads(x3, b, h):
    bh, t, d = x3.shape
    return x3.reshape(b, h, t, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, q_lens, kv_lens, causal, scale, block_q, block_k,
           interpret):
    b, tq, h, d = q.shape
    lens2 = jnp.stack([q_lens, kv_lens], axis=1).astype(jnp.int32)  # [b, 2]
    lens2 = jnp.repeat(lens2, h, axis=0)                            # [bh, 2]
    out, _ = _flash_call(_to_heads(q), _to_heads(k), _to_heads(v), lens2,
                         scale=scale, block_q=block_q,
                         block_k=block_k, causal=causal,
                         interpret=interpret, save_lse=False)
    return _from_heads(out, b, h)


def _flash_fwd(q, k, v, q_lens, kv_lens, causal, scale, block_q, block_k,
               interpret):
    b, tq, h, d = q.shape
    lens2 = jnp.stack([q_lens, kv_lens], axis=1).astype(jnp.int32)
    lens2 = jnp.repeat(lens2, h, axis=0)
    q3, k3, v3 = _to_heads(q), _to_heads(k), _to_heads(v)
    out3, lse = _flash_call(q3, k3, v3, lens2, scale=scale, block_q=block_q,
                            block_k=block_k, causal=causal,
                            interpret=interpret)
    return _from_heads(out3, b, h), (q3, k3, v3, out3, lse, lens2, b, h)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, ct):
    """Streaming FlashAttention-2 backward: dq per q-block, dk/dv per
    k-block, block softmax recomputed from the saved logsumexp — HBM
    linear in T (replaces the quadratic XLA vjp the round-2 version ran)."""
    q3, k3, v3, out3, lse, lens2, b, h = res
    do3 = _to_heads(ct)
    dq3, dk3, dv3 = _flash_grads(
        q3, k3, v3, do3, out3, lse, lens2, scale=scale, block_q=block_q,
        block_k=block_k, causal=causal, interpret=interpret)
    return (_from_heads(dq3, b, h), _from_heads(dk3, b, h),
            _from_heads(dv3, b, h), None, None)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    q_lens: Optional[jnp.ndarray] = None,
                    kv_lens: Optional[jnp.ndarray] = None,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: bool = False) -> jnp.ndarray:
    """Flash attention with ragged-length + causal masking in-kernel.

    q: [b, Tq, h, d]; k, v: [b, Tk, h, d]; q_lens / kv_lens: [b] int
    valid lengths (None = full). Returns [b, Tq, h, d]; q rows at/past
    q_lens are zero. Inputs are padded to block multiples internally.
    """
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = scale if scale is not None else d ** -0.5
    if q_lens is None:
        q_lens = jnp.full((b,), tq, jnp.int32)
    if kv_lens is None:
        kv_lens = jnp.full((b,), tk, jnp.int32)
    # round blocks UP to a multiple of 8 (sublane tile) so the compiled
    # Mosaic path never sees ragged block shapes; the inputs are padded
    # to block multiples right below, so rounding is always safe
    block_q = min(block_q, -(-max(tq, 8) // 8) * 8)
    block_k = min(block_k, -(-max(tk, 8) // 8) * 8)
    pad_q = (-tq) % block_q
    pad_k = (-tk) % block_k
    if pad_q or pad_k:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
    out = _flash(q, k, v, q_lens, kv_lens, causal, scale, block_q, block_k,
                 interpret)
    if pad_q:
        out = out[:, :tq]
    return out


def flash_supported(q: jnp.ndarray, k: jnp.ndarray) -> bool:
    """Shape gate: MXU-friendly head dim and a sequence long enough that
    streaming K/V beats one fused XLA softmax."""
    d = q.shape[-1]
    return d % 8 == 0 and q.shape[1] >= 8 and k.shape[1] >= 8
