"""Fused flash attention in Pallas — the TPU hot-loop for attention.

Like ops/pallas_rnn.py, this is the one-hop-beyond-XLA fusion: plain
attention materializes the [b, h, Tq, Tk] score matrix in HBM (the
quadratic term that kills long sequences); this kernel streams K/V blocks
through VMEM with online softmax, computing the padding/causal mask
IN-KERNEL from per-row lengths, so primal HBM traffic is linear in
sequence length. Single-chip counterpart of
parallel/sequence_parallel.py's ring attention (the same online-softmax
update run across chips).

Semantics match parallel/sequence_parallel.attention with a
lengths+causal mask exactly (tests assert parity): padded K/V positions
are ignored, q rows at/past their length return 0. The kernel is the
PRIMAL path; under jax.grad the custom_vjp recomputes with the XLA
reference, which IS quadratic in memory — long-sequence TRAINING should
shard over the `sp` mesh axis (ring attention) instead, as the docs say.

Used automatically by the attention layer on TPU for tile-friendly
shapes (head_dim % 8 == 0); `interpret=True` runs on CPU for tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_kernel(lens_ref, q_ref, k_ref, v_ref, out_ref,
                  acc_scr, m_scr, l_scr, *, scale, nk, block_q, block_k,
                  causal):
    j = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # block-skip: nothing to do when this K block is entirely past the
    # row's kv_len, or (causal) entirely above the diagonal
    i = pl.program_id(0)
    q_len = lens_ref[i, 0]
    kv_len = lens_ref[i, 1]
    needed = kk * block_k < kv_len
    if causal:
        needed = needed & (kk * block_k <= j * block_q + block_q - 1)

    @pl.when(needed)
    def _block():
        q = q_ref[0]                                  # [bq, d]
        k = k_ref[0]                                  # [bk, d]
        v = v_ref[0]                                  # [bk, d]
        # dots in the input dtype (bf16 rides the MXU single-pass), f32
        # accumulation; HIGHEST keeps f32 inputs full-precision
        # (ops/linear convention — default truncates even f32 operands)
        # but is only legal on f32 operands
        prec = jax.lax.Precision.HIGHEST if q.dtype == jnp.float32 else None
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=prec) * scale

        # in-kernel mask from lengths (+causal) — nothing quadratic in HBM
        rows = j * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = kk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = (rows < q_len) & (cols < kv_len)
        if causal:
            valid = valid & (cols <= rows)
        s = jnp.where(valid, s, NEG_INF)              # [bq, bk]

        m_old = m_scr[:]                              # [bq, 128] (bcast)
        s_max = jnp.max(s, axis=-1, keepdims=True)    # [bq, 1]
        m_new = jnp.maximum(m_old, s_max)             # [bq, 128]
        alpha = jnp.exp(m_old[:, 0:1] - m_new[:, 0:1])
        # explicit zero on masked entries: with a finite NEG_INF, a row
        # masked in EVERY block would otherwise see exp(s - m) == 1 junk
        p = jnp.where(valid, jnp.exp(s - m_new[:, 0:1]), 0.0)  # [bq, bk]
        l_new = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32,
            precision=prec)
        m_scr[:] = m_new
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kk == nk - 1)
    def _finish():
        l = l_scr[:][:, 0:1]
        out_ref[0] = jnp.where(l > 0.0, acc_scr[:] / jnp.maximum(l, 1e-30),
                               0.0).astype(out_ref.dtype)


def _flash_call(q3, k3, v3, lens2, *, scale, block_q, block_k, causal,
                interpret):
    """q3: [bh, Tq, d]; k3/v3: [bh, Tk, d]; lens2: [bh, 2] int32
    (q_len, kv_len per row)."""
    bh, tq, d = q3.shape
    tk = k3.shape[1]
    nq = tq // block_q
    nk = tk // block_k

    kernel = functools.partial(_flash_kernel, scale=scale, nk=nk,
                               block_q=block_q, block_k=block_k,
                               causal=causal)
    return pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),    # lens [bh, 2], whole
            pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, kk, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q3.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(lens2, q3, k3, v3)


def _lens_mask(q_lens, kv_lens, tq, tk, causal):
    """[b, Tq, Tk] bool mask equivalent to the in-kernel computation."""
    rows = jnp.arange(tq, dtype=jnp.int32)
    cols = jnp.arange(tk, dtype=jnp.int32)
    m = (rows[None, :, None] < q_lens[:, None, None]) & \
        (cols[None, None, :] < kv_lens[:, None, None])
    if causal:
        m = m & (cols[None, None, :] <= rows[None, :, None])
    return m


def _reference(q, k, v, mask, scale):
    """XLA attention — also the custom_vjp backward (see module docstring)."""
    prec = jax.lax.Precision.HIGHEST if q.dtype == jnp.float32 else None
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32,
                        precision=prec) * scale
    if mask is not None:
        logits = jnp.where(mask[:, None, :, :], logits, NEG_INF)
    w = jax.nn.softmax(logits, axis=-1)
    if mask is not None:
        # fully-masked rows: softmax over all -inf is uniform; zero them
        any_valid = jnp.any(mask, axis=-1)[:, None, :, None]
        w = jnp.where(any_valid, w, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v,
                      preferred_element_type=jnp.float32,
                      precision=prec).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, q_lens, kv_lens, causal, scale, block_q, block_k,
           interpret):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    q3 = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    lens2 = jnp.stack([q_lens, kv_lens], axis=1).astype(jnp.int32)  # [b, 2]
    lens2 = jnp.repeat(lens2, h, axis=0)                            # [bh, 2]
    out = _flash_call(q3, k3, v3, lens2, scale=scale, block_q=block_q,
                      block_k=block_k, causal=causal, interpret=interpret)
    return out.reshape(b, h, tq, d).transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, q_lens, kv_lens, causal, scale, block_q, block_k,
               interpret):
    out = _flash(q, k, v, q_lens, kv_lens, causal, scale, block_q, block_k,
                 interpret)
    return out, (q, k, v, q_lens, kv_lens)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, ct):
    q, k, v, q_lens, kv_lens = res
    mask = _lens_mask(q_lens, kv_lens, q.shape[1], k.shape[1], causal)
    _, vjp = jax.vjp(lambda q_, k_, v_: _reference(q_, k_, v_, mask, scale),
                     q, k, v)
    dq, dk, dv = vjp(ct)
    return dq, dk, dv, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    q_lens: Optional[jnp.ndarray] = None,
                    kv_lens: Optional[jnp.ndarray] = None,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: bool = False) -> jnp.ndarray:
    """Flash attention with ragged-length + causal masking in-kernel.

    q: [b, Tq, h, d]; k, v: [b, Tk, h, d]; q_lens / kv_lens: [b] int
    valid lengths (None = full). Returns [b, Tq, h, d]; q rows at/past
    q_lens are zero. Inputs are padded to block multiples internally.
    """
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = scale if scale is not None else d ** -0.5
    if q_lens is None:
        q_lens = jnp.full((b,), tq, jnp.int32)
    if kv_lens is None:
        kv_lens = jnp.full((b,), tk, jnp.int32)
    block_q = min(block_q, max(tq, 8))
    block_k = min(block_k, max(tk, 8))
    pad_q = (-tq) % block_q
    pad_k = (-tk) % block_k
    if pad_q or pad_k:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
    out = _flash(q, k, v, q_lens, kv_lens, causal, scale, block_q, block_k,
                 interpret)
    if pad_q:
        out = out[:, :tq]
    return out


def flash_supported(q: jnp.ndarray, k: jnp.ndarray) -> bool:
    """Shape gate: MXU-friendly head dim and a sequence long enough that
    streaming K/V beats one fused XLA softmax."""
    d = q.shape[-1]
    return d % 8 == 0 and q.shape[1] >= 8 and k.shape[1] >= 8
