"""Decode attention: the paged serving path + a recorded Pallas
experiment.

:func:`paged_attention` (bottom) is LIVE — the continuous-batching
engine's per-step attention over the paged KV pool. The Pallas kernel
that opens this file is the round-5 recorded experiment it can compose
with.

Round-5 verdict on the kernel: measured and REJECTED. The decode trace (docs/perf.md,
"the decode gap, traced") showed XLA lowering the per-step attention
(q [b,h,dh] against cached K/V over T positions) to VPU multiply-reduce
fusions at ~160 GB/s effective — the hypothesis was that a Pallas
kernel, which dictates its own block tiling, could stream the cache
with T on the lane axis at full width. Two grid shapes were measured on
device against the einsum path inside the real decode scan (bs32,
T=544, 6 layers):

  - grid (b, h) — one step per row/head: 1.86 ms/step vs 0.92 einsum.
    TPU Pallas grids run SEQUENTIALLY on the core; b*h tiny DMAs
    serialize.
  - grid (g,) — this kernel: whole-batch [b, dh, T] K/V blocks per kv
    group, all GQA query heads inside the step: 1.50 ms/step. Fewer,
    larger DMAs, still loses: Mosaic loops the leading batch dim and
    the per-b [dh, T] reductions pipeline worse than XLA's fused
    lowering of the same math.

The einsum formulation in models/decode.py remains the measured
optimum (two cache-layout variants of it also lost — see perf.md). The
kernel stays here, correct and parity-tested
(tests/test_decode.py::TestPallasDecodeAttention), as the starting
point if a future round wants to hand-tune the Mosaic lowering.

Cache layout contract: [b, g, dh, T].

Round 6 adds the LIVE serving path: :func:`paged_attention`, decode
attention over a PAGED KV cache (fixed-size pages in a preallocated
pool, per-sequence page tables — the PagedAttention design). The page
gather produces the contiguous [b, T, g, dh] view and then runs the
exact einsum formulation above (token-identical to the dense cache by
construction, pinned in tests/test_paged_decode.py), or composes with
the recorded-experiment kernel via ``use_kernel=True`` — both paths
take PER-ROW kv lengths, which is what lets one fixed-shape jitted
step serve ragged sequences (serving/engine.py)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LOG2E = 1.4426950408889634

# per-block VMEM budget for K+V (+ double buffering headroom): beyond
# this the caller falls back to XLA rather than risk a VMEM OOM
_VMEM_BYTES = 8 * 1024 * 1024


def _decode_kernel(lens_ref, q_ref, k_ref, v_ref, out_ref, *, scale,
                   rep):
    k = k_ref[...]                                    # [b, 1, dh, T]
    v = v_ref[...]
    b, _, dh, t = k.shape
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    cols = jax.lax.broadcasted_iota(jnp.int32, (b, 1, 1, t), 3)
    if lens_ref.shape[0] == 1:        # one shared length (dense decode)
        live = cols < lens_ref[0]
    else:                             # per-row lengths (ragged serving)
        live = cols < lens_ref[...].reshape(b, 1, 1, 1)
    for r in range(rep):
        q = q_ref[:, r:r + 1].astype(jnp.float32)     # [b, 1, dh, 1]
        s2 = jnp.sum(q * kf, axis=2, keepdims=True) * (scale * LOG2E)
        s2 = jnp.where(live, s2, NEG_INF)             # [b, 1, 1, T]
        m = jnp.max(s2, axis=3, keepdims=True)
        p = jnp.exp2(s2 - m)                          # [b, 1, 1, T]
        l = jnp.sum(p, axis=3, keepdims=True)
        acc = jnp.sum(vf * p, axis=3, keepdims=True)  # [b, 1, dh, 1]
        out_ref[:, r:r + 1] = (acc / l).astype(out_ref.dtype)


def decode_supported(q, k_cache) -> bool:
    """Tile-friendly and VMEM-sized? dh a sublane multiple; whole-batch
    K+V group blocks within the VMEM budget."""
    b, g, dh, t = k_cache.shape
    esize = jnp.dtype(k_cache.dtype).itemsize
    return dh % 8 == 0 and 2 * b * dh * t * esize <= _VMEM_BYTES


def decode_attention(q, k_cache, v_cache, kv_len, *, scale=None,
                     interpret=False):
    """q [b, h, dh]; k_cache/v_cache [b, g, dh, T] with h % g == 0
    (GQA: h == g*rep); kv_len: traced scalar (shared by every row) or a
    per-row [b] vector — positions >= kv_len are masked (decode calls
    always have each row's query at position kv_len-1, so this IS the
    causal mask). Returns [b, h, dh]."""
    b, h, dh = q.shape
    g = k_cache.shape[1]
    t = k_cache.shape[-1]
    assert h % g == 0, (h, g)
    rep = h // g
    if scale is None:
        scale = dh ** -0.5
    q4 = q.reshape(b, h, dh, 1)
    lens = jnp.asarray(kv_len, jnp.int32).reshape(-1)
    assert lens.shape[0] in (1, b), (lens.shape, b)

    kernel = functools.partial(_decode_kernel, scale=scale, rep=rep)
    out = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),        # lens [1|b]
            pl.BlockSpec((b, rep, dh, 1), lambda j: (0, j, 0, 0)),
            pl.BlockSpec((b, 1, dh, t), lambda j: (0, j, 0, 0)),
            pl.BlockSpec((b, 1, dh, t), lambda j: (0, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((b, rep, dh, 1), lambda j: (0, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, dh, 1), q.dtype),
        interpret=interpret,
    )(lens, q4, k_cache, v_cache)
    return out.reshape(b, h, dh)


# --------------------------------------------------------------- paged
def gather_pages(pages, page_table):
    """Contiguous per-sequence view of a paged pool: ``pages``
    [n_pages, page_size, g, dh] gathered through ``page_table`` [b, P]
    -> [b, P*page_size, g, dh]. Rows of the table beyond a sequence's
    allocation point at the reserved null page (0); the caller's length
    mask keeps those positions out of the softmax."""
    b, pp = page_table.shape
    _, ps, g, dh = pages.shape
    return pages[page_table].reshape(b, pp * ps, g, dh)


def paged_attention(q, k_pages, v_pages, page_table, kv_lens, *,
                    scale=None, use_kernel=False, interpret=False):
    """Decode attention over a PAGED KV cache (the serving engine's hot
    path — serving/engine.py).

    q [b, h, dh]: one query token per sequence (slot batch);
    k_pages/v_pages [n_pages, page_size, g, dh]: the shared page pools
    (h % g == 0 — GQA reads the cache at stored width);
    page_table [b, P] int32: each row maps the sequence's logical pages
    to physical pages (entries past the allocation = the null page 0);
    kv_lens [b] int32: per-row valid positions — position kv_lens[i]-1
    is row i's query, so the mask is both the causal mask AND the
    ragged-length mask. Returns [b, h, dh].

    The gather materializes the same [b, T, g, dh] view the dense cache
    stores, then runs models/decode.py's exact einsum formulation (the
    measured optimum of five — docs/perf.md), so paged decode is
    token-identical to the dense path. ``use_kernel=True`` instead
    transposes the view into the [b, g, dh, T] contract and composes
    with the :func:`decode_attention` GQA kernel."""
    b, h, dh = q.shape
    g = k_pages.shape[2]
    assert h % g == 0, (h, g)
    rep = h // g
    if scale is None:
        scale = dh ** -0.5
    k = gather_pages(k_pages, page_table)              # [b, T, g, dh]
    v = gather_pages(v_pages, page_table)
    lens = jnp.asarray(kv_lens, jnp.int32).reshape(-1)
    if use_kernel:
        kt = k.transpose(0, 2, 3, 1)                   # [b, g, dh, T]
        vt = v.transpose(0, 2, 3, 1)
        return decode_attention(q, kt, vt, lens, scale=scale,
                                interpret=interpret)
    t = k.shape[1]
    # identical formulation (einsum strings, mask value, softmax dtype
    # path) to models/decode.py _block at t=1 — parity is structural
    q5 = q.reshape(b, 1, g, rep, dh)
    logits = jnp.einsum("bqgrd,bkgd->bgrqk", q5,
                        k.astype(q.dtype)) * scale
    mask = jnp.arange(t)[None, :] < lens[:, None]      # [b, T]
    logits = jnp.where(mask[:, None, None, None], logits, NEG_INF)
    w = jax.nn.softmax(logits, axis=-1)
    attn = jnp.einsum("bgrqk,bkgd->bqgrd", w, v.astype(q.dtype))
    return attn.reshape(b, h, dh)
