"""Pallas decode attention — a RECORDED EXPERIMENT, not the live path.

Round-5 verdict: measured and REJECTED. The decode trace (docs/perf.md,
"the decode gap, traced") showed XLA lowering the per-step attention
(q [b,h,dh] against cached K/V over T positions) to VPU multiply-reduce
fusions at ~160 GB/s effective — the hypothesis was that a Pallas
kernel, which dictates its own block tiling, could stream the cache
with T on the lane axis at full width. Two grid shapes were measured on
device against the einsum path inside the real decode scan (bs32,
T=544, 6 layers):

  - grid (b, h) — one step per row/head: 1.86 ms/step vs 0.92 einsum.
    TPU Pallas grids run SEQUENTIALLY on the core; b*h tiny DMAs
    serialize.
  - grid (g,) — this kernel: whole-batch [b, dh, T] K/V blocks per kv
    group, all GQA query heads inside the step: 1.50 ms/step. Fewer,
    larger DMAs, still loses: Mosaic loops the leading batch dim and
    the per-b [dh, T] reductions pipeline worse than XLA's fused
    lowering of the same math.

The einsum formulation in models/decode.py remains the measured
optimum (two cache-layout variants of it also lost — see perf.md). The
kernel stays here, correct and parity-tested
(tests/test_decode.py::TestPallasDecodeAttention), as the starting
point if a future round wants to hand-tune the Mosaic lowering.

Cache layout contract: [b, g, dh, T]."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LOG2E = 1.4426950408889634

# per-block VMEM budget for K+V (+ double buffering headroom): beyond
# this the caller falls back to XLA rather than risk a VMEM OOM
_VMEM_BYTES = 8 * 1024 * 1024


def _decode_kernel(lens_ref, q_ref, k_ref, v_ref, out_ref, *, scale,
                   rep):
    kv_len = lens_ref[0]
    k = k_ref[...]                                    # [b, 1, dh, T]
    v = v_ref[...]
    b, _, dh, t = k.shape
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    cols = jax.lax.broadcasted_iota(jnp.int32, (b, 1, 1, t), 3)
    live = cols < kv_len
    for r in range(rep):
        q = q_ref[:, r:r + 1].astype(jnp.float32)     # [b, 1, dh, 1]
        s2 = jnp.sum(q * kf, axis=2, keepdims=True) * (scale * LOG2E)
        s2 = jnp.where(live, s2, NEG_INF)             # [b, 1, 1, T]
        m = jnp.max(s2, axis=3, keepdims=True)
        p = jnp.exp2(s2 - m)                          # [b, 1, 1, T]
        l = jnp.sum(p, axis=3, keepdims=True)
        acc = jnp.sum(vf * p, axis=3, keepdims=True)  # [b, 1, dh, 1]
        out_ref[:, r:r + 1] = (acc / l).astype(out_ref.dtype)


def decode_supported(q, k_cache) -> bool:
    """Tile-friendly and VMEM-sized? dh a sublane multiple; whole-batch
    K+V group blocks within the VMEM budget."""
    b, g, dh, t = k_cache.shape
    esize = jnp.dtype(k_cache.dtype).itemsize
    return dh % 8 == 0 and 2 * b * dh * t * esize <= _VMEM_BYTES


def decode_attention(q, k_cache, v_cache, kv_len, *, scale=None,
                     interpret=False):
    """q [b, h, dh]; k_cache/v_cache [b, g, dh, T] with h % g == 0
    (GQA: h == g*rep); kv_len: traced scalar — positions >= kv_len are
    masked (decode calls always have the query at position kv_len-1, so
    this IS the causal mask). Returns [b, h, dh]."""
    b, h, dh = q.shape
    g = k_cache.shape[1]
    t = k_cache.shape[-1]
    assert h % g == 0, (h, g)
    rep = h // g
    if scale is None:
        scale = dh ** -0.5
    q4 = q.reshape(b, h, dh, 1)
    lens = jnp.asarray(kv_len, jnp.int32).reshape(1)

    kernel = functools.partial(_decode_kernel, scale=scale, rep=rep)
    out = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),            # lens [1]
            pl.BlockSpec((b, rep, dh, 1), lambda j: (0, j, 0, 0)),
            pl.BlockSpec((b, 1, dh, t), lambda j: (0, j, 0, 0)),
            pl.BlockSpec((b, 1, dh, t), lambda j: (0, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((b, rep, dh, 1), lambda j: (0, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, dh, 1), q.dtype),
        interpret=interpret,
    )(lens, q4, k_cache, v_cache)
    return out.reshape(b, h, dh)
