"""Decode attention: the paged serving path + a recorded Pallas
experiment.

:func:`paged_attention` (bottom) is LIVE — the continuous-batching
engine's per-step attention over the paged KV pool. The Pallas kernel
that opens this file is the round-5 recorded experiment it can compose
with.

Round-5 verdict on the kernel: measured and REJECTED. The decode trace (docs/perf.md,
"the decode gap, traced") showed XLA lowering the per-step attention
(q [b,h,dh] against cached K/V over T positions) to VPU multiply-reduce
fusions at ~160 GB/s effective — the hypothesis was that a Pallas
kernel, which dictates its own block tiling, could stream the cache
with T on the lane axis at full width. Two grid shapes were measured on
device against the einsum path inside the real decode scan (bs32,
T=544, 6 layers):

  - grid (b, h) — one step per row/head: 1.86 ms/step vs 0.92 einsum.
    TPU Pallas grids run SEQUENTIALLY on the core; b*h tiny DMAs
    serialize.
  - grid (g,) — this kernel: whole-batch [b, dh, T] K/V blocks per kv
    group, all GQA query heads inside the step: 1.50 ms/step. Fewer,
    larger DMAs, still loses: Mosaic loops the leading batch dim and
    the per-b [dh, T] reductions pipeline worse than XLA's fused
    lowering of the same math.

The einsum formulation in models/decode.py remains the measured
optimum (two cache-layout variants of it also lost — see perf.md). The
kernel stays here, correct and parity-tested
(tests/test_decode.py::TestPallasDecodeAttention), as the starting
point if a future round wants to hand-tune the Mosaic lowering.

Cache layout contract: [b, g, dh, T].

Round 6 adds the LIVE serving path: :func:`paged_attention`, decode
attention over a PAGED KV cache (fixed-size pages in a preallocated
pool, per-sequence page tables — the PagedAttention design). The page
gather produces the contiguous [b, T, g, dh] view and then runs the
exact einsum formulation above (token-identical to the dense cache by
construction, pinned in tests/test_paged_decode.py), or composes with
the recorded-experiment kernel via ``use_kernel=True`` — both paths
take PER-ROW kv lengths, which is what lets one fixed-shape jitted
step serve ragged sequences (serving/engine.py).

Round 9 replaces the gather's traffic profile with
:func:`paged_window_attention` + the ALLOCATED-PAGES kernel
(:func:`_paged_window_kernel`): the gather path reads every slot's full
page-table width (P * page_size positions — ``max_seq_len`` traffic per
slot per step regardless of actual length), which docs/perf.md "Known
headroom" names as the decode-roofline lever. The kernel walks the
page axis with the page table SCALAR-PREFETCHED: the block index map
clamps the page-axis grid index to the slot's last allocated page, so
every out-of-range grid step repeats the previous block index and
Pallas SKIPS the DMA — HBM cache reads scale with the slot's TRUE
ragged length (rounded up to a page). The query carries a W-token
verify window per slot (speculative decoding + multi-token prefill,
serving/engine.py), accumulated with the online-softmax recurrence
across pages. Parity vs. the gather/einsum reference is pinned in
tests/test_paged_decode.py (GQA/MQA, ragged lengths, W > 1)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LOG2E = 1.4426950408889634

# per-block VMEM budget for K+V (+ double buffering headroom): beyond
# this the caller falls back to XLA rather than risk a VMEM OOM
_VMEM_BYTES = 8 * 1024 * 1024

# ---- int8 KV token-identity contract (the two-tier KV plane) ----
# The int8 paged path must be GREEDY-PREFIX-IDENTICAL to the fp
# single-tier baseline on the pinned suite (mirroring speculation's
# acceptance rule, serving/engine.py) and its attention output within
# this tolerance of the exact-einsum reference. These constants ARE
# the contract — tests/test_paged_decode.py pins against them, and a
# change here is a semantics change, not a tuning knob.
INT8_KV_RTOL = 2e-2
INT8_KV_ATOL = 2e-2
# smallest representable per-row scale: keeps all-zero K/V rows (the
# null page, unwritten pool rows) exactly zero after dequant while
# never dividing by zero in the quantizer
INT8_KV_SCALE_EPS = 1e-12


def quantize_kv(x):
    """Symmetric per-(row, kv-head) int8 quantization of K/V rows:
    ``x`` [..., dh] -> (int8 values [..., dh], float32 scales [...]).
    absmax/127 scaling with deterministic round-half-even — the paged
    scatter must be a pure function of the token run for prefix-reuse
    token identity to survive quantization (serving/prefix.py)."""
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1),
                    INT8_KV_SCALE_EPS) / 127.0
    q = jnp.clip(jnp.round(xf / s[..., None]), -127, 127)
    return q.astype(jnp.int8), s


def dequantize_kv(q, scales, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv`: int8 values [..., dh] * scales
    [...] -> ``dtype`` values [..., dh]."""
    return (q.astype(jnp.float32)
            * scales.astype(jnp.float32)[..., None]).astype(dtype)


def _decode_kernel(lens_ref, q_ref, k_ref, v_ref, out_ref, *, scale,
                   rep):
    k = k_ref[...]                                    # [b, 1, dh, T]
    v = v_ref[...]
    b, _, dh, t = k.shape
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    cols = jax.lax.broadcasted_iota(jnp.int32, (b, 1, 1, t), 3)
    if lens_ref.shape[0] == 1:        # one shared length (dense decode)
        live = cols < lens_ref[0]
    else:                             # per-row lengths (ragged serving)
        live = cols < lens_ref[...].reshape(b, 1, 1, 1)
    for r in range(rep):
        q = q_ref[:, r:r + 1].astype(jnp.float32)     # [b, 1, dh, 1]
        s2 = jnp.sum(q * kf, axis=2, keepdims=True) * (scale * LOG2E)
        s2 = jnp.where(live, s2, NEG_INF)             # [b, 1, 1, T]
        m = jnp.max(s2, axis=3, keepdims=True)
        p = jnp.exp2(s2 - m)                          # [b, 1, 1, T]
        l = jnp.sum(p, axis=3, keepdims=True)
        acc = jnp.sum(vf * p, axis=3, keepdims=True)  # [b, 1, dh, 1]
        out_ref[:, r:r + 1] = (acc / l).astype(out_ref.dtype)


def decode_supported(q, k_cache) -> bool:
    """Tile-friendly and VMEM-sized? dh a sublane multiple; whole-batch
    K+V group blocks within the VMEM budget."""
    b, g, dh, t = k_cache.shape
    esize = jnp.dtype(k_cache.dtype).itemsize
    return dh % 8 == 0 and 2 * b * dh * t * esize <= _VMEM_BYTES


def decode_attention(q, k_cache, v_cache, kv_len, *, scale=None,
                     interpret=False):
    """q [b, h, dh]; k_cache/v_cache [b, g, dh, T] with h % g == 0
    (GQA: h == g*rep); kv_len: traced scalar (shared by every row) or a
    per-row [b] vector — positions >= kv_len are masked (decode calls
    always have each row's query at position kv_len-1, so this IS the
    causal mask). Returns [b, h, dh]."""
    b, h, dh = q.shape
    g = k_cache.shape[1]
    t = k_cache.shape[-1]
    assert h % g == 0, (h, g)
    rep = h // g
    if scale is None:
        scale = dh ** -0.5
    q4 = q.reshape(b, h, dh, 1)
    lens = jnp.asarray(kv_len, jnp.int32).reshape(-1)
    assert lens.shape[0] in (1, b), (lens.shape, b)

    kernel = functools.partial(_decode_kernel, scale=scale, rep=rep)
    out = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),        # lens [1|b]
            pl.BlockSpec((b, rep, dh, 1), lambda j: (0, j, 0, 0)),
            pl.BlockSpec((b, 1, dh, t), lambda j: (0, j, 0, 0)),
            pl.BlockSpec((b, 1, dh, t), lambda j: (0, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((b, rep, dh, 1), lambda j: (0, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, dh, 1), q.dtype),
        interpret=interpret,
    )(lens, q4, k_cache, v_cache)
    return out.reshape(b, h, dh)


# --------------------------------------------------------------- paged
def gather_pages(pages, page_table):
    """Contiguous per-sequence view of a paged pool: ``pages``
    [n_pages, page_size, g, dh] gathered through ``page_table`` [b, P]
    -> [b, P*page_size, g, dh]. Rows of the table beyond a sequence's
    allocation point at the reserved null page (0); the caller's length
    mask keeps those positions out of the softmax."""
    b, pp = page_table.shape
    _, ps, g, dh = pages.shape
    return pages[page_table].reshape(b, pp * ps, g, dh)


def gather_scales(scales, page_table):
    """Per-row dequant scales gathered like :func:`gather_pages`:
    ``scales`` [n_pages, page_size, g] through ``page_table`` [b, P]
    -> [b, P*page_size, g]."""
    b, pp = page_table.shape
    _, ps, g = scales.shape
    return scales[page_table].reshape(b, pp * ps, g)


def paged_attention(q, k_pages, v_pages, page_table, kv_lens, *,
                    scale=None, use_kernel=False, interpret=False,
                    k_scales=None, v_scales=None):
    """Decode attention over a PAGED KV cache (the serving engine's hot
    path — serving/engine.py).

    q [b, h, dh]: one query token per sequence (slot batch);
    k_pages/v_pages [n_pages, page_size, g, dh]: the shared page pools
    (h % g == 0 — GQA reads the cache at stored width);
    page_table [b, P] int32: each row maps the sequence's logical pages
    to physical pages (entries past the allocation = the null page 0);
    kv_lens [b] int32: per-row valid positions — position kv_lens[i]-1
    is row i's query, so the mask is both the causal mask AND the
    ragged-length mask. Returns [b, h, dh].

    The gather materializes the same [b, T, g, dh] view the dense cache
    stores, then runs models/decode.py's exact einsum formulation (the
    measured optimum of five — docs/perf.md), so paged decode is
    token-identical to the dense path. ``use_kernel=True`` instead
    transposes the view into the [b, g, dh, T] contract and composes
    with the :func:`decode_attention` GQA kernel."""
    b, h, dh = q.shape
    g = k_pages.shape[2]
    assert h % g == 0, (h, g)
    rep = h // g
    if scale is None:
        scale = dh ** -0.5
    k = gather_pages(k_pages, page_table)              # [b, T, g, dh]
    v = gather_pages(v_pages, page_table)
    if k_scales is not None:
        # int8 pools: dequantize the GATHERED view (T rows, not the
        # whole pool) and fall through to the identical exact-einsum
        # formulation — the dequant analogue of the kernel-gate
        # fallback below
        k = dequantize_kv(k, gather_scales(k_scales, page_table),
                          q.dtype)
        v = dequantize_kv(v, gather_scales(v_scales, page_table),
                          q.dtype)
    lens = jnp.asarray(kv_lens, jnp.int32).reshape(-1)
    if use_kernel:
        kt = k.transpose(0, 2, 3, 1)                   # [b, g, dh, T]
        vt = v.transpose(0, 2, 3, 1)
        return decode_attention(q, kt, vt, lens, scale=scale,
                                interpret=interpret)
    t = k.shape[1]
    # identical formulation (einsum strings, mask value, softmax dtype
    # path) to models/decode.py _block at t=1 — parity is structural
    q5 = q.reshape(b, 1, g, rep, dh)
    logits = jnp.einsum("bqgrd,bkgd->bgrqk", q5,
                        k.astype(q.dtype)) * scale
    mask = jnp.arange(t)[None, :] < lens[:, None]      # [b, T]
    logits = jnp.where(mask[:, None, None, None], logits, NEG_INF)
    w = jax.nn.softmax(logits, axis=-1)
    attn = jnp.einsum("bgrqk,bkgd->bqgrd", w, v.astype(q.dtype))
    return attn.reshape(b, h, dh)


# ------------------------------------------- allocated-pages kernel
def _paged_window_kernel(tables_ref, used_ref, lens_ref, q_ref, k_ref,
                         v_ref, out_ref, m_ref, l_ref, acc_ref, *,
                         scale, rep, page_size, window):
    """Grid (S, P), page axis fastest. Block p of slot s is the page
    the CLAMPED index map selected — for p >= used[s] that is the same
    physical page as step p-1, so Pallas skips the DMA (the
    allocated-pages traffic contract) and ``pl.when`` skips the math.
    Online softmax carries (m, l, acc) per (kv group, window row)
    across the page axis in VMEM scratch."""
    p = pl.program_id(1)
    s = pl.program_id(0)
    used = used_ref[s]
    g = m_ref.shape[0]
    wr = m_ref.shape[1]                                # window * rep

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    @pl.when(p < used)
    def _accumulate():
        k = k_ref[0].astype(jnp.float32)               # [ps, g, dh]
        v = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)               # [W, h, dh]
        lens = lens_ref[0]                             # [W] int32
        # per-token causal/ragged mask against ABSOLUTE positions:
        # page p covers [p*ps, (p+1)*ps); token w sees < lens[w]
        lens_rep = jnp.repeat(lens, rep)               # [W*rep]
        cols = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (wr, page_size), 1)
        live = cols < lens_rep[:, None]
        for gi in range(g):
            kg = k[:, gi, :]                           # [ps, dh]
            vg = v[:, gi, :]
            qg = q[:, gi * rep:(gi + 1) * rep, :].reshape(wr, -1)
            sc = jax.lax.dot_general(
                qg, kg, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * (scale * LOG2E)
            sc = jnp.where(live, sc, NEG_INF)          # [wr, ps]
            m_prev = m_ref[gi]                         # [wr, 1]
            m_cur = jnp.maximum(m_prev,
                                jnp.max(sc, axis=1, keepdims=True))
            alpha = jnp.exp2(m_prev - m_cur)
            pm = jnp.exp2(sc - m_cur)                  # [wr, ps]
            l_ref[gi] = l_ref[gi] * alpha + \
                jnp.sum(pm, axis=1, keepdims=True)
            acc_ref[gi] = acc_ref[gi] * alpha + jax.lax.dot_general(
                pm, vg, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[gi] = m_cur

    @pl.when(p == pl.num_programs(1) - 1)
    def _finalize():
        # fully-masked rows (lens 0 never happens live; engine clamps
        # masked tokens to kv_len >= 1) still divide by a finite l
        l = jnp.maximum(l_ref[...], 1e-30)             # [g, wr, 1]
        o = acc_ref[...] / l                           # [g, wr, dh]
        dh = o.shape[-1]
        w = wr // rep
        o = o.reshape(g, w, rep, dh).transpose(1, 0, 2, 3)
        out_ref[0] = o.reshape(w, g * rep, dh).astype(out_ref.dtype)


def _paged_window_dequant_kernel(tables_ref, used_ref, lens_ref, q_ref,
                                 k_ref, v_ref, ks_ref, vs_ref, out_ref,
                                 m_ref, l_ref, acc_ref, *, scale, rep,
                                 page_size, window):
    """The dequant-FUSED twin of :func:`_paged_window_kernel`: same
    grid, same clamped index maps (scale blocks ride the same
    ``_table_map``, so a skipped page DMA skips its scale DMA too),
    same online-softmax recurrence — the only delta is the per-row
    rescale ``int8 * scale`` applied in VMEM right after the K/V block
    lands, so the HBM read is 1 byte/element + 4 bytes/row instead of
    the float pool's 2-4 bytes/element."""
    p = pl.program_id(1)
    s = pl.program_id(0)
    used = used_ref[s]
    g = m_ref.shape[0]
    wr = m_ref.shape[1]                                # window * rep

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    @pl.when(p < used)
    def _accumulate():
        # fused dequant: [ps, g, dh] int8 * [ps, g, 1] f32 scales
        k = k_ref[0].astype(jnp.float32) * \
            ks_ref[0].astype(jnp.float32)[..., None]
        v = v_ref[0].astype(jnp.float32) * \
            vs_ref[0].astype(jnp.float32)[..., None]
        q = q_ref[0].astype(jnp.float32)               # [W, h, dh]
        lens = lens_ref[0]                             # [W] int32
        lens_rep = jnp.repeat(lens, rep)               # [W*rep]
        cols = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (wr, page_size), 1)
        live = cols < lens_rep[:, None]
        for gi in range(g):
            kg = k[:, gi, :]                           # [ps, dh]
            vg = v[:, gi, :]
            qg = q[:, gi * rep:(gi + 1) * rep, :].reshape(wr, -1)
            sc = jax.lax.dot_general(
                qg, kg, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * (scale * LOG2E)
            sc = jnp.where(live, sc, NEG_INF)          # [wr, ps]
            m_prev = m_ref[gi]                         # [wr, 1]
            m_cur = jnp.maximum(m_prev,
                                jnp.max(sc, axis=1, keepdims=True))
            alpha = jnp.exp2(m_prev - m_cur)
            pm = jnp.exp2(sc - m_cur)                  # [wr, ps]
            l_ref[gi] = l_ref[gi] * alpha + \
                jnp.sum(pm, axis=1, keepdims=True)
            acc_ref[gi] = acc_ref[gi] * alpha + jax.lax.dot_general(
                pm, vg, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[gi] = m_cur

    @pl.when(p == pl.num_programs(1) - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)             # [g, wr, 1]
        o = acc_ref[...] / l                           # [g, wr, dh]
        dh = o.shape[-1]
        w = wr // rep
        o = o.reshape(g, w, rep, dh).transpose(1, 0, 2, 3)
        out_ref[0] = o.reshape(w, g * rep, dh).astype(out_ref.dtype)


def paged_kernel_supported(q, k_pages, k_scales=None) -> bool:
    """Gate for the allocated-pages kernel: tile-friendly head dim and
    a per-page K+V block inside the VMEM budget. With ``k_scales``
    (the int8 two-tier layout) the budget counts the int8 block plus
    its float32 per-row scales."""
    ps, g, dh = k_pages.shape[1:]
    esize = jnp.dtype(k_pages.dtype).itemsize
    block = 2 * ps * g * dh * esize
    if k_scales is not None:
        block += 2 * ps * g * jnp.dtype(k_scales.dtype).itemsize
    return dh % 8 == 0 and block <= _VMEM_BYTES


def paged_window_attention(q, k_pages, v_pages, page_tables, kv_lens,
                           *, scale=None, use_kernel=False,
                           interpret=False, k_scales=None,
                           v_scales=None):
    """Decode attention over the paged pool for a W-token window per
    slot (W = 1 is the classic one-token step; the speculative engine
    feeds W = spec_k + 1 — serving/engine.py).

    q [S, W, h, dh]; k_pages/v_pages [n_pages, page_size, g, dh];
    page_tables [S, P] int32; kv_lens [S, W] int32 per-TOKEN valid
    lengths (token w of slot s is the query at position
    kv_lens[s, w] - 1 — the mask is causal within the window too,
    because earlier window tokens' K/V were scattered before this
    call). Returns [S, W, h, dh].

    ``use_kernel=False`` flattens the window into the gather/einsum
    reference (:func:`paged_attention` — exact, reads the full table
    width). ``use_kernel=True`` runs the allocated-pages Pallas kernel:
    page tables and per-slot used-page counts are scalar-prefetched,
    the page-axis block index is clamped to the last allocated page so
    revisited blocks skip their DMA, and cache-read traffic is
    ceil(len/page_size) pages instead of P.

    ``k_scales``/``v_scales`` [n_pages, page_size, g] switch the pools
    to the INT8 two-tier layout (:func:`quantize_kv` rows): the gather
    path dequantizes the gathered view then runs the same exact einsum
    (the dequant analogue of the existing kernel-gate fallback), and
    the kernel path runs :func:`_paged_window_dequant_kernel`, which
    fuses the per-row rescale into the online-softmax page walk —
    int8 K/V never round-trips through HBM at float width."""
    S, W, h, dh = q.shape
    n_pages, ps, g, _ = k_pages.shape
    P = page_tables.shape[1]
    assert h % g == 0, (h, g)
    rep = h // g
    if scale is None:
        scale = dh ** -0.5
    lens = jnp.asarray(kv_lens, jnp.int32).reshape(S, W)
    quant = k_scales is not None
    if not use_kernel:
        out = paged_attention(
            q.reshape(S * W, h, dh), k_pages, v_pages,
            jnp.repeat(page_tables, W, axis=0), lens.reshape(-1),
            scale=scale, k_scales=k_scales, v_scales=v_scales)
        return out.reshape(S, W, h, dh)
    # pages actually holding live KV for each slot (>= 1 so the null
    # page still feeds the pipeline for idle slots)
    used = jnp.clip(-(-jnp.max(lens, axis=1) // ps), 1, P)

    def _table_map(si, pi, tables, used_):
        return (tables[si, jnp.minimum(pi, used_[si] - 1)], 0, 0, 0)

    def _scale_map(si, pi, tables, used_):
        return (tables[si, jnp.minimum(pi, used_[si] - 1)], 0, 0)

    kfn = _paged_window_dequant_kernel if quant else \
        _paged_window_kernel
    kernel = functools.partial(
        kfn, scale=scale, rep=rep, page_size=ps, window=W)
    in_specs = [
        pl.BlockSpec((1, W), lambda si, pi, tables, used_: (si, 0)),
        pl.BlockSpec((1, W, h, dh),
                     lambda si, pi, tables, used_: (si, 0, 0, 0)),
        pl.BlockSpec((1, ps, g, dh), _table_map),
        pl.BlockSpec((1, ps, g, dh), _table_map),
    ]
    operands = [jnp.asarray(page_tables, jnp.int32),
                used.astype(jnp.int32), lens, q, k_pages, v_pages]
    if quant:
        in_specs += [pl.BlockSpec((1, ps, g), _scale_map),
                     pl.BlockSpec((1, ps, g), _scale_map)]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, P),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, W, h, dh),
            lambda si, pi, tables, used_: (si, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, W * rep, 1), jnp.float32),
            pltpu.VMEM((g, W * rep, 1), jnp.float32),
            pltpu.VMEM((g, W * rep, dh), jnp.float32),
        ])
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, W, h, dh), q.dtype),
        interpret=interpret,
    )(*operands)
