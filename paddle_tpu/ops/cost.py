"""Cost / loss functions.

Reference: paddle/gserver/layers/CostLayer.cpp — 15+ cost layers
(multi-class cross entropy (+selfnorm), soft binary CE, squared error,
rank cost, lambda cost, multi-binary-label CE, huber two-class /
regression, smooth-L1, sum cost) plus CRFLayer, CTCLayer, NCELayer,
HierarchicalSigmoidLayer elsewhere in gserver/layers.

All costs return PER-SAMPLE values [batch]; the trainer averages. Gradients
come free from jax.grad (the reference hand-wrote each backward).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _gather_label(x: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """x[..., labels] — the label column of a [.., V] tensor."""
    return jnp.take_along_axis(x, labels[..., None].astype(jnp.int32),
                               axis=-1)[..., 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _ce_from_logits(x: jnp.ndarray, labels: jnp.ndarray,
                    label_smoothing: float) -> jnp.ndarray:
    """Stable logits CE with a width-controlled backward.

    Forward: lse - x_label (reductions + a gather — never writes a
    vocab-sized softmax). Backward: dlogits = (softmax - target) * g
    emitted as ONE fused elementwise expression whose output is cast to
    the LOGITS dtype before it leaves the fusion. Without the custom
    vjp, the logsumexp VJP materializes softmax as an f32 [.., V]
    tensor that the head's dW/dh matmuls then re-read at double width —
    on the 32k-vocab LM head that f32 write+reads were ~2.4 ms/step of
    pure dtype waste (the autodiff chain casts the very same tensor
    back to bf16 one op later anyway)."""
    return _ce_logits_fwd(x, labels, label_smoothing)[0]


def _ce_logits_fwd(x, labels, a):
    # gather/mean read the BF16 logits and upcast after: astype commutes
    # exactly with both, and keeping x.astype(f32) out of multi-use
    # scope stops XLA from materializing the full-vocab f32 tensor once
    # to share it (1 GB at [8,1024,32000] — seen in the round-5 trace);
    # logsumexp's internal upcast fuses into its own reduction
    lse = jax.scipy.special.logsumexp(x.astype(jnp.float32), axis=-1)
    nll = lse - _gather_label(x, labels).astype(jnp.float32)
    if a > 0.0:
        # single-use f32 cast: fuses into the mean's own reduction
        # (a bf16 accumulator over 32k terms would lose precision)
        nll = (1.0 - a) * nll + a * (
            lse - jnp.mean(x.astype(jnp.float32), axis=-1))
    return nll, (x, labels, lse)


def _ce_logits_bwd(a, res, g):
    x, labels, lse = res
    v = x.shape[-1]
    p = jnp.exp(x.astype(jnp.float32) - lse[..., None])
    onehot = (jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
              == labels[..., None].astype(jnp.int32))
    target = ((1.0 - a) * onehot.astype(jnp.float32) + a / v) if a > 0.0 \
        else onehot.astype(jnp.float32)
    dl = ((p - target) * g[..., None].astype(jnp.float32)).astype(x.dtype)
    # (measured: wrapping dl in lax.optimization_barrier to force a bf16
    # materialization is 38% SLOWER — XLA's choice to share the pre-cast
    # f32 tensor between the dx fusion and the dW matmul beats cutting
    # the fusion; leave the scheduler alone)
    return (dl, None)


_ce_from_logits.defvjp(_ce_logits_fwd, _ce_logits_bwd)


def cross_entropy(probs_or_logits: jnp.ndarray, labels: jnp.ndarray, *,
                  from_logits: bool = False, eps: float = 1e-10,
                  label_smoothing: float = 0.0) -> jnp.ndarray:
    """Multi-class CE with integer labels (classification_cost).

    The reference applies softmax in the preceding layer and CE on probs
    (CostLayer.cpp MultiClassCrossEntropy); from_logits=True fuses the
    numerically-stable log_softmax path, which is what the jit graph should
    prefer (XLA fuses it into one kernel). label_smoothing=a mixes the
    one-hot target with uniform mass a/V (logits path only — the probs
    path stays the gather-only fast form).
    """
    if from_logits:
        # lse - x_label form: log_softmax would MATERIALIZE a [.., V]
        # f32 tensor; logsumexp is a reduction (max-subtracted, stable)
        # and the label term is a gather, so the forward never writes a
        # vocab-sized intermediate. With smoothing a, the uniform term
        # mean(log_softmax) = mean(x) - lse is a reduction too. The
        # custom_vjp keeps the BACKWARD at the logits width as well
        # (one fused (softmax - target) * g expression).
        return _ce_from_logits(probs_or_logits, labels,
                               float(label_smoothing))
    if label_smoothing != 0.0:
        raise ValueError(
            "label_smoothing needs from_logits=True (probs CE gathers "
            "only the label column)")
    # probs path: gather the label's prob FIRST, then upcast+log only the
    # gathered column — elementwise astype/log commute with the gather,
    # so numerics are identical, but the [.., V] tensor is never
    # re-materialized in f32 (at a 32k vocab that re-materialization was
    # ~25% of a transformer train step's time)
    p = _gather_label(probs_or_logits, labels)
    return -jnp.log(jnp.maximum(p.astype(jnp.float32), eps))


def cross_entropy_with_selfnorm(probs: jnp.ndarray, labels: jnp.ndarray,
                                softmax_selfnorm_alpha: float = 0.1,
                                eps: float = 1e-10) -> jnp.ndarray:
    """CostLayer.cpp MultiClassCrossEntropyWithSelfNorm: CE + alpha*log(Z)^2."""
    z = jnp.sum(probs, axis=-1)
    ce = cross_entropy(probs / z[..., None], labels, eps=eps)
    return ce + softmax_selfnorm_alpha * jnp.square(jnp.log(jnp.maximum(z, eps)))


def soft_binary_class_cross_entropy(p: jnp.ndarray, label: jnp.ndarray,
                                    eps: float = 1e-10) -> jnp.ndarray:
    """Element-wise binary CE with soft labels, summed over features."""
    p = jnp.clip(p, eps, 1.0 - eps)
    return jnp.sum(-label * jnp.log(p) - (1.0 - label) * jnp.log1p(-p), axis=-1)


def multi_binary_label_cross_entropy(p: jnp.ndarray, labels: jnp.ndarray,
                                     eps: float = 1e-10) -> jnp.ndarray:
    """Multi-label CE: labels is a {0,1} dense matrix (reference accepts
    sparse_binary_vector; densified by the feeder)."""
    return soft_binary_class_cross_entropy(p, labels, eps)


def square_error(pred: jnp.ndarray, label: jnp.ndarray) -> jnp.ndarray:
    """SumOfSquaresCostLayer: 0.5 * sum (pred-label)^2 per sample... the
    reference computes sum of squares /2? It reports plain squared error
    summed over dims (CostLayer.cpp SumOfSquaresCostLayer::forwardImp)."""
    d = pred - label
    return 0.5 * jnp.sum(jnp.square(d), axis=-1)


mse_cost = square_error


def rank_cost(left: jnp.ndarray, right: jnp.ndarray, label: jnp.ndarray,
              weight: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """RankingCost: pairwise logistic loss on score difference.
    C = -o*log(sig(o_l - o_r)) - (1-o)*log(1-sig(...)), label in [0,1]."""
    o = (left - right)[..., 0]
    lab = label.astype(o.dtype)
    if lab.ndim > o.ndim:
        lab = lab[..., 0]
    c = jax.nn.softplus(o) - lab * o
    if weight is not None:
        c = c * weight[..., 0] if weight.ndim > c.ndim else c * weight
    return c


def lambda_cost(scores: jnp.ndarray, relevance: jnp.ndarray,
                mask: Optional[jnp.ndarray] = None,
                ndcg_num: int = 5) -> jnp.ndarray:
    """LambdaRank (LambdaCost): listwise NDCG-weighted pairwise loss over one
    query's documents laid out along the time axis.

    scores, relevance: [batch, n]; mask 1.0 on valid docs. The reference
    computes lambda gradients directly (CostLayer.cpp LambdaCost::backwardImp);
    here we build the equivalent differentiable surrogate: sum over pairs of
    |delta_ndcg| * log(1+exp(-(s_i - s_j))) for rel_i > rel_j.
    """
    b, n = scores.shape
    if mask is None:
        mask = jnp.ones_like(scores)
    rel = relevance
    # ideal DCG for normalization (top ndcg_num by relevance)
    sorted_rel = -jnp.sort(-rel, axis=-1)
    pos = jnp.arange(n)
    disc = 1.0 / jnp.log2(pos + 2.0)
    topk = (pos < ndcg_num).astype(scores.dtype)
    idcg = jnp.sum((2.0 ** sorted_rel - 1.0) * disc * topk, axis=-1,
                   keepdims=True)
    idcg = jnp.maximum(idcg, 1e-5)
    gain = (2.0 ** rel - 1.0) / idcg                      # [b, n]
    # pairwise
    s_diff = scores[:, :, None] - scores[:, None, :]      # s_i - s_j
    rel_gt = (rel[:, :, None] > rel[:, None, :]).astype(scores.dtype)
    pair_mask = mask[:, :, None] * mask[:, None, :] * rel_gt
    dgain = jnp.abs(gain[:, :, None] - gain[:, None, :])
    loss = jax.nn.softplus(-s_diff) * dgain * pair_mask
    return jnp.sum(loss, axis=(1, 2))


def huber_regression(pred: jnp.ndarray, label: jnp.ndarray,
                     delta: float = 1.0) -> jnp.ndarray:
    """HuberRegressionLoss (CostLayer.cpp)."""
    a = jnp.abs(pred - label)
    quad = 0.5 * jnp.square(a)
    lin = delta * a - 0.5 * delta * delta
    return jnp.sum(jnp.where(a <= delta, quad, lin), axis=-1)


def huber_classification(pred: jnp.ndarray, label: jnp.ndarray) -> jnp.ndarray:
    """HuberTwoClassification: labels {0,1} -> y in {-1,1}; squared hinge with
    linear tail (CostLayer.cpp HuberTwoClassification::forwardImpIn)."""
    y = 2.0 * label.astype(pred.dtype) - 1.0
    z = pred[..., 0] * y
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, jnp.square(1.0 - z), 0.0))
    return loss


def smooth_l1(pred: jnp.ndarray, label: jnp.ndarray,
              sigma: float = 1.0) -> jnp.ndarray:
    """SmoothL1CostLayer."""
    s2 = sigma * sigma
    d = jnp.abs(pred - label)
    loss = jnp.where(d < 1.0 / s2, 0.5 * s2 * jnp.square(d), d - 0.5 / s2)
    return jnp.sum(loss, axis=-1)


def sum_cost(x: jnp.ndarray) -> jnp.ndarray:
    """SumCostLayer: sum of the input as the loss."""
    return jnp.sum(x, axis=tuple(range(1, x.ndim)))


def classification_error(probs: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-sample 0/1 error (ClassificationErrorLayer / evaluator)."""
    pred = jnp.argmax(probs, axis=-1)
    return (pred != labels.astype(pred.dtype)).astype(jnp.float32)


# ---------------------------------------------------------------------------
# NCE & hierarchical sigmoid (sampled softmax family)


def nce_loss(features: jnp.ndarray, weights: jnp.ndarray, bias: jnp.ndarray,
             labels: jnp.ndarray, sample_ids: jnp.ndarray,
             num_classes: int) -> jnp.ndarray:
    """Noise-contrastive estimation (NCELayer, gserver/layers/NCELayer.cpp).

    features: [b, d]; weights: [num_classes, d]; bias: [num_classes];
    labels: [b] true class; sample_ids: [b, k] noise samples (uniform noise
    distribution, matching the reference's default uniform sampler).
    """
    k = sample_ids.shape[-1]
    log_noise = jnp.log(1.0 / num_classes)

    def logit(ids):
        w = weights[ids]                    # [..., d]
        b = bias[ids]
        return jnp.sum(features[:, None, :] * w, axis=-1) + b \
            if ids.ndim == 2 else jnp.sum(features * w, axis=-1) + b

    true_logit = logit(labels)              # [b]
    noise_logit = logit(sample_ids)         # [b, k]
    # P(true) vs k noise samples
    true_cost = jax.nn.softplus(-(true_logit - jnp.log(float(k)) - log_noise))
    noise_cost = jax.nn.softplus(noise_logit - jnp.log(float(k)) - log_noise)
    return true_cost + jnp.sum(noise_cost, axis=-1)


def hsigmoid_loss(features: jnp.ndarray, weights: jnp.ndarray,
                  bias: jnp.ndarray, labels: jnp.ndarray,
                  num_classes: int) -> jnp.ndarray:
    """Hierarchical sigmoid over an implicit complete binary tree
    (HierarchicalSigmoidLayer): classes are leaves; internal nodes are
    `num_classes - 1` logistic classifiers addressed by the binary code of
    the label (same addressing as the reference's codeTable).
    """
    depth = max(int(num_classes - 1).bit_length(), 1)
    code = labels.astype(jnp.int32) + num_classes  # leaf index in heap order

    def step(carry, _):
        node, loss = carry
        parent = node // 2
        is_right = (node % 2).astype(features.dtype)   # bit: went right?
        valid = (parent >= 1).astype(features.dtype)
        w = weights[jnp.clip(parent - 1, 0, num_classes - 2)]
        b = bias[jnp.clip(parent - 1, 0, num_classes - 2)]
        logit = jnp.sum(features * w, axis=-1) + b
        # sigmoid CE: right child -> label 1
        l = jax.nn.softplus(logit) - is_right * logit
        return (parent, loss + valid * l), None

    (_, total), _ = jax.lax.scan(
        step, (code, jnp.zeros(features.shape[0], features.dtype)), None,
        length=depth)
    return total
