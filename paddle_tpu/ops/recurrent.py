"""Recurrent cells and masked scans.

Reference: gserver/layers/LstmLayer.cpp + the fused CUDA cells
(cuda/src/hl_cuda_lstm.cu, hl_gpu_gru.cuh), GatedRecurrentLayer,
RecurrentLayer; SequenceToBatch re-packing made ragged batches dense per
timestep. TPU design: time-major `lax.scan` over the padded time axis with a
per-step validity mask — state freezes on padded steps, so results match the
ragged semantics exactly while XLA pipelines the whole scan body into fused
kernels (the same fusion hl_cuda_lstm.cu did by hand).

Layout note: gate order is [input, forget, cell(candidate), output] (paddle's
hl_lstm gate layout); GRU gates [update(z), reset(r), candidate(c)].
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import activations
from paddle_tpu.ops.linear import matmul


def lstm_cell(x4: jnp.ndarray, h: jnp.ndarray, c: jnp.ndarray,
              w_rec: jnp.ndarray, bias: Optional[jnp.ndarray],
              peep: Optional[jnp.ndarray] = None,
              act: str = "tanh", gate_act: str = "sigmoid",
              state_act: str = "tanh") -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One LSTM step.

    x4: [b, 4h] pre-projected input; w_rec: [h, 4h]; bias: [4h];
    peep: [3h] peephole weights (input|forget|output) or None.
    Returns (h', c').
    """
    hdim = h.shape[-1]
    z = x4 + matmul(h, w_rec)
    if bias is not None:
        z = z + bias
    zi, zf, zc, zo = (z[..., :hdim], z[..., hdim:2 * hdim],
                      z[..., 2 * hdim:3 * hdim], z[..., 3 * hdim:])
    ga = activations.get(gate_act)
    if peep is not None:
        pi, pf, po = peep[:hdim], peep[hdim:2 * hdim], peep[2 * hdim:]
        i = ga(zi + pi * c)
        f = ga(zf + pf * c)
    else:
        i = ga(zi)
        f = ga(zf)
    cand = activations.get(act)(zc)
    c_new = f * c + i * cand
    if peep is not None:
        o = ga(zo + po * c_new)
    else:
        o = ga(zo)
    h_new = o * activations.get(state_act)(c_new)
    return h_new, c_new


def gru_cell(x3: jnp.ndarray, h: jnp.ndarray, w_rec: jnp.ndarray,
             bias: Optional[jnp.ndarray], act: str = "tanh",
             gate_act: str = "sigmoid") -> jnp.ndarray:
    """One GRU step (paddle gate layout: update z, reset r, candidate c).

    x3: [b, 3h]; w_rec: [h, 3h] (gate part [h, 2h] + candidate part [h, h]).
    """
    hdim = h.shape[-1]
    gates_x = x3[..., :2 * hdim]
    cand_x = x3[..., 2 * hdim:]
    gates_h = matmul(h, w_rec[:, :2 * hdim])
    zr = gates_x + gates_h
    if bias is not None:
        zr = zr + bias[:2 * hdim]
    ga = activations.get(gate_act)
    z = ga(zr[..., :hdim])
    r = ga(zr[..., hdim:])
    cand = cand_x + matmul(r * h, w_rec[:, 2 * hdim:])
    if bias is not None:
        cand = cand + bias[2 * hdim:]
    c = activations.get(act)(cand)
    return (1.0 - z) * h + z * c


def simple_rnn_cell(x: jnp.ndarray, h: jnp.ndarray, w_rec: jnp.ndarray,
                    bias: Optional[jnp.ndarray], act: str = "tanh") -> jnp.ndarray:
    """RecurrentLayer: h' = act(x + h @ W + b)."""
    z = x + matmul(h, w_rec)
    if bias is not None:
        z = z + bias
    return activations.get(act)(z)


def _masked_scan(step_fn, init_carry, seq: SequenceBatch, reverse: bool):
    """Run step_fn over time with state frozen on padded steps.

    step_fn(carry, x_t) -> (new_carry, out_t); carry is a pytree of [b, ...]
    arrays. Uses time-major scan.
    """
    x = seq.data
    T = x.shape[1]
    xs = jnp.moveaxis(x, 1, 0)                       # [T, b, ...]
    tidx = jnp.arange(T, dtype=jnp.int32)
    if reverse:
        # process positions len-1 ... 0 per row: reverse the padded axis and
        # shift so each row starts at its own end. Simpler: gather per-row
        # reversed indices.
        rev_idx = jnp.clip(seq.lengths[:, None] - 1 -
                           jnp.arange(T, dtype=jnp.int32)[None, :], 0, T - 1)
        gx = jnp.take_along_axis(
            x, rev_idx.reshape(rev_idx.shape + (1,) * (x.ndim - 2)), axis=1) \
            if x.ndim > 2 else jnp.take_along_axis(x, rev_idx, axis=1)
        xs = jnp.moveaxis(gx, 1, 0)

    def body(carry, inp):
        t, x_t = inp
        valid = t < seq.lengths                      # [b] bool
        new_carry, out_t = step_fn(carry, x_t)

        def merge(n, o):
            v = valid.reshape((-1,) + (1,) * (n.ndim - 1))
            return jnp.where(v, n, o)

        merged = jax.tree_util.tree_map(merge, new_carry, carry)
        vo = valid.reshape((-1,) + (1,) * (out_t.ndim - 1))
        return merged, jnp.where(vo, out_t, jnp.zeros_like(out_t))

    carry, outs = lax.scan(body, init_carry, (tidx, xs))
    outs = jnp.moveaxis(outs, 0, 1)                  # [b, T, ...]
    if reverse:
        rev_idx = jnp.clip(seq.lengths[:, None] - 1 -
                           jnp.arange(T, dtype=jnp.int32)[None, :], 0, T - 1)
        outs = jnp.take_along_axis(
            outs, rev_idx.reshape(rev_idx.shape + (1,) * (outs.ndim - 2)),
            axis=1)
        outs = outs * seq.mask(outs.dtype).reshape(
            seq.mask().shape + (1,) * (outs.ndim - 2))
    return carry, outs


def lstm_scan(seq4: SequenceBatch, w_rec: jnp.ndarray,
              bias: Optional[jnp.ndarray], peep: Optional[jnp.ndarray] = None,
              *, reverse: bool = False, act: str = "tanh",
              gate_act: str = "sigmoid", state_act: str = "tanh",
              h0: Optional[jnp.ndarray] = None,
              c0: Optional[jnp.ndarray] = None,
              return_state: bool = False):
    """LSTM over a pre-projected sequence [b, T, 4h] -> hidden [b, T, h]."""
    b = seq4.data.shape[0]
    h = w_rec.shape[0]
    dtype = seq4.data.dtype
    h_init = h0 if h0 is not None else jnp.zeros((b, h), dtype)
    c_init = c0 if c0 is not None else jnp.zeros((b, h), dtype)

    # fused Pallas sequence kernel (hl_cuda_lstm.cu parity) when eligible
    if (not reverse and h0 is None and c0 is None):
        from paddle_tpu.ops import pallas_rnn
        if pallas_rnn.pallas_ok(b, h, act, gate_act, state_act):
            outs, hT, cT = pallas_rnn.lstm_sequence(
                seq4.data, seq4.lengths, w_rec, bias, peep)
            out_seq = seq4.with_data(outs.astype(dtype))
            if return_state:
                return out_seq, (hT.astype(dtype), cT.astype(dtype))
            return out_seq

    def step(carry, x_t):
        hh, cc = carry
        h_new, c_new = lstm_cell(x_t, hh, cc, w_rec, bias, peep,
                                 act, gate_act, state_act)
        return (h_new, c_new), h_new

    (hT, cT), outs = _masked_scan(step, (h_init, c_init), seq4, reverse)
    out_seq = seq4.with_data(outs)
    if return_state:
        return out_seq, (hT, cT)
    return out_seq


def gru_scan(seq3: SequenceBatch, w_rec: jnp.ndarray,
             bias: Optional[jnp.ndarray], *, reverse: bool = False,
             act: str = "tanh", gate_act: str = "sigmoid",
             h0: Optional[jnp.ndarray] = None,
             return_state: bool = False):
    """GRU over pre-projected [b, T, 3h] -> [b, T, h]."""
    b = seq3.data.shape[0]
    h = w_rec.shape[0]
    h_init = h0 if h0 is not None else jnp.zeros((b, h), seq3.data.dtype)

    # fused Pallas sequence kernel (hl_gpu_gru.cuh parity) when eligible
    if not reverse and h0 is None:
        from paddle_tpu.ops import pallas_rnn
        if pallas_rnn.pallas_ok(b, h, act, gate_act):
            dtype = seq3.data.dtype
            outs, hT = pallas_rnn.gru_sequence(
                seq3.data, seq3.lengths, w_rec, bias)
            out_seq = seq3.with_data(outs.astype(dtype))
            if return_state:
                return out_seq, hT.astype(dtype)
            return out_seq

    def step(carry, x_t):
        h_new = gru_cell(x_t, carry, w_rec, bias, act, gate_act)
        return h_new, h_new

    hT, outs = _masked_scan(step, h_init, seq3, reverse)
    out_seq = seq3.with_data(outs)
    if return_state:
        return out_seq, hT
    return out_seq


def rnn_scan(seq: SequenceBatch, w_rec: jnp.ndarray,
             bias: Optional[jnp.ndarray], *, reverse: bool = False,
             act: str = "tanh", h0: Optional[jnp.ndarray] = None):
    b = seq.data.shape[0]
    h = w_rec.shape[0]
    h_init = h0 if h0 is not None else jnp.zeros((b, h), seq.data.dtype)

    def step(carry, x_t):
        h_new = simple_rnn_cell(x_t, carry, w_rec, bias, act)
        return h_new, h_new

    _, outs = _masked_scan(step, h_init, seq, reverse)
    return seq.with_data(outs)


def mdlstm_2d(x: jnp.ndarray, w: jnp.ndarray, bias: Optional[jnp.ndarray],
              *, act: str = "tanh", gate_act: str = "sigmoid",
              reverse_h: bool = False, reverse_w: bool = False) -> jnp.ndarray:
    """2-D multi-dimensional LSTM over an image grid (MDLstmLayer.cpp).

    x:  [b, H, W, 5*h] pre-projected gate input — layout (in, ig, fg_y,
        fg_x, og), matching the reference's numBlocks*(3+numDims) with
        numDims=2 (MDLstmLayer.cpp:226-234).
    w:  [h, 5*h] recurrent weight, shared across both predecessor
        directions as the reference's single weight parameter is.
    bias: [9*h] = 5h gate bias + peephole (ig, fg_y, fg_x, og) each h
        (MDLstmLayer.cpp:230-232: numBlocks*(5+2*numDims)).

    Each cell (i, j) sees h/c from (i-1, j) and (i, j-1). Implemented as a
    scan over rows whose body scans over columns — XLA compiles the doubly
    nested scan once; the H*W sequential chain is inherent to the
    recurrence (the reference walks the same chain cell by cell via
    CoordIterator). reverse_h/reverse_w flip the walk direction per axis,
    giving the 4 scan directions a multi-directional stack needs.
    """
    b, H, W, d5 = x.shape
    h = d5 // 5
    fa = activations.get(act)
    ga = activations.get(gate_act)
    if bias is None:
        gate_b = jnp.zeros((5 * h,), x.dtype)
        peep = jnp.zeros((4 * h,), x.dtype)
    else:
        gate_b, peep = bias[:5 * h], bias[5 * h:]
    p_ig, p_fy, p_fx, p_og = (peep[i * h:(i + 1) * h] for i in range(4))

    if reverse_h:
        x = x[:, ::-1]
    if reverse_w:
        x = x[:, :, ::-1]

    def cell(pre, h_up, c_up, h_left, c_left):
        pre = pre + matmul(h_up + h_left, w) + gate_b
        a_in = fa(pre[..., :h])
        ig = ga(pre[..., h:2 * h] + p_ig * (c_up + c_left))
        fy = ga(pre[..., 2 * h:3 * h] + p_fy * c_up)
        fx = ga(pre[..., 3 * h:4 * h] + p_fx * c_left)
        c = ig * a_in + fy * c_up + fx * c_left
        og = ga(pre[..., 4 * h:] + p_og * c)
        return og * fa(c), c

    def col_step(carry, inp):
        h_left, c_left = carry
        pre_j, h_up_j, c_up_j = inp
        h_new, c_new = cell(pre_j, h_up_j, c_up_j, h_left, c_left)
        return (h_new, c_new), (h_new, c_new)

    def row_step(carry, pre_row):
        h_up_row, c_up_row = carry            # [W, b, h] each
        zero = jnp.zeros((b, h), x.dtype)
        _, (h_row, c_row) = lax.scan(
            col_step, (zero, zero), (pre_row, h_up_row, c_up_row))
        return (h_row, c_row), h_row

    pre = jnp.moveaxis(x, 0, 2)               # [H, W, b, 5h]
    zero_row = jnp.zeros((W, b, h), x.dtype)
    _, out = lax.scan(row_step, (zero_row, zero_row), pre)
    out = jnp.moveaxis(out, 2, 0)             # [b, H, W, h]
    if reverse_h:
        out = out[:, ::-1]
    if reverse_w:
        out = out[:, :, ::-1]
    return out
