"""Connectionist Temporal Classification loss — in-tree lattice
forward algorithm.

Reference: paddle/gserver/layers/LinearChainCTC.cpp:86-200 — the same
interleaved-blank lattice (extended label sequence of length 2U+1) with
the standard three-way recurrence (stay / advance-from-blank /
skip-a-blank when labels differ). The reference runs per-sequence loops
in log space with its logMul/logAdd helpers; here the whole batch is one
`lax.scan` over time with the recurrence expressed as a shifted
logsumexp, so XLA vectorizes the lattice across batch x states.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30


def _logaddexp3(a, b, c):
    m = jnp.maximum(jnp.maximum(a, b), c)
    m_safe = jnp.maximum(m, _NEG)
    out = m_safe + jnp.log(jnp.exp(a - m_safe) + jnp.exp(b - m_safe) +
                           jnp.exp(c - m_safe))
    return jnp.where(m > _NEG / 2, out, _NEG)


def ctc_loss(logits: jnp.ndarray, logit_paddings: jnp.ndarray,
             labels: jnp.ndarray, label_paddings: jnp.ndarray,
             blank_id: int = 0) -> jnp.ndarray:
    """Per-sequence negative log-likelihood of `labels` under CTC.

    logits:         [b, T, C] UNNORMALIZED activations (log-softmaxed here,
                    as LinearChainCTC works on normalized probs)
    logit_paddings: [b, T] — 1.0 on padding frames
    labels:         [b, U] int32
    label_paddings: [b, U] — 1.0 on padding positions
    blank_id:       index of the blank class

    Matches optax.ctc_loss's contract (the previous implementation) so it
    is a drop-in replacement; values verified against both hand-computed
    lattices and optax in tests/test_ctc.py.
    """
    b, T, C = logits.shape
    U = labels.shape[1]
    S = 2 * U + 1

    logp = jax.nn.log_softmax(logits, axis=-1)
    lab_len = jnp.sum(1.0 - label_paddings, axis=1).astype(jnp.int32)  # [b]
    seq_len = jnp.sum(1.0 - logit_paddings, axis=1).astype(jnp.int32)  # [b]

    # extended label sequence z: [blank, l0, blank, l1, ..., blank]
    labels = labels.astype(jnp.int32)
    z = jnp.full((b, S), blank_id, jnp.int32)
    z = z.at[:, 1::2].set(labels)
    s_idx = jnp.arange(S)[None, :]                                  # [1, S]
    z_valid = s_idx < (2 * lab_len[:, None] + 1)

    # skip connection allowed where z[s] is a label and differs from z[s-2]
    z_prev2 = jnp.pad(z, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
    can_skip = (z != blank_id) & (z != z_prev2) & (s_idx >= 2)

    emit = jnp.take_along_axis(logp, z[:, None, :], axis=2)         # [b,T,S]

    alpha0 = jnp.full((b, S), _NEG)
    alpha0 = alpha0.at[:, 0].set(emit[:, 0, 0])
    first_lab = jnp.where(lab_len > 0, emit[:, 0, 1], _NEG)
    alpha0 = alpha0.at[:, 1].set(first_lab)
    alpha0 = jnp.where(z_valid, alpha0, _NEG)

    def step(alpha, inp):
        t, emit_t = inp
        a1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=_NEG)[:, :S]
        a2 = jnp.pad(alpha, ((0, 0), (2, 0)), constant_values=_NEG)[:, :S]
        a2 = jnp.where(can_skip, a2, _NEG)
        new = _logaddexp3(alpha, a1, a2) + emit_t
        new = jnp.where(z_valid, new, _NEG)
        live = (t < seq_len)[:, None]
        return jnp.where(live, new, alpha), None

    ts = jnp.arange(1, T)
    emits = jnp.moveaxis(emit[:, 1:, :], 1, 0)                      # [T-1,b,S]
    alphaT, _ = lax.scan(step, alpha0, (ts, emits))

    # total = logaddexp(alpha[2U], alpha[2U-1]); empty label -> alpha[0]
    last = 2 * lab_len                                              # [b]
    a_last = jnp.take_along_axis(alphaT, last[:, None], axis=1)[:, 0]
    prev = jnp.maximum(last - 1, 0)
    a_prev = jnp.take_along_axis(alphaT, prev[:, None], axis=1)[:, 0]
    a_prev = jnp.where(lab_len > 0, a_prev, _NEG)
    total = jnp.logaddexp(a_last, a_prev)
    return -total
