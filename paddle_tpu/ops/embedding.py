"""Embedding / table lookup ops.

Reference: TableProjection (gserver/layers/TableProjection.cpp) +
SparseRowCpuMatrix row-sparse gradients (math/SparseRowMatrix.h) + the
sparse-remote prefetch path (MultiGradientMachine.h:99-166,
SparsePrefetchRowCpuMatrix, RemoteParameterUpdater.h:265).

TPU-native row-sparse path: the train step PRE-GATHERS the batch's touched
rows (`touched_rows` — the prefetch), the forward looks ids up inside that
small row block (`row_sub_lookup`), autodiff produces gradients for the
row block only (never a dense [vocab, emb] buffer), and the optimizer
scatter-updates just those rows and their slots. Tables additionally shard
rows over the mesh `mp` axis (parallel/tensor_parallel.py) — the
pserver-block-sharding equivalent.
"""

from __future__ import annotations

import jax.numpy as jnp


def embedding_lookup(table: jnp.ndarray, ids: jnp.ndarray,
                     pad_id: int = -1) -> jnp.ndarray:
    """table: [vocab, d]; ids: [...] int -> [..., d]. ids == pad_id yields 0."""
    safe = jnp.clip(ids, 0, table.shape[0] - 1).astype(jnp.int32)
    out = jnp.take(table, safe, axis=0)
    if pad_id is not None:
        out = out * (ids != pad_id)[..., None].astype(out.dtype)
    return out


def touched_ids(ids: jnp.ndarray, vocab: int) -> jnp.ndarray:
    """The batch's unique ids, static-shaped: [k = ids.size] sorted, padded
    with the out-of-range sentinel `vocab` (stays sorted; scatters back
    with mode=drop). This IS the prefetch contract row_sub_lookup's binary
    search relies on — keep train-step and lookup on this one helper."""
    flat = jnp.clip(ids.reshape(-1), 0, vocab - 1).astype(jnp.int32)
    return jnp.unique(flat, size=flat.size, fill_value=vocab)


def touched_rows(table: jnp.ndarray, ids: jnp.ndarray):
    """Prefetch: (uids, rows) for the unique ids of a batch."""
    vocab = table.shape[0]
    uids = touched_ids(ids, vocab)
    rows = jnp.take(table, jnp.clip(uids, 0, vocab - 1), axis=0)
    return uids, rows


def row_sub_lookup(uids: jnp.ndarray, rows: jnp.ndarray, ids: jnp.ndarray,
                   vocab: int, pad_id: int = -1) -> jnp.ndarray:
    """Lookup through a prefetched row block: every (valid) id of the batch
    is guaranteed to be in `uids` (it came from the same batch), located by
    binary search since uids is sorted."""
    safe = jnp.clip(ids, 0, vocab - 1).astype(jnp.int32)
    pos = jnp.searchsorted(uids, safe)
    pos = jnp.clip(pos, 0, rows.shape[0] - 1)
    out = jnp.take(rows, pos, axis=0)
    if pad_id is not None:
        out = out * (ids != pad_id)[..., None].astype(out.dtype)
    return out


def one_hot(ids: jnp.ndarray, depth: int, dtype=jnp.float32) -> jnp.ndarray:
    return (ids[..., None] == jnp.arange(depth, dtype=jnp.int32)).astype(dtype)


def sparse_dot(table: jnp.ndarray, ids: jnp.ndarray,
               weights: jnp.ndarray = None) -> jnp.ndarray:
    """Sum of table rows selected by ids (sparse_binary_vector x matrix —
    the SelectiveFC / sparse input FC pattern). ids: [b, k] padded with -1."""
    rows = embedding_lookup(table, ids)                    # [b, k, d]
    if weights is not None:
        rows = rows * weights[..., None]
    return jnp.sum(rows, axis=-2)
