"""Embedding / table lookup ops.

Reference: TableProjection (gserver/layers/TableProjection.cpp) +
SparseRowCpuMatrix row-sparse gradients (math/SparseRowMatrix.h) + the
sparse-remote prefetch path (MultiGradientMachine.h:99-166). On TPU a lookup
is a gather XLA vectorizes; row-sparse gradients are unnecessary for
correctness (dense grads) but the trainer supports sharding big tables over
the mesh 'model' axis (parallel/sharding.py) which is the pserver-block
equivalent.
"""

from __future__ import annotations

import jax.numpy as jnp


def embedding_lookup(table: jnp.ndarray, ids: jnp.ndarray,
                     pad_id: int = -1) -> jnp.ndarray:
    """table: [vocab, d]; ids: [...] int -> [..., d]. ids == pad_id yields 0."""
    safe = jnp.clip(ids, 0, table.shape[0] - 1).astype(jnp.int32)
    out = jnp.take(table, safe, axis=0)
    if pad_id is not None:
        out = out * (ids != pad_id)[..., None].astype(out.dtype)
    return out


def one_hot(ids: jnp.ndarray, depth: int, dtype=jnp.float32) -> jnp.ndarray:
    return (ids[..., None] == jnp.arange(depth, dtype=jnp.int32)).astype(dtype)


def sparse_dot(table: jnp.ndarray, ids: jnp.ndarray,
               weights: jnp.ndarray = None) -> jnp.ndarray:
    """Sum of table rows selected by ids (sparse_binary_vector x matrix —
    the SelectiveFC / sparse input FC pattern). ids: [b, k] padded with -1."""
    rows = embedding_lookup(table, ids)                    # [b, k, d]
    if weights is not None:
        rows = rows * weights[..., None]
    return jnp.sum(rows, axis=-2)
