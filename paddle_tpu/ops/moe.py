"""Mixture-of-experts FFN with capacity-based top-k routing.

No 2017 reference counterpart (the reference predates MoE); this is the
expert-parallel leg of the mesh vocabulary (dp/mp/sp/pp/ep) built the
GShard/Mesh-TF way, which is also the XLA-friendly way:

  - routing is expressed as dense one-hot dispatch/combine tensors and
    einsums, so every shape is static and the whole block stays inside
    one jit trace (no data-dependent gather/scatter control flow);
  - expert weight tables carry a leading `E` dim sharded over the mesh's
    `ep` axis; with tokens sharded over `dp`, XLA lowers the dispatch
    einsum to the all-to-all over ICI that hand-written MoE stacks issue
    explicitly.

The dispatch tensor is [n, E, C] — fine for the token counts a single
chip sees (the ep axis divides E, dp divides n), but it is the textbook
memory trade-off of einsum routing. For single-host token counts past
~100k, `dispatch_mode='sort'` (moe_sorted_ffn) replaces it with an
argsort + gather/scatter that never materializes [n,E,C] — measured
crossover and numbers in docs/perf.md.

Both dispatch and combine are built in f32 (routing decisions must not
depend on the compute dtype), then cast so the big einsums run on the
MXU in the activation dtype.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def moe_capacity(n_tokens: int, num_experts: int, k: int,
                 capacity_factor: float) -> int:
    """Per-expert token budget: ceil(k * n / E * factor), at least k."""
    cap = int(-(-k * n_tokens * capacity_factor // num_experts))
    return max(cap, k)


def moe_dispatch(gate_logits: jnp.ndarray, valid: Optional[jnp.ndarray],
                 *, k: int, capacity: int, normalize: bool = True
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-k capacity routing.

    gate_logits: [n, E] (any float dtype; routing math runs in f32).
    valid: [n] 0/1 mask (padded sequence slots must not eat capacity).

    Returns (dispatch [n,E,C] 0/1, combine [n,E,C] gate-weighted,
    aux f32 scalar — the switch-transformer load-balance loss,
    E * sum_e mean(probs_e) * mean(assigned_e), which is 1.0 at a
    perfectly uniform router).

    Normalization convention (k > 1): combine weights are divided by
    the total of the KEPT slots — if one of a token's experts
    overflows capacity, the surviving expert's weight renormalizes to
    1.0. This deliberately differs from GShard, which normalizes over
    the pre-drop top-k probability mass (leaving the survivor
    underweighted); full-mass routing on the kept experts preserved
    output scale better in our convergence tests. Pass
    normalize=False for raw gate products.
    """
    n, num_experts = gate_logits.shape
    assert 1 <= k <= num_experts, (
        f"moe_dispatch: k={k} must be in [1, num_experts={num_experts}]")
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    if valid is None:
        valid = jnp.ones((n,), jnp.float32)
    valid = valid.astype(jnp.float32)
    probs = probs * valid[:, None]

    remaining = probs
    fill = jnp.zeros((num_experts,), jnp.float32)   # kept tokens per expert
    dispatch = jnp.zeros((n, num_experts, capacity), jnp.float32)
    combine = jnp.zeros((n, num_experts, capacity), jnp.float32)
    first_choice = None
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                      # [n]
        onehot = jax.nn.one_hot(idx, num_experts,
                                dtype=jnp.float32) * valid[:, None]
        if first_choice is None:
            first_choice = onehot
        gate_j = jnp.sum(probs * onehot, axis=-1)                 # [n]
        # position of each token inside its expert's buffer: tokens
        # already kept in earlier slots (fill) + earlier tokens of this
        # slot (exclusive cumsum). Overflow (pos >= capacity) is dropped.
        pos = jnp.cumsum(onehot, axis=0) - onehot + fill[None, :]
        pos_tok = jnp.sum(pos * onehot, axis=-1)                  # [n]
        keep = ((pos_tok < capacity) & (gate_j > 0)).astype(jnp.float32)
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0)
        slot = jax.nn.one_hot(pos_tok.astype(jnp.int32), capacity,
                              dtype=jnp.float32)                  # [n, C]
        placed = (onehot * keep[:, None])[:, :, None] * slot[:, None, :]
        dispatch = dispatch + placed
        combine = combine + gate_j[:, None, None] * placed
        remaining = remaining * (1.0 - onehot)

    if normalize and k > 1:
        total = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(total, 1e-9)

    n_valid = jnp.maximum(jnp.sum(valid), 1.0)
    me = jnp.sum(probs, axis=0) / n_valid            # mean router prob
    ce = jnp.sum(first_choice, axis=0) / n_valid     # mean top-1 assignment
    aux = num_experts * jnp.sum(me * ce)
    return dispatch, combine, aux


def moe_sorted_ffn(x: jnp.ndarray, valid: Optional[jnp.ndarray],
                   gate_w: jnp.ndarray, w_up: jnp.ndarray,
                   w_down: jnp.ndarray, *, k: int = 2,
                   capacity_factor: float = 1.25,
                   capacity: Optional[int] = None,
                   act=jax.nn.relu, normalize: bool = True
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sort-based dispatch: the einsum path's O(n*E*C) dispatch/combine
    tensors replaced by an argsort + gather/scatter — the Megablocks-style
    formulation for LARGE single-host token counts (>~100k), where
    [n,E,C] no longer fits and the dispatch einsum's n*E*C*d FLOPs dwarf
    the expert FFN itself.

    Numerics match moe_ffn exactly: (token, choice) pairs are ranked in
    choice-major token order per expert (a stable argsort on expert id),
    which reproduces the einsum path's fill discipline — einsum positions
    are fill(prev rounds' KEPT) + within-round rank, and fill saturates
    at capacity exactly when total prior entries do, so keep decisions
    and kept slots agree (see tests/test_sparse.py parity test).

    Single-host by design (the scatter/gather does not ride an ep
    all-to-all the way the dispatch einsum does); for the ep-sharded
    multi-chip path keep dispatch_mode='einsum'.
    """
    n, d = x.shape
    num_experts = gate_w.shape[-1]
    if capacity is None:
        capacity = moe_capacity(n, num_experts, k, capacity_factor)
    logits = jnp.dot(x.astype(jnp.float32), gate_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    if valid is None:
        valid = jnp.ones((n,), jnp.float32)
    valid = valid.astype(jnp.float32)
    probs = probs * valid[:, None]

    remaining = probs
    idx_rounds, gate_rounds = [], []
    first_choice = None
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                      # [n]
        onehot = jax.nn.one_hot(idx, num_experts,
                                dtype=jnp.float32) * valid[:, None]
        if first_choice is None:
            first_choice = onehot
        gate_rounds.append(jnp.sum(probs * onehot, axis=-1))
        # invalid tokens route to the E sentinel: they sort past every
        # real expert and never consume capacity (einsum path: onehot
        # masked by valid)
        idx_rounds.append(jnp.where(valid > 0, idx, num_experts))
        remaining = remaining * (1.0 - onehot)

    kn = k * n
    ek = jnp.concatenate(idx_rounds).astype(jnp.int32)            # [kn]
    gk = jnp.concatenate(gate_rounds)                             # [kn]
    order = jnp.argsort(ek, stable=True)     # choice-major within expert
    es = ek[order]
    gs = gk[order]
    tok = (order % n).astype(jnp.int32)      # flat entry j*n+i -> token i
    # rank within the expert's segment = global rank - segment start
    starts = jnp.searchsorted(es, jnp.arange(num_experts + 1,
                                             dtype=es.dtype))
    pos = jnp.arange(kn, dtype=jnp.int32) - starts[es].astype(jnp.int32)
    keep = ((pos < capacity) & (es < num_experts) &
            (gs > 0)).astype(jnp.float32)
    dump = num_experts * capacity            # scratch row for drops
    dest = jnp.where(keep > 0, es * capacity + pos, dump)

    cdt = x.dtype
    xs = x[tok] * keep.astype(cdt)[:, None]                       # [kn, d]
    buf = jnp.zeros((num_experts * capacity + 1, d), cdt)
    expert_in = buf.at[dest].add(xs)[:-1].reshape(
        num_experts, capacity, d)
    h = act(jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(cdt)))
    expert_out = jnp.einsum("ecf,efd->ecd", h, w_down.astype(cdt))

    w = gs * keep
    if normalize and k > 1:
        tot = jnp.zeros((n,), jnp.float32).at[tok].add(w)
        w = w / jnp.maximum(tot, 1e-9)[tok]
    flat_out = jnp.concatenate(
        [expert_out.reshape(num_experts * capacity, d),
         jnp.zeros((1, d), cdt)])
    contrib = flat_out[dest] * w.astype(cdt)[:, None]
    y = jnp.zeros((n, d), cdt).at[tok].add(contrib)

    n_valid = jnp.maximum(jnp.sum(valid), 1.0)
    me = jnp.sum(probs, axis=0) / n_valid
    ce = jnp.sum(first_choice, axis=0) / n_valid
    aux = num_experts * jnp.sum(me * ce)
    return y, aux


def moe_ffn(x: jnp.ndarray, valid: Optional[jnp.ndarray],
            gate_w: jnp.ndarray, w_up: jnp.ndarray, w_down: jnp.ndarray,
            *, k: int = 2, capacity_factor: float = 1.25,
            capacity: Optional[int] = None,
            act=jax.nn.relu, mesh=None, ep_axis: str = "ep",
            dispatch_mode: str = "einsum"
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x [n, d] -> (y [n, d], aux loss).

    gate_w [d, E]; w_up [E, d, f]; w_down [E, f, d]. When `mesh` has an
    `ep` axis the expert-major intermediates are constrained to it so
    GSPMD keeps each expert's FFN on its owning devices and inserts the
    token all-to-all at the dispatch/combine einsums.

    `capacity` overrides the factor-derived per-expert buffer; pass
    capacity=n at inference for drop-free routing (the capacity limit
    only buys memory/balance at training scale — see models/decode.py).
    """
    if dispatch_mode == "auto":
        # measured (tools/moe_dispatch_bench.py, v5e, bf16, d=512 f=2048
        # E=8 k=2): sort beats einsum at every single-host size — 1.8x at
        # 8k tokens, 5.4x at 32k — and is the only path that compiles at
        # >=131k. einsum remains for ep meshes, where the dispatch einsum
        # carries the token all-to-all.
        ep_sharded = mesh is not None and ep_axis in mesh.axis_names \
            and mesh.shape.get(ep_axis, 1) > 1
        dispatch_mode = "einsum" if ep_sharded else "sort"
    if dispatch_mode == "sort":
        assert mesh is None or ep_axis not in mesh.axis_names or \
            mesh.shape.get(ep_axis, 1) == 1, \
            "dispatch_mode='sort' is single-host; use 'einsum' under ep"
        return moe_sorted_ffn(x, valid, gate_w, w_up, w_down, k=k,
                              capacity_factor=capacity_factor,
                              capacity=capacity, act=act)
    assert dispatch_mode == "einsum", dispatch_mode
    n, d = x.shape
    num_experts = gate_w.shape[-1]
    if capacity is None:
        capacity = moe_capacity(n, num_experts, k, capacity_factor)
    logits = jnp.dot(x.astype(jnp.float32), gate_w.astype(jnp.float32))
    dispatch, combine, aux = moe_dispatch(logits, valid, k=k,
                                          capacity=capacity)
    cdt = x.dtype

    def _ep(t):
        if mesh is not None and ep_axis in mesh.axis_names:
            spec = jax.sharding.PartitionSpec(
                ep_axis, *([None] * (t.ndim - 1)))
            return jax.lax.with_sharding_constraint(
                t, jax.sharding.NamedSharding(mesh, spec))
        return t

    # [n,E,C] x [n,d] -> [E,C,d]: the token all-to-all rides this einsum
    expert_in = _ep(jnp.einsum("nec,nd->ecd", dispatch.astype(cdt), x))
    h = _ep(act(jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(cdt))))
    expert_out = _ep(jnp.einsum("ecf,efd->ecd", h, w_down.astype(cdt)))
    y = jnp.einsum("nec,ecd->nd", combine.astype(cdt), expert_out)
    return y, aux
