"""Mixture-of-experts FFN with capacity-based top-k routing.

No 2017 reference counterpart (the reference predates MoE); this is the
expert-parallel leg of the mesh vocabulary (dp/mp/sp/pp/ep) built the
GShard/Mesh-TF way, which is also the XLA-friendly way:

  - routing is expressed as dense one-hot dispatch/combine tensors and
    einsums, so every shape is static and the whole block stays inside
    one jit trace (no data-dependent gather/scatter control flow);
  - expert weight tables carry a leading `E` dim sharded over the mesh's
    `ep` axis; with tokens sharded over `dp`, XLA lowers the dispatch
    einsum to the all-to-all over ICI that hand-written MoE stacks issue
    explicitly.

The dispatch tensor is [n, E, C] — fine for the token counts a single
chip sees (the ep axis divides E, dp divides n), but it is the textbook
memory trade-off of einsum routing; a sort-based dispatch would replace
it if single-host token counts grow past ~100k.

Both dispatch and combine are built in f32 (routing decisions must not
depend on the compute dtype), then cast so the big einsums run on the
MXU in the activation dtype.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def moe_capacity(n_tokens: int, num_experts: int, k: int,
                 capacity_factor: float) -> int:
    """Per-expert token budget: ceil(k * n / E * factor), at least k."""
    cap = int(-(-k * n_tokens * capacity_factor // num_experts))
    return max(cap, k)


def moe_dispatch(gate_logits: jnp.ndarray, valid: Optional[jnp.ndarray],
                 *, k: int, capacity: int, normalize: bool = True
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-k capacity routing.

    gate_logits: [n, E] (any float dtype; routing math runs in f32).
    valid: [n] 0/1 mask (padded sequence slots must not eat capacity).

    Returns (dispatch [n,E,C] 0/1, combine [n,E,C] gate-weighted,
    aux f32 scalar — the switch-transformer load-balance loss,
    E * sum_e mean(probs_e) * mean(assigned_e), which is 1.0 at a
    perfectly uniform router).

    Normalization convention (k > 1): combine weights are divided by
    the total of the KEPT slots — if one of a token's experts
    overflows capacity, the surviving expert's weight renormalizes to
    1.0. This deliberately differs from GShard, which normalizes over
    the pre-drop top-k probability mass (leaving the survivor
    underweighted); full-mass routing on the kept experts preserved
    output scale better in our convergence tests. Pass
    normalize=False for raw gate products.
    """
    n, num_experts = gate_logits.shape
    assert 1 <= k <= num_experts, (
        f"moe_dispatch: k={k} must be in [1, num_experts={num_experts}]")
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    if valid is None:
        valid = jnp.ones((n,), jnp.float32)
    valid = valid.astype(jnp.float32)
    probs = probs * valid[:, None]

    remaining = probs
    fill = jnp.zeros((num_experts,), jnp.float32)   # kept tokens per expert
    dispatch = jnp.zeros((n, num_experts, capacity), jnp.float32)
    combine = jnp.zeros((n, num_experts, capacity), jnp.float32)
    first_choice = None
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                      # [n]
        onehot = jax.nn.one_hot(idx, num_experts,
                                dtype=jnp.float32) * valid[:, None]
        if first_choice is None:
            first_choice = onehot
        gate_j = jnp.sum(probs * onehot, axis=-1)                 # [n]
        # position of each token inside its expert's buffer: tokens
        # already kept in earlier slots (fill) + earlier tokens of this
        # slot (exclusive cumsum). Overflow (pos >= capacity) is dropped.
        pos = jnp.cumsum(onehot, axis=0) - onehot + fill[None, :]
        pos_tok = jnp.sum(pos * onehot, axis=-1)                  # [n]
        keep = ((pos_tok < capacity) & (gate_j > 0)).astype(jnp.float32)
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0)
        slot = jax.nn.one_hot(pos_tok.astype(jnp.int32), capacity,
                              dtype=jnp.float32)                  # [n, C]
        placed = (onehot * keep[:, None])[:, :, None] * slot[:, None, :]
        dispatch = dispatch + placed
        combine = combine + gate_j[:, None, None] * placed
        remaining = remaining * (1.0 - onehot)

    if normalize and k > 1:
        total = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(total, 1e-9)

    n_valid = jnp.maximum(jnp.sum(valid), 1.0)
    me = jnp.sum(probs, axis=0) / n_valid            # mean router prob
    ce = jnp.sum(first_choice, axis=0) / n_valid     # mean top-1 assignment
    aux = num_experts * jnp.sum(me * ce)
    return dispatch, combine, aux


def moe_ffn(x: jnp.ndarray, valid: Optional[jnp.ndarray],
            gate_w: jnp.ndarray, w_up: jnp.ndarray, w_down: jnp.ndarray,
            *, k: int = 2, capacity_factor: float = 1.25,
            capacity: Optional[int] = None,
            act=jax.nn.relu, mesh=None, ep_axis: str = "ep"
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x [n, d] -> (y [n, d], aux loss).

    gate_w [d, E]; w_up [E, d, f]; w_down [E, f, d]. When `mesh` has an
    `ep` axis the expert-major intermediates are constrained to it so
    GSPMD keeps each expert's FFN on its owning devices and inserts the
    token all-to-all at the dispatch/combine einsums.

    `capacity` overrides the factor-derived per-expert buffer; pass
    capacity=n at inference for drop-free routing (the capacity limit
    only buys memory/balance at training scale — see models/decode.py).
    """
    n, d = x.shape
    num_experts = gate_w.shape[-1]
    if capacity is None:
        capacity = moe_capacity(n, num_experts, k, capacity_factor)
    logits = jnp.dot(x.astype(jnp.float32), gate_w.astype(jnp.float32))
    dispatch, combine, aux = moe_dispatch(logits, valid, k=k,
                                          capacity=capacity)
    cdt = x.dtype

    def _ep(t):
        if mesh is not None and ep_axis in mesh.axis_names:
            spec = jax.sharding.PartitionSpec(
                ep_axis, *([None] * (t.ndim - 1)))
            return jax.lax.with_sharding_constraint(
                t, jax.sharding.NamedSharding(mesh, spec))
        return t

    # [n,E,C] x [n,d] -> [E,C,d]: the token all-to-all rides this einsum
    expert_in = _ep(jnp.einsum("nec,nd->ecd", dispatch.astype(cdt), x))
    h = _ep(act(jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(cdt))))
    expert_out = _ep(jnp.einsum("ecf,efd->ecd", h, w_down.astype(cdt)))
    y = jnp.einsum("nec,ecd->nd", combine.astype(cdt), expert_out)
    return y, aux
