"""paddle_tpu — a TPU-native deep-learning framework.

Re-implements the capability surface of 2017-era PaddlePaddle (reference:
lixu18/Paddle) as an idiomatic JAX/XLA framework: functional ops compiled by
XLA, `jax.sharding.Mesh` + jit-sharded training replacing the multi-GPU
trainer and parameter-server stack, a scan-based dynamic recurrent engine
with beam search, and a `paddle.v2`-shaped user API.

Reference parity map (reference file:line cites live in each module):
  - paddle/math + paddle/cuda        -> XLA (+ paddle_tpu/ops/pallas_*)
  - paddle/gserver layers            -> paddle_tpu/ops, paddle_tpu/layers
  - config_parser / ModelConfig      -> paddle_tpu/core/topology.py
  - paddle/trainer                   -> paddle_tpu/trainer
  - paddle/parameter optimizers      -> paddle_tpu/optimizer
  - MultiGradientMachine / pserver   -> paddle_tpu/parallel (mesh + collectives)
  - go/master elastic runtime        -> paddle_tpu/trainer/coordinator.py
  - python/paddle/v2 API             -> paddle_tpu (this package's top level)
"""

__version__ = "0.5.0"

from paddle_tpu import config as _config
from paddle_tpu.config import init
from paddle_tpu import layers as layer  # paddle.v2 calls this module `layer`
from paddle_tpu import optimizer
from paddle_tpu import trainer
from paddle_tpu.trainer import event
from paddle_tpu.trainer.parameters import Parameters, create as create_parameters
from paddle_tpu.trainer.trainer import SGD
from paddle_tpu.trainer.inference import infer, Inference
from paddle_tpu import reader
from paddle_tpu import dataset
from paddle_tpu.core.topology import Topology
from paddle_tpu.core import data_type
from paddle_tpu import activation
from paddle_tpu import attr
from paddle_tpu import pooling
from paddle_tpu import evaluator
from paddle_tpu import op            # also installs LayerOutput operators
from paddle_tpu import model

__all__ = [
    "init",
    "layer",
    "optimizer",
    "trainer",
    "event",
    "Parameters",
    "create_parameters",
    "SGD",
    "infer",
    "Inference",
    "reader",
    "dataset",
    "Topology",
    "data_type",
    "activation",
    "attr",
    "pooling",
    "op",
    "model",
]
