"""paddle.v2.model — cloud-aware model save/load.

Reference: python/paddle/v2/model.py. ``save_model`` has two modes:

  - local: write ``parameters.to_tar`` to the given path (creating parent
    directories, model.py:26 mkdir_p);
  - cloud: every trainer calls in, but exactly one wins the coordinator's
    save election (model.py:53 request_save_model against the Go master;
    here trainer/coordinator.py request_save_model, service.go:474 parity)
    and writes to ``<path>/<trainer_id>/model.tar``.

The reference detects cloud mode via KUBERNETES_SERVICE_HOST + MASTER_IP
env vars; here the coordinator endpoint comes from
``PADDLE_TPU_COORDINATOR`` (``host:port``, the address a
`trainer.coordinator.CoordinatorServer` prints) so the path works in any
cluster, not just k8s.
"""

from __future__ import annotations

import os
import uuid

__all__ = ["save_model", "load_model"]

# one id per trainer process, as the reference (model.py:23)
trainer_id = str(uuid.uuid4())


def _coordinator_endpoint():
    ep = os.environ.get("PADDLE_TPU_COORDINATOR")
    if not ep:
        return None
    host, _, port = ep.rpartition(":")
    return host or "127.0.0.1", int(port)


def save_model(parameters, path: str, epoch: int = None,
               window_s: float = 30.0) -> bool:
    """Save ``parameters`` to ``path``; under a coordinator, only the
    election winner writes. Returns True if this process saved.

    ``epoch`` keys the election (one winner per epoch). Omitted — the
    reference's save_model takes no epoch; callers save once per pass —
    the coordinator grants one winner per time window, resolved
    server-side under its save lock (the Go master's
    RequestSaveModel-with-duration semantics, service.go:474); keying on
    a separately-read pass counter would let two trainers straddling a
    pass turnover both win.

    ``window_s`` is forwarded as the election window (the Go client's
    BlockDur), and this process's ``trainer_id`` rides along so the
    CURRENT winner re-requesting is re-granted (service.go:474
    TrainerID==savingTrainer) — a single trainer saving faster than the
    window never silently skips a save."""
    ep = _coordinator_endpoint()
    if ep is not None:
        from paddle_tpu.trainer.coordinator import connect
        if not connect(*ep).request_save_model(epoch, window_s,
                                               trainer_id):
            return False
        path = os.path.join(path, trainer_id, "model.tar")

    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "wb") as f:
        parameters.to_tar(f)
    return True


def load_model(parameters, path: str) -> None:
    """In-place load into an existing Parameters (model.py:71)."""
    with open(path, "rb") as f:
        parameters.init_from_tar(f)
