"""Network composition helpers — trainer_config_helpers/networks.py parity.

Reference: python/paddle/trainer_config_helpers/networks.py
(simple_img_conv_pool:65, img_conv_bn_pool:132, img_conv_group:216,
vgg_16_network:465, simple_lstm:528, lstmemory_group:786,
simple_gru:817, bidirectional_lstm:1207, simple_attention:1298,
sequence_conv_pool, text_conv_pool). These are pure composition helpers over
the layer DSL — no compute of their own; XLA fuses the resulting graph.
"""

from __future__ import annotations

from typing import Optional, Sequence

from paddle_tpu import layers as layer
from paddle_tpu import activation as act
from paddle_tpu import pooling
from paddle_tpu.core.registry import LayerOutput, _auto_name


# ---------------------------------------------------------------------------
# image stacks


def simple_img_conv_pool(input, filter_size: int, num_filters: int,
                         pool_size: int, name: Optional[str] = None,
                         pool_type=None, act=None, groups: int = 1,
                         conv_stride: int = 1, conv_padding: int = 0,
                         pool_stride: int = 1, pool_padding: int = 0,
                         num_channels: Optional[int] = None,
                         bias_attr=None, param_attr=None) -> LayerOutput:
    """conv -> pool (networks.py:65)."""
    name = name or _auto_name("conv_pool")
    c = layer.img_conv(input, filter_size=filter_size,
                       num_filters=num_filters, num_channels=num_channels,
                       stride=conv_stride, padding=conv_padding,
                       groups=groups, act=act, bias_attr=bias_attr,
                       param_attr=param_attr, name=f"{name}_conv")
    return layer.img_pool(c, pool_size=pool_size, stride=pool_stride,
                          padding=pool_padding, pool_type=pool_type,
                          name=f"{name}_pool")


def img_conv_bn_pool(input, filter_size: int, num_filters: int,
                     pool_size: int, name: Optional[str] = None,
                     pool_type=None, act=None, groups: int = 1,
                     conv_stride: int = 1, conv_padding: int = 0,
                     pool_stride: int = 1, pool_padding: int = 0,
                     num_channels: Optional[int] = None) -> LayerOutput:
    """conv -> batch_norm -> pool (networks.py:132)."""
    name = name or _auto_name("conv_bn_pool")
    c = layer.img_conv(input, filter_size=filter_size,
                       num_filters=num_filters, num_channels=num_channels,
                       stride=conv_stride, padding=conv_padding,
                       groups=groups, act=None, bias_attr=False,
                       name=f"{name}_conv")
    bn = layer.batch_norm(c, act=act, name=f"{name}_bn")
    return layer.img_pool(bn, pool_size=pool_size, stride=pool_stride,
                          padding=pool_padding, pool_type=pool_type,
                          name=f"{name}_pool")


def img_conv_group(input, conv_num_filter: Sequence[int],
                   pool_size: int, num_channels: Optional[int] = None,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False, pool_stride: int = 1,
                   pool_type=None, name: Optional[str] = None) -> LayerOutput:
    """N convs (opt. BN) then one pool — the VGG block (networks.py:216)."""
    name = name or _auto_name("conv_group")
    conv_act = conv_act or act.Relu()

    def _seq(v, n):
        return v if isinstance(v, (list, tuple)) else [v] * n

    n = len(conv_num_filter)
    pads = _seq(conv_padding, n)
    ks = _seq(conv_filter_size, n)
    bns = _seq(conv_with_batchnorm, n)
    tmp = input
    for i in range(n):
        tmp = layer.img_conv(tmp, filter_size=ks[i],
                             num_filters=conv_num_filter[i],
                             num_channels=num_channels if i == 0 else None,
                             padding=pads[i],
                             act=None if bns[i] else conv_act,
                             bias_attr=not bns[i],
                             name=f"{name}_conv{i}")
        if bns[i]:
            tmp = layer.batch_norm(tmp, act=conv_act, name=f"{name}_bn{i}")
    return layer.img_pool(tmp, pool_size=pool_size, stride=pool_stride,
                          pool_type=pool_type, name=f"{name}_pool")


def vgg_16_network(input_image, num_channels: int, num_classes: int = 1000,
                   name: str = "vgg16") -> LayerOutput:
    """VGG-16 (networks.py:465): 5 conv groups (2,2,3,3,3) + 2 fc4096."""
    tmp = input_image
    cfgs = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]
    for gi, (reps, nf) in enumerate(cfgs):
        tmp = img_conv_group(
            tmp, conv_num_filter=[nf] * reps, pool_size=2, pool_stride=2,
            num_channels=num_channels if gi == 0 else None,
            conv_with_batchnorm=True, name=f"{name}_g{gi}")
    tmp = layer.dropout(tmp, 0.5, name=f"{name}_drop0")
    tmp = layer.fc(tmp, size=4096, act=act.Relu(), name=f"{name}_fc6")
    tmp = layer.dropout(tmp, 0.5, name=f"{name}_drop1")
    tmp = layer.fc(tmp, size=4096, act=act.Relu(), name=f"{name}_fc7")
    return layer.fc(tmp, size=num_classes, act=act.Softmax(),
                    name=f"{name}_out")


# ---------------------------------------------------------------------------
# recurrent stacks


def simple_lstm(input, size: int, name: Optional[str] = None,
                reverse: bool = False, act=None, gate_act=None,
                state_act=None, mat_param_attr=None, bias_param_attr=None,
                inner_param_attr=None) -> LayerOutput:
    """fc(4*size) -> lstmemory (networks.py:528)."""
    name = name or _auto_name("lstm")
    mix = layer.fc(input, size=size * 4, act=None, bias_attr=False,
                   param_attr=mat_param_attr, name=f"{name}_transform")
    return layer.lstmemory(mix, name=name, reverse=reverse, act=act,
                           gate_act=gate_act, state_act=state_act,
                           bias_attr=bias_param_attr,
                           param_attr=inner_param_attr)


def simple_gru(input, size: int, name: Optional[str] = None,
               reverse: bool = False, act=None, gate_act=None,
               mixed_param_attr=None, gru_param_attr=None,
               gru_bias_attr=None) -> LayerOutput:
    """fc(3*size) -> grumemory (networks.py:817)."""
    name = name or _auto_name("gru")
    mix = layer.fc(input, size=size * 3, act=None, bias_attr=False,
                   param_attr=mixed_param_attr, name=f"{name}_transform")
    return layer.grumemory(mix, name=name, reverse=reverse, act=act,
                           gate_act=gate_act, param_attr=gru_param_attr,
                           bias_attr=gru_bias_attr)


def bidirectional_lstm(input, size: int, name: Optional[str] = None,
                       return_seq: bool = False) -> LayerOutput:
    """fwd & bwd simple_lstm, concat (networks.py:1207)."""
    name = name or _auto_name("bilstm")
    fwd = simple_lstm(input, size, name=f"{name}_fw", reverse=False)
    bwd = simple_lstm(input, size, name=f"{name}_bw", reverse=True)
    if return_seq:
        return layer.concat([fwd, bwd], name=f"{name}_concat")
    f_last = layer.last_seq(fwd, name=f"{name}_fw_last")
    b_first = layer.first_seq(bwd, name=f"{name}_bw_first")
    return layer.concat([f_last, b_first], name=f"{name}_concat")


def bidirectional_gru(input, size: int, name: Optional[str] = None,
                      return_seq: bool = False) -> LayerOutput:
    name = name or _auto_name("bigru")
    fwd = simple_gru(input, size, name=f"{name}_fw", reverse=False)
    bwd = simple_gru(input, size, name=f"{name}_bw", reverse=True)
    if return_seq:
        return layer.concat([fwd, bwd], name=f"{name}_concat")
    f_last = layer.last_seq(fwd, name=f"{name}_fw_last")
    b_first = layer.first_seq(bwd, name=f"{name}_bw_first")
    return layer.concat([f_last, b_first], name=f"{name}_concat")


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     name: Optional[str] = None) -> LayerOutput:
    """Bahdanau-style additive attention (networks.py:1298).

    score_t = v . tanh(enc_proj_t + W s);  context = sum_t softmax(score)_t
    * enc_t.  Runs inside a recurrent_group step: encoded_sequence /
    encoded_proj are StaticInput sequences, decoder_state a memory.
    """
    name = name or _auto_name("attention")
    dec_expand = layer.expand(decoder_state, expand_as=encoded_proj,
                              name=f"{name}_expand")
    combined = layer.addto([encoded_proj, dec_expand], act=act.Tanh(),
                           name=f"{name}_combine")
    scores = layer.fc(combined, size=1, act=act.SequenceSoftmax(),
                      bias_attr=False, param_attr=softmax_param_attr,
                      name=f"{name}_weight")
    scaled = layer.scaling(scores, encoded_sequence, name=f"{name}_scale")
    return layer.pooling(scaled, pooling_type=pooling.Sum(),
                         name=f"{name}_context")


# ---------------------------------------------------------------------------
# text conv


def sequence_conv_pool(input, context_len: int, hidden_size: int,
                       name: Optional[str] = None, context_start=None,
                       pool_type=None, context_proj_param_attr=None,
                       fc_param_attr=None, fc_act=None) -> LayerOutput:
    """context window projection -> fc -> seq pool (text CNN block)."""
    name = name or _auto_name("seq_conv_pool")
    ctx = layer.context_projection(input, context_len=context_len,
                                   context_start=context_start,
                                   param_attr=context_proj_param_attr,
                                   name=f"{name}_ctx")
    hidden = layer.fc(ctx, size=hidden_size, act=fc_act or act.Tanh(),
                      param_attr=fc_param_attr, name=f"{name}_fc")
    return layer.pooling(hidden, pooling_type=pool_type or pooling.Max(),
                         name=f"{name}_pool")


text_conv_pool = sequence_conv_pool
