"""SLO watchdog — declarative objectives + step-regression detection
with per-phase attribution.

Two complementary detectors share one watchdog:

- **Objectives** (``Objective``): declarative bounds over rolling
  windows of stats-derived metrics — step-time p99, TTFT, tokens/s,
  shed rate, anything a registered source's ``stats()`` dict exposes.
  When the out-of-bound fraction of the window (the *burn rate*)
  crosses ``burn_threshold``, the watchdog journals an ``slo/breach``
  record. Sources are polled by ``evaluate()`` — driven off-thread by
  the profiler's ``pt-obs-profiler`` sampler (obs/profile.py), or
  inline by tests.
- **Step regression / stall** (the headline): every observed step's
  wall time is compared to the rolling median of *healthy* samples;
  ``> regression_factor x median`` for ``regression_steps``
  consecutive steps journals ``slo/step_regression`` — carrying the
  *attributed phase*, the per-phase breakdown entry that grew most
  over its own rolling median — and auto-dumps a flight bundle whose
  reason names that phase (``slo_step_regression_<phase>``). The
  flight recorder's per-reason ``min_dump_interval`` guarantees a
  recent unrelated dump cannot suppress it (obs/flight.py).

Anomalous samples are NOT folded into the rolling medians, so a
sustained stall is measured against the pre-stall baseline instead of
normalizing itself away. Breach emission is cooled down per detector
key so a wedged run journals a heartbeat, not a firehose. Everything
here is advisory: the watchdog never raises into a hot path and never
throttles the workload itself.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import asdict, dataclass
from statistics import median
from typing import Callable, Dict, List, Optional

__all__ = ["Objective", "parse_objective", "SLOWatchdog", "WATCHDOG"]


@dataclass(frozen=True)
class Objective:
    """One declarative service-level objective.

    ``metric`` names a key in some registered source's stats dict
    (e.g. ``ttft_p50_ms``, ``p99_ms``, ``shed_rate``,
    ``tokens_per_s``). ``kind`` is the healthy direction: ``upper``
    means values must stay <= target (latencies, shed rate),
    ``lower`` means >= target (throughput)."""
    name: str
    metric: str
    target: float
    kind: str = "upper"          # upper: v <= target | lower: v >= target
    window: int = 32             # rolling samples per evaluation window
    burn_threshold: float = 0.5  # out-of-bound fraction that breaches

    def violated(self, value: float) -> bool:
        if self.kind == "lower":
            return value < self.target
        return value > self.target


def parse_objective(spec: str) -> Objective:
    """``"metric<=target"`` / ``"metric>=target"`` (CLI ``--slo``),
    optionally ``@window`` — e.g. ``ttft_p50_ms<=50`` or
    ``tokens_per_s>=100@64``."""
    window = 32
    body = spec.strip()
    if "@" in body:
        body, w = body.rsplit("@", 1)
        window = max(2, int(w))
    for op, kind in (("<=", "upper"), (">=", "lower")):
        if op in body:
            metric, target = body.split(op, 1)
            metric = metric.strip()
            return Objective(name=metric, metric=metric,
                             target=float(target), kind=kind,
                             window=window)
    raise ValueError(f"objective spec {spec!r}: expected "
                     f"'metric<=target' or 'metric>=target'")


class SLOWatchdog:
    """Process-global watchdog (module doc). Thread-safe; journal and
    flight-dump calls happen outside the internal lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._enabled = False
        self._objectives: List[Objective] = []
        self._sources: Dict[str, Callable[[], Optional[dict]]] = {}
        self._windows: Dict[str, deque] = {}
        self._regression_factor = 3.0
        self._regression_steps = 3
        self._median_window = 64
        self._min_samples = 8
        self._cooldown_s = 30.0
        self._step_hist: Dict[str, deque] = {}
        self._phase_hist: Dict[str, Dict[str, deque]] = {}
        self._last_phases: Dict[str, Dict[str, float]] = {}
        self._streak: Dict[str, int] = {}
        self._last_breach_t: Dict[str, float] = {}
        self._breaches = 0
        self._listeners: List[Callable[[dict], None]] = []

    # ------------------------------------------------------------ config
    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def breaches(self) -> int:
        with self._lock:
            return self._breaches

    def configure(self, objectives: Optional[List[Objective]] = None,
                  regression_factor: Optional[float] = None,
                  regression_steps: Optional[int] = None,
                  median_window: Optional[int] = None,
                  min_samples: Optional[int] = None,
                  cooldown_s: Optional[float] = None,
                  enabled: bool = True) -> None:
        with self._lock:
            if objectives is not None:
                self._objectives = list(objectives)
                self._windows.clear()
            if regression_factor is not None:
                self._regression_factor = float(regression_factor)
            if regression_steps is not None:
                self._regression_steps = max(1, int(regression_steps))
            if median_window is not None:
                self._median_window = max(4, int(median_window))
            if min_samples is not None:
                self._min_samples = max(2, int(min_samples))
            if cooldown_s is not None:
                self._cooldown_s = max(0.0, float(cooldown_s))
            self._enabled = bool(enabled)

    def add_source(self, name: str,
                   fn: Callable[[], Optional[dict]]) -> None:
        """``fn()`` returns a flat-ish stats dict (or None once its
        owner is gone — the source is then dropped). The engine,
        server, and profiler each register one."""
        with self._lock:
            self._sources[name] = fn

    def add_breach_listener(self,
                            fn: Callable[[dict], None]) -> None:
        """Subscribe to breach records — ``fn(record)`` is called for
        every ``slo/breach`` and ``slo/step_regression`` the watchdog
        emits, from the emitting thread, OUTSIDE the watchdog lock and
        after the journal record. The fleet autopilot's SLO leg rides
        this seam (fleet/autopilot.py); a raising listener is isolated
        (the watchdog never lets a subscriber break detection)."""
        with self._lock:
            self._listeners.append(fn)

    def remove_breach_listener(self,
                               fn: Callable[[dict], None]) -> None:
        with self._lock:
            try:
                self._listeners.remove(fn)
            except ValueError:
                pass

    def _notify(self, record: dict) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(dict(record))
            except Exception:  # noqa: BLE001 — advisory subscribers
                pass           # must never break breach detection

    # --------------------------------------------------- step regression
    def observe_step(self, kind: str, dt_ms: float,
                     phases: Optional[Dict[str, float]] = None) -> None:
        """One observed step of wall time ``dt_ms``; ``phases`` is the
        profiler's latest per-phase ms breakdown when this step was
        sampled (None in between — the last seen one attributes)."""
        if not self._enabled:
            return
        breach = None
        with self._lock:
            hist = self._step_hist.setdefault(
                kind, deque(maxlen=self._median_window))
            med = median(hist) if len(hist) >= self._min_samples else None
            if phases:
                self._last_phases[kind] = dict(phases)
            anomalous = med is not None \
                and dt_ms > self._regression_factor * med
            if anomalous:
                self._streak[kind] = self._streak.get(kind, 0) + 1
                if self._streak[kind] >= self._regression_steps:
                    self._streak[kind] = 0
                    key = f"step_regression/{kind}"
                    if self._cooled_locked(key):
                        phase = self._attribute_locked(kind)
                        self._breaches += 1
                        breach = {"kind_": kind,
                                  "step_ms": round(dt_ms, 3),
                                  "median_ms": round(med, 3),
                                  "factor": round(dt_ms / med, 2),
                                  "threshold": self._regression_factor,
                                  "streak": self._regression_steps,
                                  "phase": phase}
            else:
                self._streak[kind] = 0
                hist.append(dt_ms)
                if phases:
                    ph_hist = self._phase_hist.setdefault(kind, {})
                    for p, v in phases.items():
                        ph_hist.setdefault(
                            p, deque(maxlen=self._median_window)
                        ).append(v)
        if breach is not None:
            from paddle_tpu.obs.events import emit
            from paddle_tpu.obs.flight import FLIGHT
            emit("slo", "step_regression", step_kind=breach["kind_"],
                 step_ms=breach["step_ms"],
                 median_ms=breach["median_ms"],
                 factor=breach["factor"], threshold=breach["threshold"],
                 streak=breach["streak"], phase=breach["phase"])
            FLIGHT.maybe_autodump(
                f"slo_step_regression_{breach['phase']}")
            self._notify({"detector": "step_regression", **breach})

    def _attribute_locked(self, kind: str) -> str:
        """The phase whose latest sampled value grew the most over its
        own healthy median — 'which phase ate the regression'."""
        latest = self._last_phases.get(kind) or {}
        hists = self._phase_hist.get(kind) or {}
        best_phase, best_growth = None, 0.0
        for phase, val in latest.items():
            h = hists.get(phase)
            base = median(h) if h else 0.0
            growth = val - base
            if growth > best_growth:
                best_phase, best_growth = phase, growth
        return best_phase or "unattributed"

    def _cooled_locked(self, key: str) -> bool:
        now = time.monotonic()
        last = self._last_breach_t.get(key)
        if last is not None and now - last < self._cooldown_s:
            return False
        self._last_breach_t[key] = now
        return True

    # ------------------------------------------------------- objectives
    def evaluate(self) -> List[dict]:
        """Poll every source, fold metric values into the per-objective
        rolling windows, and journal ``slo/breach`` for any objective
        whose burn rate crossed its threshold. Returns the breach
        records emitted (for tests/CLI)."""
        if not self._enabled:
            return []
        with self._lock:
            sources = list(self._sources.items())
            objectives = list(self._objectives)
        if not objectives:
            return []
        stats: Dict[str, float] = {}
        dead: List[str] = []
        for name, fn in sources:
            try:
                s = fn()
            except Exception:  # noqa: BLE001 — a dying source is dropped
                s = None
            if s is None:
                dead.append(name)
                continue
            for k, v in s.items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                stats.setdefault(k, float(v))
        if dead:
            with self._lock:
                for name in dead:
                    self._sources.pop(name, None)
        breaches: List[dict] = []
        for obj in objectives:
            if obj.metric not in stats:
                continue
            value = stats[obj.metric]
            with self._lock:
                w = self._windows.setdefault(
                    obj.name, deque(maxlen=obj.window))
                w.append(value)
                if len(w) < max(2, obj.window // 2):
                    continue
                burn = sum(1 for x in w if obj.violated(x)) / len(w)
                if burn < obj.burn_threshold:
                    continue
                if not self._cooled_locked(f"breach/{obj.name}"):
                    continue
                self._breaches += 1
            breaches.append({
                "objective": obj.name, "metric": obj.metric,
                "value": round(value, 4), "target": obj.target,
                "bound": obj.kind, "burn_rate": round(burn, 3),
                "window": len(w)})
        if breaches:
            from paddle_tpu.obs.events import emit
            from paddle_tpu.obs.flight import FLIGHT
            for b in breaches:
                emit("slo", "breach", **b)
                FLIGHT.maybe_autodump(f"slo_breach_{b['objective']}")
                self._notify({"detector": "objective", **b})
        return breaches

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": self._enabled,
                "objectives": [asdict(o) for o in self._objectives],
                "sources": sorted(self._sources),
                "breaches": self._breaches,
                "regression": {
                    "factor": self._regression_factor,
                    "steps": self._regression_steps,
                    "median_window": self._median_window,
                    "min_samples": self._min_samples,
                },
            }

    def reset(self) -> None:
        """Between-tests hygiene (obs.reset_all)."""
        with self._lock:
            self._enabled = False
            self._objectives = []
            self._sources.clear()
            self._windows.clear()
            self._regression_factor = 3.0
            self._regression_steps = 3
            self._median_window = 64
            self._min_samples = 8
            self._cooldown_s = 30.0
            self._step_hist.clear()
            self._phase_hist.clear()
            self._last_phases.clear()
            self._streak.clear()
            self._last_breach_t.clear()
            self._breaches = 0
            self._listeners.clear()


#: the process-global watchdog (profiler-driven; CLI --slo wires it)
WATCHDOG = SLOWatchdog()
