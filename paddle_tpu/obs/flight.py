"""Flight recorder — an always-on bounded ring of recent spans and
events, dumped as a postmortem bundle when something dies.

The step tracer (obs/trace.py) is a *window* tool: you arm it, capture
a few steps, export. A production incident never arms anything — the
fault fires first. This module is the black box that is always
writing: a fixed-size ring (``capacity`` records, small dicts — memory
is bounded by construction and the cost per record is one dict build +
deque append; bench.py's ``flight_recorder_overhead`` row gates it in
tier-1) fed by

- every ``stat_timer``/``Tracer.span`` scope (obs/trace.py pushes a
  compact span record here even when no trace window is armed),
- every journal record (obs/events.py observer — sheds, faults, OOMs,
  preemptions, breaker flips land in the ring automatically),
- explicit :func:`record` calls on hot-path seams that want more
  detail than the journal should carry (the decode engine's per-slot
  step records — serving/engine.py — are how a request's "each decode
  step" chain stays reconstructable by trace_id).

``dump()`` writes the postmortem bundle: the ring, a metrics-registry
snapshot, the journal's last seq + recent tail, and every registered
live-state provider (active requests/slots from the serving stack).
Auto-dump fires on journal trigger kinds (trainer nonfinite/rollback
streaks, engine step_failure, breaker open, OOM), on a fatal uncaught
exception (``install_excepthook``), and on SIGTERM (cli.py wires it);
``paddle_tpu obs dump`` fetches one on demand (locally or over the
``GET /flight`` endpoint). Rate-limited so an event storm produces one
bundle, not a disk full of them.

docs/observability.md "Trace context & postmortems" documents the
bundle format; tests/test_flight.py is the chaos acceptance (an
injected mid-decode fault must yield a bundle from which the failing
request's full span chain is reconstructable by trace_id alone).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from paddle_tpu.obs import context as obs_context
from paddle_tpu.analysis.lockdep import named_lock
from paddle_tpu.utils.logging import get_logger

__all__ = ["FlightRecorder", "FLIGHT", "record", "install_excepthook",
           "BUNDLE_VERSION", "AUTO_DUMP_TRIGGERS"]

BUNDLE_VERSION = 1

#: (domain, kind) journal records that auto-dump a bundle. ``serving/
#: breaker`` additionally requires state == "open" (closing a breaker
#: is a recovery, not an incident).
AUTO_DUMP_TRIGGERS = {
    ("trainer", "nonfinite"),   # FaultEvent streak live
    ("trainer", "rollback"),    # streak hit the policy limit
    ("trainer", "oom"),
    ("engine", "step_failure"),
    ("serving", "breaker"),
    ("lockdep", "inversion"),   # would-be deadlock witnessed
    ("protocol", "violation"),  # declared machine broken (ptproto)
}


class FlightRecorder:
    """See module doc. Thread-safe; every mutator takes the one lock,
    and ``dump()`` only reads snapshots."""

    def __init__(self, capacity: int = 4096,
                 min_dump_interval: float = 30.0):
        self._lock = named_lock("obs.flight")
        self._ring: deque = deque(maxlen=int(capacity))  # ptlint: guarded-by(obs.flight)
        self.enabled = True
        self._dump_dir: Optional[str] = None
        self._min_dump_interval = float(min_dump_interval)
        # rate limit is PER REASON: an SLO-breach dump must not be
        # suppressed because an unrelated breaker-open dumped seconds
        # ago — each distinct reason gets its own interval clock
        self._last_dump_t: Dict[str, float] = {}
        self._providers: Dict[str, Callable[[], Optional[dict]]] = {}
        self._dumps = 0
        self._dump_errors = 0

    # ------------------------------------------------------------ config
    def configure(self, dump_dir: Optional[str] = None,
                  capacity: Optional[int] = None,
                  enabled: Optional[bool] = None,
                  min_dump_interval: Optional[float] = None) -> None:
        """``dump_dir`` arms auto-dump (None leaves it as-is; auto-dump
        is off until a dir is configured — manual ``dump()`` always
        works). ``capacity`` resizes the ring (contents kept, newest
        last)."""
        with self._lock:
            if dump_dir is not None:
                os.makedirs(dump_dir, exist_ok=True)
                self._dump_dir = dump_dir
            if capacity is not None:
                self._ring = deque(self._ring, maxlen=int(capacity))
            if enabled is not None:
                self.enabled = bool(enabled)
            if min_dump_interval is not None:
                self._min_dump_interval = float(min_dump_interval)

    @property
    def dump_dir(self) -> Optional[str]:
        with self._lock:
            return self._dump_dir

    @property
    def dumps(self) -> int:
        with self._lock:
            return self._dumps

    # ---------------------------------------------------------- recording
    def record(self, kind: str, name: str, **fields) -> None:
        """One ring record; ``kind`` groups it (span | event | mark |
        the caller's own vocabulary). Context IDs (trace_id, step) are
        stamped from the calling thread unless passed explicitly."""
        if not self.enabled:
            return
        ctx = obs_context.current()
        rec = {"t": time.time(), "kind": str(kind), "name": str(name)}
        if ctx.trace_id is not None and "trace_id" not in fields:
            rec["trace_id"] = ctx.trace_id
        if ctx.step is not None and "step" not in fields:
            rec["step"] = ctx.step
        rec.update(fields)
        with self._lock:
            self._ring.append(rec)

    def record_raw(self, rec: dict) -> None:
        """Append a pre-built record (the tracer's compact span shape,
        the journal observer's event records) without re-stamping."""
        if not self.enabled:
            return
        with self._lock:
            self._ring.append(rec)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    # ------------------------------------------------------- live state
    def register_state_provider(
            self, name: str,
            fn: Callable[[], Optional[dict]]) -> None:
        """``fn()`` is called at dump time and returns a JSON-able dict
        of live state (active requests, slot table, queue depths) or
        None to be skipped (dead weakref). A provider must never
        raise into a dump — failures are recorded in the bundle."""
        with self._lock:
            self._providers[name] = fn

    def unregister_state_provider(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)

    # --------------------------------------------------------------- dump
    def bundle(self, reason: str = "manual") -> dict:
        """The postmortem bundle (docs/observability.md): ring, metrics
        snapshot, journal cursor + tail, live state."""
        from paddle_tpu.obs.events import JOURNAL
        from paddle_tpu.obs.metrics import REGISTRY
        with self._lock:
            providers = dict(self._providers)
        state: Dict[str, object] = {}
        for name, fn in sorted(providers.items()):
            try:
                st = fn()
            # a dump must survive any one sick subsystem: the point of
            # the bundle is the OTHER evidence
            except Exception as e:  # noqa: BLE001
                st = {"error": repr(e)[:200]}
            if st is not None:
                state[name] = st
        try:
            metrics_text = REGISTRY.exposition()
        except Exception as e:  # noqa: BLE001 — same survival contract
            metrics_text = f"# metrics scrape failed: {e!r}"
        return {
            "v": BUNDLE_VERSION,
            "reason": str(reason),
            "ts": time.time(),
            "run_id": obs_context.ensure_run_id(),
            "host": obs_context.get_host(),
            "pid": os.getpid(),
            "ring": self.snapshot(),
            "metrics": metrics_text,
            "journal": {"last_seq": JOURNAL.last_seq,
                        "path": JOURNAL.path,
                        "tail": JOURNAL.tail(200)},
            "state": state,
        }

    def dump(self, reason: str = "manual",
             path: Optional[str] = None) -> str:
        """Write one bundle. With no ``path``: the configured dump_dir,
        else the system temp dir (an unconfigured process can still be
        asked for a postmortem)."""
        b = self.bundle(reason)
        if path is None:
            with self._lock:
                base = self._dump_dir or tempfile.gettempdir()
                n = self._dumps
            safe = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in str(reason))[:40]
            path = os.path.join(
                base, f"flight-{os.getpid()}-{n:03d}-{safe}.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(b, f)
        with self._lock:
            self._dumps += 1
            self._last_dump_t[str(reason)] = time.monotonic()
        return path

    def maybe_autodump(self, reason: str) -> Optional[str]:
        """Rate-limited dump into the configured dump_dir; a no-op
        (returns None) when auto-dump is unarmed, the recorder is off,
        or a bundle was written for this SAME ``reason`` within
        ``min_dump_interval`` (distinct reasons never suppress each
        other — a storm of one incident kind produces one bundle
        without hiding a different concurrent incident). Never raises
        — the recorder must not take down the path that triggered
        it."""
        with self._lock:
            if not self.enabled or self._dump_dir is None:
                return None
            last = self._last_dump_t.get(str(reason))
            if last is not None and \
                    time.monotonic() - last < self._min_dump_interval:
                return None
        try:
            path = self.dump(reason)
        except Exception as e:  # noqa: BLE001 — survival contract
            with self._lock:
                self._dump_errors += 1
                first = self._dump_errors == 1
            if first:
                get_logger().warning(
                    "flight recorder auto-dump failed (%r); further "
                    "failures counted silently", e)
            return None
        get_logger().warning("flight recorder: dumped postmortem "
                             "bundle to %s (reason=%s)", path, reason)
        return path

    # ------------------------------------------------------- journal hook
    def observe_journal(self, rec: dict) -> None:
        """obs/events.py observer: mirror every journal record into the
        ring and auto-dump on the trigger kinds."""
        if not self.enabled:
            return
        compact = {"t": rec.get("ts"), "kind": "event",
                   "name": f"{rec.get('domain')}/{rec.get('kind')}"}
        for k in ("trace_id", "step", "seq"):
            if k in rec:
                compact[k] = rec[k]
        # carry the small diagnostic fields; big blobs stay in the
        # journal (the bundle includes its tail anyway)
        for k, v in rec.items():
            if k in compact or k in ("v", "ts", "pid", "domain",
                                     "kind", "run_id", "host"):
                continue
            if isinstance(v, (bool, int, float)) or \
                    (isinstance(v, str) and len(v) <= 200):
                compact[k] = v
            elif isinstance(v, (list, tuple)) and len(v) <= 64 and \
                    all(isinstance(x, (bool, int, float, str))
                        for x in v):
                # short scalar lists (a step_failure's trace_ids) are
                # exactly what chain reconstruction needs
                compact[k] = list(v)
        self.record_raw(compact)
        key = (rec.get("domain"), rec.get("kind"))
        if key in AUTO_DUMP_TRIGGERS:
            if key == ("serving", "breaker") and \
                    rec.get("state") != "open":
                return
            self.maybe_autodump(f"{key[0]}_{rec.get('kind')}")

    def reset(self) -> None:
        """Between-tests hygiene (obs.reset_all): clear the ring, the
        providers (they hold closures over per-test objects), the dump
        dir and rate-limit state; the recorder stays enabled (it is
        always-on by contract)."""
        with self._lock:
            self._ring.clear()
            self._providers.clear()
            self._dump_dir = None
            self._last_dump_t.clear()
            self._dumps = 0
            self._dump_errors = 0
            self.enabled = True


#: the process-global recorder (always on; obs/__init__ wires it as a
#: journal observer and obs/trace.py feeds it spans)
FLIGHT = FlightRecorder()


def record(kind: str, name: str, **fields) -> None:
    FLIGHT.record(kind, name, **fields)


_prev_excepthook = None


def install_excepthook() -> None:
    """Dump a postmortem bundle on a fatal uncaught exception, then
    defer to the previous hook. Idempotent."""
    import sys
    global _prev_excepthook
    if _prev_excepthook is not None:
        return
    _prev_excepthook = sys.excepthook

    def hook(exc_type, exc, tb):
        FLIGHT.record("mark", "fatal_exception",
                      error=repr(exc)[:400])
        FLIGHT.maybe_autodump("fatal_exception")
        _prev_excepthook(exc_type, exc, tb)

    sys.excepthook = hook
