"""Structured event journal — one queryable record of everything that
went wrong (and the recoveries that followed).

Before this module every fault stream had its own shape: FaultEvent /
OOMEvent / DataFaultEvent objects through the trainer's event handler,
breaker state inside ``stats()``, preemptions as counters, checkpoint
saves as log lines. A chaos run or a production incident had no single
artifact to query. Now every fault-ish event flows through ONE
versioned-schema sink:

- a per-process JSONL file (``JOURNAL.configure(path)`` — CLI
  ``train --event_log`` / ``serve --event_log``), one JSON object per
  line, append-only, crash-tolerant (a torn final line is skipped by
  the reader);
- an in-memory ring (``tail()``) served over HTTP as ``GET /events``
  on both the serving front (serving/http.py) and the standalone
  observability endpoint (obs/httpd.py), and by the CLI
  ``paddle_tpu events tail``.

Schema v1 — every record carries:

    v       int     schema version (1)
    ts      float   unix seconds
    seq     int     per-process monotonic sequence number
    pid     int     emitting process
    domain  str     trainer | data | serving | engine | checkpoint
    kind    str     e.g. nonfinite, rollback, oom, quarantine,
                    data_budget, source_stall, worker_restart,
                    restart_budget, shed, breaker, preemption,
                    step_failure, save, restore, run_start, run_end

plus free-form kind-specific fields (JSON scalars; non-serializable
values are repr()'d at emit time). docs/observability.md catalogs the
kinds per domain. Emission must NEVER take down a hot path: file-write
failures are counted and warned once, not raised.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Iterator, List, Optional, Tuple

from paddle_tpu.utils.logging import get_logger

__all__ = ["SCHEMA_VERSION", "REQUIRED_FIELDS", "EventJournal", "JOURNAL",
           "emit", "emit_event", "tail", "validate", "read_journal"]

SCHEMA_VERSION = 1
REQUIRED_FIELDS = ("v", "ts", "seq", "pid", "domain", "kind")


def _jsonable(v):
    """Clamp one field value to something json.dumps accepts."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return repr(v)


def validate(rec: dict) -> dict:
    """Raise ValueError unless ``rec`` is a schema-valid v1 record;
    returns it unchanged so readers can chain."""
    if not isinstance(rec, dict):
        raise ValueError(f"event record must be an object, got "
                         f"{type(rec).__name__}")
    missing = [k for k in REQUIRED_FIELDS if k not in rec]
    if missing:
        raise ValueError(f"event record missing required fields "
                         f"{missing}: {rec!r}")
    if int(rec["v"]) != SCHEMA_VERSION:
        raise ValueError(f"unknown event schema version {rec['v']!r} "
                         f"(this reader speaks v{SCHEMA_VERSION})")
    for key in ("domain", "kind"):
        if not isinstance(rec[key], str) or not rec[key]:
            raise ValueError(f"event {key!r} must be a non-empty "
                             f"string: {rec!r}")
    for key in ("ts",):
        if not isinstance(rec[key], (int, float)):
            raise ValueError(f"event {key!r} must be numeric: {rec!r}")
    for key in ("seq", "pid"):
        if not isinstance(rec[key], int):
            raise ValueError(f"event {key!r} must be an int: {rec!r}")
    return rec


class EventJournal:
    """Thread-safe ring + optional JSONL file sink (see module doc)."""

    def __init__(self, ring_size: int = 2048):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(ring_size))
        self._seq = 0
        self._fh = None
        self._path: Optional[str] = None
        self._write_errors = 0

    @property
    def path(self) -> Optional[str]:
        with self._lock:
            return self._path

    def configure(self, path: Optional[str]) -> None:
        """Attach (or with ``None`` detach) the JSONL file sink. The
        file opens append-mode so a resumed run extends its journal."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
            self._path = path
            if path:
                d = os.path.dirname(os.path.abspath(path))
                os.makedirs(d, exist_ok=True)
                self._fh = open(path, "a", encoding="utf-8")

    def emit(self, domain: str, kind: str, **fields) -> dict:
        """Build, ring-buffer, and (when configured) persist one
        record. Never raises into the caller's hot path — a failed
        file write is counted and warned once."""
        rec = {"v": SCHEMA_VERSION, "ts": time.time(),
               "pid": os.getpid(), "domain": str(domain),
               "kind": str(kind)}
        for k, v in fields.items():
            if k not in rec:
                rec[k] = _jsonable(v)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(rec) + "\n")
                    self._fh.flush()
                except (OSError, ValueError):
                    self._write_errors += 1
                    if self._write_errors == 1:
                        get_logger().warning(
                            "event journal write to %s failed; further "
                            "failures counted silently "
                            "(journal/write_errors)", self._path)
        return rec

    def emit_event(self, event) -> dict:
        """Journal a trainer-event object (FaultEvent / OOMEvent /
        DataFaultEvent — trainer/event.py) in its canonical shape."""
        domain, kind, fields = record_fields(event)
        return self.emit(domain, kind, **fields)

    def tail(self, n: int = 100, domain: Optional[str] = None,
             kind: Optional[str] = None) -> List[dict]:
        """Newest-last slice of the in-memory ring, optionally
        filtered."""
        with self._lock:
            recs = list(self._ring)
        if domain is not None:
            recs = [r for r in recs if r["domain"] == domain]
        if kind is not None:
            recs = [r for r in recs if r["kind"] == kind]
        return recs[-int(n):]

    @property
    def write_errors(self) -> int:
        with self._lock:
            return self._write_errors

    def reset(self) -> None:
        """Detach the sink and clear the ring (between-tests hygiene —
        tests/conftest.py)."""
        self.configure(None)
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._write_errors = 0


#: the process-global journal every subsystem emits through
JOURNAL = EventJournal()


def emit(domain: str, kind: str, **fields) -> dict:
    return JOURNAL.emit(domain, kind, **fields)


def emit_event(event) -> dict:
    return JOURNAL.emit_event(event)


def tail(n: int = 100, domain: Optional[str] = None,
         kind: Optional[str] = None) -> List[dict]:
    return JOURNAL.tail(n, domain=domain, kind=kind)


def record_fields(event) -> Tuple[str, str, dict]:
    """(domain, kind, fields) for a trainer-event object. Import is
    function-level so obs never becomes a hard import edge into the
    trainer package."""
    from paddle_tpu.trainer import event as evt
    if isinstance(event, evt.OOMEvent):
        return "trainer", "oom", {
            "pass_id": event.pass_id, "batch_id": event.batch_id,
            "microbatch": event.microbatch,
            "accum_steps": event.accum_steps,
            "error": _err_str(event.error)}
    if isinstance(event, evt.DataFaultEvent):
        return "data", event.kind, {
            "count": event.count, "where": event.where,
            "error": _err_str(event.error)}
    if isinstance(event, evt.FaultEvent):
        return "trainer", event.kind, {
            "pass_id": event.pass_id, "batch_id": event.batch_id,
            "bad_streak": event.bad_streak,
            "restored_step": event.restored_step}
    return "trainer", type(event).__name__, {
        k: _jsonable(v) for k, v in vars(event).items()
        if not k.startswith("_")}


def _err_str(e) -> Optional[str]:
    return None if e is None else repr(e)[:400]


def read_journal(path: str, strict: bool = True) -> Iterator[dict]:
    """Yield schema-validated records from a JSONL journal file. A torn
    FINAL line (the process died mid-write) is always skipped; any
    other malformed line raises with ``strict`` and is skipped with a
    warning otherwise."""
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            yield validate(json.loads(line))
        except (json.JSONDecodeError, ValueError) as e:
            if i == len(lines) - 1:
                get_logger().warning(
                    "journal %s: skipping torn final line", path)
                return
            if strict:
                raise ValueError(
                    f"{path}:{i + 1}: malformed journal record: {e}"
                ) from e
            get_logger().warning("journal %s:%d: skipping malformed "
                                 "record: %s", path, i + 1, e)
