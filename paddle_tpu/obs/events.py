"""Structured event journal — one queryable record of everything that
went wrong (and the recoveries that followed).

Before this module every fault stream had its own shape: FaultEvent /
OOMEvent / DataFaultEvent objects through the trainer's event handler,
breaker state inside ``stats()``, preemptions as counters, checkpoint
saves as log lines. A chaos run or a production incident had no single
artifact to query. Now every fault-ish event flows through ONE
versioned-schema sink:

- a per-process JSONL file (``JOURNAL.configure(path)`` — CLI
  ``train --event_log`` / ``serve --event_log``), one JSON object per
  line, append-only, crash-tolerant (a torn final line is skipped by
  the reader);
- an in-memory ring (``tail()``) served over HTTP as ``GET /events``
  on both the serving front (serving/http.py) and the standalone
  observability endpoint (obs/httpd.py), and by the CLI
  ``paddle_tpu events tail``.

Schema v1 — every record carries:

    v       int     schema version (1)
    ts      float   unix seconds
    seq     int     per-process monotonic sequence number
    pid     int     emitting process
    domain  str     trainer | data | serving | engine | checkpoint |
                    slo | profile | coordinator | lockdep | embed
    kind    str     e.g. nonfinite, rollback, oom, quarantine,
                    data_budget, source_stall, worker_restart,
                    restart_budget, shed, breaker, preemption,
                    step_failure, save, restore, run_start, run_end,
                    step_regression, breach, window, stale_grant,
                    reshard, inversion, gather, update, stale_read,
                    shard_killed, shard_replaced, sample, online_pass

plus, since observability v2 (docs/observability.md "Trace context &
postmortems"), the correlation IDs the merge tooling keys on —
``run_id`` and ``host`` on every record (obs/context.py), ``trace_id``
/ ``step`` when the emitting thread has one bound — and free-form
kind-specific fields (JSON scalars; non-serializable values are
repr()'d at emit time). docs/observability.md catalogs the kinds per
domain. Emission must NEVER take down a hot path: file-write failures
are counted and warned once, not raised; observer failures (the
flight recorder's auto-dump hook) are swallowed the same way.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Iterator, List, Optional, Tuple

from paddle_tpu.obs import context as obs_context
from paddle_tpu.analysis.lockdep import named_lock
from paddle_tpu.utils.logging import get_logger

__all__ = ["SCHEMA_VERSION", "REQUIRED_FIELDS", "RESERVED_FIELDS",
           "EventJournal", "JOURNAL",
           "emit", "emit_event", "tail", "validate", "read_journal",
           "journal_segments"]

SCHEMA_VERSION = 1
REQUIRED_FIELDS = ("v", "ts", "seq", "pid", "domain", "kind")
#: field names emit() REJECTS — they would collide with the envelope
#: keys the journal stamps itself (run_id/host ride in from
#: obs/context.py and must not be spoofed per-record either)
RESERVED_FIELDS = frozenset(("v", "ts", "seq", "pid", "run_id",
                             "host"))


def _jsonable(v):
    """Clamp one field value to something json.dumps accepts."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return repr(v)


def validate(rec: dict) -> dict:
    """Raise ValueError unless ``rec`` is a schema-valid v1 record;
    returns it unchanged so readers can chain."""
    if not isinstance(rec, dict):
        raise ValueError(f"event record must be an object, got "
                         f"{type(rec).__name__}")
    missing = [k for k in REQUIRED_FIELDS if k not in rec]
    if missing:
        raise ValueError(f"event record missing required fields "
                         f"{missing}: {rec!r}")
    if int(rec["v"]) != SCHEMA_VERSION:
        raise ValueError(f"unknown event schema version {rec['v']!r} "
                         f"(this reader speaks v{SCHEMA_VERSION})")
    for key in ("domain", "kind"):
        if not isinstance(rec[key], str) or not rec[key]:
            raise ValueError(f"event {key!r} must be a non-empty "
                             f"string: {rec!r}")
    for key in ("ts",):
        if not isinstance(rec[key], (int, float)):
            raise ValueError(f"event {key!r} must be numeric: {rec!r}")
    for key in ("seq", "pid"):
        if not isinstance(rec[key], int):
            raise ValueError(f"event {key!r} must be an int: {rec!r}")
    return rec


#: configure() sentinel — "leave this rotation knob as it was"
_UNSET = object()


class EventJournal:
    """Thread-safe ring + optional JSONL file sink (see module doc).

    With ``max_bytes`` set the file sink rotates size-based with
    bounded retention: when the active file exceeds ``max_bytes`` it is
    renamed to ``<path>.1`` (existing segments shift to ``.2``…,
    anything past ``keep`` is deleted) and a fresh active file opens —
    a long serving run's journal is bounded at roughly
    ``(keep + 1) * max_bytes``. ``read_journal`` and the CLI
    ``events tail --follow`` transparently span the rotated segments."""

    def __init__(self, ring_size: int = 2048,
                 max_bytes: Optional[int] = None, keep: int = 3):
        self._lock = named_lock("obs.journal")
        self._ring: deque = deque(maxlen=int(ring_size))
        self._seq = 0
        self._fh = None
        self._path: Optional[str] = None
        self._max_bytes = int(max_bytes) if max_bytes else None
        self._keep = max(0, int(keep))
        self._sink_bytes = 0
        self._rotations = 0
        self._write_errors = 0
        self._observers: List[Callable[[dict], None]] = []
        self._observer_errors = 0

    @property
    def path(self) -> Optional[str]:
        with self._lock:
            return self._path

    @property
    def rotations(self) -> int:
        with self._lock:
            return self._rotations

    def configure(self, path: Optional[str],
                  max_bytes=_UNSET, keep=_UNSET) -> None:
        """Attach (or with ``None`` detach) the JSONL file sink. The
        file opens append-mode so a resumed run extends its journal.
        ``max_bytes``/``keep`` set the rotation policy when passed and
        are left untouched otherwise."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
            if max_bytes is not _UNSET:
                self._max_bytes = int(max_bytes) if max_bytes else None
            if keep is not _UNSET:
                self._keep = max(0, int(keep))
            self._path = path
            self._sink_bytes = 0
            if path:
                d = os.path.dirname(os.path.abspath(path))
                os.makedirs(d, exist_ok=True)
                self._fh = open(path, "a", encoding="utf-8")
                try:
                    self._sink_bytes = os.path.getsize(path)
                except OSError:
                    self._sink_bytes = 0

    def _rotate_locked(self) -> None:
        """Shift ``path -> path.1 -> … -> path.keep`` (dropping the
        oldest) and reopen a fresh active file. Called with the lock
        held, right after the write that crossed ``max_bytes``; any
        filesystem failure is absorbed into write_errors (journal
        emission never raises into a hot path)."""
        path = self._path
        try:
            self._fh.close()
        except OSError:
            pass
        self._fh = None
        try:
            drop = f"{path}.{self._keep}" if self._keep else path
            if os.path.exists(drop):
                os.remove(drop)
            for i in range(self._keep - 1, 0, -1):
                src = f"{path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{path}.{i + 1}")
            if self._keep:
                os.replace(path, f"{path}.1")
            self._rotations += 1
        except OSError:
            self._write_errors += 1
        try:
            self._fh = open(path, "a", encoding="utf-8")
            self._sink_bytes = os.path.getsize(path)
        except OSError:
            self._write_errors += 1
            self._sink_bytes = 0

    def emit(self, domain: str, kind: str, **fields) -> dict:
        """Build, ring-buffer, and (when configured) persist one
        record. Never raises into the caller's hot path ONCE the call
        is well-formed — a failed file write is counted and warned
        once; a malformed call (empty/non-str domain or kind, or a
        field colliding with an envelope key) raises immediately,
        because a record that silently overwrote its own seq/run_id
        would poison every downstream consumer. Correlation IDs
        (run_id/host always; trace_id/step when bound on the emitting
        thread — obs/context.py) are stamped unless the caller passed
        its own."""
        if not isinstance(domain, str) or not domain:
            raise ValueError(
                f"journal domain must be a non-empty str, got "
                f"{domain!r}")
        if not isinstance(kind, str) or not kind:
            raise ValueError(
                f"journal kind must be a non-empty str, got {kind!r}")
        reserved = RESERVED_FIELDS.intersection(fields)
        if reserved:
            raise ValueError(
                f"journal fields {sorted(reserved)} collide with "
                f"envelope keys (reserved: "
                f"{sorted(RESERVED_FIELDS)})")
        rec = {"v": SCHEMA_VERSION, "ts": time.time(),
               "pid": os.getpid(), "domain": domain, "kind": kind}
        for k, v in obs_context.current_fields().items():
            if k not in fields:
                rec[k] = _jsonable(v)
        for k, v in fields.items():
            if k not in rec and v is not None:
                rec[k] = _jsonable(v)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            observers = list(self._observers)
            if self._fh is not None:
                try:
                    line = json.dumps(rec) + "\n"
                    self._fh.write(line)
                    self._fh.flush()
                    self._sink_bytes += len(line)
                    if self._max_bytes is not None \
                            and self._sink_bytes >= self._max_bytes:
                        self._rotate_locked()
                except (OSError, ValueError):
                    self._write_errors += 1
                    if self._write_errors == 1:
                        get_logger().warning(
                            "event journal write to %s failed; further "
                            "failures counted silently "
                            "(journal/write_errors)", self._path)
        # observers run OUTSIDE the lock: the flight recorder's
        # auto-dump reads tail() back through it
        for fn in observers:
            try:
                fn(rec)
            except Exception:  # noqa: BLE001 — emission never raises
                with self._lock:
                    self._observer_errors += 1
                    first = self._observer_errors == 1
                if first:
                    get_logger().warning(
                        "event journal observer %r failed; further "
                        "failures counted silently", fn)
        return rec

    def emit_event(self, event) -> dict:
        """Journal a trainer-event object (FaultEvent / OOMEvent /
        DataFaultEvent — trainer/event.py) in its canonical shape."""
        domain, kind, fields = record_fields(event)
        return self.emit(domain, kind, **fields)

    def tail(self, n: int = 100, domain: Optional[str] = None,
             kind: Optional[str] = None,
             since_seq: Optional[int] = None) -> List[dict]:
        """Newest-last slice of the in-memory ring, optionally
        filtered. With ``since_seq`` the semantics flip to a CURSOR:
        the OLDEST ``n`` matching records with seq > since_seq, so a
        scraper pages forward (``GET /events?since_seq=``) without
        re-reading the ring from the start — resume from the last
        record's seq."""
        with self._lock:
            recs = list(self._ring)
        if domain is not None:
            recs = [r for r in recs if r["domain"] == domain]
        if kind is not None:
            recs = [r for r in recs if r["kind"] == kind]
        if since_seq is not None:
            return [r for r in recs if r["seq"] > int(since_seq)][:int(n)]
        return recs[-int(n):]

    @property
    def last_seq(self) -> int:
        """The newest seq handed out — the ``since_seq`` cursor a
        scraper resumes from."""
        with self._lock:
            return self._seq

    def add_observer(self, fn: Callable[[dict], None]) -> None:
        """``fn(rec)`` is called after every emit (outside the journal
        lock). The flight recorder registers here (obs/__init__)."""
        with self._lock:
            if fn not in self._observers:
                self._observers.append(fn)

    def remove_observer(self, fn) -> None:
        with self._lock:
            if fn in self._observers:
                self._observers.remove(fn)

    @property
    def write_errors(self) -> int:
        with self._lock:
            return self._write_errors

    def reset(self) -> None:
        """Detach the sink and clear the ring (between-tests hygiene —
        tests/conftest.py). Observers survive: the flight-recorder
        wiring is process topology, not state."""
        self.configure(None)
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._write_errors = 0
            self._observer_errors = 0
            self._rotations = 0
            self._max_bytes = None
            self._keep = 3


#: the process-global journal every subsystem emits through
JOURNAL = EventJournal()


def emit(domain: str, kind: str, **fields) -> dict:
    return JOURNAL.emit(domain, kind, **fields)


def emit_event(event) -> dict:
    return JOURNAL.emit_event(event)


def tail(n: int = 100, domain: Optional[str] = None,
         kind: Optional[str] = None,
         since_seq: Optional[int] = None) -> List[dict]:
    return JOURNAL.tail(n, domain=domain, kind=kind,
                        since_seq=since_seq)


def record_fields(event) -> Tuple[str, str, dict]:
    """(domain, kind, fields) for a trainer-event object. Import is
    function-level so obs never becomes a hard import edge into the
    trainer package."""
    from paddle_tpu.trainer import event as evt
    if isinstance(event, evt.OOMEvent):
        return "trainer", "oom", {
            "pass_id": event.pass_id, "batch_id": event.batch_id,
            "microbatch": event.microbatch,
            "accum_steps": event.accum_steps,
            "error": _err_str(event.error)}
    if isinstance(event, evt.DataFaultEvent):
        return "data", event.kind, {
            "count": event.count, "where": event.where,
            "error": _err_str(event.error)}
    if isinstance(event, evt.FaultEvent):
        return "trainer", event.kind, {
            "pass_id": event.pass_id, "batch_id": event.batch_id,
            "bad_streak": event.bad_streak,
            "restored_step": event.restored_step}
    return "trainer", type(event).__name__, {
        k: _jsonable(v) for k, v in vars(event).items()
        if not k.startswith("_")}


def _err_str(e) -> Optional[str]:
    return None if e is None else repr(e)[:400]


def journal_segments(path: str) -> List[str]:
    """Every on-disk file of a (possibly rotated) journal, oldest
    first: ``path.N … path.1`` then the active ``path``. Segments are
    contiguous by construction (EventJournal._rotate_locked)."""
    rotated: List[str] = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        rotated.append(f"{path}.{i}")
        i += 1
    return list(reversed(rotated)) + [path]


def read_journal(path: str, strict: bool = True,
                 domain: Optional[str] = None,
                 kind: Optional[str] = None) -> Iterator[dict]:
    """Yield schema-validated records from a JSONL journal, spanning
    rotated segments (``path.N`` oldest … ``path``) transparently. A
    torn FINAL line (the process died mid-write; only possible in the
    active file) is always skipped; any other malformed line raises
    with ``strict`` and is skipped with a warning otherwise.
    ``domain``/``kind`` filter with the SAME semantics as
    ``EventJournal.tail`` — the parity is test-pinned
    (tests/test_obs.py) so ring and file queries agree."""
    segments = journal_segments(path)
    for seg in segments:
        last_seg = seg == path
        try:
            with open(seg, encoding="utf-8") as f:
                lines = f.read().splitlines()
        except FileNotFoundError:
            if last_seg:
                raise
            continue  # rotated away between listing and open
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                rec = validate(json.loads(line))
            except (json.JSONDecodeError, ValueError) as e:
                if last_seg and i == len(lines) - 1:
                    get_logger().warning(
                        "journal %s: skipping torn final line", seg)
                    return
                if strict:
                    raise ValueError(
                        f"{seg}:{i + 1}: malformed journal record: {e}"
                    ) from e
                get_logger().warning(
                    "journal %s:%d: skipping malformed record: %s",
                    seg, i + 1, e)
                continue
            if domain is not None and rec["domain"] != domain:
                continue
            if kind is not None and rec["kind"] != kind:
                continue
            yield rec
