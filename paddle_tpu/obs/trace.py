"""Step tracing — nested host-side spans exportable as Chrome trace
JSON, with XLA compile events attached.

``jax.profiler`` already produces device-side XPlane traces
(tools/xplane_top.py); what it cannot show is the HOST schedule a
production trainer or decode engine lives or dies by — where the step
loop waits on data, how long a checkpoint write holds its thread, when
a compile lands in the middle of serving traffic. This tracer records
exactly that:

- ``span(name)`` context managers build a per-thread stack (spans know
  their parent), recording wall-clock start/duration;
- every ``utils.stats.stat_timer`` scope automatically becomes a span
  while a trace is active — so ``train_step``, ``train/data_wait``,
  ``checkpoint/write``, ``serving/forward`` and
  ``serving/decode_step`` all show up with zero per-site wiring;
- ``start(capture_compiles=True)`` additionally captures JAX's compile
  log stream (the same ``jax_log_compiles`` capture
  analysis/sanitizer.py's compile_watch uses) as instant events, so a
  recompile appears AT its position in the timeline;
- ``chrome_trace()`` / ``save(path)`` emit the ``traceEvents`` JSON
  chrome://tracing and Perfetto load directly.

Overhead when idle is one attribute check per stat_timer scope; the
tracer is OFF by default and meant for bounded windows (a few steps),
not always-on production use — spans accumulate in memory.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["Tracer", "TRACER", "span", "instant"]


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _SpanCtx:
    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._tracer._push(self.name)
        return self

    def __exit__(self, *exc):
        self._tracer._pop(self.name, self._t0,
                          time.perf_counter(), self.args)
        return False


class _CompileLogHandler(logging.Handler):
    """Captures 'Compiling <name> ...' records as instant events (the
    regex is shared with analysis/sanitizer.py's compile_watch)."""

    def __init__(self, tracer: "Tracer"):
        super().__init__(level=logging.DEBUG)
        self._tracer = tracer

    def emit(self, record: logging.LogRecord) -> None:
        from paddle_tpu.analysis.sanitizer import _COMPILE_RE
        try:
            msg = record.getMessage()
        except Exception:                    # defensive: logging contract
            return
        m = _COMPILE_RE.match(msg)
        if m is None or not msg.startswith("Compiling"):
            return
        self._tracer.instant("xla_compile", function=m.group(1))


class Tracer:
    """See module doc. start()/stop() bound a trace window; span() and
    instant() are no-ops outside one."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.enabled = False
        self._spans: List[dict] = []
        self._instants: List[dict] = []
        self._handler: Optional[_CompileLogHandler] = None
        self._log_state = None

    # ------------------------------------------------------------ lifecycle
    def start(self, capture_compiles: bool = True) -> "Tracer":
        with self._lock:
            if self.enabled:
                return self
            self._spans = []
            self._instants = []
            self.enabled = True
        if capture_compiles:
            self._arm_compile_capture()
        return self

    def stop(self) -> "Tracer":
        self._disarm_compile_capture()
        with self._lock:
            self.enabled = False
        return self

    def reset(self) -> None:
        self.stop()
        with self._lock:
            self._spans = []
            self._instants = []

    def _arm_compile_capture(self) -> None:
        import jax
        handler = _CompileLogHandler(self)
        jlog = logging.getLogger("jax")
        prev_flag = jax.config.jax_log_compiles
        jax.config.update("jax_log_compiles", True)
        # keep JAX's own stream handler quiet for the window, exactly
        # like compile_watch does (the records are WARNING level)
        muted = [(h, h.level) for h in jlog.handlers]
        for h, _ in muted:
            h.setLevel(logging.ERROR)
        jlog.addHandler(handler)
        prev_propagate = jlog.propagate
        jlog.propagate = False
        with self._lock:
            self._handler = handler
            self._log_state = (prev_flag, muted, prev_propagate)

    def _disarm_compile_capture(self) -> None:
        with self._lock:
            handler, state = self._handler, self._log_state
            self._handler = None
            self._log_state = None
        if handler is None:
            return
        import jax
        prev_flag, muted, prev_propagate = state
        jlog = logging.getLogger("jax")
        jlog.removeHandler(handler)
        for h, lvl in muted:
            h.setLevel(lvl)
        jlog.propagate = prev_propagate
        jax.config.update("jax_log_compiles", prev_flag)

    # ------------------------------------------------------------ recording
    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def _push(self, name: str) -> None:
        self._stack().append(name)

    def _pop(self, name: str, t0: float, t1: float, args: dict) -> None:
        st = self._stack()
        if st and st[-1] == name:
            st.pop()
        parent = st[-1] if st else None
        rec = {"name": name, "t0": t0, "t1": t1, "parent": parent,
               "tid": threading.get_ident(),
               "thread": threading.current_thread().name}
        if args:
            rec["args"] = args
        with self._lock:
            if self.enabled:
                self._spans.append(rec)

    def span(self, name: str, **args):
        """Context manager; a shared no-op object when tracing is off
        (the hot-path cost of an inactive tracer is this one check)."""
        if not self.enabled:
            return _NULL_SPAN
        return _SpanCtx(self, name, args)

    def instant(self, name: str, **args) -> None:
        if not self.enabled:
            return
        st = self._stack()
        rec = {"name": name, "t": time.perf_counter(),
               "parent": st[-1] if st else None,
               "tid": threading.get_ident(),
               "thread": threading.current_thread().name}
        if args:
            rec["args"] = args
        with self._lock:
            if self.enabled:
                self._instants.append(rec)

    # -------------------------------------------------------------- export
    def spans(self) -> List[dict]:
        with self._lock:
            return list(self._spans)

    def instants(self) -> List[dict]:
        with self._lock:
            return list(self._instants)

    def chrome_trace(self) -> Dict[str, list]:
        """The chrome://tracing / Perfetto ``traceEvents`` format:
        complete events (ph "X") for spans, instants (ph "i") for
        compile events, microsecond timestamps."""
        pid = os.getpid()
        events = []
        for s in self.spans():
            ev = {"ph": "X", "name": s["name"], "pid": pid,
                  "tid": s["tid"], "ts": s["t0"] * 1e6,
                  "dur": (s["t1"] - s["t0"]) * 1e6,
                  "args": {**s.get("args", {}),
                           "parent": s["parent"],
                           "thread": s["thread"]}}
            events.append(ev)
        for i in self.instants():
            events.append({"ph": "i", "s": "t", "name": i["name"],
                           "pid": pid, "tid": i["tid"],
                           "ts": i["t"] * 1e6,
                           "args": {**i.get("args", {}),
                                    "parent": i["parent"]}})
        events.sort(key=lambda e: e["ts"])
        return {"traceEvents": events,
                "displayTimeUnit": "ms"}

    def save(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.chrome_trace(), f)
        return path


#: the process-global tracer utils.stats.stat_timer reports through
TRACER = Tracer()


def span(name: str, **args):
    return TRACER.span(name, **args)


def instant(name: str, **args) -> None:
    TRACER.instant(name, **args)
