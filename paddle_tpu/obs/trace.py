"""Step tracing — nested host-side spans exportable as Chrome trace
JSON, with XLA compile events attached and correlation IDs stamped.

``jax.profiler`` already produces device-side XPlane traces
(tools/xplane_top.py); what it cannot show is the HOST schedule a
production trainer or decode engine lives or dies by — where the step
loop waits on data, how long a checkpoint write holds its thread, when
a compile lands in the middle of serving traffic. This tracer records
exactly that:

- ``span(name)`` context managers build a per-thread stack (spans know
  their parent), recording wall-clock start/duration;
- every ``utils.stats.stat_timer`` scope automatically becomes a span
  while a trace is active — so ``train_step``, ``train/data_wait``,
  ``checkpoint/write``, ``serving/forward`` and
  ``serving/decode_step`` all show up with zero per-site wiring;
- every span carries the calling thread's bound ``trace_id`` / ``step``
  (obs/context.py), and the chrome export stamps ``run_id``/``host``/
  ``pid`` metadata — ``tools/trace_merge.py`` fuses N hosts' exports
  into one Perfetto timeline on exactly these IDs;
- ``start(capture_compiles=True)`` additionally captures JAX's compile
  log stream (the same ``jax_log_compiles`` capture
  analysis/sanitizer.py's compile_watch uses) as instant events, so a
  recompile appears AT its position in the timeline;
- ``chrome_trace()`` / ``save(path)`` emit the ``traceEvents`` JSON
  chrome://tracing and Perfetto load directly.

Memory is BOUNDED: spans/instants live in rings of ``max_spans`` /
``max_instants`` (default generous; a forgotten ``start()`` can no
longer grow without limit) and overflow increments the
``paddle_tpu_trace_dropped_total`` counter on the metrics registry.

Two capture modes compose:

- the explicit trace WINDOW (``start()``/``stop()``) fills the
  exportable span ring as before;
- the always-on FLIGHT feed: when the flight recorder (obs/flight.py)
  is enabled — it is by default — every closed span also lands as a
  compact record in its postmortem ring, so a fault that fires with no
  trace armed still has the recent span history. Overhead is one dict
  + deque append per scope, gated by bench.py's
  ``flight_recorder_overhead`` row.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from paddle_tpu.obs import context as obs_context
from paddle_tpu.obs.metrics import REGISTRY

__all__ = ["Tracer", "TRACER", "span", "instant"]

#: default span-ring bound — generous (a 1 ms/step trainer fills it in
#: ~a minute of tracing) but FIXED: trace memory can't run away
DEFAULT_MAX_SPANS = 65536

_DROPPED = REGISTRY.counter(
    "paddle_tpu_trace_dropped_total",
    "spans/instants dropped by the tracer's bounded ring "
    "(obs/trace.py max_spans)")


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _SpanCtx:
    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._tracer._push(self.name)
        return self

    def __exit__(self, *exc):
        self._tracer._pop(self.name, self._t0,
                          time.perf_counter(), self.args)
        return False


class _CompileLogHandler(logging.Handler):
    """Captures 'Compiling <name> ...' records as instant events (the
    regex is shared with analysis/sanitizer.py's compile_watch)."""

    def __init__(self, tracer: "Tracer"):
        super().__init__(level=logging.DEBUG)
        self._tracer = tracer

    def emit(self, record: logging.LogRecord) -> None:
        from paddle_tpu.analysis.sanitizer import _COMPILE_RE
        try:
            msg = record.getMessage()
        except Exception:                    # defensive: logging contract
            return
        m = _COMPILE_RE.match(msg)
        if m is None or not msg.startswith("Compiling"):
            return
        self._tracer.instant("xla_compile", function=m.group(1))


class Tracer:
    """See module doc. start()/stop() bound a trace window; span() and
    instant() still feed the flight recorder outside one."""

    def __init__(self, max_spans: int = DEFAULT_MAX_SPANS,
                 max_instants: int = 8192):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.enabled = False
        self._spans: deque = deque(maxlen=int(max_spans))
        self._instants: deque = deque(maxlen=int(max_instants))
        self.dropped = 0
        self._handler: Optional[_CompileLogHandler] = None
        self._log_state = None
        self._flight = None           # lazy obs.flight.FLIGHT handle
        # wall-clock anchor for perf_counter timestamps: exported ts
        # become unix-epoch microseconds, so two hosts' traces share a
        # time base (modulo skew — trace_merge adjusts that)
        self._epoch_wall = time.time()
        self._epoch_pc = time.perf_counter()

    def _flight_recorder(self):
        f = self._flight
        if f is None:
            from paddle_tpu.obs.flight import FLIGHT
            self._flight = f = FLIGHT
        return f

    def configure(self, max_spans: Optional[int] = None,
                  max_instants: Optional[int] = None) -> None:
        """Resize the rings (contents kept, newest last)."""
        with self._lock:
            if max_spans is not None:
                self._spans = deque(self._spans, maxlen=int(max_spans))
            if max_instants is not None:
                self._instants = deque(self._instants,
                                       maxlen=int(max_instants))

    # ------------------------------------------------------------ lifecycle
    def start(self, capture_compiles: bool = True) -> "Tracer":
        with self._lock:
            if self.enabled:
                return self
            self._spans.clear()
            self._instants.clear()
            self._epoch_wall = time.time()
            self._epoch_pc = time.perf_counter()
            self.enabled = True
        if capture_compiles:
            self._arm_compile_capture()
        return self

    def stop(self) -> "Tracer":
        self._disarm_compile_capture()
        with self._lock:
            self.enabled = False
        return self

    def reset(self) -> None:
        self.stop()
        with self._lock:
            self._spans.clear()
            self._instants.clear()
            self.dropped = 0
        self._flight = None

    def _arm_compile_capture(self) -> None:
        import jax
        handler = _CompileLogHandler(self)
        jlog = logging.getLogger("jax")
        prev_flag = jax.config.jax_log_compiles
        jax.config.update("jax_log_compiles", True)
        # keep JAX's own stream handler quiet for the window, exactly
        # like compile_watch does (the records are WARNING level)
        muted = [(h, h.level) for h in jlog.handlers]
        for h, _ in muted:
            h.setLevel(logging.ERROR)
        jlog.addHandler(handler)
        prev_propagate = jlog.propagate
        jlog.propagate = False
        with self._lock:
            self._handler = handler
            self._log_state = (prev_flag, muted, prev_propagate)

    def _disarm_compile_capture(self) -> None:
        with self._lock:
            handler, state = self._handler, self._log_state
            self._handler = None
            self._log_state = None
        if handler is None:
            return
        import jax
        prev_flag, muted, prev_propagate = state
        jlog = logging.getLogger("jax")
        jlog.removeHandler(handler)
        for h, lvl in muted:
            h.setLevel(lvl)
        jlog.propagate = prev_propagate
        jax.config.update("jax_log_compiles", prev_flag)

    # ------------------------------------------------------------ recording
    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def _push(self, name: str) -> None:
        self._stack().append(name)

    def _ring_append(self, ring: deque, rec: dict) -> None:
        # deque(maxlen) drops silently; count it so the loss is visible
        # as paddle_tpu_trace_dropped_total
        if ring.maxlen is not None and len(ring) == ring.maxlen:
            self.dropped += 1
            _DROPPED.inc()
        ring.append(rec)

    def _pop(self, name: str, t0: float, t1: float, args: dict) -> None:
        st = self._stack()
        if st and st[-1] == name:
            st.pop()
        parent = st[-1] if st else None
        ctx = obs_context.current()
        rec = {"name": name, "t0": t0, "t1": t1, "parent": parent,
               "tid": threading.get_ident(),
               "thread": threading.current_thread().name}
        if ctx.trace_id is not None:
            rec["trace_id"] = ctx.trace_id
        if ctx.step is not None:
            rec["step"] = ctx.step
        if args:
            rec["args"] = args
        with self._lock:
            if self.enabled:
                self._ring_append(self._spans, rec)
        flight = self._flight_recorder()
        if flight.enabled:
            frec = {"t": time.time() - (t1 - t0), "kind": "span",
                    "name": name, "dur_s": t1 - t0,
                    "thread": rec["thread"]}
            if ctx.trace_id is not None:
                frec["trace_id"] = ctx.trace_id
            if ctx.step is not None:
                frec["step"] = ctx.step
            flight.record_raw(frec)

    def span(self, name: str, **args):
        """Context manager; a shared no-op object when neither a trace
        window nor the flight recorder wants spans (the hot-path cost
        of a fully-off tracer is this one check)."""
        if not self.enabled and not self._flight_recorder().enabled:
            return _NULL_SPAN
        return _SpanCtx(self, name, args)

    def instant(self, name: str, **args) -> None:
        if not self.enabled:
            return
        st = self._stack()
        ctx = obs_context.current()
        rec = {"name": name, "t": time.perf_counter(),
               "parent": st[-1] if st else None,
               "tid": threading.get_ident(),
               "thread": threading.current_thread().name}
        if ctx.trace_id is not None:
            rec["trace_id"] = ctx.trace_id
        if ctx.step is not None:
            rec["step"] = ctx.step
        if args:
            rec["args"] = args
        with self._lock:
            if self.enabled:
                self._ring_append(self._instants, rec)

    # -------------------------------------------------------------- export
    def spans(self) -> List[dict]:
        with self._lock:
            return list(self._spans)

    def instants(self) -> List[dict]:
        with self._lock:
            return list(self._instants)

    def chrome_trace(self) -> Dict[str, object]:
        """The chrome://tracing / Perfetto ``traceEvents`` format:
        complete events (ph "X") for spans, instants (ph "i") for
        compile events, microsecond timestamps, plus process metadata
        (``run_id``/``host``/``pid``) keying the cross-process merge
        (tools/trace_merge.py)."""
        pid = os.getpid()
        host = obs_context.get_host()
        run_id = obs_context.ensure_run_id()
        with self._lock:
            wall0, pc0 = self._epoch_wall, self._epoch_pc

        def wall_us(t_pc: float) -> float:
            return (wall0 + (t_pc - pc0)) * 1e6

        events: List[dict] = [
            {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
             "args": {"name": f"{host} pid={pid}"}}]
        timed: List[dict] = []
        for s in self.spans():
            ev = {"ph": "X", "name": s["name"], "pid": pid,
                  "tid": s["tid"], "ts": wall_us(s["t0"]),
                  "dur": (s["t1"] - s["t0"]) * 1e6,
                  "args": {**s.get("args", {}),
                           "parent": s["parent"],
                           "thread": s["thread"]}}
            for k in ("trace_id", "step"):
                if k in s:
                    ev["args"][k] = s[k]
            timed.append(ev)
        for i in self.instants():
            ev = {"ph": "i", "s": "t", "name": i["name"],
                  "pid": pid, "tid": i["tid"],
                  "ts": wall_us(i["t"]),
                  "args": {**i.get("args", {}),
                           "parent": i["parent"]}}
            for k in ("trace_id", "step"):
                if k in i:
                    ev["args"][k] = i[k]
            timed.append(ev)
        timed.sort(key=lambda e: e["ts"])
        events.extend(timed)
        return {"traceEvents": events,
                "displayTimeUnit": "ms",
                "metadata": {"run_id": run_id, "host": host,
                             "pid": pid,
                             "dropped": self.dropped}}

    def save(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.chrome_trace(), f)
        return path


#: the process-global tracer utils.stats.stat_timer reports through
TRACER = Tracer()


def span(name: str, **args):
    return TRACER.span(name, **args)


def instant(name: str, **args) -> None:
    TRACER.instant(name, **args)
