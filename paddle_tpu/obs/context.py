"""Trace context — the correlation IDs that turn per-process telemetry
into an end-to-end story.

PR 7 built the reporting plane (metrics, journal, spans) but none of
its records could answer "what was THIS request/step doing when it
failed?": spans and journal records carried no IDs. This module is the
ID plane the rest of ``obs`` stamps from:

- **run_id** — one id per training/serving run, process-global. Set it
  explicitly (CLI ``--run_id``), or it is generated lazily on first
  use so every journal record of a process shares one. Multi-host jobs
  pass the same run_id to every worker (the coordinator workers in
  tests/trace_merge_worker.py do this via an env var) so a merged
  timeline groups by run.
- **host** — ``socket.gethostname()``, overridable via the
  ``PADDLE_TPU_HOST`` env var (subprocess chaos tests simulate
  distinct hosts on one machine) or :func:`set_host`.
- **trace_id** — one id per serving request, minted at the HTTP front
  (or on ``submit()`` when a caller bypasses it) and carried through
  admission → queue wait → engine slot → every decode step →
  settle/shed. ``bind(trace_id=...)`` scopes it to the current thread;
  cross-thread hops (the serving worker pool, the engine loop) carry
  it explicitly on the request object and re-bind.
- **step** — the trainer's global step, stamped via :func:`set_step`
  once per iteration so every span/journal record the step produces is
  attributable.

``current_fields()`` is what the journal (obs/events.py), the tracer
(obs/trace.py) and the flight recorder (obs/flight.py) merge into
their records. Everything here is host-side bookkeeping — nothing
touches a traced function.
"""

from __future__ import annotations

import os
import socket
import threading
import uuid
from typing import Dict, Optional

__all__ = ["TraceContext", "bind", "current", "current_fields",
           "new_trace_id", "ensure_run_id", "get_run_id", "set_run_id",
           "get_host", "set_host", "set_step", "reset"]


class TraceContext:
    """One immutable-ish frame of correlation IDs. ``bind()`` pushes a
    derived frame onto the calling thread's stack; fields that are
    ``None`` fall through to the process scope (run_id/host)."""

    __slots__ = ("trace_id", "span_id", "step", "extra")

    def __init__(self, trace_id: Optional[str] = None,
                 span_id: Optional[str] = None,
                 step: Optional[int] = None, **extra):
        self.trace_id = trace_id
        self.span_id = span_id
        self.step = step
        self.extra = extra

    @property
    def run_id(self) -> str:
        return ensure_run_id()

    @property
    def host(self) -> str:
        return get_host()

    def fields(self) -> Dict[str, object]:
        out: Dict[str, object] = {"run_id": self.run_id,
                                  "host": self.host}
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        if self.span_id is not None:
            out["span_id"] = self.span_id
        if self.step is not None:
            out["step"] = self.step
        out.update(self.extra)
        return out

    def __repr__(self):
        return (f"TraceContext(trace_id={self.trace_id!r}, "
                f"step={self.step!r})")


# ----------------------------------------------------------- process scope
_lock = threading.Lock()
_run_id: Optional[str] = None
_host: Optional[str] = None
_tls = threading.local()


def new_trace_id() -> str:
    """A fresh 16-hex-char request id (collision-safe at serving
    scale; short enough to grep a journal by hand)."""
    return uuid.uuid4().hex[:16]


def set_run_id(run_id: Optional[str]) -> None:
    global _run_id
    with _lock:
        _run_id = run_id


def get_run_id() -> Optional[str]:
    with _lock:
        return _run_id


def ensure_run_id() -> str:
    """The process run_id, generated once on first use so every record
    a process emits shares one id even when nobody set it."""
    global _run_id
    with _lock:
        if _run_id is None:
            _run_id = os.environ.get("PADDLE_TPU_RUN_ID") \
                or "run-" + uuid.uuid4().hex[:12]
        return _run_id


def set_host(host: Optional[str]) -> None:
    global _host
    with _lock:
        _host = host


def get_host() -> str:
    global _host
    with _lock:
        if _host is None:
            _host = os.environ.get("PADDLE_TPU_HOST") \
                or socket.gethostname()
        return _host


# ------------------------------------------------------------ thread scope
def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = [TraceContext()]
        _tls.stack = st
    return st


def current() -> TraceContext:
    """The calling thread's innermost context (every thread has a
    default frame carrying just the process run_id/host)."""
    return _stack()[-1]


def current_fields() -> Dict[str, object]:
    """What the journal/tracer/flight-recorder stamp onto a record."""
    return current().fields()


class _Bound:
    __slots__ = ("_ctx",)

    def __init__(self, ctx: TraceContext):
        self._ctx = ctx

    def __enter__(self) -> TraceContext:
        _stack().append(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        st = _stack()
        if len(st) > 1 and st[-1] is self._ctx:
            st.pop()
        return False


def bind(trace_id: Optional[str] = None, span_id: Optional[str] = None,
         step: Optional[int] = None, **extra) -> _Bound:
    """Context manager: push a derived context for the current thread.
    ``None`` fields inherit from the innermost frame, so nesting
    ``bind(step=3)`` inside ``bind(trace_id=t)`` keeps the trace_id."""
    cur = current()
    ctx = TraceContext(
        trace_id=trace_id if trace_id is not None else cur.trace_id,
        span_id=span_id if span_id is not None else cur.span_id,
        step=step if step is not None else cur.step,
        **{**cur.extra, **extra})
    return _Bound(ctx)


def set_step(step: Optional[int]) -> None:
    """Stamp the trainer's global step on the calling thread's current
    frame — a one-liner per iteration instead of re-indenting the whole
    step body under a ``with`` (trainer/trainer.py's loop)."""
    current().step = step


def reset() -> None:
    """Between-tests hygiene (obs.reset_all): drop the process run_id /
    host override and the calling thread's bind stack."""
    set_run_id(None)
    set_host(None)
    _tls.stack = [TraceContext()]
