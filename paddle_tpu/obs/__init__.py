"""paddle_tpu.obs — the unified observability layer.

One place the whole framework reports through (docs/observability.md):

- :mod:`paddle_tpu.obs.metrics` — thread-safe metrics registry
  (counters / gauges / histograms with labels) with ONE Prometheus
  text exposition path; absorbs ``utils/stats`` and the serving
  ``stats()`` plumbing.
- :mod:`paddle_tpu.obs.events`  — versioned-schema structured event
  journal (JSONL file + in-memory ring): faults, OOMs, data faults,
  quarantines, sheds, breaker flips, preemptions, checkpoints — every
  record stamped with run_id/host and, when bound, trace_id/step.
- :mod:`paddle_tpu.obs.context` — the correlation-ID plane: per-run
  ``run_id``, per-request ``trace_id`` (minted at the HTTP front),
  per-iteration ``step``.
- :mod:`paddle_tpu.obs.trace`   — host-side step tracing (bounded span
  rings) with Chrome trace export and XLA-compile instants.
- :mod:`paddle_tpu.obs.flight`  — the always-on flight recorder: a
  bounded ring of recent spans + events, auto-dumped as a postmortem
  bundle on faults/breaker-open/step-failure/SIGTERM and on demand
  (``paddle_tpu obs dump``).
- :mod:`paddle_tpu.obs.merge`   — cross-process fusion of N per-host
  journals + chrome traces into one timeline
  (``paddle_tpu trace merge`` / tools/trace_merge.py).
- :mod:`paddle_tpu.obs.httpd`   — standalone /metrics + /events +
  /flight + /profile endpoint for trainer/coordinator processes.
- :mod:`paddle_tpu.obs.profile` — continuous step profiler (per-phase
  breakdown, live MFU/roofline gauges, device-memory telemetry on the
  ``pt-obs-profiler`` thread, deep ``jax.profiler.trace`` windows).
- :mod:`paddle_tpu.obs.slo`     — SLO watchdog: declarative objectives
  over rolling windows + step-regression detection with per-phase
  attribution, journaled under the ``slo`` domain.

The perf regression gate rides on the same layer: ``bench.py``'s smoke
tier measures through ``compile_watch`` / ``host_sync_watch``
(analysis/sanitizer.py) and ``tools/bench_gate.py`` enforces
``BENCH_SMOKE_BASELINE.json`` in tier-1 — including the flight
recorder's always-on overhead row.
"""

from paddle_tpu.obs import context  # noqa: F401
from paddle_tpu.obs.context import (bind, current_fields,  # noqa: F401
                                    new_trace_id)
from paddle_tpu.obs.events import (JOURNAL, EventJournal, emit,  # noqa: F401
                                   emit_event, journal_segments,
                                   read_journal, tail, validate)
from paddle_tpu.obs.flight import FLIGHT, FlightRecorder  # noqa: F401
from paddle_tpu.obs.httpd import (build_obs_http_server,  # noqa: F401
                                  start_obs_server)
from paddle_tpu.obs.metrics import (REGISTRY, MetricsRegistry,  # noqa: F401
                                    stats_families)
from paddle_tpu.obs.profile import PROFILER, StepProfiler  # noqa: F401
from paddle_tpu.obs.protocol import WITNESS, ProtocolWitness  # noqa: F401
from paddle_tpu.obs.slo import (WATCHDOG, Objective,  # noqa: F401
                                SLOWatchdog, parse_objective)
from paddle_tpu.obs.trace import TRACER, Tracer, span  # noqa: F401

__all__ = [
    "REGISTRY", "MetricsRegistry", "stats_families",
    "JOURNAL", "EventJournal", "emit", "emit_event", "tail",
    "read_journal", "journal_segments", "validate",
    "TRACER", "Tracer", "span",
    "FLIGHT", "FlightRecorder",
    "PROFILER", "StepProfiler",
    "WITNESS", "ProtocolWitness",
    "WATCHDOG", "SLOWatchdog", "Objective", "parse_objective",
    "context", "bind", "current_fields", "new_trace_id",
    "build_obs_http_server", "start_obs_server",
    "reset_all",
]

# the flight recorder mirrors every journal record into its ring and
# auto-dumps on the trigger kinds — wired once at import so any entry
# point into the obs package arms it
JOURNAL.add_observer(FLIGHT.observe_journal)

# the protocol witness rides the same observer seam: every record
# advances the catalog-declared machines, and a violation's own
# protocol/violation emission is a flight auto-dump trigger
JOURNAL.add_observer(WITNESS.observe_journal)
from paddle_tpu.obs.protocol import _install_collector as \
    _install_protocol_collector  # noqa: E402

_install_protocol_collector()


def reset_all() -> None:
    """Zero every observability surface (registry values, journal ring
    + sink, tracer, flight recorder, trace context, utils/stats
    counters/timers) — the between-tests hygiene hook
    (tests/conftest.py autouse fixture)."""
    from paddle_tpu.analysis.lockdep import LOCKDEP
    from paddle_tpu.utils.stats import global_counters, global_stat
    REGISTRY.reset()
    JOURNAL.reset()
    TRACER.reset()
    FLIGHT.reset()
    PROFILER.reset()
    WATCHDOG.reset()
    context.reset()
    global_counters.reset()
    global_stat.reset()
    LOCKDEP.reset()
    WITNESS.reset()
