"""paddle_tpu.obs — the unified observability layer.

One place the whole framework reports through (docs/observability.md):

- :mod:`paddle_tpu.obs.metrics` — thread-safe metrics registry
  (counters / gauges / histograms with labels) with ONE Prometheus
  text exposition path; absorbs ``utils/stats`` and the serving
  ``stats()`` plumbing.
- :mod:`paddle_tpu.obs.events`  — versioned-schema structured event
  journal (JSONL file + in-memory ring): faults, OOMs, data faults,
  quarantines, sheds, breaker flips, preemptions, checkpoints.
- :mod:`paddle_tpu.obs.trace`   — host-side step tracing with Chrome
  trace export and XLA-compile instants.
- :mod:`paddle_tpu.obs.httpd`   — standalone /metrics + /events
  endpoint for trainer/coordinator processes.

The perf regression gate rides on the same layer: ``bench.py``'s smoke
tier measures through ``compile_watch`` / ``host_sync_watch``
(analysis/sanitizer.py) and ``tools/bench_gate.py`` enforces
``BENCH_SMOKE_BASELINE.json`` in tier-1.
"""

from paddle_tpu.obs.events import (JOURNAL, EventJournal, emit,  # noqa: F401
                                   emit_event, read_journal, tail,
                                   validate)
from paddle_tpu.obs.httpd import (build_obs_http_server,  # noqa: F401
                                  start_obs_server)
from paddle_tpu.obs.metrics import (REGISTRY, MetricsRegistry,  # noqa: F401
                                    stats_families)
from paddle_tpu.obs.trace import TRACER, Tracer, span  # noqa: F401

__all__ = [
    "REGISTRY", "MetricsRegistry", "stats_families",
    "JOURNAL", "EventJournal", "emit", "emit_event", "tail",
    "read_journal", "validate",
    "TRACER", "Tracer", "span",
    "build_obs_http_server", "start_obs_server",
    "reset_all",
]


def reset_all() -> None:
    """Zero every observability surface (registry values, journal ring
    + sink, tracer, utils/stats counters/timers) — the between-tests
    hygiene hook (tests/conftest.py autouse fixture)."""
    from paddle_tpu.utils.stats import global_counters, global_stat
    REGISTRY.reset()
    JOURNAL.reset()
    TRACER.reset()
    global_counters.reset()
    global_stat.reset()
