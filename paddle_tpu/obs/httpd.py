"""Standalone observability endpoint for non-serving processes.

The serving front already exposes GET /metrics and /events
(serving/http.py); trainers and coordinators had nothing — a fleet
scheduler could see the decode engine but not the training job next to
it. This is the missing piece: a tiny stdlib HTTP server any process
can start (CLI ``train --metrics_port``) exposing

  GET /metrics   Prometheus text exposition of the global registry
                 (obs/metrics.py — trainer, data-pipeline, fault and
                 decode-engine domains via the utils/stats bridge)
  GET /events    the event journal's in-memory ring as JSON
                 (?n=100&domain=...&kind=... filters; ?since_seq=N
                 pages forward from a cursor — the response's
                 "last_seq" is the next cursor)
  GET /flight    the flight recorder's postmortem bundle, on demand
                 (obs/flight.py; `paddle_tpu obs dump --url` fetches
                 this)
  GET /profile   the continuous profiler's live snapshot (per-phase
                 breakdown, MFU/roofline, memory + page-pool
                 telemetry — obs/profile.py) plus the SLO watchdog
                 state; ?deep_steps=N arms a jax.profiler.trace
                 window over the next N observed steps (the artifact
                 dir rides in subsequent snapshots/bundles)
  GET /health    {"status": "ok"} liveness probe

Scrape handlers only READ snapshots; they never touch the train step.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

__all__ = ["build_obs_http_server", "start_obs_server"]


def build_obs_http_server(host: str = "127.0.0.1",
                          port: int = 0) -> ThreadingHTTPServer:
    """Bound (not yet serving) observability HTTP server; port 0 picks
    a free one (see ``.server_address``). Caller runs
    ``.serve_forever()`` (usually on a thread) and ``.shutdown()``."""
    from paddle_tpu.obs.events import JOURNAL
    from paddle_tpu.obs.metrics import REGISTRY

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):       # scrapes are not news
            pass

        def _json(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            url = urlparse(self.path)
            if url.path == "/metrics":
                body = REGISTRY.exposition().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif url.path == "/events":
                qs = parse_qs(url.query)
                try:
                    n = int(qs.get("n", ["100"])[0])
                    since = qs.get("since_seq", [None])[0]
                    since = int(since) if since is not None else None
                except ValueError:
                    self._json(400, {"error": "n/since_seq must be "
                                              "integers"})
                    return
                self._json(200, {"events": JOURNAL.tail(
                    n, domain=qs.get("domain", [None])[0],
                    kind=qs.get("kind", [None])[0], since_seq=since),
                    "last_seq": JOURNAL.last_seq})
            elif url.path == "/flight":
                from paddle_tpu.obs.flight import FLIGHT
                self._json(200, FLIGHT.bundle(reason="http"))
            elif url.path == "/profile":
                from paddle_tpu.obs.profile import PROFILER
                from paddle_tpu.obs.slo import WATCHDOG
                qs = parse_qs(url.query)
                payload = {}
                deep = qs.get("deep_steps", [None])[0]
                if deep is not None:
                    try:
                        payload["armed_trace_dir"] = \
                            PROFILER.arm_window(int(deep))
                    except ValueError:
                        self._json(400, {"error": "deep_steps must "
                                                  "be an integer"})
                        return
                payload["profile"] = PROFILER.snapshot()
                payload["slo"] = WATCHDOG.snapshot()
                self._json(200, payload)
            elif url.path == "/health":
                self._json(200, {"status": "ok"})
            else:
                self._json(404, {"error": f"no route {url.path}"})

    return ThreadingHTTPServer((host, port), Handler)


def start_obs_server(host: str = "127.0.0.1",
                     port: int = 0) -> ThreadingHTTPServer:
    """Build + serve on a daemon thread (named ``pt-obs-http`` per the
    thread-hygiene convention). Returns the server; the bound port is
    ``server.server_address[1]``; stop with ``server.shutdown()``."""
    httpd = build_obs_http_server(host, port)
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="pt-obs-http")
    t.start()
    return httpd
