"""Metrics registry — one exposition path for the whole framework.

The 2017 reference printed ``Stat.h`` timers per pass and called it
observability; the modern twin is a process-global registry of named
counters / gauges / histograms (with labels) rendered as Prometheus
text exposition format 0.0.4, scrapeable from every long-lived process:
the serving front (serving/http.py GET /metrics), and the trainer /
coordinator via the standalone endpoint (obs/httpd.py, CLI
``train --metrics_port``).

Three sources feed one exposition (docs/observability.md):

- families registered directly on :data:`REGISTRY`
  (``counter()``/``gauge()``/``histogram()``);
- the ``utils/stats`` bridge collector: every ``global_counters`` name
  becomes a ``paddle_tpu_counter_total{name="..."}`` series and every
  ``global_stat`` timer a ``paddle_tpu_timer_seconds_*{name="..."}``
  family — the trainer, data-pipeline, fault and decode-engine domains
  all count through utils/stats, so they are scrapeable for free;
- per-scrape ``extra`` families: serving/http.py flattens
  ``InferenceServer.stats()`` through :func:`stats_families` with the
  PR-6-compatible ``paddle_tpu_serving_*`` names (test-pinned).

Thread-safe throughout: serving workers, data-pipeline workers and the
scrape handler hit the registry concurrently.
"""

from __future__ import annotations

import math
import re
import threading

from paddle_tpu.analysis.lockdep import named_lock
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["MetricsRegistry", "MetricFamily", "SampleFamily", "REGISTRY",
           "stats_families", "escape_label_value", "DEFAULT_BUCKETS"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: default histogram buckets (seconds) — spans a CPU-smoke train step
#: through a tunneled-TPU serving forward
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def escape_label_value(v) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(h: str) -> str:
    return h.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v) -> str:
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_labels(labels: Dict[str, object]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Counter:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _Gauge:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _Histogram:
    __slots__ = ("_lock", "buckets", "_counts", "_sum", "_count")

    def __init__(self, buckets: Sequence[float]):
        self._lock = threading.Lock()
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = tuple(bs)
        self._counts = [0] * len(bs)
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(cumulative per-bucket counts excluding +Inf, sum, count).
        Bucket counts are cumulative at record time (observe adds to
        every bucket >= v), so monotonicity holds by construction."""
        with self._lock:
            return list(self._counts), self._sum, self._count


_KINDS = {"counter": _Counter, "gauge": _Gauge, "histogram": _Histogram}


class MetricFamily:
    """One named metric with a fixed label schema and per-labelset
    children. With no ``labelnames`` the family IS its single child:
    ``fam.inc()`` / ``fam.set()`` / ``fam.observe()`` work directly."""

    def __init__(self, name: str, kind: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        if kind not in _KINDS:
            raise ValueError(f"kind must be one of {sorted(_KINDS)}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self._buckets = tuple(buckets if buckets is not None
                              else DEFAULT_BUCKETS)
        self._lock = named_lock("obs.metrics.family")
        self._children: Dict[Tuple[str, ...], object] = {}

    def _make_child(self):
        if self.kind == "histogram":
            return _Histogram(self._buckets)
        return _KINDS[self.kind]()

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, "
                f"got {tuple(sorted(kv))}")
        key = tuple(str(kv[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    # ----- label-less convenience (the family is its own child)
    def _default(self):
        return self.labels(**{})

    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self._default().dec(n)

    def set(self, v: float) -> None:
        self._default().set(v)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    def value(self, **kv) -> float:
        return self.labels(**kv).value

    def reset(self) -> None:
        with self._lock:
            self._children.clear()

    def samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        """[(sample name, labels, value)] — histograms expand into
        ``_bucket{le=}`` / ``_sum`` / ``_count`` series."""
        with self._lock:
            children = dict(self._children)
        out: List[Tuple[str, Dict[str, str], float]] = []
        for key, child in sorted(children.items()):
            labels = dict(zip(self.labelnames, key))
            if self.kind == "histogram":
                counts, total, count = child.snapshot()
                for b, c in zip(child.buckets, counts):
                    out.append((f"{self.name}_bucket",
                                {**labels, "le": _fmt_value(b)}, c))
                out.append((f"{self.name}_bucket",
                            {**labels, "le": "+Inf"}, count))
                out.append((f"{self.name}_sum", labels, total))
                out.append((f"{self.name}_count", labels, count))
            else:
                out.append((self.name, labels, child.value))
        return out


class SampleFamily:
    """A pre-computed family (one scrape's worth of samples) — the
    shape collectors and the stats()-flattening path produce."""

    def __init__(self, name: str, kind: str, help: str = "",
                 samples: Optional[List[Tuple[str, Dict[str, str],
                                              float]]] = None):
        self.name = name
        self.kind = kind
        self.help = help
        self._samples = list(samples or [])

    def add(self, labels: Dict[str, str], value: float,
            suffix: str = "") -> "SampleFamily":
        self._samples.append((self.name + suffix, labels, value))
        return self

    def samples(self):
        return list(self._samples)


class MetricsRegistry:
    """Process-global family registry + pluggable collectors.

    ``reset()`` clears every family's children and is what the test
    fixture calls between tests (registrations and collectors
    survive — the shape of the catalog is static, the values are not).
    """

    def __init__(self):
        self._lock = named_lock("obs.metrics.registry")
        self._families: Dict[str, MetricFamily] = {}
        self._collectors: List[Callable[[], Iterable]] = []

    # ------------------------------------------------------------ creation
    def _family(self, name: str, kind: str, help: str,
                labelnames: Sequence[str],
                buckets: Optional[Sequence[float]] = None) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.labelnames}, cannot re-register "
                        f"as {kind}{tuple(labelnames)}")
                return fam
            fam = MetricFamily(name, kind, help, labelnames, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> MetricFamily:
        return self._family(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> MetricFamily:
        return self._family(name, "gauge", help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None
                  ) -> MetricFamily:
        return self._family(name, "histogram", help, labelnames, buckets)

    # ----------------------------------------------------------- collectors
    def register_collector(self, fn: Callable[[], Iterable]) -> None:
        """``fn()`` is called at scrape time and returns an iterable of
        family-like objects (``.name``/``.kind``/``.help``/
        ``.samples()``)."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    # ------------------------------------------------------------- scraping
    def collect(self, extra: Iterable = ()) -> List:
        with self._lock:
            fams = list(self._families.values())
            collectors = list(self._collectors)
        for c in collectors:
            fams.extend(c())
        fams.extend(extra)
        return sorted(fams, key=lambda f: f.name)

    def exposition(self, extra: Iterable = ()) -> str:
        """Prometheus text exposition 0.0.4. One HELP/TYPE pair per
        family name (first registration wins on a collision)."""
        out: List[str] = []
        seen: Dict[str, str] = {}
        for fam in self.collect(extra):
            if fam.name not in seen:
                seen[fam.name] = fam.kind
                if fam.help:
                    out.append(f"# HELP {fam.name} "
                               f"{_escape_help(fam.help)}")
                out.append(f"# TYPE {fam.name} {fam.kind}")
            for name, labels, value in fam.samples():
                out.append(f"{name}{_fmt_labels(labels)} "
                           f"{_fmt_value(value)}")
        return "\n".join(out) + "\n"

    def reset(self) -> None:
        """Zero every family (keep registrations + collectors) — the
        between-tests hygiene hook (tests/conftest.py)."""
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            fam.reset()

    def clear(self) -> None:
        """Drop families AND collectors (full teardown; rarely what a
        test wants — the stats bridge would be lost too)."""
        with self._lock:
            self._families.clear()
            self._collectors.clear()


#: the process-global registry every subsystem reports through
REGISTRY = MetricsRegistry()


# ----------------------------------------------------- stats() flattening
def stats_families(prefix: str, stats: dict,
                   counter_keys: Iterable[str] = ()) -> List[SampleFamily]:
    """Flatten a nested ``stats()`` dict into exposition families,
    PR-6-compatible: leaf keys in ``counter_keys`` keep their cumulative
    (counter) semantics, every other numeric leaf is a gauge, nested
    dicts recurse with an underscored prefix, non-numeric leaves are
    skipped. Names like ``paddle_tpu_serving_engine_finished`` are
    test-pinned — do not change this flattening."""
    counter_keys = set(counter_keys)
    fams: List[SampleFamily] = []

    def walk(pfx: str, d: dict) -> None:
        for key in sorted(d):
            val = d[key]
            name = f"{pfx}_{key}"
            if isinstance(val, dict):
                walk(name, val)
                continue
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                continue
            kind = "counter" if key in counter_keys else "gauge"
            fams.append(SampleFamily(
                name, kind, f"{pfx} stats() field {key!r}",
                [(name, {}, float(val))]))

    walk(prefix, stats)
    return fams


# ------------------------------------------------------ utils/stats bridge
def _stats_bridge() -> List[SampleFamily]:
    """Scrape-time view of utils/stats: the trainer, data-pipeline,
    fault and decode-engine domains all count through
    ``global_counters`` / ``global_stat``, so one bridge makes every
    domain scrapeable without per-site registry plumbing."""
    from paddle_tpu.utils.stats import global_counters, global_stat
    fams: List[SampleFamily] = []
    counters = global_counters.items()
    if counters:
        fams.append(SampleFamily(
            "paddle_tpu_counter_total", "counter",
            "utils.stats global_counters; the counter name "
            "(domain/what) rides in the 'name' label",
            [("paddle_tpu_counter_total", {"name": k}, float(v))
             for k, v in sorted(counters.items())]))
    timers = global_stat.items()
    if timers:
        count = SampleFamily(
            "paddle_tpu_timer_count", "counter",
            "utils.stats stat_timer scopes entered, per timer name")
        total = SampleFamily(
            "paddle_tpu_timer_seconds_total", "counter",
            "utils.stats stat_timer cumulative seconds, per timer name")
        mx = SampleFamily(
            "paddle_tpu_timer_max_seconds", "gauge",
            "utils.stats stat_timer worst single scope, per timer name")
        for k, item in sorted(timers.items()):
            c, t, m = item.snapshot()
            count.add({"name": k}, c)
            total.add({"name": k}, t)
            mx.add({"name": k}, m)
        fams.extend([count, total, mx])
    return fams


REGISTRY.register_collector(_stats_bridge)


def _lockdep_bridge() -> List[SampleFamily]:
    """Scrape-time view of the ptlockdep witness
    (analysis/lockdep.py): order-graph size, inversions, and per-name
    contention / hold-time telemetry.  Imported lazily — lockdep is
    the module the obs plane builds its OWN locks from."""
    from paddle_tpu.analysis.lockdep import LOCKDEP
    snap = LOCKDEP.metrics_snapshot()
    fams: List[SampleFamily] = [
        SampleFamily(
            "paddle_tpu_lockdep_edges", "gauge",
            "distinct acquisition-order edges in the lockdep graph",
            [("paddle_tpu_lockdep_edges", {}, float(snap["edges"]))]),
        SampleFamily(
            "paddle_tpu_lockdep_inversions_total", "counter",
            "lock-order inversions witnessed since reset",
            [("paddle_tpu_lockdep_inversions_total", {},
              float(snap["inversions"]))]),
    ]
    if snap["contentions"]:
        fams.append(SampleFamily(
            "paddle_tpu_lockdep_contentions_total", "counter",
            "acquires that found the named lock already held",
            [("paddle_tpu_lockdep_contentions_total", {"name": k},
              float(v))
             for k, v in sorted(snap["contentions"].items())]))
    if snap["hold_ms"]:
        fams.append(SampleFamily(
            "paddle_tpu_lockdep_hold_time_ms", "gauge",
            "cumulative milliseconds the named lock was held",
            [("paddle_tpu_lockdep_hold_time_ms", {"name": k}, float(v))
             for k, v in sorted(snap["hold_ms"].items())]))
    if snap["acquisitions"]:
        fams.append(SampleFamily(
            "paddle_tpu_lockdep_acquisitions_total", "counter",
            "acquisitions of the named lock since reset",
            [("paddle_tpu_lockdep_acquisitions_total", {"name": k},
              float(v))
             for k, v in sorted(snap["acquisitions"].items())]))
    return fams


REGISTRY.register_collector(_lockdep_bridge)
