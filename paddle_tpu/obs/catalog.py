"""ptproto — the declared observability contract (docs/static_analysis.md).

One module declares everything the journal/metric substrate is allowed
to say, and three consumers read it so they cannot drift:

- **JOURNALS** — every legal journal ``(domain, kind)`` with its
  required/optional field names.  ptlint R11 checks every literal
  ``emit()`` site against it (and reports stale catalog entries);
  ``paddle_tpu obs catalog`` dumps it for external scrapers.
- **METRICS / METRIC_PREFIXES** — every ``paddle_tpu_*`` metric family
  (name, type, label set) plus the dynamic stats-flattened prefixes.
  ptlint R12 cross-checks registrations AND the
  ``docs/observability.md`` tables in both directions.
- **PROTOCOLS** — correlation-keyed state machines for the orderings
  the repo already enforces ad hoc (hop start->settle|torn|error,
  route->[failover*]->exactly-one settle, shard kill->replace->restore,
  ...).  ptlint R13 proves every exit path of a start-emitting function
  reaches a terminal statically; obs/protocol.py's ProtocolWitness
  advances the same machines at runtime; loadgen/verdict.py
  reconstructs fault evidence chains from the same matchers.

The module is import-light (dataclasses only — no jax, no obs
runtime) so the analysis rules can load it in any environment.

Machine semantics (shared by the witness and the verdict):

- a record matching a protocol's ``start`` opens a machine for its
  correlation key; a second start while open SUPERSEDES the previous
  instance (legal: a failover hop re-starts the same trace_id —
  tests/test_fleet_faults.py pins that a SIGKILL'd replica's hop
  never settles);
- ``intermediates`` append to the open machine's chain; unmatched
  intermediates are ignored (they may precede/outlive the machine);
- a ``Terminal`` closes the machine.  A terminal whose
  ``orphan_violates`` is True arriving for a key with NO open machine
  is a violation — that is the exactly-once property (a second
  fleet/settle for a settled trace, a hop settle with no hop start);
- machines still open are NOT live violations (a killed replica
  legitimately never settles its hop); ``ProtocolWitness.finalize()``
  reports them on demand.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

__all__ = [
    "JournalKind", "MetricFamilyDecl", "EventMatch", "Terminal",
    "Protocol", "FaultChainSpec", "JOURNALS", "METRICS",
    "METRIC_PREFIXES", "PROTOCOLS", "FAULT_FAMILIES",
    "journal_entry", "protocol_for_start", "catalog_as_dict",
]


# --------------------------------------------------------------------- journal
@dataclass(frozen=True)
class JournalKind:
    """One legal (domain, kind): which fields every emit site must
    pass (``required``) and which it may (``optional``).  ``dynamic``
    marks kinds whose emit goes through a non-literal dispatch
    (``emit_event`` on trainer-event objects) — R11's stale-entry
    check exempts them because no literal site exists to count."""
    domain: str
    kind: str
    required: Tuple[str, ...] = ()
    optional: Tuple[str, ...] = ()
    dynamic: bool = False
    description: str = ""

    @property
    def key(self) -> Tuple[str, str]:
        return (self.domain, self.kind)


def _j(domain, kind, required=(), optional=(), dynamic=False, desc=""):
    return JournalKind(domain, kind, tuple(required), tuple(optional),
                       dynamic, desc)


_JOURNAL_DECLS = (
    # -- artifacts (warm-start plane, PR 18)
    _j("artifacts", "load", ("name", "digest", "source"),
       desc="AOT executable served from the artifact store"),
    _j("artifacts", "build", ("name", "digest", "build_ms",
                              "payload_bytes"),
       desc="cold compile persisted into the store"),
    _j("artifacts", "build_failed", ("name", "digest", "detail"),
       desc="built in-process but could not be persisted"),
    _j("artifacts", "fallback", ("name", "path", "reason", "detail"),
       desc="stored artifact unusable; degraded to JIT"),
    _j("artifacts", "verify_failed", ("name", "path", "detail"),
       desc="store verify pass found a bad frame"),
    # -- autopilot (fleet controller, PR 16)
    _j("autopilot", "scale_up", ("replica", "endpoint", "reason",
                                 "evidence"),
       desc="autoscaler spawned a replica; evidence is the journaled "
            "signal that justified it"),
    _j("autopilot", "scale_down", ("replica", "reason", "evidence"),
       desc="autoscaler drained+stopped a replica"),
    _j("autopilot", "spawn_failed", ("replica", "error", "reason")),
    _j("autopilot", "stop_failed", ("replica", "error")),
    _j("autopilot", "deploy_start", ("replicas", "force"),
       desc="rolling deploy began (protocol: autopilot_deploy)"),
    _j("autopilot", "deploy_step", ("replica", "ready"),
       ("drain_settled", "endpoint", "step_s")),
    _j("autopilot", "deploy_done", ("replicas", "wall_s")),
    _j("autopilot", "deploy_paused", ("replica", "breaches",
                                      "remaining"),
       ("completed", "reason")),
    _j("autopilot", "deploy_compile_budget_breach",
       ("compiles", "budget"), ("per_function",)),
    # -- checkpoint
    _j("checkpoint", "save", ("step", "path", "background")),
    _j("checkpoint", "restore", ("step", "path")),
    # -- coordinator (membership plane)
    _j("coordinator", "join", ("worker_id", "rejoin", "generation",
                               "workers")),
    _j("coordinator", "leave", ("worker_id", "generation", "workers")),
    _j("coordinator", "lease_expired", ("worker_id", "workers")),
    _j("coordinator", "generation", ("generation", "reason")),
    _j("coordinator", "reshard", ("reason", "generation", "todo",
                                  "pending", "workers")),
    _j("coordinator", "stale_grant", ("rpc", "task_id",
                                      "grant_generation",
                                      "current_generation")),
    _j("coordinator", "clock_sync", ("offset_s", "rtt_s", "samples")),
    # -- data pipeline (literal quarantine site + DataFaultEvent kinds)
    _j("data", "quarantine", ("count", "where"), ("error",)),
    _j("data", "data_budget", ("count", "where"), ("error",),
       dynamic=True, desc="ErrorBudget exhausted (DataFaultEvent)"),
    _j("data", "source_stall", ("count", "where"), ("error",),
       dynamic=True),
    _j("data", "worker_restart", ("count", "where"), ("error",),
       dynamic=True),
    # -- embed (sharded parameter service, PR 14)
    _j("embed", "update", ("shard_id", "rows", "seq", "dup"),
       desc="WAL-durable sparse update applied (ack follows append)"),
    _j("embed", "gather", ("shard_id", "rows")),
    _j("embed", "snapshot", ("shard_id", "rows", "wal_upto")),
    _j("embed", "restore", ("shard_id", "from_snapshot", "replayed")),
    _j("embed", "shard_killed", ("shard_id",)),
    _j("embed", "shard_replaced", ("shard_id", "replayed",
                                   "endpoint")),
    _j("embed", "stale_read", ("shard_id", "rows", "age_s", "bound_s"),
       ("trace_id",)),
    _j("embed", "push_failed", (), ("error", "shard_id", "rows", "seq",
                                    "trace_id")),
    _j("embed", "sample", (), ("ids", "label"),
       desc="online-training sample journaled from the serving path"),
    _j("embed", "online_pass", ("batches", "samples"), ("loss_last",)),
    # -- engine (decode)
    _j("engine", "preemption", ("generated", "evictions",
                                "free_pages"), ("trace_id",)),
    _j("engine", "prefix_evict", ("pages", "free_pages",
                                  "engine_step")),
    _j("engine", "cow_copy_failure", ("error",), ("trace_id",)),
    _j("engine", "draft_failure", ("error", "engine_step")),
    _j("engine", "step_failure", ("error", "engine_step"),
       ("trace_ids", "waiting_trace_ids")),
    # -- engine two-tier KV plane (PR 20)
    _j("engine", "page_spill", ("page", "key_pages", "spilled_now",
                                "free_pages", "engine_step"),
       desc="cold trie page spilled device->host instead of freed "
            "(protocol: kv_page_spill)"),
    _j("engine", "page_restore", ("page", "key_pages", "spilled_now",
                                  "engine_step"),
       desc="spilled page restored host->device on a prefix match, "
            "before prefill was charged"),
    _j("engine", "spill_integrity", ("reason", "engine_step"),
       ("error", "page", "key_pages"),
       desc="spill entry dropped (crc_mismatch / read_failed / "
            "restore_write_failed) — degrades to a prefix miss, "
            "never restores a torn page"),
    _j("engine", "dequant_fallback", ("reason", "kv_quant"),
       desc="int8 KV requested but the fused dequant kernel is "
            "unsupported here; decode uses the exact-einsum path"),
    # -- fleet (router plane, PR 15/16)
    _j("fleet", "join", ("replica", "endpoint")),
    _j("fleet", "rejoin", ("replica", "endpoint")),
    _j("fleet", "lease_lapse", ("replica",)),
    _j("fleet", "route", ("trace_id", "replica", "hop",
                          "affinity_pages", "prompt_len", "max_new"),
       desc="request placed on a replica (protocol: fleet_request)"),
    _j("fleet", "reroute", ("trace_id", "replica", "reason")),
    _j("fleet", "failover", ("trace_id", "victim", "hop", "why",
                             "streamed")),
    _j("fleet", "settle", ("trace_id", "replica", "hops", "tokens"),
       desc="exactly-once terminal of fleet_request"),
    _j("fleet", "reject", ("trace_id", "reason"), ("total_tokens",)),
    _j("fleet", "drain", ("replica", "settled")),
    _j("fleet", "undrain", ("replica",)),
    _j("fleet", "stale_view", ("error", "replicas", "max_stale_s")),
    _j("fleet", "stale_view_expired", ("stale_s", "dropped")),
    _j("fleet", "view_recovered", ("stale_s", "replicas")),
    # -- lockdep / obs / profile
    _j("lockdep", "inversion", (), (),
       desc="lock-order inversion with both stacks (fields are the "
            "witness's cycle payload)"),
    _j("obs", "selfcheck", ("probe",)),
    _j("profile", "window", ("dir",)),
    # -- protocol (ptproto runtime witness — obs/protocol.py)
    _j("protocol", "violation", ("protocol", "key", "reason"),
       ("chain", "record", "state"),
       desc="a declared machine saw an illegal record; chain is the "
            "offending record refs (domain/kind/seq)"),
    # -- serving (single-replica front)
    _j("serving", "hop", ("trace_id", "phase"),
       ("tokens", "streamed", "reason"),
       desc="replica-side stream lifecycle (protocol: serving_hop); "
            "phase in start|settle|torn|error"),
    _j("serving", "drain", ("action",)),
    _j("serving", "shed", ("reason",),
       ("trace_id", "where", "rows", "limit", "estimated_bytes",
        "budget", "queue_depth", "retry_after", "new_batch_limit")),
    _j("serving", "breaker", ("state",),
       ("probe_failed", "trips", "failure_rate")),
    # -- slo watchdog (PR 11)
    _j("slo", "breach", (), (),
       desc="burn-rate breach (payload is the watchdog's evidence)"),
    _j("slo", "step_regression", ("step_kind", "step_ms", "median_ms",
                                  "factor", "threshold", "streak",
                                  "phase")),
    # -- soak (loadgen, PR 17)
    _j("soak", "run_start", ("seed", "duration_s", "workload",
                             "families", "chat_requests",
                             "ctr_requests")),
    _j("soak", "run_end", ("stopped_early",)),
    _j("soak", "request", ("workload", "trace_id", "outcome"),
       ("tokens", "ttft_ms", "tok_ms", "total_ms", "sched_lag_ms",
        "gather_ms", "score", "label")),
    _j("soak", "fault_injected", ("family", "action", "target",
                                  "at_s"),
       ("fired", "replica", "shard", "probe_trace", "rejoins",
        "killed_at", "routers", "outage_s", "spilled", "restored")),
    _j("soak", "replica_final", ("replica", "kv_pages_leaked",
                                 "active_slots", "kv_pages_used")),
    _j("soak", "online_step", ("batches", "samples", "loss")),
    _j("soak", "ctr_error", ("trace_id", "error")),
    # -- trainer (literal sites + FaultEvent/OOMEvent kinds)
    _j("trainer", "run_start", ("job", "config")),
    _j("trainer", "run_end", ("job",)),
    _j("trainer", "oom", ("microbatch", "accum_steps"),
       ("error", "batch_rows", "pass_id", "batch_id")),
    _j("trainer", "nonfinite", ("pass_id", "batch_id", "bad_streak"),
       ("restored_step",), dynamic=True),
    _j("trainer", "rollback", ("pass_id", "batch_id", "bad_streak"),
       ("restored_step",), dynamic=True),
    _j("trainer", "reshape", ("generation", "worker_id")),
    _j("trainer", "plan_adopted", ("provenance", "microbatch",
                                   "accum_steps")),
)

JOURNALS: Dict[Tuple[str, str], JournalKind] = {
    d.key: d for d in _JOURNAL_DECLS}


def journal_entry(domain: str, kind: str) -> Optional[JournalKind]:
    return JOURNALS.get((str(domain), str(kind)))


# --------------------------------------------------------------------- metrics
@dataclass(frozen=True)
class MetricFamilyDecl:
    """One fixed-name ``paddle_tpu_*`` family: its type and label
    set.  ``collector`` marks families produced by a scrape-time
    SampleFamily bridge rather than a REGISTRY.counter/gauge/histogram
    registration (labels ride on .add(), not on labelnames)."""
    name: str
    type: str                       # counter | gauge | histogram
    labels: Tuple[str, ...] = ()
    collector: bool = False
    description: str = ""


def _m(name, type_, labels=(), collector=False, desc=""):
    return MetricFamilyDecl(name, type_, tuple(labels), collector, desc)


_METRIC_DECLS = (
    # artifacts store gauges (artifacts/store.py)
    _m("paddle_tpu_artifacts_hits", "gauge"),
    _m("paddle_tpu_artifacts_misses", "gauge"),
    _m("paddle_tpu_artifacts_fallbacks", "gauge"),
    _m("paddle_tpu_artifacts_build_ms", "gauge"),
    # decode-engine prefix cache + speculation (serving/engine.py)
    _m("paddle_tpu_prefix_hit_pages", "counter"),
    _m("paddle_tpu_prefix_miss_pages", "counter"),
    _m("paddle_tpu_prefix_cow_copies", "counter"),
    _m("paddle_tpu_prefix_shared_pages", "gauge"),
    _m("paddle_tpu_spec_proposed_tokens_total", "counter"),
    _m("paddle_tpu_spec_accepted_tokens_total", "counter"),
    # two-tier KV plane (serving/engine.py + serving/spill.py, PR 20)
    _m("paddle_tpu_kv_pages_spilled_total", "counter"),
    _m("paddle_tpu_kv_pages_restored_total", "counter"),
    _m("paddle_tpu_kv_spill_integrity_drops_total", "counter"),
    _m("paddle_tpu_kv_pages_spilled_now", "gauge"),
    # continuous profiler (obs/profile.py)
    _m("paddle_tpu_profile_step_ms", "gauge", ("kind",)),
    _m("paddle_tpu_profile_mfu", "gauge", ("kind",)),
    _m("paddle_tpu_profile_roofline_frac", "gauge", ("kind",)),
    _m("paddle_tpu_profile_phase_ms", "gauge", ("kind", "phase")),
    _m("paddle_tpu_profile_page_pool_occupancy", "gauge", ("pool",)),
    _m("paddle_tpu_profile_page_pool_occupancy_trend", "gauge",
       ("pool",)),
    _m("paddle_tpu_profile_device_bytes_in_use", "gauge"),
    _m("paddle_tpu_profile_hbm_watermark_bytes", "gauge"),
    # tracing (obs/trace.py)
    _m("paddle_tpu_trace_dropped_total", "counter"),
    # utils/stats scrape bridge (obs/metrics.py _stats_bridge)
    _m("paddle_tpu_counter_total", "counter", ("name",),
       collector=True),
    _m("paddle_tpu_timer_count", "counter", ("name",), collector=True),
    _m("paddle_tpu_timer_seconds_total", "counter", ("name",),
       collector=True),
    _m("paddle_tpu_timer_max_seconds", "gauge", ("name",),
       collector=True),
    # lockdep witness bridge (obs/metrics.py _lockdep_bridge)
    _m("paddle_tpu_lockdep_edges", "gauge", (), collector=True),
    _m("paddle_tpu_lockdep_inversions_total", "counter", (),
       collector=True),
    _m("paddle_tpu_lockdep_contentions_total", "counter", ("name",),
       collector=True),
    _m("paddle_tpu_lockdep_hold_time_ms", "gauge", ("name",),
       collector=True),
    _m("paddle_tpu_lockdep_acquisitions_total", "counter", ("name",),
       collector=True),
    # protocol witness bridge (obs/protocol.py)
    _m("paddle_tpu_protocol_tracked", "gauge", ("protocol",),
       collector=True, desc="machines currently open"),
    _m("paddle_tpu_protocol_completed", "gauge", ("protocol",),
       collector=True, desc="machines closed by a terminal"),
    _m("paddle_tpu_protocol_violations_total", "counter",
       ("protocol",), collector=True),
)

METRICS: Dict[str, MetricFamilyDecl] = {m.name: m for m in _METRIC_DECLS}

# Dynamic families: flattened from a stats() dict or formatted with a
# runtime key — declared as prefixes because their member names are
# not statically enumerable.  R12 requires every f-string registration
# head to match one of these, and docs tokens under a prefix are legal.
METRIC_PREFIXES: Dict[str, str] = {
    "paddle_tpu_serving_": "InferenceServer.stats() flattened "
                           "(serving/http.py prometheus_text)",
    "paddle_tpu_fleet_": "FleetRouter.stats() flattened "
                         "(fleet/obs.py)",
    "paddle_tpu_autopilot_": "Autoscaler.stats() flattened "
                             "(fleet/obs.py)",
    "paddle_tpu_coord_": "coordinator task-plane gauges "
                         "(trainer/coordinator.py)",
    "paddle_tpu_embed_shard_": "per-shard embed-service gauges "
                               "(embed/obs.py)",
    "paddle_tpu_embed_client_": "per-client embed gauges "
                                "(embed/obs.py)",
}


# ------------------------------------------------------------------- protocols
@dataclass(frozen=True)
class EventMatch:
    """Match one journal record: domain + kind, plus optional literal
    field constraints (``where``) — e.g. serving/hop phase=start."""
    domain: str
    kind: str
    where: Tuple[Tuple[str, object], ...] = ()

    def matches(self, rec: dict) -> bool:
        if rec.get("domain") != self.domain \
                or rec.get("kind") != self.kind:
            return False
        return all(rec.get(k) == v for k, v in self.where)


@dataclass(frozen=True)
class Terminal:
    match: EventMatch
    orphan_violates: bool = False


@dataclass(frozen=True)
class Protocol:
    """One correlation-keyed machine.  ``key`` is the record field
    carrying the correlation key (None = a single global machine).
    ``check_paths`` opts the protocol into ptlint R13's static
    exit-path proof — only meaningful where start and terminals are
    emitted by the same function (cross-process protocols are the
    runtime witness's job alone)."""
    name: str
    key: Optional[str]
    start: EventMatch
    intermediates: Tuple[EventMatch, ...] = ()
    terminals: Tuple[Terminal, ...] = ()
    check_paths: bool = False
    on_restart: str = "supersede"   # or "extend": re-start continues
    description: str = ""

    def terminal(self, kind: str) -> Terminal:
        for t in self.terminals:
            if t.match.kind == kind:
                return t
        raise KeyError(f"{self.name}: no terminal kind {kind!r}")

    def intermediate(self, kind: str) -> EventMatch:
        for m in self.intermediates:
            if m.kind == kind:
                return m
        raise KeyError(f"{self.name}: no intermediate kind {kind!r}")


_PROTOCOL_DECLS = (
    Protocol(
        "serving_hop", "trace_id",
        start=EventMatch("serving", "hop", (("phase", "start"),)),
        terminals=(
            Terminal(EventMatch("serving", "hop",
                                (("phase", "settle"),)), True),
            Terminal(EventMatch("serving", "hop",
                                (("phase", "torn"),)), True),
            Terminal(EventMatch("serving", "hop",
                                (("phase", "error"),)), True),
        ),
        check_paths=True,
        description="replica-side stream: every hop that starts "
                    "settles, tears, or errors — a start with no "
                    "terminal is a process lost mid-stream"),
    Protocol(
        "fleet_request", "trace_id",
        start=EventMatch("fleet", "route"),
        intermediates=(EventMatch("fleet", "failover"),
                       EventMatch("fleet", "reroute")),
        terminals=(
            Terminal(EventMatch("fleet", "settle"), True),
            Terminal(EventMatch("fleet", "reject"), False),
        ),
        check_paths=True,
        on_restart="extend",        # a post-failover re-route is the
        description="router-side request: route -> [failover|reroute]* "
                    "-> exactly-one settle (or a reject); a settle "
                    "for an unrouted/settled trace violates "
                    "exactly-once"),
    Protocol(
        "embed_shard_failover", "shard_id",
        start=EventMatch("embed", "shard_killed"),
        intermediates=(EventMatch("embed", "shard_replaced"),),
        terminals=(Terminal(EventMatch("embed", "restore"), False),),
        description="WAL exactly-once failover: a killed shard is "
                    "replaced and replays its WAL (append-before-ack "
                    "means no acked update is lost)"),
    Protocol(
        "artifacts_degrade", "name",
        start=EventMatch("artifacts", "fallback"),
        terminals=(
            Terminal(EventMatch("artifacts", "build"), False),
            Terminal(EventMatch("artifacts", "build_failed"), False),
            Terminal(EventMatch("artifacts", "load"), False),
        ),
        description="a fallback (stored artifact unusable) must be "
                    "followed by a backfill build / build_failed for "
                    "the same name — degrade is never silent"),
    Protocol(
        "fleet_lease", "replica",
        start=EventMatch("fleet", "lease_lapse"),
        terminals=(Terminal(EventMatch("fleet", "rejoin"), False),),
        description="a lapsed lease heals by rejoin (or the replica "
                    "stays dead — unterminated is legal, audited by "
                    "the soak verdict per injected fault)"),
    Protocol(
        "fleet_registry_view", None,
        start=EventMatch("fleet", "stale_view"),
        terminals=(
            Terminal(EventMatch("fleet", "view_recovered"), False),
            Terminal(EventMatch("fleet", "stale_view_expired"),
                     False),
        ),
        description="bounded-staleness registry outage: a stale view "
                    "either recovers or expires"),
    Protocol(
        "kv_page_spill", None,
        start=EventMatch("engine", "page_spill"),
        terminals=(
            Terminal(EventMatch("engine", "page_restore"), False),
            Terminal(EventMatch("engine", "spill_integrity"), False),
        ),
        description="two-tier KV lifecycle: a spilled page is later "
                    "restored or dropped with journaled integrity "
                    "evidence; still-spilled is legal (capacity "
                    "headroom, audited by page_accounting) — spill "
                    "and restore are emitted by different engine "
                    "paths, so this is runtime/verdict-only, not "
                    "check_paths"),
    Protocol(
        "autopilot_deploy", None,
        start=EventMatch("autopilot", "deploy_start"),
        intermediates=(
            EventMatch("autopilot", "deploy_step"),
            EventMatch("autopilot", "deploy_compile_budget_breach"),
        ),
        terminals=(
            Terminal(EventMatch("autopilot", "deploy_done"), False),
            Terminal(EventMatch("autopilot", "deploy_paused"), False),
        ),
        check_paths=True,
        description="a rolling deploy always lands on done or "
                    "paused-with-evidence, even through exceptions"),
)

PROTOCOLS: Dict[str, Protocol] = {p.name: p for p in _PROTOCOL_DECLS}


@dataclass(frozen=True)
class FaultChainSpec:
    """How the soak verdict maps one injected-fault family onto a
    protocol: which field of the ``soak/fault_injected`` record
    carries the machine's correlation key.  loadgen/verdict.py
    reconstructs the evidence chain from the referenced protocol's
    matchers — the same objects the runtime witness advances."""
    family: str
    protocol: str
    fault_key: Optional[str]        # field on the fault record


FAULT_FAMILIES: Dict[str, FaultChainSpec] = {
    "p": FaultChainSpec("p", "fleet_request", "probe_trace"),
    "o": FaultChainSpec("o", "embed_shard_failover", "shard"),
    "k": FaultChainSpec("k", "fleet_lease", "replica"),
    "q": FaultChainSpec("q", "fleet_registry_view", None),
    "s": FaultChainSpec("s", "kv_page_spill", None),
}


def protocol_for_start(rec_or_match) -> Optional[Protocol]:
    """The protocol whose start matcher matches ``rec_or_match`` (a
    journal record dict), or None."""
    for p in PROTOCOLS.values():
        if p.start.matches(rec_or_match):
            return p
    return None


# ------------------------------------------------------------------ CLI export
def catalog_as_dict() -> dict:
    """The whole contract as plain JSON-able data — ``paddle_tpu obs
    catalog`` dumps this for external scrapers and dashboards."""
    return {
        "v": 1,
        "journals": [
            {"domain": d.domain, "kind": d.kind,
             "required": list(d.required),
             "optional": list(d.optional),
             "dynamic": d.dynamic,
             "description": d.description}
            for d in sorted(JOURNALS.values(),
                            key=lambda d: d.key)],
        "metrics": [
            {"name": m.name, "type": m.type,
             "labels": list(m.labels), "collector": m.collector,
             "description": m.description}
            for m in sorted(METRICS.values(), key=lambda m: m.name)],
        "metric_prefixes": dict(sorted(METRIC_PREFIXES.items())),
        "protocols": [
            {"name": p.name, "key": p.key,
             "start": {"domain": p.start.domain, "kind": p.start.kind,
                       "where": dict(p.start.where)},
             "intermediates": [
                 {"domain": m.domain, "kind": m.kind,
                  "where": dict(m.where)} for m in p.intermediates],
             "terminals": [
                 {"domain": t.match.domain, "kind": t.match.kind,
                  "where": dict(t.match.where),
                  "orphan_violates": t.orphan_violates}
                 for t in p.terminals],
             "check_paths": p.check_paths,
             "description": p.description}
            for p in sorted(PROTOCOLS.values(),
                            key=lambda p: p.name)],
        "fault_families": {
            f: {"protocol": s.protocol, "fault_key": s.fault_key}
            for f, s in sorted(FAULT_FAMILIES.items())},
    }
