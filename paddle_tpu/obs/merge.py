"""Cross-process trace/journal merge — N per-host observability
streams fused into one timeline.

Every journal record carries ``run_id/host/pid`` (obs/events.py) and
every chrome-trace export carries ``metadata.{run_id,host,pid}``
(obs/trace.py), so a multi-host job — coordinator workers, a serving
fleet — leaves one journal + one trace per process. This module fuses
them:

- :func:`merge_journals` reads N JSONL journals, adjusts each file's
  timestamps by its clock offset, sorts, and assigns a MONOTONE merged
  sequence number ``mseq`` (original per-process ``seq``/``host``/
  ``pid`` preserved) — one queryable journal for the whole job.
- :func:`merge_traces` does the same for chrome-trace JSON exports,
  remapping colliding pids and labeling each process
  ``<host> pid=<pid>`` so Perfetto shows one timeline with a lane per
  host.

Clock skew: wall clocks on different hosts disagree. Each worker that
heartbeats a coordinator can measure its offset against the
coordinator's clock (``trainer/coordinator.sync_clock`` — min-RTT
sampling over the existing RPC channel) and journals it as a
``clock_sync`` record (``offset_s`` = local − coordinator). The merge
reads the LAST such record per journal and subtracts it, putting every
stream on the coordinator's time base; ``--offset host=SECONDS``
overrides per host when no sync record exists.

CLI: ``paddle_tpu trace merge`` / ``tools/trace_merge.py``. Acceptance
(tests/test_trace_merge.py): two subprocess coordinator workers with
an injected 2.5 s skew merge into one journal whose steps interleave
in true order with strictly monotone ``mseq``.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List, Optional, Sequence

from paddle_tpu.obs.events import read_journal

__all__ = ["journal_clock_offset", "merge_journals", "merge_traces",
           "main"]


def journal_clock_offset(path: str) -> Optional[float]:
    """The LAST ``clock_sync`` record's ``offset_s`` in a journal
    (local − reference seconds), or None when the journal never
    synced."""
    off = None
    for rec in read_journal(path, strict=False, kind="clock_sync"):
        if isinstance(rec.get("offset_s"), (int, float)):
            off = float(rec["offset_s"])
    return off


def _resolve_offset(path: str, host: Optional[str],
                    offsets: Optional[Dict[str, float]],
                    synced: Optional[float]) -> float:
    """Per-stream offset resolution: explicit path key, then explicit
    host key, then the stream's own clock_sync record, else 0."""
    if offsets:
        if path in offsets:
            return float(offsets[path])
        if host is not None and host in offsets:
            return float(offsets[host])
    return synced if synced is not None else 0.0


def merge_journals(paths: Sequence[str],
                   offsets: Optional[Dict[str, float]] = None,
                   out: Optional[str] = None) -> List[dict]:
    """Fuse N journals into one list sorted by skew-adjusted time.
    Each record gains ``mseq`` (monotone across the merge, 1-based)
    and ``ts_adj`` (reference-clock seconds); ``seq``/``host``/``pid``
    stay as emitted. With ``out``, also writes the merged JSONL."""
    merged: List[dict] = []
    for path in paths:
        synced = journal_clock_offset(path)
        recs = list(read_journal(path, strict=False))
        for rec in recs:
            host = rec.get("host")
            off = _resolve_offset(path, host, offsets, synced)
            rec = dict(rec)
            rec["ts_adj"] = rec["ts"] - off
            rec.setdefault("host", os.path.basename(path))
            merged.append(rec)
    # stable sort on (adjusted time, host, per-process seq): ties keep
    # each process's own order
    merged.sort(key=lambda r: (r["ts_adj"], str(r.get("host")),
                               r["seq"]))
    for i, rec in enumerate(merged):
        rec["mseq"] = i + 1
    if out:
        with open(out, "w", encoding="utf-8") as f:
            for rec in merged:
                f.write(json.dumps(rec) + "\n")
    return merged


def merge_traces(paths: Sequence[str],
                 offsets: Optional[Dict[str, float]] = None,
                 out: Optional[str] = None) -> dict:
    """Fuse N chrome-trace JSON exports into one Perfetto-loadable
    trace: timestamps skew-adjusted onto the reference clock, pids
    remapped when two processes collide, one ``process_name`` metadata
    row per input (``<host> pid=<pid>``)."""
    events: List[dict] = []
    meta_rows: List[dict] = []
    seen_pids: Dict[int, str] = {}
    hosts: List[str] = []
    next_pid = 1
    for path in paths:
        with open(path, encoding="utf-8") as f:
            blob = json.load(f)
        meta = blob.get("metadata", {}) or {}
        host = meta.get("host") or os.path.basename(path)
        orig_pid = meta.get("pid")
        hosts.append(host)
        off = _resolve_offset(path, host, offsets, None)
        # one merged pid per input file; collisions (same pid on two
        # hosts, or pid-less exports) get a fresh lane
        stream_pids: Dict[object, int] = {}

        def lane(pid) -> int:
            nonlocal next_pid
            if pid not in stream_pids:
                cand = pid if isinstance(pid, int) else next_pid
                while cand in seen_pids:
                    cand = next_pid = next_pid + 1
                stream_pids[pid] = cand
                seen_pids[cand] = host
                meta_rows.append(
                    {"ph": "M", "name": "process_name", "pid": cand,
                     "tid": 0,
                     "args": {"name": f"{host} pid={pid}"}})
            return stream_pids[pid]

        for ev in blob.get("traceEvents", []):
            if ev.get("ph") == "M":
                continue                    # re-labeled per stream
            ev = dict(ev)
            ev["pid"] = lane(ev.get("pid", orig_pid))
            if isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = ev["ts"] - off * 1e6
            args = dict(ev.get("args") or {})
            args.setdefault("host", host)
            ev["args"] = args
            events.append(ev)
    events.sort(key=lambda e: e.get("ts", 0.0))
    merged = {"traceEvents": meta_rows + events,
              "displayTimeUnit": "ms",
              "metadata": {"merged_from": list(paths),
                           "hosts": hosts}}
    if out:
        with open(out, "w", encoding="utf-8") as f:
            json.dump(merged, f)
    return merged


def _parse_offsets(pairs: Sequence[str]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for p in pairs:
        key, _, val = p.partition("=")
        if not key or not val:
            raise SystemExit(f"--offset wants HOST=SECONDS, got {p!r}")
        out[key] = float(val)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_merge",
        description="fuse per-host journals + chrome traces into one "
                    "timeline (docs/observability.md)")
    ap.add_argument("--journal", nargs="*", default=[],
                    help="per-host journal JSONL files")
    ap.add_argument("--trace", nargs="*", default=[],
                    help="per-host chrome-trace JSON exports "
                         "(Tracer.save)")
    ap.add_argument("--out-journal", default=None,
                    help="merged journal JSONL output path")
    ap.add_argument("--out-trace", default=None,
                    help="merged Perfetto trace JSON output path")
    ap.add_argument("--offset", action="append", default=[],
                    metavar="HOST=SECONDS",
                    help="clock offset override (local - reference) "
                         "for a host or input path; defaults to each "
                         "journal's clock_sync record, else 0")
    args = ap.parse_args(argv)
    if not args.journal and not args.trace:
        ap.error("nothing to merge: pass --journal and/or --trace")
    offsets = _parse_offsets(args.offset)
    summary: Dict[str, object] = {"job": "trace_merge"}
    if args.journal:
        # journals' clock_sync offsets also cover their host's traces
        for path in args.journal:
            off = journal_clock_offset(path)
            if off is not None:
                for rec in read_journal(path, strict=False,
                                        kind="clock_sync"):
                    offsets.setdefault(str(rec.get("host")), off)
        merged = merge_journals(args.journal, offsets,
                                out=args.out_journal)
        summary["journals"] = len(args.journal)
        summary["records"] = len(merged)
        summary["hosts"] = sorted(
            {str(r.get("host")) for r in merged})
        if args.out_journal:
            summary["out_journal"] = args.out_journal
    if args.trace:
        mt = merge_traces(args.trace, offsets, out=args.out_trace)
        summary["traces"] = len(args.trace)
        summary["trace_events"] = len(mt["traceEvents"])
        if args.out_trace:
            summary["out_trace"] = args.out_trace
    print(json.dumps(summary))
    return 0
