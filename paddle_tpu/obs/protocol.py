"""ptproto runtime half — the protocol witness (docs/observability.md).

``ProtocolWitness`` observes the event journal (the same observer
seam the flight recorder uses — obs/__init__.py arms it) and advances
the machines declared in obs/catalog.py ``PROTOCOLS`` per correlation
key.  When a record breaks a machine's rules it journals
``protocol/violation`` carrying the offending chain — which trips the
flight recorder's auto-dump, so the bundle holding the evidence is on
disk before anyone asks.

Live violations (journaled the moment they happen):

- **orphan terminal** — a terminal with ``orphan_violates`` arrives
  for a key with no open machine: a second ``fleet/settle`` for a
  settled trace (exactly-once broken), a hop settle with no start.

Lazy violations (``finalize()``, on demand — NOT per-test):

- **unterminated** — machines still open when asked.  A killed
  replica legitimately never settles its hop (tests/test_fleet_faults
  pins that shape), so open machines are only violations when a test
  explicitly declares the world quiesced.

The tier-1 conftest arms an autouse fixture asserting zero LIVE
violations per test (opt-out marker ``protocol_violation_expected``,
mirroring ``_lockdep_witness``); the chaos acceptance in
tests/test_protocol.py drives ``finalize()`` against a deliberately
torn hop.

Scrape side: ``paddle_tpu_protocol_{tracked,completed,violations_total}``
per-protocol gauges ride a registry collector, same pattern as the
lockdep bridge.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from paddle_tpu.obs.catalog import PROTOCOLS, Protocol

__all__ = ["ProtocolWitness", "WITNESS"]

_CHAIN_KEEP = 32          # offending-chain records kept per machine


class _Machine:
    __slots__ = ("protocol", "key", "chain", "starts")

    def __init__(self, protocol: str, key, rec_ref):
        self.protocol = protocol
        self.key = key
        self.chain: List[dict] = [rec_ref]
        self.starts = 1


def _ref(rec: dict) -> dict:
    """The compact record reference violations carry: enough to find
    the full record in the journal/flight bundle by seq."""
    out = {"domain": rec.get("domain"), "kind": rec.get("kind"),
           "seq": rec.get("seq")}
    for k in ("trace_id", "phase", "replica", "shard_id", "name"):
        if k in rec:
            out[k] = rec[k]
    return out


class ProtocolWitness:
    """Advance every declared protocol machine from the journal
    stream.  Thread-safe; never raises into the emit path (the
    journal's observer harness also guards, but violations are
    emitted OUTSIDE our lock to keep the journal's lock ordering)."""

    def __init__(self, protocols: Optional[Dict[str, Protocol]] = None):
        self._protocols = dict(protocols or PROTOCOLS)
        self._lock = threading.Lock()
        self._open: Dict[Tuple[str, object], _Machine] = {}
        self._completed: Dict[str, int] = {}
        self._superseded: Dict[str, int] = {}
        self._violations: List[dict] = []
        # (domain, kind) -> [(protocol, role, matcher-ish)] so one
        # journal record costs one dict lookup, not a protocol scan
        self._dispatch: Dict[Tuple[str, str], list] = {}
        for p in self._protocols.values():
            self._dispatch.setdefault(
                (p.start.domain, p.start.kind), []).append(
                    (p, "start", p.start))
            for m in p.intermediates:
                self._dispatch.setdefault(
                    (m.domain, m.kind), []).append((p, "inter", m))
            for t in p.terminals:
                self._dispatch.setdefault(
                    (t.match.domain, t.match.kind), []).append(
                        (p, "terminal", t))

    # ------------------------------------------------------------ observe
    def observe_journal(self, rec: dict) -> None:
        """Journal observer (obs/__init__.py wires it). Violations
        detected under the lock are journaled after it drops."""
        if rec.get("domain") == "protocol":
            return
        routes = self._dispatch.get((rec.get("domain"),
                                     rec.get("kind")))
        if not routes:
            return
        pending: List[dict] = []
        with self._lock:
            for proto, role, obj in routes:
                if role == "terminal":
                    if not obj.match.matches(rec):
                        continue
                    self._on_terminal(proto, obj, rec, pending)
                elif role == "start":
                    if not obj.matches(rec):
                        continue
                    self._on_start(proto, rec)
                else:
                    if not obj.matches(rec):
                        continue
                    mk = (proto.name, self._key_of(proto, rec))
                    m = self._open.get(mk)
                    if m is not None:
                        m.chain.append(_ref(rec))
                        del m.chain[:-_CHAIN_KEEP]
        for v in pending:
            self._journal_violation(v)

    @staticmethod
    def _key_of(proto: Protocol, rec: dict):
        return rec.get(proto.key) if proto.key else None

    def _on_start(self, proto: Protocol, rec: dict) -> None:
        mk = (proto.name, self._key_of(proto, rec))
        m = self._open.get(mk)
        if m is not None:
            if proto.on_restart == "extend":
                # a re-route after failover CONTINUES the same
                # request machine — same trace, next hop
                m.chain.append(_ref(rec))
                m.starts += 1
                del m.chain[:-_CHAIN_KEEP]
                return
            # a fresh start supersedes the stale instance (a failover
            # hop re-uses the trace_id; the dead hop's tear is the
            # fleet plane's story, not a protocol violation here)
            self._superseded[proto.name] = \
                self._superseded.get(proto.name, 0) + 1
        self._open[mk] = _Machine(proto.name, mk[1], _ref(rec))

    def _on_terminal(self, proto: Protocol, term, rec: dict,
                     pending: List[dict]) -> None:
        mk = (proto.name, self._key_of(proto, rec))
        m = self._open.pop(mk, None)
        if m is not None:
            m.chain.append(_ref(rec))
            self._completed[proto.name] = \
                self._completed.get(proto.name, 0) + 1
            return
        if term.orphan_violates:
            v = {"protocol": proto.name, "key": mk[1],
                 "reason": "orphan_terminal",
                 "chain": [_ref(rec)], "record": _ref(rec)}
            self._violations.append(v)
            pending.append(v)

    # ---------------------------------------------------------- violations
    def _journal_violation(self, v: dict) -> None:
        # local import: obs.events imports nothing from here, but the
        # witness is constructed at obs import time — stay lazy
        from paddle_tpu.obs.events import emit as journal_emit
        journal_emit("protocol", "violation", protocol=v["protocol"],
                     key=v["key"], reason=v["reason"],
                     chain=v.get("chain"), record=v.get("record"))

    def finalize(self) -> List[dict]:
        """Close every still-open machine as ``unterminated`` and
        journal the violations.  For tests that have quiesced the
        world and expect every machine settled — NOT called per-test
        (open machines are legal: a SIGKILL'd replica never settles
        its hop)."""
        with self._lock:
            stragglers = list(self._open.values())
            self._open.clear()
            out = []
            for m in stragglers:
                v = {"protocol": m.protocol, "key": m.key,
                     "reason": "unterminated", "chain": list(m.chain),
                     "record": m.chain[-1] if m.chain else None}
                self._violations.append(v)
                out.append(v)
        for v in out:
            self._journal_violation(v)
        return out

    # -------------------------------------------------------------- state
    @property
    def violation_count(self) -> int:
        with self._lock:
            return len(self._violations)

    def violations(self) -> List[dict]:
        with self._lock:
            return list(self._violations)

    def open_machines(self) -> List[dict]:
        with self._lock:
            return [{"protocol": m.protocol, "key": m.key,
                     "chain": list(m.chain)}
                    for m in self._open.values()]

    def counts(self) -> dict:
        with self._lock:
            tracked: Dict[str, int] = {}
            for m in self._open.values():
                tracked[m.protocol] = tracked.get(m.protocol, 0) + 1
            return {"tracked": tracked,
                    "completed": dict(self._completed),
                    "superseded": dict(self._superseded),
                    "violations": len(self._violations)}

    def reset(self) -> None:
        with self._lock:
            self._open.clear()
            self._completed.clear()
            self._superseded.clear()
            del self._violations[:]


WITNESS = ProtocolWitness()


def _protocol_bridge():
    """Registry collector: per-protocol machine gauges, same pattern
    as obs/metrics.py's lockdep bridge."""
    from paddle_tpu.obs.metrics import SampleFamily
    with WITNESS._lock:
        tracked: Dict[str, int] = {}
        for m in WITNESS._open.values():
            tracked[m.protocol] = tracked.get(m.protocol, 0) + 1
        completed = dict(WITNESS._completed)
        viol: Dict[str, int] = {}
        for v in WITNESS._violations:
            viol[v["protocol"]] = viol.get(v["protocol"], 0) + 1
    fams = []
    if tracked:
        fams.append(SampleFamily(
            "paddle_tpu_protocol_tracked", "gauge",
            "protocol machines currently open, per protocol",
            [("paddle_tpu_protocol_tracked", {"protocol": k},
              float(n)) for k, n in sorted(tracked.items())]))
    if completed:
        fams.append(SampleFamily(
            "paddle_tpu_protocol_completed", "gauge",
            "protocol machines closed by a terminal since reset",
            [("paddle_tpu_protocol_completed", {"protocol": k},
              float(n)) for k, n in sorted(completed.items())]))
    if viol:
        fams.append(SampleFamily(
            "paddle_tpu_protocol_violations_total", "counter",
            "protocol violations witnessed since reset",
            [("paddle_tpu_protocol_violations_total",
              {"protocol": k}, float(n))
             for k, n in sorted(viol.items())]))
    return fams


def _install_collector() -> None:
    from paddle_tpu.obs.metrics import REGISTRY
    REGISTRY.register_collector(_protocol_bridge)
