"""Continuous step profiler + device-memory telemetry.

bench.py answers "how fast CAN it go" offline; nothing answered "where
is the time and memory going RIGHT NOW" in a live run. This module
closes that gap with three always-cheap surfaces:

- **Continuous profiler** — the trainer and decode engine call
  ``PROFILER.on_step(kind)`` once per jitted step (a no-op attribute
  read when disabled). Every ``sample_every``-th step the profiler
  diffs the existing ``stat_timer`` accumulators into a per-phase
  breakdown (train: data_wait / h2d / compute / settle; decode:
  decode_step), pulls FLOPs + bytes from the cached executable's
  ``.lower().compile().cost_analysis()`` via a lazily-invoked cost
  source, and exports live ``paddle_tpu_profile_mfu`` /
  ``paddle_tpu_profile_roofline_frac`` gauges. The roofline math lives
  HERE and bench.py imports it, so the live gauges and the offline
  bench rows are one computation by construction.
- **Device-memory telemetry** — a ``pt-obs-profiler`` daemon thread
  samples ``device.memory_stats()`` (live bytes + HBM watermark) and
  registered page-pool accounting (occupancy level + trend) off the
  hot path, and drives the SLO watchdog's objective evaluation
  (obs/slo.py).
- **Deep windows** — ``arm_window(steps)`` captures a
  ``jax.profiler.trace`` artifact over the next N observed steps
  (CLI ``paddle_tpu profile --steps N``; ``GET /profile`` on the obs
  and serving endpoints). The artifact path rides in the profiler's
  flight-bundle state so a postmortem links straight to the trace.

Every sampled step is also fed to ``obs.slo.WATCHDOG`` so step-time
regressions are detected with per-phase attribution. The profiler is
OFF by default; ``enable()`` is wired by the CLI (``--profile_every``)
and by tests. ``reset()`` (via ``obs.reset_all``) stops the sampler
thread — the conftest thread-leak fixture polices the ``pt-obs``
prefix.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from statistics import median
from typing import Callable, Dict, List, Optional, Tuple

from paddle_tpu.obs.metrics import REGISTRY
from paddle_tpu.analysis.lockdep import named_lock

__all__ = [
    "PEAK_FLOPS", "PEAK_HBM_GBPS", "device_lookup", "device_peak_flops",
    "device_hbm_gbps", "compiled_flops", "compiled_bytes", "cost_of",
    "roofline", "StepProfiler", "PROFILER",
]


# --------------------------------------------------------------- roofline
# Peak dense bf16 FLOP/s per JAX device, by device_kind substring.
# v2/v3 JAX devices are single cores; v4+ are full (mega)chips.
# bench.py imports these — live gauges and offline rows must agree.
PEAK_FLOPS = [
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12), ("v5 lite", 197e12), ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 61.5e12),
    ("v2", 22.5e12),
]

# Peak HBM GB/s by device_kind substring (same matching as PEAK_FLOPS).
PEAK_HBM_GBPS = [
    ("v6", 1640.0), ("trillium", 1640.0),
    ("v5p", 2765.0), ("v5 lite", 819.0), ("v5e", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
]


def device_lookup(dev, table) -> Optional[float]:
    kind = getattr(dev, "device_kind", "").lower()
    if "tpu" not in kind:
        return None
    for key, val in table:
        if key in kind:
            return val
    return None


def device_peak_flops(dev) -> Optional[float]:
    return device_lookup(dev, PEAK_FLOPS)


def device_hbm_gbps(dev) -> Optional[float]:
    return device_lookup(dev, PEAK_HBM_GBPS)


def _cost_field(compiled, field: str) -> Optional[float]:
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        v = float(ca.get(field, 0.0))
        return v if v > 0 else None
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return None


def compiled_flops(compiled) -> Optional[float]:
    """Model FLOPs per step from XLA's own cost analysis."""
    return _cost_field(compiled, "flops")


def compiled_bytes(compiled) -> Optional[float]:
    """HBM bytes per step from the compiler's post-fusion cost analysis.
    Pallas custom calls count at their operand/result boundary (their
    internal streaming is invisible — same caveat as flops)."""
    return _cost_field(compiled, "bytes accessed")


def cost_of(fn, *args, **kwargs) -> Tuple[Optional[float], Optional[float]]:
    """(flops, bytes) for one jitted callable at concrete args, via the
    AOT path. This COMPILES (the AOT executable does not share the jit
    cache) — call once per executable, never per step."""
    compiled = fn.lower(*args, **kwargs).compile()
    return compiled_flops(compiled), compiled_bytes(compiled)


def roofline(ms: float, flops: Optional[float] = None,
             bytes_acc: Optional[float] = None,
             peak_flops: Optional[float] = None,
             hbm_gbps: Optional[float] = None,
             mxu: bool = True) -> dict:
    """The decode-row discipline, shared by bench rows and live gauges:
    a step cannot beat its HBM traffic at peak bandwidth NOR its model
    FLOPs at peak MXU, so the BINDING bound (max of the two) is a hard
    floor; ``roofline_frac`` = measured / binding bound, and ``mfu`` =
    achieved FLOP/s over peak."""
    out: dict = {}
    if not ms or ms <= 0:
        return out
    if flops and peak_flops:
        out["mfu"] = flops / (ms * 1e-3) / peak_flops
    bounds = {}
    if bytes_acc and hbm_gbps:
        bounds["hbm"] = bytes_acc / (hbm_gbps * 1e9) * 1e3
    if flops and peak_flops and mxu:
        bounds["mxu"] = flops / peak_flops * 1e3
    if bounds:
        binding = max(bounds, key=bounds.get)
        out["roofline_ms"] = bounds[binding]
        out["roofline_bound"] = binding
        out["roofline_frac"] = ms / bounds[binding]
    return out


# ------------------------------------------------------------- gauges
# Registered at import so the families (HELP/TYPE) are always present in
# the exposition; REGISTRY.reset() zeroes values but keeps registrations.
_G_STEP = REGISTRY.gauge(
    "paddle_tpu_profile_step_ms",
    "continuous profiler: mean wall ms/step over the last sample window,"
    " per step kind (train/decode)", ("kind",))
_G_PHASE = REGISTRY.gauge(
    "paddle_tpu_profile_phase_ms",
    "continuous profiler: per-phase ms/step from stat_timer deltas "
    "(train: data_wait/h2d/compute/settle; decode: decode_step)",
    ("kind", "phase"))
_G_MFU = REGISTRY.gauge(
    "paddle_tpu_profile_mfu",
    "live model-FLOPs utilization: cost_analysis flops / step wall time "
    "/ device peak — same computation as bench.py rows", ("kind",))
_G_ROOF = REGISTRY.gauge(
    "paddle_tpu_profile_roofline_frac",
    "live measured-ms / binding roofline bound (hbm vs mxu), same "
    "computation as bench.py rows", ("kind",))
_G_MEM = REGISTRY.gauge(
    "paddle_tpu_profile_device_bytes_in_use",
    "live device memory in use, summed over local devices "
    "(device.memory_stats; 0 where the backend reports none)")
_G_WATERMARK = REGISTRY.gauge(
    "paddle_tpu_profile_hbm_watermark_bytes",
    "high-water device memory: max(peak_bytes_in_use, observed "
    "bytes_in_use) since enable/reset")
_G_POOL = REGISTRY.gauge(
    "paddle_tpu_profile_page_pool_occupancy",
    "KV page-pool occupancy fraction (allocated / total_usable), per "
    "registered pool", ("pool",))
_G_POOL_TREND = REGISTRY.gauge(
    "paddle_tpu_profile_page_pool_occupancy_trend",
    "KV page-pool occupancy slope in fraction/second over the sampler's "
    "rolling window (positive = filling up)", ("pool",))


#: phase name -> stat_timer name, per step kind. "compute" is the jitted
#: dispatch scope; data_wait/h2d run in the feed pipeline; settle is the
#: one device->host sync.
PHASE_TIMERS: Dict[str, Tuple[Tuple[str, str], ...]] = {
    "train": (("data_wait", "train/data_wait"),
              ("h2d", "train/h2d"),
              ("compute", "train_step"),
              ("settle", "train/settle")),
    "decode": (("decode_step", "serving/decode_step"),),
}


class _KindState:
    __slots__ = ("steps", "t_last", "dt_sum", "dt_n", "baseline",
                 "phase_ms", "step_ms", "last_sample_step")

    def __init__(self):
        self.steps = 0
        self.t_last: Optional[float] = None
        self.dt_sum = 0.0           # wall ms accumulated since last sample
        self.dt_n = 0
        self.baseline: Dict[str, float] = {}   # timer name -> total seconds
        self.phase_ms: Dict[str, float] = {}   # latest per-phase ms/step
        self.step_ms: deque = deque(maxlen=256)
        self.last_sample_step = 0


class StepProfiler:
    """Process-global continuous profiler (module doc). All public
    methods are thread-safe; ``on_step`` is the per-step hot hook and
    returns after one attribute read when disabled."""

    def __init__(self):
        self._lock = named_lock("obs.profile")
        self._enabled = False
        self._sample_every = 8
        self._kinds: Dict[str, _KindState] = {}
        # cost sources: kind -> zero-arg callable returning
        # (flops, bytes); invoked lazily ONCE per enable (compiling).
        self._cost_src: Dict[str, Callable[[], tuple]] = {}
        self._cost: Dict[str, Tuple[Optional[float], Optional[float]]] = {}
        self._cost_failed: Dict[str, bool] = {}
        # test/CPU escape hatch: force peaks instead of device lookup
        self._peak_flops_override: Optional[float] = None
        self._hbm_gbps_override: Optional[float] = None
        self._assume_mxu: Optional[bool] = None
        # memory sampler
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._mem_interval = 0.5
        self._watermark = 0.0
        self._mem_bytes = 0.0
        self._pools: Dict[str, Callable[[], Optional[dict]]] = {}
        self._pool_hist: Dict[str, deque] = {}
        self._pool_stats: Dict[str, dict] = {}
        # deep profile window
        self._window_remaining = 0
        self._window_dir: Optional[str] = None
        self._window_started = False
        self._last_trace_dir: Optional[str] = None

    # ------------------------------------------------------------ config
    @property
    def enabled(self) -> bool:
        return self._enabled

    def configure(self, sample_every: Optional[int] = None,
                  peak_flops: Optional[float] = None,
                  hbm_gbps: Optional[float] = None,
                  assume_mxu: Optional[bool] = None) -> None:
        with self._lock:
            if sample_every is not None:
                self._sample_every = max(1, int(sample_every))
            if peak_flops is not None:
                self._peak_flops_override = float(peak_flops)
            if hbm_gbps is not None:
                self._hbm_gbps_override = float(hbm_gbps)
            if assume_mxu is not None:
                self._assume_mxu = bool(assume_mxu)

    def enable(self, sample_every: Optional[int] = None,
               memory_interval: Optional[float] = None) -> None:
        """Turn sampling on; with ``memory_interval`` also start the
        off-thread device-memory sampler (``pt-obs-profiler``)."""
        self.configure(sample_every=sample_every)
        with self._lock:
            self._enabled = True
        # postmortem bundles carry the live breakdown + trace link
        from paddle_tpu.obs.flight import FLIGHT
        FLIGHT.register_state_provider("profiler", self.snapshot)
        from paddle_tpu.obs.slo import WATCHDOG
        WATCHDOG.add_source("profiler", self._slo_source)
        if memory_interval is not None:
            self.start_memory_sampler(memory_interval)

    def disable(self) -> None:
        with self._lock:
            self._enabled = False
        self.stop_memory_sampler()

    # ---------------------------------------------------------- per-step
    def set_cost_source(self, kind: str, fn: Callable[[], tuple]) -> None:
        """Register the lazy (flops, bytes) provider for a step kind —
        the trainer wires a closure that AOT-compiles its current
        executable (``cost_of``). Invoked at most once per enable, off
        the first sampled step."""
        with self._lock:
            self._cost_src[kind] = fn
            self._cost.pop(kind, None)
            self._cost_failed.pop(kind, None)

    def on_step(self, kind: str = "train") -> None:
        """Once per jitted step. Fast path (disabled): one attr read."""
        if not self._enabled:
            return
        now = time.perf_counter()
        dt_ms: Optional[float] = None
        phases: Optional[Dict[str, float]] = None
        need_cost = False
        window_action = None
        with self._lock:
            st = self._kinds.get(kind)
            if st is None:
                st = self._kinds[kind] = _KindState()
            st.steps += 1
            if st.t_last is not None:
                dt_ms = (now - st.t_last) * 1e3
                st.dt_sum += dt_ms
                st.dt_n += 1
                st.step_ms.append(dt_ms)
            st.t_last = now
            sample = st.steps % self._sample_every == 0
            if sample:
                phases = self._sample_phases_locked(kind, st)
                if kind not in self._cost and kind in self._cost_src \
                        and not self._cost_failed.get(kind):
                    need_cost = True
            if self._window_remaining > 0:
                if not self._window_started:
                    self._window_started = True
                    window_action = ("start", self._window_dir)
                self._window_remaining -= 1
                if self._window_remaining == 0:
                    window_action = ("stop", self._window_dir)
        # everything below runs OUTSIDE the lock: cost_of compiles,
        # the watchdog journals (whose flight auto-dump snapshots us).
        if window_action is not None:
            self._drive_window(window_action)
        if need_cost:
            self._resolve_cost(kind)
        if phases is not None:
            self._publish(kind)
        if dt_ms is not None:
            from paddle_tpu.obs.slo import WATCHDOG
            WATCHDOG.observe_step(kind, dt_ms, phases)

    def _sample_phases_locked(self, kind: str,
                              st: _KindState) -> Dict[str, float]:
        from paddle_tpu.utils.stats import global_stat
        steps = max(1, st.steps - st.last_sample_step)
        st.last_sample_step = st.steps
        timers = global_stat.items()
        out: Dict[str, float] = {}
        for phase, timer in PHASE_TIMERS.get(kind, ()):
            item = timers.get(timer)
            total = item.snapshot()[1] if item is not None else 0.0
            delta = total - st.baseline.get(timer, 0.0)
            st.baseline[timer] = total
            out[phase] = max(0.0, delta) * 1e3 / steps
        st.phase_ms = out
        return out

    def _resolve_cost(self, kind: str) -> None:
        src = self._cost_src.get(kind)
        if src is None:
            return
        try:
            flops, nbytes = src()
        except Exception:  # noqa: BLE001 — profiling never takes down a run
            flops = nbytes = None
        with self._lock:
            if flops is None and nbytes is None:
                self._cost_failed[kind] = True
            else:
                self._cost[kind] = (flops, nbytes)

    def _peaks(self):
        if self._peak_flops_override is not None \
                or self._hbm_gbps_override is not None:
            return self._peak_flops_override, self._hbm_gbps_override
        try:
            import jax
            dev = jax.local_devices()[0]
        except Exception:  # noqa: BLE001 — no backend is not an error here
            return None, None
        return device_peak_flops(dev), device_hbm_gbps(dev)

    def _mxu_ok(self) -> bool:
        if self._assume_mxu is not None:
            return self._assume_mxu
        try:
            from paddle_tpu.config import global_config
            return global_config().compute_dtype == "bfloat16"
        except Exception:  # noqa: BLE001 — config optional at import time
            return False

    def _publish(self, kind: str) -> None:
        """Refresh the gauges for one kind after a sampled step."""
        with self._lock:
            st = self._kinds.get(kind)
            if st is None:
                return
            mean_ms = st.dt_sum / st.dt_n if st.dt_n else None
            st.dt_sum, st.dt_n = 0.0, 0
            phases = dict(st.phase_ms)
            cost = self._cost.get(kind)
        for phase, ms in phases.items():
            _G_PHASE.labels(kind=kind, phase=phase).set(round(ms, 4))
        if mean_ms is None:
            return
        _G_STEP.labels(kind=kind).set(round(mean_ms, 4))
        if cost is None:
            return
        peak_flops, hbm_gbps = self._peaks()
        rf = roofline(mean_ms, flops=cost[0], bytes_acc=cost[1],
                      peak_flops=peak_flops, hbm_gbps=hbm_gbps,
                      mxu=self._mxu_ok())
        if "mfu" in rf:
            _G_MFU.labels(kind=kind).set(round(rf["mfu"], 6))
        if "roofline_frac" in rf:
            _G_ROOF.labels(kind=kind).set(round(rf["roofline_frac"], 4))

    # -------------------------------------------------------- deep window
    def arm_window(self, steps: int, out_dir: Optional[str] = None) -> str:
        """Capture a jax.profiler trace over the next ``steps`` observed
        steps. Returns the artifact directory (created lazily by the
        profiler at start)."""
        import tempfile
        out_dir = out_dir or tempfile.mkdtemp(prefix="pt-profile-trace-")
        with self._lock:
            self._window_remaining = max(1, int(steps))
            self._window_dir = out_dir
            self._window_started = False
        return out_dir

    def _drive_window(self, action) -> None:
        what, out_dir = action
        try:
            import jax
            if what == "start":
                jax.profiler.start_trace(out_dir)
                return
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — tracing is best-effort
            with self._lock:
                self._window_remaining = 0
                self._window_started = False
            return
        with self._lock:
            self._last_trace_dir = out_dir
            self._window_started = False
        from paddle_tpu.obs.events import emit
        emit("profile", "window", dir=out_dir)

    def finish_window(self) -> Optional[str]:
        """Force-close an armed/started window (CLI teardown). Returns
        the trace dir if a capture was stopped."""
        with self._lock:
            started = self._window_started
            out_dir = self._window_dir
            self._window_remaining = 0
        if started:
            self._drive_window(("stop", out_dir))
            return out_dir
        return None

    # ----------------------------------------------------- memory sampler
    def register_pool(self, name: str,
                      fn: Callable[[], Optional[dict]]) -> None:
        """``fn()`` returns a PagePool ``accounting()`` dict, or None
        once the owner is gone (weakref closure) — the pool is then
        dropped."""
        with self._lock:
            self._pools[name] = fn

    def start_memory_sampler(self, interval: float = 0.5) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._mem_interval = max(0.05, float(interval))
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._mem_loop, name="pt-obs-profiler", daemon=True)
            self._thread.start()

    def stop_memory_sampler(self) -> None:
        with self._lock:
            th, self._thread = self._thread, None
        self._stop.set()
        if th is not None and th.is_alive():
            th.join(timeout=5.0)

    def _mem_loop(self) -> None:
        stop = self._stop
        while not stop.wait(self._mem_interval):
            self.sample_memory()
            from paddle_tpu.obs.slo import WATCHDOG
            WATCHDOG.evaluate()

    def sample_memory(self) -> dict:
        """One device-memory + pool-occupancy sample (the thread body;
        also callable inline from tests/CLI)."""
        in_use = peak = 0.0
        try:
            import jax
            for dev in jax.local_devices():
                ms = dev.memory_stats()
                if not ms:
                    continue
                in_use += float(ms.get("bytes_in_use", 0) or 0)
                peak += float(ms.get("peak_bytes_in_use", 0) or 0)
        except Exception:  # noqa: BLE001 — CPU backends report nothing
            pass
        now = time.monotonic()
        with self._lock:
            self._mem_bytes = in_use
            self._watermark = max(self._watermark, peak, in_use)
            watermark = self._watermark
            pools = list(self._pools.items())
        _G_MEM.set(in_use)
        _G_WATERMARK.set(watermark)
        dead: List[str] = []
        for name, fn in pools:
            try:
                acct = fn()
            except Exception:  # noqa: BLE001 — a dying engine is not fatal
                acct = None
            if acct is None:
                dead.append(name)
                continue
            total = float(acct.get("total_usable", 0) or 0)
            occ = float(acct.get("allocated", 0) or 0) / total \
                if total > 0 else 0.0
            with self._lock:
                hist = self._pool_hist.setdefault(name, deque(maxlen=64))
                hist.append((now, occ))
                trend = 0.0
                if len(hist) >= 2 and hist[-1][0] > hist[0][0]:
                    trend = (hist[-1][1] - hist[0][1]) \
                        / (hist[-1][0] - hist[0][0])
                self._pool_stats[name] = {
                    "occupancy": round(occ, 4),
                    "trend_per_s": round(trend, 6),
                }
            _G_POOL.labels(pool=name).set(round(occ, 4))
            _G_POOL_TREND.labels(pool=name).set(round(trend, 6))
        if dead:
            with self._lock:
                for name in dead:
                    self._pools.pop(name, None)
                    self._pool_hist.pop(name, None)
                    self._pool_stats.pop(name, None)
        return {"bytes_in_use": in_use, "watermark": watermark}

    # ---------------------------------------------------------- read side
    def _slo_source(self) -> dict:
        """Rolling step-time stats for the watchdog's declarative
        objectives (metric keys: step_time_ms / step_time_p99_ms, and
        decode_* for the decode kind)."""
        out: Dict[str, float] = {}
        with self._lock:
            for kind, st in self._kinds.items():
                if not st.step_ms:
                    continue
                xs = sorted(st.step_ms)
                p99 = xs[min(len(xs) - 1, int(0.99 * (len(xs) - 1)))]
                pfx = "" if kind == "train" else f"{kind}_"
                out[f"{pfx}step_time_ms"] = xs[len(xs) // 2]
                out[f"{pfx}step_time_p99_ms"] = p99
        return out

    def snapshot(self) -> dict:
        """One JSON-able view of everything live — served on
        ``GET /profile`` and embedded in flight bundles as the
        ``profiler`` state."""
        with self._lock:
            kinds = {}
            for kind, st in self._kinds.items():
                ms = list(st.step_ms)
                kinds[kind] = {
                    "steps": st.steps,
                    "step_ms": round(ms[-1], 4) if ms else None,
                    "step_ms_median":
                        round(median(ms), 4) if ms else None,
                    "phases": {p: round(v, 4)
                               for p, v in st.phase_ms.items()},
                }
            cost = {k: {"flops": v[0], "bytes": v[1]}
                    for k, v in self._cost.items()}
            out = {
                "enabled": self._enabled,
                "sample_every": self._sample_every,
                "kinds": kinds,
                "cost": cost,
                "memory": {
                    "bytes_in_use": self._mem_bytes,
                    "watermark_bytes": self._watermark,
                },
                "pools": {k: dict(v)
                          for k, v in self._pool_stats.items()},
                "window": {
                    "remaining": self._window_remaining,
                    "last_trace_dir": self._last_trace_dir,
                },
            }
        gauges = {}
        for fam, key in ((_G_MFU, "mfu"), (_G_ROOF, "roofline_frac")):
            for _, labels, value in fam.samples():
                gauges.setdefault(key, {})[labels.get("kind", "")] = value
        out.update(gauges)
        return out

    def reset(self) -> None:
        """Between-tests hygiene (obs.reset_all): stop the sampler
        thread, drop state, and disable."""
        self.stop_memory_sampler()
        self.finish_window()
        with self._lock:
            self._enabled = False
            self._sample_every = 8
            self._kinds.clear()
            self._cost_src.clear()
            self._cost.clear()
            self._cost_failed.clear()
            self._peak_flops_override = None
            self._hbm_gbps_override = None
            self._assume_mxu = None
            self._watermark = 0.0
            self._mem_bytes = 0.0
            self._pools.clear()
            self._pool_hist.clear()
            self._pool_stats.clear()
            self._window_remaining = 0
            self._window_dir = None
            self._window_started = False
            self._last_trace_dir = None


#: the process-global profiler every hot loop reports through
PROFILER = StepProfiler()
