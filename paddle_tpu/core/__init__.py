from paddle_tpu.core import data_type, sequence, initializers, registry, topology

__all__ = ["data_type", "sequence", "initializers", "registry", "topology"]
