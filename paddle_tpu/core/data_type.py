"""Input data type declarations — the v2 API's `paddle.data_type` module.

Reference: python/paddle/trainer/PyDataProvider2.py input_types (dense_vector,
sparse_binary_vector, sparse_vector, integer_value and their *_sequence /
*_sub_sequence variants) consumed by python/paddle/v2/data_feeder.py.

Here each type doubles as the feed-conversion spec: the DataFeeder uses it to
turn per-sample Python/numpy data into dense device arrays (with segment
lengths for sequence types) — the role py_paddle/dataprovider_converter.py:254
played.
"""

from __future__ import annotations

import dataclasses
from enum import Enum


class SeqType(Enum):
    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


@dataclasses.dataclass(frozen=True)
class InputType:
    """Declares shape/kind of one data source layer's feed."""
    dim: int
    kind: str  # 'dense' | 'integer' | 'sparse_binary' | 'sparse_float'
    seq_type: SeqType = SeqType.NO_SEQUENCE


def dense_vector(dim: int, seq_type: SeqType = SeqType.NO_SEQUENCE) -> InputType:
    return InputType(dim, "dense", seq_type)


def dense_array(dim: int) -> InputType:  # alias used by some v2 scripts
    return InputType(dim, "dense", SeqType.NO_SEQUENCE)


def integer_value(value_range: int,
                  seq_type: SeqType = SeqType.NO_SEQUENCE) -> InputType:
    return InputType(value_range, "integer", seq_type)


def sparse_binary_vector(dim: int,
                         seq_type: SeqType = SeqType.NO_SEQUENCE) -> InputType:
    return InputType(dim, "sparse_binary", seq_type)


def sparse_float_vector(dim: int,
                        seq_type: SeqType = SeqType.NO_SEQUENCE) -> InputType:
    return InputType(dim, "sparse_float", seq_type)


sparse_vector = sparse_float_vector


def dense_vector_sequence(dim: int) -> InputType:
    return dense_vector(dim, SeqType.SEQUENCE)


def dense_vector_sub_sequence(dim: int) -> InputType:
    return dense_vector(dim, SeqType.SUB_SEQUENCE)


def integer_value_sequence(value_range: int) -> InputType:
    return integer_value(value_range, SeqType.SEQUENCE)


def integer_value_sub_sequence(value_range: int) -> InputType:
    return integer_value(value_range, SeqType.SUB_SEQUENCE)


def sparse_binary_vector_sequence(dim: int) -> InputType:
    return sparse_binary_vector(dim, SeqType.SEQUENCE)


def sparse_float_vector_sequence(dim: int) -> InputType:
    return sparse_float_vector(dim, SeqType.SEQUENCE)
