"""Parameter initialization policies.

Reference: paddle/parameter/Parameter.cpp randomize() — default init is
uniform(-sqrt(3/width), sqrt(3/width)) keyed off `initial_std`/`initial_mean`
/`initial_strategy` in ParameterConfig.proto, with `initial_smart` choosing
1/sqrt(fan_in). Exposed through ParamAttr (trainer_config_helpers/attrs.py).
"""

from __future__ import annotations

import math
from typing import Callable, Sequence, Tuple

import jax
import jax.numpy as jnp

Initializer = Callable[[jax.Array, Tuple[int, ...], jnp.dtype], jax.Array]


def normal(std: float = 0.01, mean: float = 0.0) -> Initializer:
    def init(key, shape, dtype=jnp.float32):
        return mean + std * jax.random.normal(key, shape, dtype)
    return init


def uniform(scale: float) -> Initializer:
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -scale, scale)
    return init


def constant(value: float = 0.0) -> Initializer:
    def init(key, shape, dtype=jnp.float32):
        return jnp.full(shape, value, dtype)
    return init


zeros = constant(0.0)
ones = constant(1.0)


def smart_normal(fan_in_axis: int = 0) -> Initializer:
    """The reference's `initial_smart`: std = 1/sqrt(fan_in)."""
    def init(key, shape, dtype=jnp.float32):
        fan_in = shape[fan_in_axis] if shape else 1
        std = 1.0 / math.sqrt(max(fan_in, 1))
        return std * jax.random.normal(key, shape, dtype)
    return init


def xavier(fan_in_axes: Sequence[int] = (0,)) -> Initializer:
    def init(key, shape, dtype=jnp.float32):
        fan_in = 1
        for a in fan_in_axes:
            fan_in *= shape[a]
        scale = math.sqrt(3.0 / max(fan_in, 1))
        return jax.random.uniform(key, shape, dtype, -scale, scale)
    return init


def msra(fan_in_axes: Sequence[int] = (0,)) -> Initializer:
    """He/MSRA init for conv/relu stacks."""
    def init(key, shape, dtype=jnp.float32):
        fan_in = 1
        for a in fan_in_axes:
            fan_in *= shape[a]
        std = math.sqrt(2.0 / max(fan_in, 1))
        return std * jax.random.normal(key, shape, dtype)
    return init
