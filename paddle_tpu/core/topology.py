"""Topology — the serializable model graph and its traced executor.

Reference parity: python/paddle/v2/topology.py:25 wraps the ModelConfig
protobuf produced by config_parser; paddle/gserver NeuralNetwork walks layers
in topological order (NeuralNetwork.cpp:235-260) calling forward/backward.

Here the graph is recovered from output LayerOutputs (parse_network-style
trim, python/paddle/v2/layer.py:263), serialized as JSON (the
serialized-topology-as-contract pattern replacing ModelConfig.proto), and
executed as ONE pure function `forward(params, state, feed, ...)` that jit
traces — autodiff via `jax.grad` replaces every per-layer backward().
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import (ApplyContext, LayerOutput, ParamSpec,
                                      StateSpec, get_layer_impl, make_layer)
from paddle_tpu.core.sequence import SequenceBatch


def _collect(outputs: Sequence[LayerOutput]) -> List[LayerOutput]:
    """Topological order (parents first) of the sub-graph reaching `outputs`."""
    order: List[LayerOutput] = []
    seen: Dict[int, bool] = {}
    # iterative DFS to survive deep graphs
    stack = [(o, False) for o in reversed(list(outputs))]
    while stack:
        node, expanded = stack.pop()
        if expanded:
            order.append(node)
            continue
        if seen.get(id(node)):
            continue
        seen[id(node)] = True
        stack.append((node, True))
        for p in reversed(node.parents):
            if not seen.get(id(p)):
                stack.append((p, False))
    return order


#: declared-output names already warned about (once per head, not per build)
_warned_orphan_outputs: set = set()


class Topology:
    """The model: layers in topo order + parameter/state specs."""

    def __init__(self, outputs: Union[LayerOutput, Sequence[LayerOutput]],
                 extra_outputs: Sequence[LayerOutput] = ()):
        if isinstance(outputs, LayerOutput):
            outputs = [outputs]
        self.outputs = list(outputs) + list(extra_outputs)
        self.layers = _collect(self.outputs)
        names = [l.name for l in self.layers]
        dup = {n for n in names if names.count(n) > 1}
        if dup:
            raise ValueError(f"duplicate layer names in topology: {sorted(dup)}")
        self.by_name = {l.name: l for l in self.layers}
        # a ModelSpec's cost nodes carry the spec's declared inference
        # head (ModelSpec.__post_init__); if that head is NOT in this
        # graph the builder is holding a cost-only topology — warn so
        # inference is built from spec.output, not discovered missing
        # at serving time (the transformer's probs side branch)
        for o in self.outputs:
            declared = getattr(o, "declared_output", None)
            if declared is not None and declared not in self.by_name \
                    and declared not in _warned_orphan_outputs:
                _warned_orphan_outputs.add(declared)  # once per head name
                import warnings
                warnings.warn(
                    f"topology built from a cost graph that does NOT "
                    f"contain the model's declared output "
                    f"{declared!r} (a side branch): build inference "
                    "topologies from spec.output, or pass "
                    "extra_outputs=[spec.output] here", stacklevel=2)
                break
        # merge param specs (shared params must agree on shape)
        self.param_specs: Dict[str, ParamSpec] = {}
        self.state_specs: Dict[str, StateSpec] = {}
        for l in self.layers:
            for ps in l.params:
                if ps.name in self.param_specs:
                    prev = self.param_specs[ps.name]
                    if tuple(prev.shape) != tuple(ps.shape):
                        raise ValueError(
                            f"shared parameter {ps.name!r} shape mismatch: "
                            f"{prev.shape} vs {ps.shape}")
                else:
                    self.param_specs[ps.name] = ps
            for ss in l.states:
                self.state_specs[ss.name] = ss

    # ------------------------------------------------------------------ init
    def init_params(self, rng: Optional[jax.Array] = None,
                    only: Optional[Sequence[str]] = None
                    ) -> Dict[str, jax.Array]:
        """Initialize parameters. `only` restricts to a subset of names
        (same per-name keys as a full init, so values are identical)."""
        if rng is None:
            rng = jax.random.PRNGKey(0)
        wanted = None if only is None else set(only)
        params = {}
        for i, (name, ps) in enumerate(sorted(self.param_specs.items())):
            if wanted is not None and name not in wanted:
                continue
            key = jax.random.fold_in(rng, i)
            params[name] = ps.initializer(key, tuple(ps.shape), ps.dtype)
        return params

    def init_state(self) -> Dict[str, jax.Array]:
        return {name: jnp.full(tuple(ss.shape), ss.init_value, ss.dtype)
                for name, ss in sorted(self.state_specs.items())}

    # --------------------------------------------------------------- forward
    def forward(self, params: Dict[str, jax.Array],
                state: Dict[str, jax.Array],
                feed: Dict[str, Any], *, mode: str = "train",
                rng: Optional[jax.Array] = None,
                output_names: Optional[Sequence[str]] = None,
                sparse_sub: Optional[Dict[str, Any]] = None,
                injected: Optional[Dict[str, Any]] = None,
                skip: Sequence[str] = (), mesh=None, n_real=None,
                taps: Optional[Dict[str, Any]] = None):
        """Pure forward pass.

        Returns (outputs_dict, new_state). `outputs_dict` maps layer name ->
        value for requested outputs (default: self.outputs).
        `sparse_sub`: {param_name: (uids, rows)} prefetched row blocks —
        embedding layers whose table appears here look ids up inside the
        block so gradients stay row-sparse (SparseRowMatrix parity).
        `injected`/`skip`: pre-computed values (e.g. the pipelined body's
        boundary activation) and layer names NOT to execute here — a
        skipped, un-injected value consumed downstream raises KeyError.
        `taps`: {layer name: zero array added to that layer's output} —
        differentiating the caller's loss w.r.t. a tap yields the
        activation cotangent d(loss)/d(output) (gradient_printer support).
        """
        ctx = ApplyContext(mode, rng, state)
        ctx.sparse_sub = sparse_sub
        ctx.mesh = mesh     # layers may pick sp/mp-aware code paths
        # real (un-padded) rows in the batch; row-COUPLED layers (moe
        # capacity routing) must exclude feeder pad rows, which the
        # per-row cost mask cannot do for them
        ctx.n_real = n_real
        values: Dict[str, Any] = dict(injected or {})
        skip_set = set(skip)
        wanted = set(output_names) if output_names is not None else \
            {o.name for o in self.outputs}
        for layer in self.layers:
            if layer.name in values or layer.name in skip_set:
                continue
            impl = get_layer_impl(layer.type)
            if layer.type == "data":
                if layer.name not in feed:
                    raise KeyError(f"missing feed for data layer {layer.name!r}")
                values[layer.name] = impl["apply"](ctx, layer.name,
                                                   layer.config, {},
                                                   [feed[layer.name]])
            else:
                lparams = {ps.name: params[ps.name] for ps in layer.params}
                inputs = [values[p.name] for p in layer.parents]
                values[layer.name] = impl["apply"](ctx, layer.name,
                                                   layer.config, lparams,
                                                   inputs)
            if taps and layer.name in taps:
                v, t = values[layer.name], taps[layer.name]
                from paddle_tpu.core.sequence import SequenceBatch
                if isinstance(v, SequenceBatch):
                    v = SequenceBatch(v.data + t, v.lengths,
                                      v.segment_ids, v.num_segments)
                else:
                    v = v + t
                values[layer.name] = v
        new_state = dict(state)
        new_state.update(ctx.state_updates)
        outs = {n: values[n] for n in wanted if n in values}
        return outs, new_state

    # ----------------------------------------------------------- sparse path
    def sparse_tables(self) -> Dict[str, str]:
        """param_name -> ids data-layer name, for every embedding table
        marked ParamAttr(sparse=True) whose ids come straight from a data
        layer (the prefetchable set — MultiGradientMachine.h:99-166).
        Sparse tables fed by computed ids fall back to dense gradients."""
        out: Dict[str, str] = {}
        dense_fallback = set()
        for l in self.layers:
            if l.type != "embedding":
                continue
            for ps in l.params:
                if not getattr(ps.attr, "sparse", False):
                    continue
                if not (l.parents and l.parents[0].type == "data"):
                    dense_fallback.add(ps.name)     # computed ids
                elif ps.name in out and out[ps.name] != l.parents[0].name:
                    dense_fallback.add(ps.name)     # shared across sources
                else:
                    out[ps.name] = l.parents[0].name
        for n in dense_fallback:
            out.pop(n, None)
        return out

    def remote_tables(self) -> Dict[str, str]:
        """param_name -> ids data-layer name, for every embedding table
        marked ``remote=True`` — the set :class:`embed.lookup.RemoteLookup`
        must gather rows for before each forward. Remote ids must come
        straight from a data layer (they are fetched host-side, before
        the jitted forward can compute anything)."""
        out: Dict[str, str] = {}
        for l in self.layers:
            if l.type != "embedding" or not l.config.get("_remote"):
                continue
            assert l.parents and l.parents[0].type == "data", \
                f"remote embedding {l.name!r} must read ids from a " \
                "data layer (rows are gathered host-side per batch)"
            out[l.config["_w_name"]] = l.parents[0].name
        return out

    # ------------------------------------------------------------ data layers
    def data_layers(self) -> Dict[str, LayerOutput]:
        """Name -> data layer, in declaration order (feeding order contract,
        mirrors Topology.data_layers in v2/topology.py)."""
        return {l.name: l for l in self.layers if l.type == "data"}

    def get_layer(self, name: str) -> LayerOutput:
        """The layer node by name (v2/topology.py Topology.get_layer;
        pinned by the reference's test_topology.py test_get_layer)."""
        if name not in self.by_name:
            raise ValueError(f"layer {name!r} not in topology; have "
                             f"{sorted(self.by_name)}")
        return self.by_name[name]

    def data_type(self):
        """[(name, InputType)] — v2 API compatibility for DataFeeder."""
        from paddle_tpu.core import data_type as dt
        out = []
        for name, l in self.data_layers().items():
            out.append((name, l.config["input_type"]))
        return out

    # ----------------------------------------------------------- serialization
    def serialize(self) -> str:
        """JSON model config — the ModelConfig.proto contract equivalent."""
        layers = []
        for l in self.layers:
            layers.append({
                "name": l.name,
                "type": l.type,
                "inputs": [p.name for p in l.parents],
                "config": _jsonify(l.config),
            })
        return json.dumps({
            "format": "paddle_tpu.topology.v1",
            "layers": layers,
            "outputs": [o.name for o in self.outputs],
        }, indent=1)

    @staticmethod
    def deserialize(blob: Union[str, bytes]) -> "Topology":
        spec = json.loads(blob)
        assert spec.get("format") == "paddle_tpu.topology.v1", "bad topology blob"
        built: Dict[str, LayerOutput] = {}
        for ld in spec["layers"]:
            cfg = _unjsonify(ld["config"])
            inputs = [built[n] for n in ld["inputs"]]
            node = make_layer(ld["type"], ld["name"], inputs, **cfg)
            built[ld["name"]] = node
        return Topology([built[n] for n in spec["outputs"]])

    def proto(self) -> str:
        """v2 API compat alias (Topology.proto() returned the ModelConfig pb)."""
        return self.serialize()


def _jsonify(obj):
    from paddle_tpu.core.data_type import InputType, SeqType
    from paddle_tpu.core.registry import ParamAttr
    if isinstance(obj, dict):
        # "_obj_*" keys hold runtime-only objects (e.g. captured
        # sub-topologies) rebuilt on deserialize — never serialized.
        return {k: _jsonify(v) for k, v in obj.items()
                if not k.startswith("_obj_")}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    if isinstance(obj, InputType):
        return {"__input_type__": [obj.dim, obj.kind, obj.seq_type.value]}
    if isinstance(obj, SeqType):
        return {"__seq_type__": obj.value}
    if isinstance(obj, ParamAttr):
        # initializer callables are init-time only; dropped in serialization
        d = {
            "name": obj.name, "learning_rate": obj.learning_rate,
            "l1_rate": obj.l1_rate, "l2_rate": obj.l2_rate,
            "is_static": obj.is_static, "sparse": obj.sparse,
            "initial_std": obj.initial_std, "initial_mean": obj.initial_mean,
            "gradient_clipping_threshold": obj.gradient_clipping_threshold}
        hooks = obj.update_hooks
        if hooks is not None:
            d["update_hooks"] = [
                {"type": h.type,
                 "sparsity_ratio": getattr(h, "sparsity_ratio", None)}
                for h in (hooks if isinstance(hooks, (list, tuple))
                          else [hooks])]
        return {"__param_attr__": d}
    return obj


def _unjsonify(obj):
    from paddle_tpu.core.data_type import InputType, SeqType
    from paddle_tpu.core.registry import ParamAttr
    if isinstance(obj, dict):
        if "__input_type__" in obj:
            d, k, s = obj["__input_type__"]
            return InputType(d, k, SeqType(s))
        if "__seq_type__" in obj:
            return SeqType(obj["__seq_type__"])
        if "__param_attr__" in obj:
            d = dict(obj["__param_attr__"])
            if d.get("update_hooks"):
                from paddle_tpu.attr import HookAttribute
                d["update_hooks"] = [
                    HookAttribute(h["type"], h.get("sparsity_ratio"))
                    for h in d["update_hooks"]]
            return ParamAttr(**d)
        return {k: _unjsonify(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unjsonify(v) for v in obj]
    return obj
