"""Ragged / nested sequence batches — the framework's `Argument` sequence layout.

Reference: paddle/parameter/Argument.h:84-93 threads
`sequenceStartPositions` / `subSequenceStartPositions` (two levels of offsets)
through every layer so variable-length and nested sequences train without
per-sample looping; gserver/layers/SequenceToBatch.h re-packs ragged rows into
dense per-timestep batches for RNNs.

TPU-native design: XLA wants static shapes, so a batch of ragged sequences is
a dense padded array plus integer lengths — masking replaces re-packing
(`SequenceToBatch` is unnecessary: a scan over the padded time axis with a
`t < length` mask does the same work without gather/scatter, and XLA fuses the
mask into the cell math). Nested (sub-)sequences carry a per-position
`segment_ids` plane mapping each timestep to its inner sequence, which is what
segment-reductions need (`jax.ops.segment_sum` style) — the generalization the
reference later called LoD (framework/lod_tensor.h:51).
"""

from __future__ import annotations

from typing import Optional, Sequence as PySequence

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
class SequenceBatch:
    """A batch of padded variable-length sequences.

    data:        [batch, max_len, *feature_dims]  (or [batch, max_len] for ids)
    lengths:     [batch] int32 — valid timesteps per row
    segment_ids: optional [batch, max_len] int32 — inner-sequence index per
                 position (for nested sequences); -1 on padding
    num_segments: optional [batch] int32 — inner sequences per row
    """

    def __init__(self, data, lengths, segment_ids=None, num_segments=None):
        self.data = data
        self.lengths = lengths
        self.segment_ids = segment_ids
        self.num_segments = num_segments

    # --- pytree protocol -------------------------------------------------
    def tree_flatten(self):
        children = (self.data, self.lengths, self.segment_ids, self.num_segments)
        return children, None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # --- basic properties ------------------------------------------------
    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_len(self) -> int:
        return self.data.shape[1]

    @property
    def is_nested(self) -> bool:
        return self.segment_ids is not None

    def mask(self, dtype=jnp.float32) -> jnp.ndarray:
        """[batch, max_len] 1.0 on valid positions, 0.0 on padding."""
        t = jnp.arange(self.max_len, dtype=jnp.int32)[None, :]
        return (t < self.lengths[:, None]).astype(dtype)

    def bool_mask(self) -> jnp.ndarray:
        t = jnp.arange(self.max_len, dtype=jnp.int32)[None, :]
        return t < self.lengths[:, None]

    def masked_data(self) -> jnp.ndarray:
        """Zero out padding positions."""
        m = self.mask(self.data.dtype)
        return self.data * m.reshape(m.shape + (1,) * (self.data.ndim - 2))

    def with_data(self, data) -> "SequenceBatch":
        return SequenceBatch(data, self.lengths, self.segment_ids,
                             self.num_segments)

    def total_tokens(self) -> jnp.ndarray:
        return jnp.sum(self.lengths)

    def __repr__(self):
        return (f"SequenceBatch(data={getattr(self.data, 'shape', None)}, "
                f"lengths={getattr(self.lengths, 'shape', None)}, "
                f"nested={self.is_nested})")


def pack_sequences(rows: PySequence[np.ndarray], max_len: Optional[int] = None,
                   pad_value=0, dtype=None) -> SequenceBatch:
    """Pack a list of per-sample [len, ...] arrays into a padded SequenceBatch.

    This is the host-side converter that plays the role of
    py_paddle/dataprovider_converter.py (numpy -> Argument with
    sequenceStartPositions).
    """
    rows = [np.asarray(r) for r in rows]
    lengths = np.asarray([r.shape[0] for r in rows], dtype=np.int32)
    ml = int(max_len if max_len is not None else (lengths.max() if len(rows) else 0))
    ml = max(ml, 1)
    feat = rows[0].shape[1:] if rows else ()
    if dtype is None:
        dtype = rows[0].dtype if rows else np.float32
    out = np.full((len(rows), ml) + feat, pad_value, dtype=dtype)
    for i, r in enumerate(rows):
        n = min(r.shape[0], ml)
        out[i, :n] = r[:n]
    return SequenceBatch(jnp.asarray(out), jnp.asarray(np.minimum(lengths, ml)))


def pack_nested_sequences(rows: PySequence[PySequence[np.ndarray]],
                          pad_value=0, dtype=None) -> SequenceBatch:
    """Pack a list of per-sample lists of subsequences (nested sequences).

    Each sample is a list of [sub_len, ...] arrays. Flattened along time with
    segment_ids marking subsequence membership — the two-level
    subSequenceStartPositions layout (Argument.h:89-90) as dense planes.
    """
    flat_rows, seg_rows, num_segs = [], [], []
    for sample in rows:
        parts = [np.asarray(p) for p in sample]
        flat_rows.append(np.concatenate(parts, axis=0) if parts
                         else np.zeros((0,), dtype=np.float32))
        seg = np.concatenate([np.full(p.shape[0], i, dtype=np.int32)
                              for i, p in enumerate(parts)]) if parts else \
            np.zeros((0,), dtype=np.int32)
        seg_rows.append(seg)
        num_segs.append(len(parts))
    packed = pack_sequences(flat_rows, pad_value=pad_value, dtype=dtype)
    ml = packed.max_len
    seg_arr = np.full((len(rows), ml), -1, dtype=np.int32)
    for i, s in enumerate(seg_rows):
        seg_arr[i, :min(len(s), ml)] = s[:ml]
    return SequenceBatch(packed.data, packed.lengths, jnp.asarray(seg_arr),
                         jnp.asarray(np.asarray(num_segs, dtype=np.int32)))


def bucket_length(n: int, buckets: PySequence[int] = (16, 32, 64, 128, 256, 512, 1024)) -> int:
    """Round a max length up to a bucket to bound XLA recompilation.

    The reference pays zero padding via SequenceToBatch; on TPU we instead pay
    bounded padding for static shapes, amortised by bucketing.
    """
    for b in buckets:
        if n <= b:
            return b
    return int(np.ceil(n / buckets[-1]) * buckets[-1])
