"""Layer/op registries and graph node types.

Reference parity: the `@config_layer('fc')` registry in
python/paddle/trainer/config_parser.py (:1786 and siblings) validated configs
and computed output sizes in Python; REGISTER_LAYER (gserver/layers/Layer.h:31)
bound the C++ compute. Here both halves live together: a registered LayerImpl
carries `build` (validate + shape-infer + declare params — the config_parser
half) and `apply` (pure JAX compute — the gserver half, compiled by XLA).
"""

from __future__ import annotations

import dataclasses
import itertools
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers

# ---------------------------------------------------------------------------
# Parameter declaration


@dataclasses.dataclass
class ParamAttr:
    """Per-parameter attributes — reference: ParameterConfig.proto +
    trainer_config_helpers/attrs.py ParameterAttribute (lr, l2, sparse,
    is_static, shared name)."""
    name: Optional[str] = None
    learning_rate: float = 1.0
    l1_rate: Optional[float] = None
    l2_rate: Optional[float] = None
    is_static: bool = False
    sparse: bool = False            # row-sparse gradient (embedding tables)
    remote: bool = False            # table lives in the sharded embed store
                                    # (paddle_tpu/embed) — no local param;
                                    # rows arrive via ctx.sparse_sub
    initializer: Optional[Any] = None
    initial_std: Optional[float] = None
    initial_mean: float = 0.0
    gradient_clipping_threshold: Optional[float] = None
    # ParameterUpdaterHook (ParameterUpdaterHook.cpp StaticPruningHook):
    # e.g. HookAttribute("pruning", sparsity_ratio=0.6)
    update_hooks: Optional[Any] = None

    @staticmethod
    def of(x) -> "ParamAttr":
        if x is None:
            return ParamAttr()
        if isinstance(x, ParamAttr):
            return x
        if isinstance(x, dict):
            return ParamAttr(**x)
        raise TypeError(f"cannot convert {x!r} to ParamAttr")


@dataclasses.dataclass
class ParamSpec:
    name: str
    shape: Tuple[int, ...]
    initializer: Any
    attr: ParamAttr
    dtype: Any = jnp.float32


@dataclasses.dataclass
class StateSpec:
    """Non-trainable state (e.g. batch-norm moving stats). Reference keeps
    these as parameters with is_static + moving-average update hooks; we keep
    them in a separate 'state' collection updated functionally."""
    name: str
    shape: Tuple[int, ...]
    init_value: float = 0.0
    dtype: Any = jnp.float32


# ---------------------------------------------------------------------------
# Graph nodes


@dataclasses.dataclass
class LayerMeta:
    """Static description of one layer's output (what config_parser tracked:
    size, image dims, sequence level)."""
    size: int                       # feature dimension
    seq_level: int = 0              # 0: sample, 1: sequence, 2: nested
    height: int = 0                 # spatial dims for image layers
    width: int = 0
    channels: int = 0
    depth: int = 0                  # for 3D conv
    is_integer: bool = False        # integer ids (embedding input)


_name_counters: Dict[str, "itertools.count"] = {}


def _auto_name(layer_type: str) -> str:
    c = _name_counters.setdefault(layer_type, itertools.count())
    return f"__{layer_type}_{next(c)}__"


def reset_name_counters():
    _name_counters.clear()


class LayerOutput:
    """The object a DSL call returns; doubles as the graph node.

    Mirrors trainer_config_helpers.layers.LayerOutput: holds name, type,
    parents, and the static config. The full graph is recovered by walking
    `parents` from the requested outputs (python/paddle/v2/layer.py
    parse_network:263 does the same trim).
    """

    def __init__(self, layer_type: str, name: Optional[str], parents:
                 Sequence["LayerOutput"], config: Dict[str, Any],
                 meta: LayerMeta, params: List[ParamSpec],
                 states: List[StateSpec]):
        self.type = layer_type
        self.name = name or _auto_name(layer_type)
        self.parents = list(parents)
        self.config = config
        self.meta = meta
        self.params = params
        self.states = states

    @property
    def size(self) -> int:
        return self.meta.size

    def __repr__(self):
        return f"LayerOutput({self.type}:{self.name}, size={self.meta.size})"


# ---------------------------------------------------------------------------
# Apply-time context


class ApplyContext:
    """Runtime context threaded through layer `apply` calls."""

    def __init__(self, mode: str, rng: Optional[jax.Array], state: Dict[str, Any]):
        self.mode = mode                  # 'train' | 'test'
        self._rng = rng
        self.state = dict(state)          # read view
        self.state_updates: Dict[str, Any] = {}

    @property
    def is_train(self) -> bool:
        return self.mode == "train"

    def rng_for(self, layer_name: str) -> jax.Array:
        if self._rng is None:
            return jax.random.PRNGKey(0)
        # deterministic digest — python hash() is salted per process and
        # would break seeded reproducibility of dropout/NCE sampling
        digest = zlib.crc32(layer_name.encode()) & 0x7FFFFFFF
        return jax.random.fold_in(self._rng, digest)

    def get_state(self, name: str):
        return self.state[name]

    def set_state(self, name: str, value):
        self.state_updates[name] = value


# ---------------------------------------------------------------------------
# Registry

# layer type -> dict(build=..., apply=...)
_LAYER_REGISTRY: Dict[str, Dict[str, Callable]] = {}


def register_layer(layer_type: str):
    """Register a layer implementation.

    build(name, cfg, input_metas) -> (LayerMeta, [ParamSpec], [StateSpec])
    apply(ctx, name, cfg, params, inputs) -> output (array or SequenceBatch)
    """
    def deco(cls):
        _LAYER_REGISTRY[layer_type] = {
            "build": cls.build, "apply": cls.apply, "cls": cls}
        return cls
    return deco


def get_layer_impl(layer_type: str) -> Dict[str, Callable]:
    if layer_type not in _LAYER_REGISTRY:
        raise KeyError(f"unknown layer type {layer_type!r}; registered: "
                       f"{sorted(_LAYER_REGISTRY)}")
    return _LAYER_REGISTRY[layer_type]


def registered_layer_types() -> List[str]:
    return sorted(_LAYER_REGISTRY)


def make_layer(layer_type: str, name: Optional[str],
               inputs: Sequence[LayerOutput], **config) -> LayerOutput:
    """Construct a graph node: run the build half, wrap the result."""
    impl = get_layer_impl(layer_type)
    name = name or _auto_name(layer_type)
    metas = [i.meta for i in inputs]
    meta, params, states = impl["build"](name, config, metas)
    return LayerOutput(layer_type, name, inputs, config, meta, params, states)


def default_weight_init(attr: ParamAttr, fan_in_axes=(0,)):
    if attr.initializer is not None:
        return attr.initializer
    if attr.initial_std is not None:
        return initializers.normal(attr.initial_std, attr.initial_mean)
    return initializers.xavier(fan_in_axes)
