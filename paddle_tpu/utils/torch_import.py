"""Import torch weights into Parameters (python/paddle/utils/torch2paddle.py).

The reference reads serialized torch7 nn modules and copies tensors into
paddle parameter files in layer order. The modern equivalent is a
``state_dict``: ``import_torch_state_dict`` copies its tensors into an
existing :class:`Parameters`, either by an explicit ``name_map``
(our-name -> torch-key) or positionally in definition order, the
reference's convention (torch2paddle.py: layers are walked and assigned
sequentially).

Shape adaptation: ``torch.nn.Linear`` stores ``[out, in]`` while fc
parameters here are ``[in, out]`` (layers/base.py FCLayer.build), so a 2-D
source whose transposed shape matches is transposed; anything else must
match exactly or the import raises.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

import numpy as np

__all__ = ["import_torch_state_dict"]


def _to_numpy(t) -> np.ndarray:
    if hasattr(t, "detach"):          # torch.Tensor without importing torch
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def _fit(name: str, src: np.ndarray, want: tuple, transpose) -> np.ndarray:
    if transpose is True and src.ndim == 2:
        if tuple(src.T.shape) != tuple(want):
            raise ValueError(
                f"transpose=True but {name!r} source {tuple(src.shape)} "
                f"transposed does not give {tuple(want)}")
        return np.ascontiguousarray(src.T)
    if tuple(src.shape) == tuple(want):
        if transpose == "auto" and src.ndim == 2 and \
                src.shape[0] == src.shape[1]:
            import warnings
            warnings.warn(
                f"square 2-D tensor for {name!r}: transpose='auto' cannot "
                "tell a torch Linear [out,in] from a matching [in,out] "
                "layout — kept as-is; pass transpose=True (per-name via "
                "name_map ordering, or import it separately) if this came "
                "from torch.nn.Linear", stacklevel=3)
        return src
    if transpose == "auto" and src.ndim == 2 and \
            tuple(src.T.shape) == tuple(want):
        return np.ascontiguousarray(src.T)   # torch Linear [out,in] -> [in,out]
    raise ValueError(
        f"torch tensor for {name!r} has shape {tuple(src.shape)}, "
        f"parameter wants {tuple(want)}")


def import_torch_state_dict(parameters, state_dict: Mapping[str, object],
                            name_map: Optional[Dict[str, str]] = None,
                            strict: bool = True,
                            transpose="auto") -> int:
    """Copy torch tensors into ``parameters`` in place; returns the count.

    With ``name_map`` only the listed parameters load. Without it, the
    torch entries are assigned to parameters positionally (both sides in
    their definition order); ``strict`` then requires equal counts.

    ``transpose``: ``"auto"`` (default) transposes a 2-D source only when
    the exact shape does not fit but the transpose does — and warns on
    square matrices, where the two layouts are indistinguishable;
    ``True`` forces the Linear [out,in]->[in,out] transpose for every
    2-D tensor; ``False`` requires exact shape matches.
    """
    if name_map is None:
        pnames = list(parameters.names())
        tkeys = list(state_dict.keys())
        if len(pnames) != len(tkeys):
            if strict:
                raise ValueError(
                    f"positional import needs equal counts: {len(pnames)} "
                    f"parameters vs {len(tkeys)} torch tensors "
                    "(pass name_map)")
            import warnings
            short, long_ = sorted((len(pnames), len(tkeys)))
            side = "parameters" if len(pnames) > len(tkeys) \
                else "torch tensors"
            warnings.warn(
                f"positional import with strict=False: {len(pnames)} "
                f"parameters vs {len(tkeys)} torch tensors — only the "
                f"first {short} pairs load, {long_ - short} trailing "
                f"{side} are skipped", stacklevel=2)
        name_map = dict(zip(pnames, tkeys))
    n = 0
    for pname, tkey in name_map.items():
        if tkey not in state_dict:
            raise KeyError(f"state_dict has no key {tkey!r} (for {pname!r})")
        want = parameters.get_shape(pname)
        parameters[pname] = _fit(pname, _to_numpy(state_dict[tkey]), want,
                                 transpose)
        n += 1
    return n
