from paddle_tpu.utils import stats

__all__ = ["stats"]
