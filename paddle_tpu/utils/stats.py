"""Scoped wall-clock stat timers.

Reference: paddle/utils/Stat.h — `REGISTER_TIMER(name)` RAII scopes
aggregated into `globalStat` (StatSet :63,:111) with periodic printing
(--log_period) and per-thread breakdown; compiled out unless WITH_TIMER.

Here: a process-global registry of named timers with count/total/max/min,
a `stat_timer(name)` context manager, and `print_all_status()` — plus a
bridge to jax.profiler trace annotations so the same scopes show up in
XPlane traces when profiling on TPU.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict

import jax

from paddle_tpu.analysis.lockdep import named_lock


class StatItem:
    # add() is a read-modify-write reached concurrently from the
    # pt-serve / pt-data worker pools (serving/forward, pipeline
    # timers) — the per-item lock keeps count/total consistent where
    # the bare += used to drop updates under contention
    __slots__ = ("count", "total", "max", "min", "_lock")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.min = float("inf")
        self._lock = named_lock("stats.item")

    def add(self, dt: float):
        with self._lock:
            self.count += 1
            self.total += dt
            self.max = max(self.max, dt)
            self.min = min(self.min, dt)

    def snapshot(self):
        """(count, total, max) read atomically — the obs metrics
        bridge scrapes this (paddle_tpu/obs/metrics.py)."""
        with self._lock:
            return self.count, self.total, self.max

    def __str__(self):
        avg = self.total / self.count if self.count else 0.0
        return (f"count={self.count} total={self.total * 1e3:.2f}ms "
                f"avg={avg * 1e3:.3f}ms max={self.max * 1e3:.3f}ms "
                f"min={(self.min if self.count else 0.0) * 1e3:.3f}ms")


class StatSet:
    def __init__(self):
        self._lock = named_lock("stats.statset")
        self._stats: Dict[str, StatItem] = {}
        self.enabled = True

    def get(self, name: str) -> StatItem:
        with self._lock:
            if name not in self._stats:
                self._stats[name] = StatItem()
            return self._stats[name]

    def reset(self):
        with self._lock:
            self._stats.clear()

    def items(self):
        with self._lock:
            return dict(self._stats)

    def print_all_status(self):
        for name, item in sorted(self.items().items()):
            print(f"Stat={name:<30} {item}")


class CounterSet:
    """Process-global named event counters (the counter half of Stat.h's
    globalStat). Timers measure durations; counters count occurrences —
    quarantined samples, worker restarts, source stalls
    (reader/pipeline.py), corrupt chunks — so chaos tests can diff exact
    fault counts around an epoch."""

    def __init__(self):
        self._lock = named_lock("stats.counters")
        self._counts: Dict[str, int] = {}

    def bump(self, name: str, n: int = 1) -> int:
        with self._lock:
            v = self._counts.get(name, 0) + n
            self._counts[name] = v
            return v

    def value(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def reset(self):
        with self._lock:
            self._counts.clear()

    def items(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def print_all_status(self):
        for name, v in sorted(self.items().items()):
            print(f"Counter={name:<30} {v}")


global_stat = StatSet()
global_counters = CounterSet()


_TRACER = None


def _tracer():
    """Lazy obs.trace handle (import-cycle-free: obs imports this
    module; the first stat_timer call happens long after both are
    loaded)."""
    global _TRACER
    if _TRACER is None:
        from paddle_tpu.obs.trace import TRACER
        _TRACER = TRACER
    return _TRACER


@contextlib.contextmanager
def stat_timer(name: str):
    """REGISTER_TIMER parity; also emits a jax.profiler named scope,
    and — while a host trace is active (obs/trace.py) — a span, so
    every timed scope (train_step, data wait, checkpoint write,
    serving/decode_step) lands in the Chrome trace for free."""
    if not global_stat.enabled:
        yield
        return
    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        with _tracer().span(name):
            yield
    global_stat.get(name).add(time.perf_counter() - t0)


def timed(name: str):
    def deco(fn):
        def wrapper(*a, **kw):
            with stat_timer(name):
                return fn(*a, **kw)
        return wrapper
    return deco
