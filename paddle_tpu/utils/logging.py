"""Logging + version stamping — paddle/utils glog/gflags surface.

Reference: paddle/utils/Logging.h (glog wrappers initializeLogging,
setMinLogLevel, installFailureWriter) and Version.h (version::printVersion,
paddle/scripts' PADDLE_VERSION stamp). Python logging plays glog's role;
the format mirrors glog's `[LEVEL datetime file:line]` so log-scraping
tooling carries over.
"""

from __future__ import annotations

import logging
import sys

VERSION = "0.3.0"               # round-3 framework version stamp
ISA_TARGET = "tpu-xla"          # the reference stamped WITH_GPU/avx flags

_FMT = "[%(levelname).1s %(asctime)s %(filename)s:%(lineno)d] %(message)s"
_initialized = False


class _StderrHandler(logging.StreamHandler):
    """Resolves ``sys.stderr`` at EMIT time, not construction: glog
    writes to whatever stderr currently is, so stderr redirection (and
    pytest's capture) works no matter which module logged first."""

    def __init__(self):
        logging.Handler.__init__(self)

    @property
    def stream(self):
        return sys.stderr


def initialize_logging(level: int = logging.INFO) -> logging.Logger:
    """initializeLogging parity: root logger with the glog line format."""
    global _initialized
    logger = logging.getLogger("paddle_tpu")
    if not _initialized:
        handler = _StderrHandler()
        handler.setFormatter(logging.Formatter(_FMT, "%m%d %H:%M:%S"))
        logger.addHandler(handler)
        logger.propagate = False
        _initialized = True
    logger.setLevel(level)
    return logger


def get_logger(name: str = "paddle_tpu") -> logging.Logger:
    initialize_logging()
    return logging.getLogger(name)


def set_min_log_level(level: int) -> None:
    """setMinLogLevel parity (glog numeric levels also accepted: 0..3 ->
    INFO/WARNING/ERROR/FATAL)."""
    glog_map = {0: logging.INFO, 1: logging.WARNING, 2: logging.ERROR,
                3: logging.CRITICAL}
    initialize_logging().setLevel(glog_map.get(level, level))


def version() -> str:
    """version::printVersion parity — framework + runtime versions."""
    import jax

    return (f"paddle_tpu {VERSION} (target {ISA_TARGET}, "
            f"jax {jax.__version__})")


def print_version() -> None:
    print(version())
