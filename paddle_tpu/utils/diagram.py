"""Model topology -> Graphviz dot (python/paddle/utils/make_model_diagram.py).

The reference walks a protobuf ModelConfig and emits one box per layer with
``name: type, size`` labels and parent edges; here the graph is the
LayerOutput DAG a Topology already holds. Data layers are drawn as ovals and
cost/output heads double-peripheried, which is all the reference's diagram
conveys — no graphviz binary is needed to produce the .dot text.
"""

from __future__ import annotations

from typing import Union

__all__ = ["topology_to_dot", "make_diagram"]


def _esc(s: str) -> str:
    return s.replace('"', '\\"')


def topology_to_dot(topology, graph_name: str = "model") -> str:
    """Render a Topology (or a single output LayerOutput) as dot text."""
    from paddle_tpu.core.topology import Topology
    if not isinstance(topology, Topology):
        topology = Topology(topology)
    heads = {o.name for o in topology.outputs}
    lines = [f'digraph "{_esc(graph_name)}" {{',
             "  rankdir=BT;",  # inputs at the bottom, as the reference
             '  node [fontsize=10, shape=box];']
    for lyr in topology.layers:
        label = f"{lyr.name}\\n{lyr.type}, size={lyr.meta.size}"
        attrs = [f'label="{_esc(label)}"']
        if lyr.type == "data":
            attrs.append("shape=oval")
        if lyr.name in heads:
            attrs.append("peripheries=2")
        lines.append(f'  "{_esc(lyr.name)}" [{", ".join(attrs)}];')
    for lyr in topology.layers:
        for p in lyr.parents:
            lines.append(f'  "{_esc(p.name)}" -> "{_esc(lyr.name)}";')
    lines.append("}")
    return "\n".join(lines) + "\n"


def make_diagram(config_or_topology: Union[str, object], dot_path: str,
                 graph_name: str = "model") -> str:
    """Write the dot file for a topology object or a serialized-topology
    JSON path (make_model_diagram.py:usage 'config_file dot_file'). Returns
    the dot text."""
    topo = config_or_topology
    if isinstance(topo, str):
        from paddle_tpu.core.topology import Topology
        with open(topo) as f:
            topo = Topology.deserialize(f.read())
    dot = topology_to_dot(topo, graph_name)
    with open(dot_path, "w") as f:
        f.write(dot)
    return dot
